"""Communicators: group + CID + per-comm collective vtable + p2p dispatch.

Re-design of ``/root/reference/ompi/communicator/communicator.h`` /
``comm.c`` / ``comm_cid.c``: a communicator owns its group, a context id
agreed across members (``comm_cid.c:53-93``; carries an FT epoch ``:78``),
and a per-comm collective vtable ``c_coll`` filled by priority vote of the
coll components (``coll_base_comm_select.c``).  Point-to-point dispatches to
the selected pml module the way ``MPI_Send`` does
(``ompi/mpi/c/send.c:93`` → ``MCA_PML_CALL``).  ULFM state (revoked flag,
failure checks before communication, ``comm_ft.c``) is carried here.
"""
from __future__ import annotations

import threading
from typing import Any, Optional, Sequence

import numpy as np

from ompi_tpu.api import op as op_mod
from ompi_tpu.api.attributes import AttributeHost
from ompi_tpu.api.errhandler import ERRORS_ARE_FATAL, Errhandler
from ompi_tpu.api.errors import ErrorClass, MpiError, RevokedError
from ompi_tpu.api.group import Group
from ompi_tpu.api.info import Info
from ompi_tpu.api.request import CompletedRequest, Request, waitall
from ompi_tpu.api.status import ANY_SOURCE, ANY_TAG, PROC_NULL, Status
from ompi_tpu.datatype import Datatype, from_numpy_dtype

_ft_state_mod = None


def _ft_state():
    """Cached ft.state module ref (import is lazy to avoid a cycle, but a
    sys.modules lookup per _check_state would cost ~0.2us on the device
    fast path)."""
    global _ft_state_mod
    if _ft_state_mod is None:
        from ompi_tpu.ft import state

        _ft_state_mod = state
    return _ft_state_mod

# collective function slots a coll module can fill (``mca/coll/coll.h``
# module struct equivalent; *_array are the TPU device-buffer entry points)
COLL_FUNCTIONS = (
    "barrier", "bcast", "gather", "gatherv", "scatter", "scatterv",
    "allgather", "allgatherv", "alltoall", "alltoallv", "alltoallw",
    "reduce", "allreduce", "reduce_scatter", "reduce_scatter_block",
    "scan", "exscan",
    "ibarrier", "ibcast", "igather", "iscatter", "iallgather", "ialltoall",
    "ireduce", "iallreduce", "ireduce_scatter", "iscan", "iexscan",
    "allreduce_array", "bcast_array", "allgather_array",
    "reduce_scatter_array", "alltoall_array", "ppermute_array",
    "psum_scatter_array", "reduce_array", "gather_array", "scatter_array",
    "allgatherv_array", "alltoallv_array", "scan_array", "exscan_array",
    "persistent_coll", "partitioned_coll", "device_barrier",
    "agree", "iagree",
    "neighbor_allgather", "neighbor_alltoall",
)


def as_buffer(buf) -> tuple[np.ndarray, int, Datatype]:
    """Normalize a user buffer to (ndarray, count, datatype).

    Accepts an ndarray (count/type inferred), or an explicit
    ``(ndarray, count, Datatype)`` triple for derived layouts.
    """
    if isinstance(buf, tuple):
        arr, count, dt = buf
        return np.asarray(arr), count, dt
    arr = np.asarray(buf)
    return arr, arr.size, from_numpy_dtype(arr.dtype)


#: live-communicator registry for debugger introspection
#: (``runtime.debugger.comm_table`` — the handle-table walk of
#: ``ompi/debuggers/ompi_common_dll.c``).  Weak: registration must not
#: keep freed communicators alive.
_live_comms: "weakref.WeakSet" = None  # initialized lazily below


def _register_live(comm) -> None:
    global _live_comms
    import weakref

    if _live_comms is None:
        _live_comms = weakref.WeakSet()
    _live_comms.add(comm)


def live_comms() -> list:
    """Snapshot of live communicators (debugger support)."""
    return sorted(_live_comms or [], key=lambda c: (c.cid, c.epoch))


#: per-(members, tag) invocation counters for the sessions-model CID
#: bootstrap: create_from_group is collective over the group, so every
#: member's N-th call with the same (members, tag) pairs up — the count
#: keys successive agreements apart without any pre-existing channel
_group_cid_seq: dict = {}
_group_cid_lock = threading.Lock()


def _agree_group_cid(client, group, tag: str) -> int:
    """Coord-assisted CID agreement for parent-less construction: first
    member through publishes a bridge-range CID (globally unique, so no
    per-member freeness confirmation is needed) via atomic
    put-if-absent; every member adopts the winner."""
    base = (tuple(group.world_ranks), str(tag))
    with _group_cid_lock:
        seq = _group_cid_seq.get(base, 0)
        _group_cid_seq[base] = seq + 1
    from ompi_tpu import dpm

    proposed = dpm._new_bridge_cid(client)
    key = f"__group_cid__:{base!r}:{seq}"
    return int(client.put_new(-1, key, proposed))


class Comm(AttributeHost):
    _cid_lock = threading.Lock()

    def __init__(
        self,
        group: Group,
        cid: int,
        rte,
        name: str = "",
        epoch: int = 0,
        parent: Optional["Comm"] = None,
        remote_group: Optional[Group] = None,
    ) -> None:
        self.group = group
        self.cid = cid
        self.epoch = epoch  # FT epoch: revoked CIDs can't be confused on reuse
        self.rte = rte
        self.name = name or f"comm#{cid}"
        self.c_coll: dict[str, Any] = {}
        self.coll_modules: list = []
        self.errhandler: Errhandler = ERRORS_ARE_FATAL
        self.info = Info()
        self.topo = None          # set by topo framework (cart/graph/dist_graph)
        self.revoked = False
        self.freed = False
        self.remote_group = remote_group  # inter-communicator remote side
        self.pml = None           # selected pml module (set at selection time)
        self._rev_key = None      # lazy (ft_scope, cid, epoch) probe key
        self._rank = group.rank_of(rte.my_world_rank) if rte else 0
        if parent is not None:
            self.errhandler = parent.errhandler
        _register_live(self)

    # -- accessors -------------------------------------------------------
    @property
    def rank(self) -> int:
        return self._rank

    @property
    def size(self) -> int:
        return self.group.size

    @property
    def is_inter(self) -> bool:
        return self.remote_group is not None

    @property
    def remote_size(self) -> int:
        return self.remote_group.size if self.remote_group else 0

    def world_rank(self, rank: int) -> int:
        return self.group.world_rank(rank)

    def as_rank(self, rank: int) -> "Comm":
        """Conductor-model facade: this communicator acting as ``rank``.

        In the device-world (single-controller) model the one process hosts
        every rank; p2p issued through ``as_rank(i)`` carries i as the
        source — the in-process analog of ``mpirun --oversubscribe`` rank
        multiplexing.  Shares all communicator state with self.
        """
        import copy

        if not 0 <= rank < self.size:
            raise MpiError(ErrorClass.ERR_RANK, f"invalid rank {rank}")
        view = copy.copy(self)
        view._rank = rank
        return view

    def get_name(self) -> str:
        return self.name

    def set_name(self, name: str) -> None:
        self.name = name

    def set_errhandler(self, eh: Errhandler) -> None:
        self.errhandler = eh

    def get_errhandler(self) -> Errhandler:
        return self.errhandler

    def call_errhandler(self, errorcode) -> None:
        """``MPI_Comm_call_errhandler`` (fatal default handler aborts,
        ERRORS_RETURN raises the MpiError to the caller)."""
        try:
            cls = ErrorClass(int(errorcode))
        except ValueError:
            cls = ErrorClass.ERR_OTHER
        self._err(MpiError(cls, f"user-raised code {int(errorcode)}"))

    def set_info(self, info: Info) -> None:
        """``MPI_Comm_set_info``: replace the comm's info hints."""
        self.info = info.dup()

    def get_info(self) -> Info:
        """``MPI_Comm_get_info``."""
        return self.info.dup()

    def _check_state(self, peer: Optional[int] = None) -> None:
        # NOTE: allreduce_array inlines the peer=None predicate
        # (freed + is_revoked) on its fast path — mirror any new
        # comm-wide check added here into that method too
        if self.freed:
            raise MpiError(ErrorClass.ERR_COMM, "communicator was freed")
        if self.is_revoked():
            self._err(RevokedError(f"{self.name} revoked"))
        if peer is not None and peer not in (ANY_SOURCE, PROC_NULL):
            if not 0 <= peer < (self.remote_size if self.is_inter else self.size):
                raise MpiError(ErrorClass.ERR_RANK, f"invalid rank {peer}")
            # ULFM early liveness check (send.c:84); an intercomm peer
            # rank indexes the remote group
            from ompi_tpu.ft import state as ft_state

            peer_world = (self.remote_group if self.is_inter
                          else self.group).world_rank(peer)
            if ft_state.is_failed(peer_world):
                from ompi_tpu.api.errors import ProcFailedError

                self._err(ProcFailedError(
                    f"peer {peer} has failed", (peer,)))

    def _err(self, error: MpiError) -> None:
        self.errhandler.invoke(self, error)
        raise error  # ERRORS_RETURN handler already raised; fatal aborts

    # -- coll dispatch ---------------------------------------------------
    def _coll(self, name: str):
        fn = self.c_coll.get(name)
        if fn is None:
            raise MpiError(
                ErrorClass.ERR_UNSUPPORTED_OPERATION,
                f"no coll component provides '{name}' on {self.name}")
        return fn

    # blocking host collectives (numpy buffers) -------------------------
    def barrier(self) -> None:
        self._check_state()
        self._coll("barrier")(self)

    def bcast(self, buf, root: int = 0):
        self._check_state()
        return self._coll("bcast")(self, buf, root)

    def reduce(self, sendbuf, op: op_mod.Op = op_mod.SUM, root: int = 0):
        self._check_state()
        return self._coll("reduce")(self, sendbuf, op, root)

    def allreduce(self, sendbuf, op: op_mod.Op = op_mod.SUM):
        self._check_state()
        return self._coll("allreduce")(self, sendbuf, op)

    def gather(self, sendbuf, root: int = 0):
        self._check_state()
        return self._coll("gather")(self, sendbuf, root)

    def gatherv(self, sendbuf, root: int = 0):
        self._check_state()
        return self._coll("gatherv")(self, sendbuf, root)

    def scatter(self, sendbuf, root: int = 0):
        self._check_state()
        return self._coll("scatter")(self, sendbuf, root)

    def scatterv(self, sendbufs, root: int = 0):
        self._check_state()
        return self._coll("scatterv")(self, sendbufs, root)

    def allgather(self, sendbuf):
        self._check_state()
        return self._coll("allgather")(self, sendbuf)

    def allgatherv(self, sendbuf):
        self._check_state()
        return self._coll("allgatherv")(self, sendbuf)

    def alltoall(self, sendbuf):
        self._check_state()
        return self._coll("alltoall")(self, sendbuf)

    def alltoallv(self, sendbufs):
        """``MPI_Alltoallv``: ``sendbufs[r]`` goes to rank r; returns a
        list where entry r is rank r's block, typed as
        ``sendbufs[r].dtype`` (symmetric exchanges — use ``alltoallw``
        with ``recvtypes`` when pairs exchange different dtypes)."""
        self._check_state()
        return self._coll("alltoallv")(self, sendbufs)

    def alltoallw(self, sendbufs, recvtypes=None):
        """``MPI_Alltoallw``: per-peer buffers and per-peer datatypes
        (recvtypes: numpy dtype per source rank)."""
        self._check_state()
        return self._coll("alltoallw")(self, sendbufs, recvtypes)

    def reduce_scatter(self, sendbuf, recvcounts=None,
                       op: op_mod.Op = op_mod.SUM):
        self._check_state()
        return self._coll("reduce_scatter")(self, sendbuf, recvcounts, op)

    def reduce_scatter_block(self, sendbuf, op: op_mod.Op = op_mod.SUM):
        """``MPI_Reduce_scatter_block``: equal-sized blocks — sendbuf has
        size*blockcount elements, each rank receives its reduced block."""
        self._check_state()
        arr = np.asarray(sendbuf)
        lead = arr.shape[-1] if arr.ndim else arr.size
        n = self.size
        if lead % n:
            raise MpiError(
                ErrorClass.ERR_BUFFER,
                f"reduce_scatter_block needs length divisible by {n}, "
                f"got {lead}")
        out = self._coll("reduce_scatter")(self, sendbuf,
                                           [lead // n] * n, op)
        if (isinstance(out, list) and len(out) == n
                and self.rte is not None and self.rte.is_device_world):
            return np.stack(out)   # single-controller: the whole table
        return out                  # multiprocess: my block

    def scan(self, sendbuf, op: op_mod.Op = op_mod.SUM):
        self._check_state()
        return self._coll("scan")(self, sendbuf, op)

    def exscan(self, sendbuf, op: op_mod.Op = op_mod.SUM):
        self._check_state()
        return self._coll("exscan")(self, sendbuf, op)

    # nonblocking variants ----------------------------------------------
    def ibarrier(self) -> Request:
        self._check_state()
        return self._coll("ibarrier")(self)

    def ibcast(self, buf, root: int = 0) -> Request:
        self._check_state()
        return self._coll("ibcast")(self, buf, root)

    def iallreduce(self, sendbuf, op: op_mod.Op = op_mod.SUM) -> Request:
        self._check_state()
        return self._coll("iallreduce")(self, sendbuf, op)

    def iallgather(self, sendbuf) -> Request:
        self._check_state()
        return self._coll("iallgather")(self, sendbuf)

    def ialltoall(self, sendbuf) -> Request:
        self._check_state()
        return self._coll("ialltoall")(self, sendbuf)

    def ireduce(self, sendbuf, op: op_mod.Op = op_mod.SUM,
                root: int = 0) -> Request:
        self._check_state()
        return self._coll("ireduce")(self, sendbuf, op, root)

    def _icompleted(self, fn, *args) -> Request:
        """Eager "nonblocking" form for slots without an overlapped
        schedule: runs the collective NOW and returns a born-complete
        request.  LIMITATION vs MPI locality: the call blocks until the
        collective finishes, so a program that interleaves one of these
        with dependent point-to-point before waiting can deadlock where
        a true nonblocking implementation would not (libnbc-backed slots
        — iallreduce/ibcast/iscan/... — do overlap properly)."""
        self._check_state()
        r = CompletedRequest()
        r.result = fn(*args)
        return r

    def _icoll(self, name: str, blocking, *args) -> Request:
        """Route to a module-provided overlapped schedule (libnbc) when
        one filled the slot; eager completed-request form otherwise."""
        fn = self.c_coll.get(name)
        if fn is not None:
            self._check_state()
            return fn(self, *args)
        return self._icompleted(blocking, *args)

    def iscan(self, sendbuf, op: op_mod.Op = op_mod.SUM) -> Request:
        return self._icoll("iscan", self.scan, sendbuf, op)

    def iexscan(self, sendbuf, op: op_mod.Op = op_mod.SUM) -> Request:
        return self._icoll("iexscan", self.exscan, sendbuf, op)

    def igather(self, sendbuf, root: int = 0) -> Request:
        return self._icoll("igather", self.gather, sendbuf, root)

    def igatherv(self, sendbuf, root: int = 0) -> Request:
        return self._icompleted(self.gatherv, sendbuf, root)

    def iscatter(self, sendbuf, root: int = 0) -> Request:
        return self._icoll("iscatter", self.scatter, sendbuf, root)

    def iscatterv(self, sendbufs, root: int = 0) -> Request:
        return self._icompleted(self.scatterv, sendbufs, root)

    def iallgatherv(self, sendbuf) -> Request:
        return self._icompleted(self.allgatherv, sendbuf)

    def ialltoallv(self, sendbufs) -> Request:
        return self._icompleted(self.alltoallv, sendbufs)

    def ialltoallw(self, sendbufs, recvtypes=None) -> Request:
        return self._icompleted(self.alltoallw, sendbufs, recvtypes)

    def ireduce_scatter(self, sendbuf, recvcounts=None,
                        op: op_mod.Op = op_mod.SUM) -> Request:
        return self._icoll("ireduce_scatter", self.reduce_scatter,
                           sendbuf, recvcounts, op)

    def ireduce_scatter_block(self, sendbuf,
                              op: op_mod.Op = op_mod.SUM) -> Request:
        return self._icompleted(self.reduce_scatter_block, sendbuf, op)

    def ineighbor_allgather(self, sendbuf) -> Request:
        return self._icompleted(self.neighbor_allgather, sendbuf)

    def ineighbor_allgatherv(self, sendbuf) -> Request:
        return self._icompleted(self.neighbor_allgatherv, sendbuf)

    def ineighbor_alltoall(self, sendbufs) -> Request:
        return self._icompleted(self.neighbor_alltoall, sendbufs)

    def ineighbor_alltoallv(self, sendbufs) -> Request:
        return self._icompleted(self.neighbor_alltoallv, sendbufs)

    def ineighbor_alltoallw(self, sendbufs, recvtypes=None) -> Request:
        return self._icompleted(self.neighbor_alltoallw, sendbufs,
                                recvtypes)

    # device-array collectives (jax.Array over the ICI mesh) ------------
    def allreduce_array(self, x, op: op_mod.Op = op_mod.SUM):
        # THE hot call of the framework (DP gradient sync): inline the
        # state check and skip the _coll indirection — one dict probe on
        # the per-comm vtable, then straight into the module fast path
        if self.freed or self.is_revoked():
            self._check_state()
        fn = self.c_coll.get("allreduce_array")
        if fn is None:
            return self._coll("allreduce_array")(self, x, op)  # raise path
        return fn(self, x, op)

    def bcast_array(self, x, root: int = 0):
        self._check_state()
        return self._coll("bcast_array")(self, x, root)

    def allgather_array(self, x):
        self._check_state()
        return self._coll("allgather_array")(self, x)

    def reduce_scatter_array(self, x, op: op_mod.Op = op_mod.SUM):
        self._check_state()
        return self._coll("reduce_scatter_array")(self, x, op)

    def reduce_array(self, x, op: op_mod.Op = op_mod.SUM, root: int = 0):
        self._check_state()
        return self._coll("reduce_array")(self, x, op, root)

    def gather_array(self, x, root: int = 0):
        self._check_state()
        return self._coll("gather_array")(self, x, root)

    def scatter_array(self, x, root: int = 0):
        self._check_state()
        return self._coll("scatter_array")(self, x, root)

    def allgatherv_array(self, x, counts):
        self._check_state()
        return self._coll("allgatherv_array")(self, x, counts)

    def alltoallv_array(self, x, counts):
        self._check_state()
        return self._coll("alltoallv_array")(self, x, counts)

    def scan_array(self, x, op: op_mod.Op = op_mod.SUM):
        self._check_state()
        return self._coll("scan_array")(self, x, op)

    def exscan_array(self, x, op: op_mod.Op = op_mod.SUM):
        self._check_state()
        return self._coll("exscan_array")(self, x, op)

    #: blocking collectives coll_init may bind (MPI_*_init set)
    _PCOLL_NAMES = frozenset({
        "barrier", "bcast", "reduce", "allreduce", "gather", "gatherv",
        "scatter", "scatterv", "allgather", "allgatherv", "alltoall",
        "alltoallv", "alltoallw", "reduce_scatter",
        "reduce_scatter_block", "scan", "exscan"})

    def coll_init(self, coll: str, template=None, *args):
        """Persistent collective (MPI_Allreduce_init & friends, MPI-4 /
        the reference's mpiext/pcollreq): ONE interface on every path —
        a restartable request (``start()``/``wait()``/``.result``).  On
        the device path each start() re-dispatches the pre-compiled
        program bound at init; on host paths it re-runs the selected
        algorithm (schedule reuse, which is what pcollreq provides).
        ``template=None`` binds zero-argument collectives (barrier).
        For the bare callable compiled-program handle on device arrays,
        use ``allreduce_array_init``."""
        self._check_state()
        from ompi_tpu.api.request import PersistentP2P

        fn = self.c_coll.get("persistent_coll")
        if fn is not None and template is not None:
            handle = fn(self, coll, template, *args)

            def _start_dev():
                r = CompletedRequest()
                r.result = handle(template)
                return r

            return PersistentP2P(_start_dev)
        if coll not in self._PCOLL_NAMES:
            raise MpiError(ErrorClass.ERR_UNSUPPORTED_OPERATION,
                           f"no persistent binding for '{coll}'")
        blocking = getattr(self, coll)
        call_args = () if template is None and not args \
            else (template, *args)

        def _start():
            r = CompletedRequest()
            r.result = blocking(*call_args)
            return r

        return PersistentP2P(_start)

    def allreduce_array_init(self, template, op: op_mod.Op = op_mod.SUM):
        """Low-level persistent DEVICE collective: the bound compiled
        program as a bare callable handle (``h(x)`` = one SPC bump + the
        XLA dispatch).  ``coll_init`` wraps the same binding in the
        uniform MPI request interface."""
        fn = self.c_coll.get("persistent_coll")
        if fn is None:
            raise MpiError(ErrorClass.ERR_UNSUPPORTED_OPERATION,
                           "no device persistent-collective provider on "
                           f"{self.name}; use coll_init for the host "
                           "persistent request form")
        return fn(self, "allreduce", template, op)

    def alltoall_array(self, x):
        self._check_state()
        return self._coll("alltoall_array")(self, x)

    def ppermute_array(self, x, perm: Sequence[tuple]):
        self._check_state()
        return self._coll("ppermute_array")(self, x, perm)

    # -- p2p dispatch (→ selected pml, like MCA_PML_CALL) ---------------
    def send(self, buf, dest: int, tag: int = 0) -> None:
        self._check_state(dest)
        if dest == PROC_NULL:
            return
        self.pml.send(self, buf, dest, tag)

    def recv(self, buf, source: int = ANY_SOURCE, tag: int = ANY_TAG) -> Status:
        self._check_state(source)
        if source == PROC_NULL:
            return Status(source=PROC_NULL, tag=ANY_TAG)
        return self.pml.recv(self, buf, source, tag)

    def isend(self, buf, dest: int, tag: int = 0) -> Request:
        self._check_state(dest)
        if dest == PROC_NULL:
            return CompletedRequest()
        return self.pml.isend(self, buf, dest, tag)

    def irecv(self, buf, source: int = ANY_SOURCE, tag: int = ANY_TAG) -> Request:
        self._check_state(source)
        if source == PROC_NULL:
            return CompletedRequest(Status(source=PROC_NULL, tag=ANY_TAG))
        return self.pml.irecv(self, buf, source, tag)

    def ssend(self, buf, dest: int, tag: int = 0) -> None:
        """``MPI_Ssend``: returns only after the receiver matched."""
        self.issend(buf, dest, tag).wait()

    def issend(self, buf, dest: int, tag: int = 0) -> Request:
        self._check_state(dest)
        if dest == PROC_NULL:
            return CompletedRequest()
        return self.pml.isend(self, buf, dest, tag, sync=True)

    def rsend(self, buf, dest: int, tag: int = 0) -> None:
        """``MPI_Rsend``: the caller asserts the recv is posted; with a
        posted recv it behaves exactly like send (MPI guarantees nothing
        extra), so it shares the standard path like pml/ob1 does."""
        self.send(buf, dest, tag)

    def irsend(self, buf, dest: int, tag: int = 0) -> Request:
        return self.isend(buf, dest, tag)

    def bsend(self, buf, dest: int, tag: int = 0) -> None:
        """``MPI_Bsend``: copies into the attached buffer space and
        returns immediately; the user's buffer is reusable on return."""
        self.ibsend(buf, dest, tag)   # ibsend is already locally complete

    def ibsend(self, buf, dest: int, tag: int = 0) -> Request:
        from ompi_tpu.api import buffer as _bsend

        self._check_state(dest)
        if dest == PROC_NULL:
            return CompletedRequest()
        arr = np.ascontiguousarray(buf)
        _bsend.claim(arr.nbytes)
        try:
            inner = self.pml.isend(self, arr.copy(), dest, tag)
        except Exception:
            _bsend.release(arr.nbytes)   # claim must not leak
            raise
        _bsend.track(inner, arr.nbytes)
        # buffered semantics: the returned request is LOCALLY complete —
        # the message lives in the (conceptual) attach buffer; only
        # Buffer_detach waits for the real delivery.  A rendezvous-size
        # inner request must not leak to the caller or bsend-then-wait-
        # then-recv pairs would deadlock (the pattern Bsend exists for).
        return CompletedRequest()

    # -- persistent point-to-point (``MPI_Send_init``/``Recv_init``) ----
    def send_init(self, buf, dest: int, tag: int = 0) -> Request:
        from ompi_tpu.api.request import CompletedRequest as _CR, \
            PersistentP2P

        self._check_state(dest)
        if dest == PROC_NULL:
            return PersistentP2P(lambda: _CR())
        return PersistentP2P(lambda: self.pml.isend(self, buf, dest, tag))

    def ssend_init(self, buf, dest: int, tag: int = 0) -> Request:
        from ompi_tpu.api.request import CompletedRequest as _CR, \
            PersistentP2P

        self._check_state(dest)
        if dest == PROC_NULL:
            return PersistentP2P(lambda: _CR())
        return PersistentP2P(
            lambda: self.pml.isend(self, buf, dest, tag, sync=True))

    def bsend_init(self, buf, dest: int, tag: int = 0) -> Request:
        """``MPI_Bsend_init``: persistent buffered-mode send — every
        start() claims attach-buffer space and completes locally."""
        from ompi_tpu.api.request import PersistentP2P

        self._check_state(dest)
        return PersistentP2P(lambda: self.ibsend(buf, dest, tag))

    def rsend_init(self, buf, dest: int, tag: int = 0) -> Request:
        """``MPI_Rsend_init``: ready mode shares the standard path (with
        a posted recv they are identical, like pml/ob1)."""
        return self.send_init(buf, dest, tag)

    def recv_init(self, buf, source: int = ANY_SOURCE,
                  tag: int = ANY_TAG) -> Request:
        from ompi_tpu.api.request import CompletedRequest as _CR, \
            PersistentP2P

        self._check_state(source)
        if source == PROC_NULL:
            return PersistentP2P(
                lambda: _CR(Status(source=PROC_NULL, tag=ANY_TAG)))
        return PersistentP2P(lambda: self.pml.irecv(self, buf, source, tag))

    # -- partitioned point-to-point (MPI-4 ``MPI_Psend_init`` family) ----
    def psend_init(self, buf, partitions: int, dest: int,
                   tag: int = 0) -> Request:
        """``MPI_Psend_init``: a partitioned persistent send.  After
        ``start()``, each of the ``partitions`` equal slices of ``buf``
        is released for transfer by ``req.pready(p)`` (or
        ``pready_range``/``pready_list``); the request completes once
        every partition was readied and sent.  Ready runs are aggregated
        onto fewer wire messages under the
        ``otpu_part_persist_min_partitions`` var (``mca/part/persist``).
        """
        from ompi_tpu.mca.part import part_module

        self._check_state(dest)
        return part_module().psend_init(self, buf, partitions, dest, tag)

    def precv_init(self, buf, partitions: int, source: int,
                   tag: int = 0) -> Request:
        """``MPI_Precv_init``: the receive side of a partitioned pairing.
        ``req.parrived(p)`` reports per-partition arrival — exact even
        when the sender used a different partition count (byte-framed
        wire protocol).  Wildcards are not supported (MPI-4)."""
        from ompi_tpu.mca.part import part_module

        self._check_state(source)
        return part_module().precv_init(self, buf, partitions, source, tag)

    def pallreduce_init(self, buckets, op: op_mod.Op = op_mod.SUM) -> Request:
        """Partitioned persistent allreduce (the ``MPI_Pallreduce_init``
        analog of MPI-4's partitioned model applied to a collective):
        each entry of ``buckets`` is bound once as its own persistent
        allreduce; ``req.pready(i)`` releases bucket i — on the device
        path that is one pre-compiled XLA dispatch, so bucket i's
        reduction overlaps the computation still producing bucket i+1
        (bucketed gradient overlap).  ``req.parrived(i)`` tests bucket
        completion; after all preadys the request is complete and
        ``req.result[i]`` holds bucket i's reduction.  On host comms
        without a device binding each pready runs the blocking
        allreduce (every rank must pready in the same order)."""
        self._check_state()
        from ompi_tpu.mca.part.pcoll import PartitionedCollRequest

        fn = self.c_coll.get("partitioned_coll")
        handles = fn(self, "allreduce", buckets, op) \
            if fn is not None else None
        return PartitionedCollRequest(self, "allreduce", buckets, (op,),
                                      handles)

    def sendrecv_replace(self, buf, dest: int, source: int = ANY_SOURCE,
                         sendtag: int = 0, recvtag: int = ANY_TAG) -> Status:
        """``MPI_Sendrecv_replace``: the received message overwrites the
        sent buffer (staged through a copy, like the reference).  ``buf``
        must be a writable ndarray — replacement into a list/tuple would
        be silently lost."""
        if not isinstance(buf, np.ndarray) or not buf.flags.writeable:
            raise MpiError(ErrorClass.ERR_BUFFER,
                           "sendrecv_replace needs a writable ndarray")
        arr = np.ascontiguousarray(buf)
        st = self.sendrecv(arr.copy(), dest, arr, source, sendtag, recvtag)
        if buf is not arr:
            np.copyto(buf, arr)
        return st

    def sendrecv(self, sendbuf, dest: int, recvbuf, source: int = ANY_SOURCE,
                 sendtag: int = 0, recvtag: int = ANY_TAG) -> Status:
        self._check_state(dest)
        sreq = self.isend(sendbuf, dest, sendtag) if dest != PROC_NULL else None
        st = self.recv(recvbuf, source, recvtag)
        if sreq is not None:
            sreq.wait()
        return st

    def probe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG) -> Status:
        self._check_state(source)
        return self.pml.probe(self, source, tag, blocking=True)

    def iprobe(self, source: int = ANY_SOURCE,
               tag: int = ANY_TAG) -> tuple[bool, Optional[Status]]:
        self._check_state(source)
        return self.pml.probe(self, source, tag, blocking=False)

    def mprobe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG):
        self._check_state(source)
        return self.pml.mprobe(self, source, tag, blocking=True)

    def improbe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG):
        self._check_state(source)
        return self.pml.mprobe(self, source, tag, blocking=False)

    def send_obj(self, obj: Any, dest: int, tag: int = 0) -> None:
        from ompi_tpu.api.request import waitall

        waitall(self.isend_obj(obj, dest, tag))

    def isend_obj(self, obj: Any, dest: int, tag: int = 0) -> list:
        """Nonblocking ``send_obj``: returns the requests to waitall.

        The payload buffer is referenced by the returned requests, so the
        caller only needs to keep the request list alive.
        """
        import pickle

        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        hdr = np.array([payload.size], dtype=np.int64)
        return [self.isend(hdr, dest, tag), self.isend(payload, dest, tag)]

    def bcast_obj(self, obj: Any = None, root: int = 0) -> Any:
        """Broadcast an arbitrary picklable object (size agreed first)."""
        import pickle

        if self.rank == root:
            payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
            self.bcast(np.array([payload.size], np.int64), root=root)
            self.bcast(payload, root=root)
            return obj
        hdr = np.asarray(self.bcast(np.zeros(1, np.int64), root=root))
        payload = np.asarray(self.bcast(
            np.zeros(int(hdr[0]), np.uint8), root=root))
        return pickle.loads(payload.tobytes())

    def recv_obj(self, source: int = ANY_SOURCE, tag: int = ANY_TAG) -> Any:
        import pickle

        hdr = np.zeros(1, dtype=np.int64)
        st = self.recv(hdr, source, tag)
        payload = np.zeros(int(hdr[0]), dtype=np.uint8)
        self.recv(payload, st.source, tag)
        return pickle.loads(payload.tobytes())

    # -- management ------------------------------------------------------
    def _next_cid(self) -> int:
        """Agree on the next free CID across members (``comm_cid.c:53``).

        Multi-round like the reference: each member proposes its first
        locally-free id (unreserved), the group takes the MAX, then a
        second allreduce confirms the winner is free on *every* member
        (it may not be: group-scoped create_group allocations make
        bitmaps diverge).  On conflict, re-propose above the loser.
        """
        from ompi_tpu.runtime import init as rt

        if self.rte is not None and self.rte.is_device_world:
            # single process backs every co-located rank: one bitmap,
            # local find-and-set IS the agreement
            return rt.next_local_cid()
        floor = 0
        while True:
            local = rt.candidate_cid(floor)
            agreed = int(np.asarray(self.allreduce(
                np.array([local], dtype=np.int64), op_mod.MAX)).ravel()[0])
            ok = 1 if rt.is_cid_free(agreed) else 0
            all_ok = int(np.asarray(self.allreduce(
                np.array([ok], dtype=np.int64), op_mod.MIN)).ravel()[0])
            if all_ok:
                rt.reserve_cid(agreed)
                return agreed
            floor = agreed + 1

    # -- sessions-model construction (MPI-4, ``ompi/communicator``
    # ``ompi_comm_create_from_group`` / ``ompi_intercomm_create_from_groups``)
    @classmethod
    def create_from_group(cls, group: Group, tag: str = "",
                          info: Optional[Info] = None,
                          errhandler=None, name: str = "") -> Optional["Comm"]:
        """``MPI_Comm_create_from_group``: a communicator from a bare
        group — NO parent communicator, NO MPI_Init required; the active
        instance (opened by a Session or by world init) supplies the pml
        and the CID machinery.  Collective over the group's members;
        ``tag`` disambiguates concurrent creations from overlapping
        groups (MPI-4's string tag).

        CID path: the classic agreement needs a communicator to run
        over, which is exactly what doesn't exist yet — the reference
        solves the bootstrap with a PMIx-assisted exchange; here the
        coord service plays PMIx: the first member through publishes a
        CID drawn from the globally-unique bridge range under an
        atomic put-if-absent keyed by (members, tag, invocation), and
        everyone adopts the winner.  Single-process instances (device
        world / singleton) allocate locally.
        """
        from ompi_tpu import instance as inst_mod
        from ompi_tpu.runtime import init as rt

        inst = inst_mod.current()
        if inst is None:
            raise MpiError(
                ErrorClass.ERR_SESSION,
                "no active instance: open a Session (Session.init) or "
                "call init() before create_from_group")
        rte = inst.rte
        if not rte.is_device_world and \
                group.rank_of(rte.my_world_rank) < 0:
            return None   # not a member (the conductor hosts every rank)
        client = getattr(rte, "client", None)
        if client is None or rte.is_device_world:
            cid = rt.next_local_cid()
        else:
            cid = _agree_group_cid(client, group, tag)
            rt.reserve_cid(cid)
        newcomm = cls(group, cid, rte,
                      name=name or f"from_group~{tag or cid}")
        if info is not None:
            newcomm.info = info.dup()
        if errhandler is not None:
            newcomm.errhandler = errhandler
        cls._wire_new_comm(newcomm, inst.pml)
        return newcomm

    @classmethod
    def create_intercomm_from_groups(cls, local_group: Group,
                                     local_leader: int,
                                     remote_group: Group,
                                     remote_leader: int, tag: str = "",
                                     info: Optional[Info] = None,
                                     errhandler=None) -> Optional["Comm"]:
        """``MPI_Intercomm_create_from_groups``: an intercommunicator
        from two disjoint groups with no parent and no bridge comm.
        The local intracomm (the collective channel every intercomm
        carries) is built first via :meth:`create_from_group`; the
        bridge CID is agreed through the coord service under a key both
        sides derive identically from the UNION of the groups + tag."""
        from ompi_tpu import instance as inst_mod
        from ompi_tpu.runtime import init as rt

        inst = inst_mod.current()
        if inst is None:
            raise MpiError(
                ErrorClass.ERR_SESSION,
                "no active instance: open a Session (Session.init) or "
                "call init() before create_intercomm_from_groups")
        rte = inst.rte
        overlap = set(local_group.world_ranks) & \
            set(remote_group.world_ranks)
        if overlap:
            raise MpiError(ErrorClass.ERR_GROUP,
                           f"groups overlap on ranks {sorted(overlap)}")
        local = cls.create_from_group(local_group, tag=f"{tag}//local",
                                      info=info)
        if local is None:
            return None
        client = getattr(rte, "client", None)
        if client is None or rte.is_device_world:
            cid = rt.next_local_cid()
        else:
            union = Group(sorted(set(local_group.world_ranks)
                                 | set(remote_group.world_ranks)))
            cid = _agree_group_cid(client, union, f"{tag}//inter")
            rt.reserve_cid(cid)
        inter = cls(local_group, cid, rte,
                    name=f"from_groups~{tag or cid}",
                    remote_group=remote_group)
        if errhandler is not None:
            inter.errhandler = errhandler
        inter.local_comm = local
        local._finish_create(inter)
        return inter

    # comm_compare results (``mpi.h`` MPI_IDENT family)
    IDENT = 0
    CONGRUENT = 1
    SIMILAR = 2
    UNEQUAL = 3

    def dup(self) -> "Comm":
        self._check_state()
        newcomm = Comm(self.group, self._next_cid(), self.rte,
                       name=f"{self.name}~dup", epoch=self.epoch, parent=self)
        self._attrs_copy_to(newcomm)
        newcomm.info = self.info.dup()
        self._finish_create(newcomm)
        return newcomm

    def idup(self) -> tuple["Comm", Request]:
        """``MPI_Comm_idup``: the dup itself is collective-synchronous
        here (CID agreement), so the request is born complete."""
        newcomm = self.dup()
        req = CompletedRequest()
        req.result = newcomm
        return newcomm, req

    def dup_with_info(self, info: Info) -> "Comm":
        """``MPI_Comm_dup_with_info``: dup, with the new comm's hints
        REPLACED by ``info`` instead of inherited."""
        newcomm = self.dup()
        newcomm.info = info.dup()
        return newcomm

    def compare(self, other: "Comm") -> int:
        """``MPI_Comm_compare``: IDENT (same object), CONGRUENT (same
        group(s) + order, different context), SIMILAR (same members,
        other order), UNEQUAL.  Intercomms compare local AND remote
        groups; an intercomm never matches an intracomm."""
        if self is other:
            return Comm.IDENT
        if self.is_inter != other.is_inter:
            return Comm.UNEQUAL
        mine = list(self.group.world_ranks)
        theirs = list(other.group.world_ranks)
        if self.is_inter:
            rm = list(self.remote_group.world_ranks)
            rt = list(other.remote_group.world_ranks)
            if mine == theirs and rm == rt:
                return Comm.CONGRUENT
            if sorted(mine) == sorted(theirs) and sorted(rm) == sorted(rt):
                return Comm.SIMILAR
            return Comm.UNEQUAL
        if mine == theirs:
            return Comm.CONGRUENT
        if sorted(mine) == sorted(theirs):
            return Comm.SIMILAR
        return Comm.UNEQUAL

    def split(self, color, key=0) -> Optional["Comm"]:
        """``MPI_Comm_split``.

        Multi-process model: each rank passes its (color, key); the table is
        exchanged with an allgather over the parent.  Device-world
        (conductor) model: color/key may be scalars or (size,) arrays of
        per-rank values; the table is local.  Returns the subcommunicator
        containing this (facade) rank, or None for color < 0 (UNDEFINED).
        """
        self._check_state()
        if self.rte is not None and self.rte.is_device_world:
            colors = np.broadcast_to(np.asarray(color, np.int64), (self.size,))
            keys = np.broadcast_to(np.asarray(key, np.int64), (self.size,))
            table = np.stack([colors, keys,
                              np.arange(self.size, dtype=np.int64)], 1)
        else:
            mine = np.array([color, key, self.rank], dtype=np.int64)
            table = np.asarray(self.allgather(mine)).reshape(self.size, 3)
        # one CID per distinct non-negative color, allocated in sorted order
        # so every member observes the same assignment (comm_cid.c agreement)
        distinct = sorted({int(c) for c, _, _ in table if c >= 0})
        cids = {c: self._next_cid() for c in distinct}
        my_color = int(table[self.rank, 0])
        if my_color < 0:  # MPI_UNDEFINED
            return None
        members = sorted((int(k), int(r)) for c, k, r in table
                         if c == my_color)
        ranks = [self.group.world_rank(r) for _, r in members]
        newcomm = Comm(Group(ranks), cids[my_color], self.rte,
                       name=f"{self.name}~split", epoch=self.epoch,
                       parent=self)
        self._finish_create(newcomm)
        return newcomm

    def split_type(self, split_type: str = "shared", key: int = 0) -> "Comm":
        """``MPI_Comm_split_type``: 'shared' = same host/ICI domain."""
        color = self.rte.locality_color(split_type)
        return self.split(color, key)

    def create(self, group: Group) -> Optional["Comm"]:
        self._check_state()
        cid = self._next_cid()
        if group.rank_of(self.rte.my_world_rank) < 0:
            return None
        newcomm = Comm(group, cid, self.rte, name=f"{self.name}~create",
                       epoch=self.epoch, parent=self)
        self._finish_create(newcomm)
        return newcomm

    def create_group(self, group: Group, tag: int = 0) -> Optional["Comm"]:
        """Non-collective over the parent: only group members participate.

        The CID must still be agreed across the *group* (a purely local
        allocation can hand members of the same new comm different CIDs),
        so members run the multi-round agreement over parent p2p on a
        reserved tag (the reference's comm_create_group activation uses
        tagged parent traffic the same way).
        """
        if group.rank_of(self.rte.my_world_rank) < 0:
            return None
        from ompi_tpu.runtime import init as rt

        if self.rte is not None and self.rte.is_device_world:
            cid = rt.next_local_cid()
        else:
            cid = self._agree_cid_group(group, tag)
        newcomm = Comm(group, cid, self.rte,
                       name=f"{self.name}~create_group", epoch=self.epoch,
                       parent=self)
        self._finish_create(newcomm)
        return newcomm

    def _agree_cid_group(self, group: Group, tag: int) -> int:
        """Multi-round CID agreement among group members via parent p2p."""
        from ompi_tpu.runtime import init as rt

        members = [self.group.rank_of(w) for w in group.world_ranks]
        leader = members[0]
        t = -(1 << 20) - tag  # reserved internal tag space

        def xchg(value: int, combine) -> int:
            buf = np.array([value], dtype=np.int64)
            if self.rank == leader:
                acc = value
                got = np.zeros(1, dtype=np.int64)
                for m in members[1:]:
                    self.recv(got, m, t)
                    acc = combine(acc, int(got[0]))
                out = np.array([acc], dtype=np.int64)
                for m in members[1:]:
                    self.send(out, m, t)
                return acc
            self.send(buf, leader, t)
            got = np.zeros(1, dtype=np.int64)
            self.recv(got, leader, t)
            return int(got[0])

        floor = 0
        while True:
            agreed = xchg(rt.candidate_cid(floor), max)
            all_ok = xchg(1 if rt.is_cid_free(agreed) else 0, min)
            if all_ok:
                rt.reserve_cid(agreed)
                return agreed
            floor = agreed + 1

    @staticmethod
    def _wire_new_comm(newcomm: "Comm", pml) -> None:
        """The one post-construction wiring sequence every new comm gets
        (parented or sessions-model alike): pml attach + coll selection."""
        from ompi_tpu.mca.coll.base import comm_select

        newcomm.pml = pml
        if pml is not None:
            add = getattr(pml, "add_comm", None)
            if add is not None:
                add(newcomm)
        comm_select(newcomm)

    def _finish_create(self, newcomm: "Comm") -> None:
        Comm._wire_new_comm(newcomm, self.pml)

    def topo_test(self) -> str:
        """``MPI_Topo_test``: "cart" | "graph" | "dist_graph" |
        "undefined"."""
        if self.topo is None:
            return "undefined"
        return self.topo.kind   # every topo class defines it; fail loudly

    # -- process topologies (``ompi/mca/topo``) -------------------------
    def cart_create(self, dims: Sequence[int], periods=None,
                    reorder: bool = False) -> Optional["Comm"]:
        """``MPI_Cart_create``.

        ``reorder=True`` in the device-world model maps the grid onto the
        ICI mesh device order (the treematch hardware-mapping analog) —
        cart neighbors then sit one ICI hop apart.
        """
        from ompi_tpu.mca.topo import CartTopo

        dims = list(dims)
        if periods is None:
            periods = [False] * len(dims)
        grid = int(np.prod(dims)) if dims else 1
        if grid > self.size:
            raise MpiError(ErrorClass.ERR_DIMS,
                           f"grid {dims} larger than comm size {self.size}")
        # ranks beyond the grid are excluded (MPI_COMM_NULL).  reorder=True
        # keeps device order in the conductor model: the device world is
        # built from jax.devices() order, which enumerates the ICI mesh
        # row-major — already matching our row-major cart convention.
        if self.rte is not None and self.rte.is_device_world:
            # conductor split needs the whole color table, not my scalar
            color = np.array([0 if r < grid else -1
                              for r in range(self.size)])
            key = np.arange(self.size)
        else:
            color = 0 if self.rank < grid else -1
            key = self.rank
            if reorder:
                # treematch-style hardware mapping (the reference's
                # topo/treematch, topo_treematch_dist_graph_create.c):
                # order ranks by node so row-major cart neighbors — the
                # highest-traffic pairs in halo patterns — land on the
                # same node wherever possible.  The reorder decision must
                # be COLLECTIVE: a rank with unresolved locality must not
                # fall back alone while its peers reorder (membership of
                # the grid would diverge)
                order = self._node_major_order()
                ok = 1 if order is not None else 0
                from ompi_tpu.api import op as _op

                all_ok = int(np.asarray(self.allreduce(
                    np.array([ok], np.int64), op_mod.MIN)).ravel()[0])
                if all_ok and order is not None:
                    key = order.index(self.rank)
                    color = 0 if key < grid else -1
        sub = self.split(color, key)
        if sub is None:
            return None
        sub.topo = CartTopo(dims, periods)
        sub.name = f"{self.name}~cart"
        return sub

    def cart_map(self, dims: Sequence[int], periods=None) -> int:
        """``MPI_Cart_map``: the rank this process WOULD get in a
        reordered cart over ``dims`` — UNDEFINED when it would be left
        out (``ompi/mpi/c/cart_map.c``; base mapping + the node-major
        treematch ordering cart_create(reorder=True) uses)."""
        from ompi_tpu.api.status import UNDEFINED

        dims = list(dims)
        grid = int(np.prod(dims)) if dims else 1
        if grid > self.size:
            raise MpiError(ErrorClass.ERR_DIMS,
                           f"grid {dims} larger than comm size {self.size}")
        order = self._node_major_order()
        newrank = order.index(self.rank) if order is not None else self.rank
        return newrank if newrank < grid else UNDEFINED

    def graph_map(self, index: Sequence[int], edges: Sequence[int]) -> int:
        """``MPI_Graph_map``: identity-family mapping like the base
        component (``mca/topo/base/topo_base_graph_map.c``)."""
        from ompi_tpu.api.status import UNDEFINED

        nnodes = len(index)
        return self.rank if self.rank < nnodes else UNDEFINED

    def _node_major_order(self) -> Optional[list]:
        """Comm ranks sorted by (node, rank); None if locality unknown."""
        rte = self.rte
        if rte is None:
            return None
        nodes = [rte.node_of(w) for w in self.group.world_ranks]
        if any(n is None for n in nodes):
            return None
        return sorted(range(self.size), key=lambda r: (str(nodes[r]), r))

    def cart_coords(self, rank: Optional[int] = None) -> list:
        self._require_topo("cart")
        return self.topo.coords_of(self.rank if rank is None else rank)

    def cart_rank(self, coords) -> int:
        self._require_topo("cart")
        return self.topo.rank_of(coords)

    def cart_shift(self, direction: int, disp: int = 1) -> tuple:
        self._require_topo("cart")
        return self.topo.shift(self.rank, direction, disp)

    def cart_get(self) -> tuple:
        self._require_topo("cart")
        return (list(self.topo.dims), list(self.topo.periods),
                self.cart_coords())

    def cart_sub(self, remain_dims) -> Optional["Comm"]:
        """``MPI_Cart_sub``: keep the axes where remain_dims is true."""
        self._require_topo("cart")
        from ompi_tpu.mca.topo import CartTopo

        coords = self.cart_coords()
        dropped = tuple(c for c, keep in zip(coords, remain_dims)
                        if not keep)

        # one color per combination of dropped coordinates
        def color_of(rank: int) -> int:
            c0 = 0
            for c, dim, keep in zip(self.topo.coords_of(rank),
                                    self.topo.dims, remain_dims):
                if not keep:
                    c0 = c0 * dim + c
            return c0

        if self.rte is not None and self.rte.is_device_world:
            color = np.array([color_of(r) for r in range(self.size)])
            key = np.arange(self.size)
        else:
            color, key = color_of(self.rank), self.rank
        sub = self.split(color, key)
        if sub is None:
            return None
        sub.topo = CartTopo(
            [d for d, keep in zip(self.topo.dims, remain_dims) if keep],
            [p for p, keep in zip(self.topo.periods, remain_dims) if keep])
        sub.name = f"{self.name}~sub{dropped}"
        return sub

    def graph_create(self, index, edges,
                     reorder: bool = False) -> Optional["Comm"]:
        from ompi_tpu.mca.topo import GraphTopo

        nnodes = len(index)
        if self.rte is not None and self.rte.is_device_world:
            color = np.array([0 if r < nnodes else -1
                              for r in range(self.size)])
            key = np.arange(self.size)
        else:
            color, key = (0 if self.rank < nnodes else -1), self.rank
        sub = self.split(color, key)
        if sub is None:
            return None
        sub.topo = GraphTopo(index, edges)
        sub.name = f"{self.name}~graph"
        return sub

    def dist_graph_create_adjacent(self, sources, destinations,
                                   sourceweights=None, destweights=None,
                                   reorder: bool = False) -> "Comm":
        from ompi_tpu.mca.topo import DistGraphTopo

        sub = self.dup()
        sub.topo = DistGraphTopo(sources, destinations, sourceweights,
                                 destweights)
        sub.name = f"{self.name}~distgraph"
        return sub

    def _require_topo(self, kind: str) -> None:
        if self.topo is None or self.topo.kind != kind:
            raise MpiError(ErrorClass.ERR_TOPOLOGY,
                           f"{self.name} has no {kind} topology")

    # neighbor collectives (``coll_base_neighbor_*``): p2p compositions
    # over the attached topology's (sources, destinations)
    def neighbor_allgather(self, sendbuf) -> list:
        if self.topo is None:
            raise MpiError(ErrorClass.ERR_TOPOLOGY,
                           f"{self.name} has no topology")
        srcs, dsts = self.topo.neighbors(self.rank)
        if self.rte is not None and self.rte.is_device_world:
            # conductor model: leading axis of sendbuf indexes ranks
            table = np.asarray(sendbuf)
            return [None if s == PROC_NULL else np.array(table[s], copy=True)
                    for s in srcs]
        arr = np.ascontiguousarray(sendbuf)
        reqs = [self.isend(arr, d, tag=-3) for d in dsts if d != PROC_NULL]
        out = []
        for s in srcs:
            if s == PROC_NULL:
                out.append(None)
            else:
                buf = np.empty_like(arr)
                self.recv(buf, s, tag=-3)
                out.append(buf)
        waitall(reqs)
        return out

    def neighbor_alltoall(self, sendbufs) -> list:
        if self.topo is None:
            raise MpiError(ErrorClass.ERR_TOPOLOGY,
                           f"{self.name} has no topology")
        srcs, dsts = self.topo.neighbors(self.rank)
        if self.rte is not None and self.rte.is_device_world:
            # conductor model: sendbufs[r][k] is rank r's buffer for its
            # k-th destination.  Pair inbound slots with senders' outbound
            # slots FIFO per (src, dst) channel — the per-source ordering
            # real message passing gives, correct even when a neighbor
            # appears twice (periodic size-2 ring)
            from collections import defaultdict, deque

            chan: dict = defaultdict(deque)
            for r in range(self.size):
                _, r_dsts = self.topo.neighbors(r)
                for k, d in enumerate(r_dsts):
                    if d != PROC_NULL:
                        chan[(r, d)].append(np.asarray(sendbufs[r][k]))
            return [None if s == PROC_NULL
                    else np.array(chan[(s, self.rank)].popleft(), copy=True)
                    for s in srcs]
        if len(sendbufs) != len(dsts):
            raise MpiError(ErrorClass.ERR_ARG,
                           f"need {len(dsts)} send buffers, got "
                           f"{len(sendbufs)}")
        reqs = []
        template = None  # all blocks are same-sized (MPI neighbor semantics)
        for d, buf in zip(dsts, sendbufs):
            if d != PROC_NULL:
                arr = np.ascontiguousarray(buf)
                template = arr
                reqs.append(self.isend(arr, d, tag=-4))
        out = []
        for s in srcs:
            if s == PROC_NULL:
                out.append(None)
            elif template is None:
                raise MpiError(ErrorClass.ERR_ARG,
                               "cannot size receive blocks: no real "
                               "destination buffer to mirror")
            else:
                buf = np.empty_like(template)
                self.recv(buf, s, tag=-4)
                out.append(buf)
        waitall(reqs)
        return out

    # neighbor v/w variants: per-neighbor sizes (and dtypes for w) ride
    # the object channel — FIFO per (src, dst) pair like the fixed-size
    # forms, with the single-controller table model mirrored
    def neighbor_allgatherv(self, sendbuf) -> list:
        self._require_any_topo()
        srcs, dsts = self.topo.neighbors(self.rank)
        if self.rte is not None and self.rte.is_device_world:
            table = sendbuf   # table[r] = rank r's (arbitrary-size) buffer
            return [None if s == PROC_NULL else np.asarray(table[s]).copy()
                    for s in srcs]
        from ompi_tpu.api.request import waitall

        arr = np.ascontiguousarray(sendbuf)
        reqs = [r for d in dsts if d != PROC_NULL
                for r in self.isend_obj(arr, d, tag=-6)]
        out = [None if s == PROC_NULL else self.recv_obj(s, tag=-6)
               for s in srcs]
        waitall(reqs)
        return out

    def neighbor_alltoallv(self, sendbufs) -> list:
        self._require_any_topo()
        srcs, dsts = self.topo.neighbors(self.rank)
        if self.rte is not None and self.rte.is_device_world:
            from collections import defaultdict, deque

            chan: dict = defaultdict(deque)
            for r in range(self.size):
                _, r_dsts = self.topo.neighbors(r)
                for k, d in enumerate(r_dsts):
                    if d != PROC_NULL:
                        chan[(r, d)].append(np.asarray(sendbufs[r][k]))
            return [None if s == PROC_NULL
                    else chan[(s, self.rank)].popleft().copy()
                    for s in srcs]
        if len(sendbufs) != len(dsts):
            raise MpiError(ErrorClass.ERR_ARG,
                           f"need {len(dsts)} send buffers, got "
                           f"{len(sendbufs)}")
        from ompi_tpu.api.request import waitall

        reqs = [r for b, d in zip(sendbufs, dsts) if d != PROC_NULL
                for r in self.isend_obj(np.ascontiguousarray(b), d,
                                        tag=-6)]
        out = [None if s == PROC_NULL else self.recv_obj(s, tag=-6)
               for s in srcs]
        waitall(reqs)
        return out

    def neighbor_alltoallw(self, sendbufs, recvtypes=None) -> list:
        """Per-neighbor buffers AND per-neighbor receive dtypes."""
        out = self.neighbor_alltoallv(sendbufs)
        if recvtypes is None:
            return out
        typed = []
        for j, b in enumerate(out):
            if b is None:
                typed.append(None)
                continue
            rt_ = recvtypes[j] if isinstance(recvtypes, (list, tuple)) \
                else recvtypes
            typed.append(np.ascontiguousarray(b).reshape(-1)
                         .view(np.uint8).view(np.dtype(rt_)))
        return typed

    def _require_any_topo(self) -> None:
        if self.topo is None:
            raise MpiError(ErrorClass.ERR_TOPOLOGY,
                           f"{self.name} has no topology")

    def release_coll_modules(self) -> None:
        """Tear down per-comm coll module state (shared segments etc.).

        Called from free(); also from runtime finalize for WORLD/SELF,
        which the user never frees (ompi_mpi_finalize does the same)."""
        for mod in self.coll_modules:
            close = getattr(mod, "comm_unquery", None)
            if close is not None:
                try:
                    close(self)
                except Exception:
                    pass
        self.coll_modules = []

    def free(self) -> None:
        if self.freed:
            # double-free must not touch a newer communicator's state
            # (release/del_comm are keyed by bare cid)
            return
        self._attrs_delete_all()
        self.release_coll_modules()
        if self.pml is not None:
            del_comm = getattr(self.pml, "del_comm", None)
            if del_comm is not None:
                del_comm(self)
        if self.cid > 1:
            from ompi_tpu.runtime import init as rt

            rt.retire_cid(self.cid)
        self.freed = True

    # -- dynamic process management (``ompi/dpm``) ----------------------
    def spawn(self, command, maxprocs: int, root: int = 0) -> "Comm":
        from ompi_tpu import dpm

        return dpm.spawn(self, command, maxprocs, root)

    def spawn_multiple(self, commands, maxprocs, root: int = 0) -> "Comm":
        from ompi_tpu import dpm

        return dpm.spawn_multiple(self, commands, maxprocs, root)

    def create_intercomm(self, local_leader: int, bridge_comm: "Comm",
                         remote_leader: int, tag: int = 0) -> "Comm":
        """``MPI_Intercomm_create``: join two disjoint intracomms into an
        intercommunicator through leaders that share ``bridge_comm``
        (``ompi/communicator/comm.c`` ``ompi_intercomm_create``).

        Leaders exchange group membership + a proposed CID over the
        bridge (MAX wins), then EVERY member of both groups confirms the
        winner is locally free — per-process CID bitmaps diverge, so the
        multi-round confirm of ``_next_cid``/``create_group`` is needed
        here too; on a conflict both sides re-propose above the loser.
        """
        from ompi_tpu.runtime import init as rt

        self._check_state()
        btag = -(1 << 22) - (int(tag) % (1 << 20))
        remote = None
        floor = 0
        while True:
            if self.rank == local_leader:
                proposed = rt.candidate_cid(floor)
                bridge_comm.send_obj(
                    {"cid": proposed,
                     "ranks": list(self.group.world_ranks)},
                    remote_leader, tag=btag)
                theirs = bridge_comm.recv_obj(remote_leader, tag=btag)
                payload = {"cid": max(int(proposed), int(theirs["cid"])),
                           "remote": theirs["ranks"]}
            else:
                payload = None
            payload = self.bcast_obj(payload, root=local_leader)
            cid = int(payload["cid"])
            remote = payload["remote"]
            ok = 1 if rt.is_cid_free(cid) else 0
            grp_ok = int(np.asarray(self.allreduce(
                np.array([ok], np.int64), op_mod.MIN)).ravel()[0])
            if self.rank == local_leader:
                bridge_comm.send_obj(grp_ok, remote_leader, tag=btag)
                their_ok = int(bridge_comm.recv_obj(remote_leader,
                                                    tag=btag))
                both = min(grp_ok, their_ok)
            else:
                both = None
            both = int(self.bcast_obj(both, root=local_leader))
            if both:
                break
            floor = cid + 1
        rt.reserve_cid(cid)
        inter = Comm(self.group, cid, self.rte,
                     name=f"{self.name}~inter", epoch=self.epoch,
                     parent=self, remote_group=Group(
                         [int(r) for r in remote]))
        inter.local_comm = self
        self._finish_create(inter)
        return inter

    def accept(self, port: str, root: int = 0) -> "Comm":
        from ompi_tpu import dpm

        return dpm.accept(self, port, root)

    def connect(self, port: str, root: int = 0) -> "Comm":
        from ompi_tpu import dpm

        return dpm.connect(self, port, root)

    def merge(self, high: bool = False) -> "Comm":
        from ompi_tpu import dpm

        return dpm.merge(self, high)

    def abort(self, errorcode: int = 1) -> None:
        from ompi_tpu.runtime import init as rt

        rt.abort(self, errorcode)

    # -- ULFM FT API (``ompi/mpiext/ftmpi``) ----------------------------
    def revoke(self) -> None:
        from ompi_tpu.ft import revoke as ft_revoke

        ft_revoke.revoke(self)

    def shrink(self) -> "Comm":
        from ompi_tpu.ft import shrink as ft_shrink

        return ft_shrink.shrink(self)

    def agree(self, flag: int) -> int:
        # NOT _check_state: ULFM's agreement is the recovery primitive and
        # must keep working on a revoked communicator (like shrink)
        if self.freed:
            raise MpiError(ErrorClass.ERR_COMM, "communicator was freed")
        return self._coll("agree")(self, flag)

    def get_failed(self) -> Group:
        from ompi_tpu.ft import state as ft_state

        failed = [r for r in self.group.world_ranks if ft_state.is_failed(r)]
        return Group(failed)

    def ack_failed(self, num_to_ack: Optional[int] = None) -> int:
        """``MPIX_Comm_ack_failed``: acknowledge known failures.

        Acknowledged ranks stop tripping ``agree`` into ProcFailedError.
        Returns the number of failures acknowledged.
        """
        from ompi_tpu.ft import state as ft_state

        failed = [r for r in self.group.world_ranks if ft_state.is_failed(r)]
        if num_to_ack is not None:
            failed = failed[:num_to_ack]
        self._acked_failed = frozenset(failed) | getattr(
            self, "_acked_failed", frozenset())
        return len(self._acked_failed)

    @property
    def ft_scope(self) -> str:
        """Revocation scope: job-local CIDs are scoped to the job (a
        dpm-spawned job's cid-0 COMM_WORLD must not inherit the parent
        job's revoked cid 0); bridge CIDs (>= 2^20) are globally unique
        and share one scope."""
        if self.cid >= (1 << 20):
            return "#bridge"
        return str(getattr(self.rte, "job", "0"))

    def is_revoked(self) -> bool:
        if not self.revoked:
            # hot path (every _check_state): prebuilt key + cached module
            # ref, one set-membership probe
            key = self._rev_key
            if key is None:
                key = self._rev_key = (self.ft_scope, self.cid, self.epoch)
            if _ft_state().is_revoked_key(key):
                self.revoked = True
        return self.revoked

    def __repr__(self) -> str:
        return (f"Comm({self.name}, cid={self.cid}, rank={self.rank}/"
                f"{self.size})")
