"""Process groups (``ompi/group/group.c`` — ordered rank sets with set
algebra and rank translation)."""
from __future__ import annotations

from typing import Optional, Sequence

from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.api.status import UNDEFINED

IDENT = 0
SIMILAR = 1
UNEQUAL = 2


class Group:
    """An ordered set of world ranks (proc ids)."""

    def __init__(self, world_ranks: Sequence[int]):
        self._ranks = tuple(world_ranks)
        if len(set(self._ranks)) != len(self._ranks):
            raise MpiError(ErrorClass.ERR_GROUP, "duplicate ranks in group")

    @classmethod
    def from_session_pset(cls, session, pset_name: str) -> "Group":
        """``MPI_Group_from_session_pset``: the group behind a named
        process set of an open session (the sessions-model entry into
        group land — no communicator needed yet)."""
        return session.group_from_pset(pset_name)

    # -- accessors -------------------------------------------------------
    @property
    def size(self) -> int:
        return len(self._ranks)

    def rank_of(self, world_rank: int) -> int:
        """Group rank of a world proc (UNDEFINED if absent)."""
        try:
            return self._ranks.index(world_rank)
        except ValueError:
            return UNDEFINED

    def world_rank(self, group_rank: int) -> int:
        return self._ranks[group_rank]

    @property
    def world_ranks(self) -> tuple:
        return self._ranks

    def translate_ranks(self, ranks: Sequence[int], other: "Group") -> list[int]:
        out = []
        for r in ranks:
            out.append(other.rank_of(self._ranks[r]))
        return out

    def compare(self, other: "Group") -> int:
        if self._ranks == other._ranks:
            return IDENT
        if set(self._ranks) == set(other._ranks):
            return SIMILAR
        return UNEQUAL

    # -- set algebra (``MPI_Group_union`` etc.) -------------------------
    def union(self, other: "Group") -> "Group":
        seen = list(self._ranks)
        extra = [r for r in other._ranks if r not in self._ranks]
        return Group(seen + extra)

    def intersection(self, other: "Group") -> "Group":
        return Group([r for r in self._ranks if r in other._ranks])

    def difference(self, other: "Group") -> "Group":
        return Group([r for r in self._ranks if r not in other._ranks])

    def incl(self, ranks: Sequence[int]) -> "Group":
        return Group([self._ranks[r] for r in ranks])

    def excl(self, ranks: Sequence[int]) -> "Group":
        drop = set(ranks)
        return Group([r for i, r in enumerate(self._ranks) if i not in drop])

    def range_incl(self, ranges: Sequence[tuple]) -> "Group":
        idx: list[int] = []
        for first, last, stride in ranges:
            idx.extend(range(first, last + (1 if stride > 0 else -1), stride))
        return self.incl(idx)

    def range_excl(self, ranges: Sequence[tuple]) -> "Group":
        idx: list[int] = []
        for first, last, stride in ranges:
            idx.extend(range(first, last + (1 if stride > 0 else -1), stride))
        return self.excl(idx)

    def __len__(self) -> int:
        return self.size

    def __repr__(self) -> str:
        return f"Group({list(self._ranks)})"


GROUP_EMPTY = Group(())
