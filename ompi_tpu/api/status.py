"""MPI_Status equivalent (``ompi/include/mpi.h.in`` MPI_Status +
``ompi/mpi/c`` get_count/get_elements semantics)."""
from __future__ import annotations

from dataclasses import dataclass

from ompi_tpu.api.errors import ErrorClass

ANY_SOURCE = -1
ANY_TAG = -1
PROC_NULL = -2
ROOT = -4          # intercomm collective root sentinel (MPI_ROOT)
UNDEFINED = -32766


@dataclass
class Status:
    source: int = UNDEFINED
    tag: int = UNDEFINED
    error: ErrorClass = ErrorClass.SUCCESS
    _nbytes: int = 0
    _cancelled: bool = False

    def get_count(self, datatype) -> int:
        """Number of whole datatype elements received (UNDEFINED if partial)."""
        if datatype.size == 0:
            return 0 if self._nbytes == 0 else UNDEFINED
        n, rem = divmod(self._nbytes, datatype.size)
        return n if rem == 0 else UNDEFINED

    def get_elements(self, datatype) -> int:
        """Number of completed elementary items received."""
        return datatype.element_count(self._nbytes)

    def is_cancelled(self) -> bool:
        return self._cancelled

    def set_cancelled(self, flag: bool) -> None:
        self._cancelled = flag

    def set_elements(self, datatype, count: int) -> None:
        self._nbytes = count * datatype.size
