"""part — MPI-4 partitioned point-to-point communication framework
(``/root/reference/ompi/mca/part/``).

Partitioned communication splits one persistent transfer into
application-visible partitions: ``Psend_init``/``Precv_init`` build a
reusable request, ``MPI_Start`` activates an epoch, the sender releases
individual partitions with ``Pready``/``Pready_range``/``Pready_list``
as their data is produced, and the receiver observes per-partition
arrival with ``Parrived``.  It is the MPI feature behind bucketed
gradient overlap: per-partition readiness lets communication of finished
shards proceed while the rest are still being computed.

The single built-in component is ``persist`` — the re-design of the
reference's ``part/persist``: ready partitions are mapped onto ordinary
pml/ob1 messages (so the eager/RNDV/RGET ladder, striping, and FT
semantics all apply), with N app partitions travelling as fewer wire
messages under the ``otpu_part_persist_min_partitions`` aggregation var.
Receive-side arrival tracking is byte-framed, so mismatched send/receive
partition counts pair correctly as MPI-4 requires.
"""
from __future__ import annotations

from ompi_tpu.base import mca


def part_framework() -> mca.Framework:
    return mca.framework("part", "partitioned point-to-point communication")


def part_module():
    """The selected part module (process singleton, like pml selection)."""
    fw = part_framework()
    comp = fw.selected if fw.selected is not None else fw.select()
    if comp is None:
        from ompi_tpu.api.errors import ErrorClass, MpiError

        raise MpiError(ErrorClass.ERR_UNSUPPORTED_OPERATION,
                       "no part component available")
    return comp.get_module()
