"""part/persist — partitioned requests over the pml (the default and, as
in the reference, only part component).

Re-design of ``/root/reference/ompi/mca/part/persist``: a partitioned
send request owns a contiguous user buffer split into P equal
partitions; each ``Pready`` marks its partition transferable and the
component maps maximal contiguous ready runs onto ordinary pml messages
— one wire message may carry several app partitions (aggregation var
``otpu_part_persist_min_partitions``, the ``part_persist_min_message_
count`` analog), so N partitions travel as <= N fragments.  Every wire
message is byte-framed (epoch, byte offset, byte length header), which
is what lets a receiver partitioned differently from the sender track
``Parrived`` exactly: arrival is counted in bytes against the RECEIVER's
partition boundaries, so mismatched send/recv partition counts pair
correctly as MPI-4 requires.

The receive side is driven by the progress engine: while a partitioned
recv is active it registers a progress callback that improbes the pml's
unexpected queue for wire-tagged messages and lands payloads straight
into the user buffer — no posted-receive window to size, no truncation.
Epoch numbers (one per start, both sides count starts) keep a restarted
sender's messages from being folded into the previous epoch; pml
per-channel FIFO ordering guarantees an epoch is drained in full before
the next one's messages are reachable, and anything probed early is
stashed for the matching start.

Wire tags live in the reserved internal space ``-(1 << 21) - tag`` (user
tags are capped below 2^20, keeping the space disjoint from the CID
agreement's ``-(1 << 20) - tag`` and the intercomm bridge's
``-(1 << 22) - tag``).
"""
from __future__ import annotations

import bisect
import threading

import numpy as np

from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.api.request import Request, RequestState
from ompi_tpu.api.status import ANY_SOURCE, PROC_NULL, Status
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType
from ompi_tpu.runtime import spc, trace

_WIRE_TAG_BASE = -(1 << 21)
_MAX_USER_TAG = 1 << 20
_HDR_BYTES = 24          # int64[3]: epoch, byte offset, byte length


def _wire_tag(tag: int) -> int:
    return _WIRE_TAG_BASE - tag


def _check_buffer(buf, partitions: int, writable: bool) -> np.ndarray:
    """Partitioned buffers must be contiguous ndarrays whose element
    count divides evenly into partitions (loud errors, no silent
    copies — the request keeps a live VIEW so data written between
    start() and Pready is what travels)."""
    if not isinstance(buf, np.ndarray) or not buf.flags.c_contiguous:
        raise MpiError(ErrorClass.ERR_BUFFER,
                       "partitioned communication needs a C-contiguous "
                       "ndarray buffer")
    if writable and not buf.flags.writeable:
        raise MpiError(ErrorClass.ERR_BUFFER,
                       "partitioned receive buffer must be writable")
    if not isinstance(partitions, (int, np.integer)) or partitions <= 0:
        raise MpiError(ErrorClass.ERR_ARG,
                       f"invalid partition count {partitions!r}")
    if buf.size % partitions:
        raise MpiError(
            ErrorClass.ERR_COUNT,
            f"buffer of {buf.size} elements does not divide into "
            f"{partitions} equal partitions")
    return buf


def _check_tag(tag: int) -> int:
    # wildcards are not supported in partitioned communication (MPI-4
    # §4.2) and negative tags would collide with internal tag spaces
    if not 0 <= int(tag) < _MAX_USER_TAG:
        raise MpiError(ErrorClass.ERR_TAG,
                       f"partitioned tag must be in [0, 2^20), got {tag}")
    return int(tag)


class PartRequest(Request):
    """Common partitioned-request state (one side of one pairing)."""

    side = "?"

    def __init__(self, module, comm, buf, partitions: int, peer: int,
                 tag: int, writable: bool) -> None:
        super().__init__(persistent=True)
        self._module = module
        self._comm = comm
        self._null = peer == PROC_NULL
        if not self._null:
            _check_buffer(buf, partitions, writable)
        elif not isinstance(partitions, (int, np.integer)) or \
                partitions <= 0:
            raise MpiError(ErrorClass.ERR_ARG,
                           f"invalid partition count {partitions!r}")
        self._buf = buf
        self._bytes = (buf.reshape(-1).view(np.uint8)
                       if not self._null else np.empty(0, np.uint8))
        self.partitions = int(partitions)
        self.nbytes = 0 if self._null else buf.nbytes
        self._psize = self.nbytes // self.partitions
        self.peer = peer
        self.tag = _check_tag(tag)
        self._plock = threading.Lock()
        self._epoch = -1

    def _check_partition(self, p) -> int:
        if not isinstance(p, (int, np.integer)) or not \
                0 <= p < self.partitions:
            raise MpiError(
                ErrorClass.ERR_ARG,
                f"partition {p!r} out of range [0, {self.partitions})")
        return int(p)


class PsendRequest(PartRequest):
    """``MPI_Psend_init`` product: Pready marks partitions transferable;
    contiguous ready runs >= min_partitions flush as one pml message
    each (everything flushes once the last partition is readied)."""

    side = "send"

    def __init__(self, module, comm, buf, partitions, dest, tag):
        super().__init__(module, comm, buf, partitions, dest, tag,
                         writable=False)
        self._ready = np.zeros(self.partitions, bool)

    def _start(self) -> None:
        with self._plock:
            self._epoch += 1
            self._ready[:] = False
            self._nready = 0
            self._runs: list[list[int]] = []   # pending [lo, hi) ready runs
            self._inflight = 0
            self._flushed_all = False
            self._send_error = None
            # min_partitions is latched per epoch so a mid-epoch var
            # change cannot strand an already-deferred run
            self._minp = max(1, self._module.min_partitions())

    def pready(self, partition) -> None:
        # THE hot call of partitioned communication (one per gradient
        # bucket per step in the overlap pattern): flag checks, one
        # bitmap bit, a run merge — tracing costs one flag check when off
        spc.record("part_pready")
        t0 = trace.now() if trace.enabled else None
        if self.state is not RequestState.ACTIVE:
            raise MpiError(ErrorClass.ERR_REQUEST,
                           "Pready on an inactive partitioned request "
                           "(call start() first)")
        p = self._check_partition(partition)
        with self._plock:
            if self._ready[p]:
                raise MpiError(ErrorClass.ERR_ARG,
                               f"partition {p} was already marked ready "
                               "in this epoch")
            self._ready[p] = True
            self._nready += 1
            self._merge_run(p)
            force = self._nready == self.partitions
            out = self._pop_runs(force)
            if force:
                self._flushed_all = True
            self._inflight += len(out)
        for lo, hi in out:
            self._send_run(lo, hi)
        if force and not out:
            # everything already flushed by earlier preadys
            self._maybe_complete()
        if t0 is not None:
            trace.span("pready", "part", t0,
                       args={"partition": p, "nbytes": self._psize,
                             "cid": self._comm.cid})
            trace.hist_record("pready", self._psize, trace.now() - t0)

    def parrived(self, partition):
        raise MpiError(ErrorClass.ERR_REQUEST,
                       "Parrived on a partitioned SEND request (the "
                       "standard defines it for the receive side only)")

    # -- run bookkeeping (under _plock) ----------------------------------
    def _merge_run(self, p: int) -> None:
        runs = self._runs
        i = bisect.bisect_left(runs, [p, p])
        # merge with predecessor ending at p and/or successor starting
        # at p+1 (runs are disjoint and sorted by lo)
        if i > 0 and runs[i - 1][1] == p:
            runs[i - 1][1] = p + 1
            if i < len(runs) and runs[i][0] == p + 1:
                runs[i - 1][1] = runs[i][1]
                runs.pop(i)
        elif i < len(runs) and runs[i][0] == p + 1:
            runs[i][0] = p
        else:
            runs.insert(i, [p, p + 1])

    def _pop_runs(self, force: bool) -> list:
        if force:
            out, self._runs = self._runs, []
            return out
        out = [r for r in self._runs if r[1] - r[0] >= self._minp]
        if out:
            self._runs = [r for r in self._runs if r[1] - r[0] < self._minp]
        return out

    # -- wire -------------------------------------------------------------
    def _send_run(self, lo: int, hi: int) -> None:
        if self._null:
            # nothing travels to PROC_NULL — and nothing may be counted:
            # the docs tell users to read part_msgs to verify aggregation
            with self._plock:
                self._inflight -= 1
            self._maybe_complete()
            return
        off = lo * self._psize
        ln = (hi - lo) * self._psize
        spc.record("part_msgs")
        spc.record("part_bytes", ln)
        msg = np.empty(_HDR_BYTES + ln, np.uint8)
        msg[:_HDR_BYTES] = np.array([self._epoch, off, ln],
                                    np.int64).view(np.uint8)
        msg[_HDR_BYTES:] = self._bytes[off:off + ln]
        try:
            inner = self._comm.pml.isend(self._comm, msg, self.peer,
                                         _wire_tag(self.tag))
        except MpiError as exc:
            with self._plock:
                self._inflight -= 1
                self._send_error = exc
            self._maybe_complete()
            raise
        inner.on_complete(self._inner_done)

    def _inner_done(self, inner) -> None:
        with self._plock:
            self._inflight -= 1
            if inner.error is not None:
                self._send_error = inner.error
        self._maybe_complete()

    def _maybe_complete(self) -> None:
        with self._plock:
            done = self._flushed_all and self._inflight == 0
            err = self._send_error
        if done:
            self.status._nbytes = self.nbytes
            self.complete(err)


class PrecvRequest(PartRequest):
    """``MPI_Precv_init`` product: a progress-engine callback drains
    wire-tagged messages from the pml and lands payload bytes straight
    into the user buffer; Parrived reads per-partition byte counts."""

    side = "recv"

    def __init__(self, module, comm, buf, partitions, source, tag):
        if source == ANY_SOURCE:
            raise MpiError(ErrorClass.ERR_ARG,
                           "partitioned communication does not support "
                           "MPI_ANY_SOURCE")
        super().__init__(module, comm, buf, partitions, source, tag,
                         writable=True)
        self._arrived = np.zeros(self.partitions, np.int64)
        self._registered = False
        if not self._null:
            grp = comm.remote_group if comm.is_inter else comm.group
            src_world = grp.world_rank(source)
            self._key = (comm.cid, comm.world_rank(comm.rank), src_world,
                         self.tag)
            # one outstanding partitioned pairing per (comm, peer, tag)
            # channel: a predecessor abandoned without free() must not
            # bleed its stashed future-epoch payloads into this request
            # (epoch counters restart per request)
            module.stash_clear(self._key)

    def _start(self) -> None:
        from ompi_tpu.runtime import progress

        with self._plock:
            self._epoch += 1
            self._arrived[:] = 0
            self._total_arrived = 0
        if self._null:
            self.status = Status(source=PROC_NULL, tag=self.tag, _nbytes=0)
            self.complete()
            return
        if not self._registered:
            progress.register(self._poll)
            self._registered = True
        # messages probed ahead of this start (a fast sender's next
        # epoch) were stashed under our epoch number — land them now
        for off, payload in self._module.stash_pop(self._key, self._epoch):
            self._apply(off, payload)
        self._poll()

    def pready(self, partition) -> None:
        raise MpiError(ErrorClass.ERR_REQUEST,
                       "Pready on a partitioned RECEIVE request (the "
                       "standard defines it for the send side only)")

    def parrived(self, partition) -> bool:
        """``MPI_Parrived``: has partition ``partition`` fully arrived
        in the current epoch?  Polls the progress engine once on a miss
        (like test())."""
        spc.record("part_parrived")
        p = self._check_partition(partition)
        if self.persistent and self.state is RequestState.INACTIVE \
                and self._epoch < 0:
            raise MpiError(ErrorClass.ERR_REQUEST,
                           "Parrived on a never-started partitioned "
                           "request")
        if self._null or self.complete_flag:
            self._raise_if_error()
            return True
        if self._arrived[p] >= self._psize and self._psize > 0:
            return True
        from ompi_tpu.runtime.progress import progress

        progress()
        self._raise_if_error()
        return bool(self._arrived[p] >= self._psize and self._psize > 0)

    # -- progress-engine drain -------------------------------------------
    def _poll(self) -> int:
        """Progress callback: drain wire messages for this request."""
        if self.complete_flag:
            return 0
        events = 0
        wtag = _wire_tag(self.tag)
        while not self.complete_flag:
            found, msg = self._comm.pml.mprobe(
                self._comm, self.peer, wtag, blocking=False)
            if not found:
                break
            nb = msg.status._nbytes
            buf = np.empty(nb, np.uint8)
            msg.recv(buf)
            if nb < _HDR_BYTES:
                self._finish(MpiError(ErrorClass.ERR_INTERN,
                                      "short partitioned wire message"))
                return events + 1
            epoch, off, ln = (int(v) for v in
                              buf[:_HDR_BYTES].view(np.int64))
            payload = buf[_HDR_BYTES:_HDR_BYTES + ln]
            events += 1
            if epoch != self._epoch:
                # pml FIFO means only a FUTURE epoch can show up here
                # (the sender restarted); hold it for the matching start
                self._module.stash_put(self._key, epoch, (off, payload))
                continue
            self._apply(off, payload)
        return events

    def _apply(self, off: int, payload: np.ndarray) -> None:
        ln = len(payload)
        if off + ln > self.nbytes:
            self._finish(MpiError(
                ErrorClass.ERR_TRUNCATE,
                f"partitioned message [{off}, {off + ln}) overruns the "
                f"{self.nbytes}-byte receive buffer (mismatched total "
                "counts)"))
            return
        t0 = trace.now() if trace.enabled else None
        with self._plock:
            self._bytes[off:off + ln] = payload
            if self._psize > 0 and ln > 0:
                p0 = off // self._psize
                p1 = (off + ln - 1) // self._psize
                for p in range(p0, p1 + 1):
                    seg = (min(off + ln, (p + 1) * self._psize)
                           - max(off, p * self._psize))
                    self._arrived[p] += seg
            self._total_arrived += ln
            done = self._total_arrived >= self.nbytes
        spc.record("part_bytes", ln)
        if t0 is not None:
            trace.span("part_arrive", "part", t0,
                       args={"nbytes": ln, "offset": off,
                             "cid": self._comm.cid})
        if done:
            self._finish(None)

    def _finish(self, error) -> None:
        self.status = Status(source=self.peer, tag=self.tag,
                             _nbytes=self._total_arrived)
        self._unregister()
        self.complete(error)

    def _unregister(self) -> None:
        # the drain callback lives only while an epoch is in flight —
        # a comm full of idle partitioned requests must not tax the
        # progress loop
        if self._registered:
            from ompi_tpu.runtime import progress

            progress.unregister(self._poll)
            self._registered = False

    def free(self) -> None:
        self._unregister()
        if not self._null:
            # stale future-epoch payloads must not leak (nor surface in
            # a later request that reuses this (cid, peer, tag) channel)
            self._module.stash_clear(self._key)
        super().free()


class PartPersistModule:
    """One per process (like the pml module): builds partitioned
    requests and holds the cross-epoch message stash."""

    def __init__(self, component: "PartPersistComponent") -> None:
        self.component = component
        self._stash: dict = {}
        self._lock = threading.Lock()

    def min_partitions(self) -> int:
        var = getattr(self.component, "_minp_var", None)
        return int(var.value) if var is not None else 1

    def psend_init(self, comm, buf, partitions, dest, tag) -> PsendRequest:
        return PsendRequest(self, comm, buf, partitions, dest, tag)

    def precv_init(self, comm, buf, partitions, source,
                   tag) -> PrecvRequest:
        return PrecvRequest(self, comm, buf, partitions, source, tag)

    def stash_put(self, key, epoch: int, item) -> None:
        with self._lock:
            self._stash.setdefault(key, {}).setdefault(epoch, []).append(
                item)

    def stash_pop(self, key, epoch: int) -> list:
        with self._lock:
            per_key = self._stash.get(key)
            if not per_key:
                return []
            out = per_key.pop(epoch, [])
            if not per_key:
                self._stash.pop(key, None)
            return out

    def stash_clear(self, key) -> None:
        with self._lock:
            self._stash.pop(key, None)


class PartPersistComponent(Component):
    name = "persist"
    priority = 20

    def register_vars(self, fw) -> None:
        self.register_var("priority", vtype=VarType.INT, default=20,
                          help="Selection priority of part/persist")
        self._minp_var = self.register_var(
            "min_partitions", vtype=VarType.INT, default=1,
            help="Aggregation threshold: a contiguous run of ready "
                 "partitions is held until it spans at least this many "
                 "before travelling as one pml message (the final "
                 "Pready always flushes everything), so N app "
                 "partitions may ride fewer wire messages")

    def get_module(self) -> PartPersistModule:
        mod = getattr(self, "_module", None)
        if mod is None:
            mod = self._module = PartPersistModule(self)
        return mod

    def close(self) -> None:
        # the stash is keyed by (cid, ranks, tag): a re-init reuses CIDs,
        # so stale entries must not leak across runtime lifetimes
        self._module = None


COMPONENT = PartPersistComponent()
