"""pcoll — partitioned persistent collectives (MPI-4's partitioned
model applied to collectives; the ``Pallreduce_init`` analog).

A partitioned collective binds a LIST of buckets once; each
``pready(i)`` releases bucket i for reduction.  On the device path the
bucket's pre-compiled program (``coll/xla`` ``persistent_coll``
machinery) is dispatched immediately — XLA's async dispatch means the
reduction of bucket i runs while the application is still producing
bucket i+1, which is exactly the bucketed-gradient-overlap pattern
(``parallel_bucket_overlap`` expresses the same schedule in-jit for the
trainer).  On host comms without a device binding each pready runs the
blocking collective, so every rank must pready in the same order (the
trainer's deterministic late-layer-first schedule satisfies this).
"""
from __future__ import annotations

import threading

from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.api.request import Request, RequestState
from ompi_tpu.api.status import Status
from ompi_tpu.runtime import spc, trace


class PartitionedCollRequest(Request):
    """Restartable partitioned collective: start()/pready(i)/parrived(i)
    /wait(), with ``result[i]`` = bucket i's reduction."""

    side = "coll"

    def __init__(self, comm, coll: str, buckets, args=(), handles=None):
        super().__init__(persistent=True)
        buckets = list(buckets)
        if not buckets:
            raise MpiError(ErrorClass.ERR_ARG,
                           "partitioned collective needs >= 1 bucket")
        self._comm = comm
        self._coll = coll
        self._buckets = buckets
        self._args = tuple(args)
        self._handles = handles      # device bindings, or None (host)
        self.partitions = len(buckets)
        self.result: list = [None] * self.partitions
        self._plock = threading.Lock()

    def start(self, buckets=None) -> None:
        """``MPI_Start`` with optional data rebinding: device arrays are
        immutable, so a new round passes fresh buckets matching the
        bound templates (the ``PersistentColl.start(x)`` convention)."""
        if buckets is not None:
            buckets = list(buckets)
            if len(buckets) != self.partitions:
                raise MpiError(
                    ErrorClass.ERR_ARG,
                    f"rebind needs {self.partitions} buckets, got "
                    f"{len(buckets)}")
            self._buckets = buckets
        super().start()

    def _start(self) -> None:
        with self._plock:
            self._done = [False] * self.partitions
            self._ndone = 0
            self.result = [None] * self.partitions

    def _check_partition(self, p) -> int:
        import numpy as np

        if not isinstance(p, (int, np.integer)) or not \
                0 <= p < self.partitions:
            raise MpiError(
                ErrorClass.ERR_ARG,
                f"bucket {p!r} out of range [0, {self.partitions})")
        return int(p)

    def pready(self, partition) -> None:
        spc.record("part_pready")
        t0 = trace.now() if trace.enabled else None
        if self.state is not RequestState.ACTIVE:
            raise MpiError(ErrorClass.ERR_REQUEST,
                           "Pready on an inactive partitioned collective "
                           "(call start() first)")
        p = self._check_partition(partition)
        with self._plock:
            if self._done[p]:
                raise MpiError(ErrorClass.ERR_ARG,
                               f"bucket {p} was already released in "
                               "this epoch")
            self._done[p] = True
        x = self._buckets[p]
        try:
            if self._handles is not None:
                out = self._handles[p](x)      # async device dispatch
            else:
                out = getattr(self._comm, self._coll)(x, *self._args)
        except Exception:
            # a failed dispatch (e.g. a rebind whose bucket mismatches
            # the bound template) must not wedge the request: the bucket
            # was NOT released, so un-mark it — the epoch stays
            # restartable and a corrected pready(p) can retry
            with self._plock:
                self._done[p] = False
            raise
        nbytes = int(getattr(x, "nbytes", 0) or 0)
        spc.record("part_bytes", nbytes)
        with self._plock:
            self.result[p] = out
            self._ndone += 1
            done = self._ndone == self.partitions
        if t0 is not None:
            trace.span("pready", "part", t0,
                       args={"partition": p, "nbytes": nbytes,
                             "cid": self._comm.cid, "coll": self._coll})
        if done:
            self.status = Status(_nbytes=sum(
                int(getattr(b, "nbytes", 0) or 0) for b in self._buckets))
            self.complete()

    def parrived(self, partition) -> bool:
        """Bucket released AND its device result materialized (host
        results are synchronous, so released == arrived there)."""
        spc.record("part_parrived")
        p = self._check_partition(partition)
        if self.persistent and self.state is RequestState.INACTIVE:
            raise MpiError(ErrorClass.ERR_REQUEST,
                           "Parrived on a never-started partitioned "
                           "collective")
        with self._plock:
            out = self.result[p] if self._done[p] else None
        if out is None:
            return False
        is_ready = getattr(out, "is_ready", None)
        return True if is_ready is None else bool(is_ready())
