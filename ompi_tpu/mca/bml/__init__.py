"""bml — BTL multiplexer (``/root/reference/ompi/mca/bml/`` r2): builds
per-peer endpoint lists of usable BTLs ordered by latency/bandwidth."""
from ompi_tpu.mca.bml.r2 import Bml  # noqa: F401
