"""bml — BTL multiplexer (``/root/reference/ompi/mca/bml/`` r2): builds
per-peer endpoint lists of usable BTLs ordered by latency/bandwidth."""
from ompi_tpu.mca.bml.r2 import Bml  # noqa: F401


def resolve_bml(pml):
    """The bml behind a (possibly wrapped) pml module, or None.

    Interposition wrappers (monitoring, vprotocol) chain via ``_inner``;
    this is the one place that knows how to walk them."""
    inner = pml
    while inner is not None and not hasattr(inner, "bml"):
        inner = getattr(inner, "_inner", None)
    return getattr(inner, "bml", None) if inner is not None else None
