"""bml/r2 equivalent: per-peer BTL endpoint selection.

``/root/reference/ompi/mca/bml/r2/bml_r2.c`` builds, for every peer, the
list of BTLs that can reach it, ordered for latency (eager sends) and
striped by bandwidth (large transfers).  Here: query every available btl
component for reachability at add_procs time; the lowest-latency endpoint
serves eager traffic, the full list serves striping.
"""
from __future__ import annotations

from typing import Callable, Optional

from ompi_tpu.base import mca
from ompi_tpu.mca.btl.base import Endpoint, Frag


class Bml:
    def __init__(self, rte, recv_cb: Callable[[Frag], None]) -> None:
        self.rte = rte
        self._endpoints: dict[int, list[Endpoint]] = {}
        fw = mca.framework("btl", "byte transfer layer", multi_select=True)
        self.btls = []
        for btl in fw.select_all():
            btl.set_recv_callback(recv_cb)
            setup = getattr(btl, "setup", None)
            if setup is not None:
                try:
                    if setup(rte) is False:
                        continue  # transport not usable in this process model
                except Exception as exc:
                    from ompi_tpu.base import output as _o

                    _o.output(fw.stream, 1, "btl %s setup failed: %s",
                              btl.name, exc)
                    close = getattr(btl, "close", None)
                    if close is not None:
                        try:
                            close()  # release partially-acquired resources
                        except Exception:
                            pass
                    continue
            self.btls.append(btl)
            from ompi_tpu.runtime import progress as prog

            prog.register(btl.progress)

    def add_proc(self, world_rank: int) -> list[Endpoint]:
        eps = []
        for btl in self.btls:
            ep = btl.reachable(world_rank, self.rte)
            if ep is not None:
                eps.append(ep)
        eps.sort(key=lambda e: (e.btl.latency, -e.btl.bandwidth))
        self._endpoints[world_rank] = eps
        return eps

    def endpoint(self, world_rank: int) -> Optional[Endpoint]:
        """Lowest-latency endpoint for the peer (eager path)."""
        eps = self._endpoints.get(world_rank)
        if eps is None:
            eps = self.add_proc(world_rank)
        return eps[0] if eps else None

    def endpoints(self, world_rank: int) -> list[Endpoint]:
        eps = self._endpoints.get(world_rank)
        if eps is None:
            eps = self.add_proc(world_rank)
        return eps

    def finalize(self) -> None:
        # resource release itself happens in each component's close() via
        # the framework close lifecycle (mca.close_all in runtime finalize)
        from ompi_tpu.runtime import progress as prog

        for btl in self.btls:
            prog.unregister(btl.progress)
