"""accelerator/jax — TPU HBM residency + staging.

The ``opal_cuda_check_bufs`` analog (``common_cuda.c``): tells the datatype
engine, the pml, and the coll decision path whether a buffer lives in device
HBM (→ XLA collective path, DEVICE convertor flag) or host memory (→ host
pack/unpack).  Registration of device memory is implicit in jax.Array
ownership; ``register``/``deregister`` keep an interval-tree bookkeeping of
exposed host regions for the RMA path (rcache equivalent).

The **staging pool** is the ``rcache/grdma`` reuse analog
(``opal/mca/rcache/grdma/rcache_grdma.c``): grdma exists so repeated
transfers reuse pinned registrations instead of re-pinning per call;
here, repeated host-path collectives reuse warmed staging buffers
(LRU keyed on (shape, dtype)) instead of re-allocating.  A fresh
``np.empty`` is lazily mapped and re-faults its pages on every call —
measured ~6x the warmed-checkout cost (36µs vs 6µs per 1MB buffer,
``bench.py staging_micro_row``).  On the 1-core host harness that tax
is <1% of a 25ms collective (end-to-end within noise); it matters
where transfers are fast relative to allocation, which is exactly the
regime grdma targets.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Optional

import numpy as np

from ompi_tpu.base.containers import IntervalTree
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType, registry

_rcache = IntervalTree()

# module-level vars (the vprotocol pattern: this framework's component
# is consumed by direct import, not framework selection)
_pool_var = registry.register(
    "accelerator", "jax", "staging_pool", vtype=VarType.BOOL, default=True,
    help="Reuse host staging buffers across collective calls "
         "(rcache/grdma-style LRU); 0 allocates fresh per call")
_pool_bytes_var = registry.register(
    "accelerator", "jax", "staging_pool_bytes", vtype=VarType.SIZE,
    default="64m",
    help="Total bytes of idle staging buffers kept for reuse before "
         "LRU eviction")


class _StagingPool:
    """LRU pool of reusable host staging buffers (grdma-style reuse).

    ``acquire`` returns a warmed buffer when one of the exact
    (shape, dtype) is cached (contents undefined, like ``np.empty``);
    ``release`` returns it for reuse, evicting least-recently-used
    entries beyond ``max_bytes``.  Unless explicitly overridden
    (tests), enablement and capacity follow the MCA vars.
    """

    def __init__(self, max_bytes: Optional[int] = None,
                 enabled: Optional[bool] = None) -> None:
        self._lock = threading.Lock()
        self._free: OrderedDict[tuple, list] = OrderedDict()
        self._bytes = 0
        self._max_bytes = max_bytes
        self._enabled = enabled
        self.hits = 0
        self.misses = 0

    @property
    def enabled(self) -> bool:
        if self._enabled is not None:
            return self._enabled
        return bool(_pool_var.value)

    @enabled.setter
    def enabled(self, v) -> None:
        self._enabled = bool(v) if v is not None else None

    @property
    def max_bytes(self) -> int:
        if self._max_bytes is not None:
            return self._max_bytes
        return int(_pool_bytes_var.value)

    @max_bytes.setter
    def max_bytes(self, v) -> None:
        self._max_bytes = int(v) if v is not None else None

    @staticmethod
    def _key(shape, dtype) -> tuple:
        if isinstance(shape, (int, np.integer)):
            shape = (int(shape),)
        return tuple(int(s) for s in shape), np.dtype(dtype).str

    def acquire(self, shape, dtype) -> np.ndarray:
        key = self._key(shape, dtype)
        if self.enabled:
            with self._lock:
                lst = self._free.get(key)
                if lst:
                    self._free.move_to_end(key)
                    buf = lst.pop()
                    self._bytes -= buf.nbytes
                    self.hits += 1
                    return buf
                self.misses += 1
        return np.empty(key[0], np.dtype(dtype))

    def release(self, buf: np.ndarray) -> None:
        if not self.enabled or buf.base is not None:
            return   # never pool views: the base owns the memory
        if buf.nbytes > self.max_bytes:
            return   # could never be retained — and pushing it through
                     # the LRU would flush every warm buffer first
        key = self._key(buf.shape, buf.dtype)
        with self._lock:
            lst = self._free.setdefault(key, [])
            if any(b is buf for b in lst):
                return   # double release: pooling the same ndarray
                         # twice would alias two later acquires
            lst.append(buf)
            self._free.move_to_end(key)
            self._bytes += buf.nbytes
            while self._bytes > self.max_bytes and self._free:
                _, lst = self._free.popitem(last=False)   # LRU key out
                self._bytes -= sum(b.nbytes for b in lst)

    def clear(self) -> None:
        with self._lock:
            self._free.clear()
            self._bytes = 0
            self.hits = self.misses = 0


staging = _StagingPool()


def staging_acquire(shape, dtype) -> np.ndarray:
    """Checkout a host staging buffer (contents undefined)."""
    return staging.acquire(shape, dtype)


def staging_release(buf: np.ndarray) -> None:
    """Return a buffer checked out with :func:`staging_acquire`."""
    staging.release(buf)


def is_device_array(x: Any) -> bool:
    """True if x is a jax.Array whose committed home is an accelerator."""
    try:
        import jax
    except ImportError:  # pragma: no cover
        return False
    # Any jax.Array takes the XLA collective path — CPU-backed jax Arrays
    # included (virtual-device meshes in tests): the mesh is what matters,
    # not the platform.
    return isinstance(x, jax.Array)


def to_host(x) -> np.ndarray:
    """Stage a device array to host memory (D2H)."""
    return np.asarray(x)


def from_host(arr: np.ndarray, sharding=None):
    """Stage host memory to device (H2D), optionally sharded."""
    import jax

    return jax.device_put(arr, sharding)


def register(buf: np.ndarray, key: Any = None):
    """Expose a host region (RMA window registration)."""
    addr = buf.__array_interface__["data"][0]
    _rcache.insert(addr, addr + buf.nbytes, key or buf)
    return addr


def deregister(buf: np.ndarray) -> None:
    addr = buf.__array_interface__["data"][0]
    _rcache.delete(addr, addr + buf.nbytes)


def lookup(addr: int, nbytes: int):
    hit = _rcache.find_containing(addr, addr + nbytes)
    return None if hit is None else hit[2]


class JaxAcceleratorComponent(Component):
    name = "jax"
    priority = 50

    def open(self) -> bool:
        try:
            import jax  # noqa: F401

            return True
        except ImportError:  # pragma: no cover
            return False


COMPONENT = JaxAcceleratorComponent()
