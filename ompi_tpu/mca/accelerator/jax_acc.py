"""accelerator/jax — TPU HBM residency + staging.

The ``opal_cuda_check_bufs`` analog (``common_cuda.c``): tells the datatype
engine, the pml, and the coll decision path whether a buffer lives in device
HBM (→ XLA collective path, DEVICE convertor flag) or host memory (→ host
pack/unpack).  Registration of device memory is implicit in jax.Array
ownership; ``register``/``deregister`` keep an interval-tree bookkeeping of
exposed host regions for the RMA path (rcache equivalent).
"""
from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ompi_tpu.base.containers import IntervalTree
from ompi_tpu.base.mca import Component

_rcache = IntervalTree()


def is_device_array(x: Any) -> bool:
    """True if x is a jax.Array whose committed home is an accelerator."""
    try:
        import jax
    except ImportError:  # pragma: no cover
        return False
    # Any jax.Array takes the XLA collective path — CPU-backed jax Arrays
    # included (virtual-device meshes in tests): the mesh is what matters,
    # not the platform.
    return isinstance(x, jax.Array)


def to_host(x) -> np.ndarray:
    """Stage a device array to host memory (D2H)."""
    return np.asarray(x)


def from_host(arr: np.ndarray, sharding=None):
    """Stage host memory to device (H2D), optionally sharded."""
    import jax

    return jax.device_put(arr, sharding)


def register(buf: np.ndarray, key: Any = None):
    """Expose a host region (RMA window registration)."""
    addr = buf.__array_interface__["data"][0]
    _rcache.insert(addr, addr + buf.nbytes, key or buf)
    return addr


def deregister(buf: np.ndarray) -> None:
    addr = buf.__array_interface__["data"][0]
    _rcache.delete(addr, addr + buf.nbytes)


def lookup(addr: int, nbytes: int):
    hit = _rcache.find_containing(addr, addr + nbytes)
    return None if hit is None else hit[2]


class JaxAcceleratorComponent(Component):
    name = "jax"
    priority = 50

    def open(self) -> bool:
        try:
            import jax  # noqa: F401

            return True
        except ImportError:  # pragma: no cover
            return False


COMPONENT = JaxAcceleratorComponent()
