"""accelerator/jax — TPU HBM residency + staging.

The ``opal_cuda_check_bufs`` analog (``common_cuda.c``): tells the datatype
engine, the pml, and the coll decision path whether a buffer lives in device
HBM (→ XLA collective path, DEVICE convertor flag) or host memory (→ host
pack/unpack).  Registration of device memory is implicit in jax.Array
ownership; ``register``/``deregister`` keep an interval-tree bookkeeping of
exposed host regions for the RMA path (rcache equivalent).

The **staging pool** is the ``rcache/grdma`` reuse analog
(``opal/mca/rcache/grdma/rcache_grdma.c``): grdma exists so repeated
transfers reuse pinned registrations instead of re-pinning per call;
here, repeated host-path collectives reuse warmed staging buffers
(LRU keyed on (shape, dtype)) instead of re-allocating.  A fresh
``np.empty`` is lazily mapped and re-faults its pages on every call —
measured ~6x the warmed-checkout cost (36µs vs 6µs per 1MB buffer,
``bench.py staging_micro_row``).  On the 1-core host harness that tax
is <1% of a 25ms collective (end-to-end within noise); it matters
where transfers are fast relative to allocation, which is exactly the
regime grdma targets.
"""
from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict, deque
from typing import Any, Optional

import numpy as np

from ompi_tpu.base.containers import IntervalTree
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType, registry
from ompi_tpu.runtime import profile, sanitizer, spc, trace
from ompi_tpu.runtime.hotpath import hot_path

_rcache = IntervalTree()

# module-level vars (the vprotocol pattern: this framework's component
# is consumed by direct import, not framework selection)
_pool_var = registry.register(
    "accelerator", "jax", "staging_pool", vtype=VarType.BOOL, default=True,
    help="Reuse host staging buffers across collective calls "
         "(rcache/grdma-style LRU); 0 allocates fresh per call")
_pool_bytes_var = registry.register(
    "accelerator", "jax", "staging_pool_bytes", vtype=VarType.SIZE,
    default="64m",
    help="Total bytes of idle staging buffers kept for reuse before "
         "LRU eviction")


#: smallest size class kept (below this an np.empty is cheaper than the
#: pool bookkeeping)
_MIN_CLASS = 256


class _StagingPool:
    """Size-class binned pool of reusable host staging buffers
    (grdma-style reuse, fastpath redesign).

    Free memory is held as raw 1-D uint8 OWNER arrays binned by
    power-of-two size class; ``acquire`` pops the most-recently-released
    buffer of the class (warm pages first, O(1)) and returns it shaped
    as a (shape, dtype) view, ``release`` maps the view back to its raw
    class buffer in O(1) through the checkout table.  Contents are
    undefined, like ``np.empty``, and nothing touches the buffer on
    acquire — warmth is the whole point.

    The previous exact-(shape, dtype)-keyed design measured an e2e
    **regression** (BENCH_SWEEP `staging_pool_e2e` 0.78x) despite a
    6.65x reuse micro: every release ran an O(n) identity scan of the
    key's free list, eviction dumped the ENTIRE least-recently-used key
    (a repeated-collective loop whose one hot key rotated to the front
    lost its whole warm set at once), and odd-size blocks (`_blocks`
    rounds ranks' shares up and down by one element) fragmented across
    keys that could never reuse each other's memory.  Size-class bins
    fix the fragmentation, the checkout table makes release O(1), and
    eviction now retires ONE cold buffer at a time from the
    least-recently-USED class, never the hot class at the deque's end.

    Unless explicitly overridden (tests), enablement and capacity follow
    the MCA vars.
    """

    #: otpu-lint lock-discipline contract: every pool structure —
    #: including the checkout table the double-release guard scans —
    #: mutates only under the pool lock.  The lint pass found _checkout
    #: inserting into _out OUTSIDE the lock: between acquire's unlock
    #: and the insert, a concurrent double release of the same adopted
    #: owner passed the guard (its bytes looked neither free nor
    #: checked out) and repooled memory that was in use — exactly the
    #: PR 4 aliasing family.  The lock is an RLock because the weakref
    #: purge callback can fire from GC while the owning thread already
    #: holds it.
    _guarded_by = {"_free": "_lock", "_out": "_lock", "_adopted": "_lock",
                   "_bytes": "_lock", "hits": "_lock", "misses": "_lock"}

    def __init__(self, max_bytes: Optional[int] = None,
                 enabled: Optional[bool] = None) -> None:
        self._lock = threading.RLock()
        # size class -> deque of raw uint8 owner arrays (LIFO: the back
        # is the most recently released = warmest pages)
        self._free: OrderedDict[int, deque] = OrderedDict()
        # id(view handed out) -> (weakref(view), raw owner): release()
        # maps the caller's array back to pool memory without walking
        # .base chains; the weakref both guards against id() reuse and
        # purges the entry if the view dies unreleased
        self._out: dict[int, tuple] = {}
        # id(owner) of adopted foreign buffers currently in _free: a
        # double release of the same owner array would otherwise repool
        # two aliases of one memory block (two later acquires would
        # share bytes).  The pooled view keeps the owner alive, so the
        # id stays valid for exactly as long as it is in this set.
        self._adopted: set[int] = set()
        self._bytes = 0
        self._max_bytes = max_bytes
        self._enabled = enabled
        self.hits = 0
        self.misses = 0
        self._warned_noncontig = False

    @property
    def enabled(self) -> bool:
        if self._enabled is not None:
            return self._enabled
        return bool(_pool_var.value)

    @enabled.setter
    def enabled(self, v) -> None:
        self._enabled = bool(v) if v is not None else None

    @property
    def max_bytes(self) -> int:
        if self._max_bytes is not None:
            return self._max_bytes
        return int(_pool_bytes_var.value)

    @max_bytes.setter
    def max_bytes(self, v) -> None:
        self._max_bytes = int(v) if v is not None else None

    @staticmethod
    def _class_of(nbytes: int) -> int:
        if nbytes <= _MIN_CLASS:
            return _MIN_CLASS
        return 1 << (int(nbytes) - 1).bit_length()

    def _checkout(self, raw: np.ndarray, shape, dtype) -> np.ndarray:
        nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize \
            if shape else np.dtype(dtype).itemsize
        view = raw[:nbytes].view(dtype).reshape(shape)
        token = id(view)
        with self._lock:
            # the insert must be visible BEFORE the pool lock is ever
            # released with raw popped from its free bin: release()'s
            # double-release guard scans _out under the lock, and an
            # entry registered after the unlock left a window where the
            # owner looked neither free nor checked out
            self._out[token] = (
                weakref.ref(view, lambda _r, t=token: self._purge(t)),
                raw)
        return view

    def _purge(self, token: int) -> None:
        """Weakref callback: a checked-out view died unreleased.  Runs
        under the pool lock (RLock: GC may fire it while the owning
        thread already holds the lock)."""
        with self._lock:
            self._out.pop(token, None)

    @hot_path
    def acquire(self, shape, dtype) -> np.ndarray:
        if isinstance(shape, (int, np.integer)):
            shape = (int(shape),)
        shape = tuple(int(s) for s in shape)
        dtype = np.dtype(dtype)
        if not self.enabled:
            return np.empty(shape, dtype)
        nbytes = int(np.prod(shape)) * dtype.itemsize if shape \
            else dtype.itemsize
        cls = self._class_of(nbytes)
        t0 = time.perf_counter_ns() \
            if (trace.enabled or profile.enabled) else 0
        out = None
        with self._lock:
            dq = self._free.get(cls)
            if dq:
                raw = dq.pop()          # back = warmest
                if not dq:
                    del self._free[cls]
                else:
                    self._free.move_to_end(cls)
                if raw.base is not None:        # adopted foreign owner
                    self._adopted.discard(id(raw.base))
                self._bytes -= raw.nbytes
                self.hits += 1
                # checkout registration in the SAME critical section as
                # the free-bin pop (the RLock re-enters in _checkout):
                # a popped owner must never be observable as neither
                # free nor checked out, or a stale concurrent release of
                # the same owner slips past the double-release guard and
                # repools bytes that are in use
                out = self._checkout(raw, shape, dtype)
            else:
                raw = None
                self.misses += 1
        hit = out is not None
        if hit:
            spc.record("fastpath_staging_hits")
        else:
            spc.record("fastpath_staging_misses")
            # fresh allocation OUTSIDE the lock (first-touch page faults
            # are the expensive part); the owner was never pooled, so
            # nothing can race its checkout registration
            raw = np.empty(cls, np.uint8)
            out = self._checkout(raw, shape, dtype)
        if trace.enabled:
            name = "staging_hit" if hit else "staging_miss"
            trace.span(name, "staging", t0, args={"nbytes": nbytes})
            trace.hist_record(name, nbytes, time.perf_counter_ns() - t0)
        if profile.enabled:
            profile.stage_span("send.staging", t0)
        return out

    @hot_path
    def release(self, buf: np.ndarray) -> None:
        if not self.enabled:
            return
        if not buf.flags.c_contiguous:
            if sanitizer.enabled:
                sanitizer.fail(
                    "non-C-contiguous buffer released to the staging "
                    f"pool (shape {tuple(buf.shape)}, dtype {buf.dtype})"
                    " — layout bug in the caller")
            # fastpath satellite: this used to vanish silently, leaking
            # the buffer from the pool's accounting — warn loudly once
            # (per-pool) so the caller's layout bug is visible
            if not self._warned_noncontig:
                self._warned_noncontig = True
                from ompi_tpu.base.output import show_help

                show_help("help-accel-staging", "non-contiguous-release",
                          shape=tuple(buf.shape), dtype=str(buf.dtype))
            return
        with self._lock:
            self._release_locked(buf)

    def _release_locked(self, buf: np.ndarray) -> None:
        entry = self._out.pop(id(buf), None)
        if entry is not None and entry[0]() is buf:
            raw = entry[1]              # pool view: repool its raw owner
        elif buf.base is not None:
            return   # foreign view (or a pool sub-view): the base owns
                     # the memory — pooling it would alias the caller
        else:
            # foreign owner (a caller's np.empty handed back): adopt it
            # as a flat byte view — the view's .base keeps it alive
            raw = buf.reshape(-1).view(np.uint8)
            if raw.nbytes < _MIN_CLASS:
                return
        # always binned at the FLOOR class so every buffer in a bin
        # covers every request mapped there (requests bin at the
        # ceiling).  Pool-allocated raws are class-flat (floor ==
        # ceiling), but an adopted odd-size raw must never ride a
        # checkout back into its CEILING class — a later acquire of
        # that class would overrun it.
        cls = 1 << (int(raw.nbytes).bit_length() - 1)
        if raw.nbytes > self.max_bytes:
            return   # could never be retained — and pushing it through
                     # the LRU would flush every warm buffer first
        if raw.base is not None and (
                id(raw.base) in self._adopted
                or any(e[1].base is raw.base
                       for e in list(self._out.values()))):
            # double release: the owner is already in a free bin, or
            # its bytes are checked out right now (re-released after
            # an acquire popped it) — repooling would alias two
            # later acquires.  Both checks run under the pool lock
            # (held by release) so racing releases cannot all pass.
            if sanitizer.enabled:
                sanitizer.fail(
                    "double release of a staging owner buffer "
                    f"({raw.nbytes} bytes): already pooled or "
                    "checked out — repooling would alias two "
                    "later acquires")
            return
        dq = self._free.get(cls)
        if dq is None:
            dq = self._free[cls] = deque()
        dq.append(raw)
        if raw.base is not None:            # adopted foreign owner
            self._adopted.add(id(raw.base))
        self._free.move_to_end(cls)
        self._bytes += raw.nbytes
        # evict ONE cold buffer at a time from the least-recently-
        # used class — never the hot class we just touched
        while self._bytes > self.max_bytes and self._free:
            cold_cls, cold = next(iter(self._free.items()))
            victim = cold.popleft()      # front = coldest
            if victim.base is not None:
                self._adopted.discard(id(victim.base))
            self._bytes -= victim.nbytes
            if not cold:
                del self._free[cold_cls]

    def clear(self) -> None:
        with self._lock:
            self._free.clear()
            self._out.clear()
            self._adopted.clear()
            self._bytes = 0
            self.hits = self.misses = 0

    def stats(self) -> dict:
        """Occupancy snapshot for the telemetry sampler: pooled bytes,
        outstanding checkouts, lifetime hit/miss counts."""
        with self._lock:
            return {"bytes": self._bytes, "out": len(self._out),
                    "hits": self.hits, "misses": self.misses}


staging = _StagingPool()

# staging-pool occupancy for otpu_top (sampler-thread-only provider)
from ompi_tpu.runtime import telemetry as _telemetry

_telemetry.register_source("staging", staging.stats)


def staging_acquire(shape, dtype) -> np.ndarray:
    """Checkout a host staging buffer (contents undefined)."""
    return staging.acquire(shape, dtype)


def staging_release(buf: np.ndarray) -> None:
    """Return a buffer checked out with :func:`staging_acquire`."""
    staging.release(buf)


def is_device_array(x: Any) -> bool:
    """True if x is a jax.Array whose committed home is an accelerator."""
    try:
        import jax
    except ImportError:  # pragma: no cover
        return False
    # Any jax.Array takes the XLA collective path — CPU-backed jax Arrays
    # included (virtual-device meshes in tests): the mesh is what matters,
    # not the platform.
    return isinstance(x, jax.Array)


def to_host(x) -> np.ndarray:
    """Stage a device array to host memory (D2H)."""
    return np.asarray(x)


def from_host(arr: np.ndarray, sharding=None):
    """Stage host memory to device (H2D), optionally sharded."""
    import jax

    return jax.device_put(arr, sharding)


def register(buf: np.ndarray, key: Any = None):
    """Expose a host region (RMA window registration)."""
    addr = buf.__array_interface__["data"][0]
    _rcache.insert(addr, addr + buf.nbytes, key or buf)
    return addr


def deregister(buf: np.ndarray) -> None:
    addr = buf.__array_interface__["data"][0]
    _rcache.delete(addr, addr + buf.nbytes)


def lookup(addr: int, nbytes: int):
    hit = _rcache.find_containing(addr, addr + nbytes)
    return None if hit is None else hit[2]


class JaxAcceleratorComponent(Component):
    name = "jax"
    priority = 50

    def open(self) -> bool:
        try:
            import jax  # noqa: F401

            return True
        except ImportError:  # pragma: no cover
            return False


COMPONENT = JaxAcceleratorComponent()

from ompi_tpu.base.output import register_help as _rh

_rh("help-accel-staging", "non-contiguous-release",
    "A non-C-contiguous buffer (shape {shape}, dtype {dtype}) was "
    "released to the staging pool and cannot be repooled: staging "
    "checkouts are contiguous, so a transformed (transposed/strided) "
    "array points at a layout bug in the caller.  The buffer is "
    "dropped; this warning is shown once.")
