"""accelerator — device-memory framework.

Equivalent of the reference CUDA glue (``/root/reference/opal/mca/common/
cuda/common_cuda.c`` — dlopen'd driver table, ``opal_cuda_check_bufs``
residency test) re-designed for TPU: residency checks on ``jax.Array``,
HBM/host staging, and pinned-host allocation for the BTL bounce path.
"""
