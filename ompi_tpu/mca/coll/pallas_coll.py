"""coll/pallas — explicit remote-DMA ring collectives (ICI p2p path).

Slots in below coll/xla (priority 85 < 90): XLA's compiler-scheduled
collectives stay the default, and this component is the explicit-schedule
alternative — ring allreduce / reduce-scatter / all-gather / pipelined
bcast / neighbor permute written directly against the interconnect with
``pltpu.make_async_remote_copy`` (``ompi_tpu/ops/pallas_collectives.py``).
Reductions cover sum/max/min/prod; payloads above ``vmem_max_bytes``
use the segmented HBM-resident kernels (bounded VMEM window), so the
size ceiling is HBM (``max_bytes``), not VMEM; ``bidirectional`` routes
fused-size all-reduces over both ICI directions at once.  Raise
``--mca coll_pallas_priority 95`` to make it own these slots; any call
shape it does not cover (MINLOC/user ops, general permutations) delegates
to the next module in the comm's stack, the way coll/tuned falls through
to coll/basic.

Capability probe: real multi-chip TPU runs the compiled kernels;
elsewhere (tests, virtual CPU meshes) they run in Pallas interpreter
mode — override with ``--mca coll_pallas_interpret 0/1``.

Reference slot: the explicit BTL RDMA transport
(``opal/mca/btl/btl.h:949,987``) + its ring schedules
(``coll_base_allreduce.c:341``), per SURVEY.md §2.6's "Pallas remote
DMA" row.
"""
from __future__ import annotations

import numpy as np

from ompi_tpu.api import op as op_mod
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType


#: MPI op name -> ring-kernel fold name (ompi_tpu/ops/pallas_collectives)
_RING_OPS = {"SUM": "sum", "MAX": "max", "MIN": "min", "PROD": "prod"}

#: per-rank payload ceiling when the kernels run in the Pallas
#: interpreter (tests, virtual meshes): the interpreter executes the
#: segment loop in Python, so routing arbitrarily large payloads to it
#: would turn sub-second coll/xla calls into minutes — above this,
#: delegate regardless of max_bytes
_INTERPRET_MAX_BYTES = 16 << 20


class PallasCollModule:
    def __init__(self, comm, devices, axis_name: str, interpret: bool,
                 max_bytes: int, vmem_max_bytes: int,
                 seg_bytes: int, bidirectional: bool,
                 min_bytes: int = 0, wire16: bool = False) -> None:
        import jax
        from jax.sharding import Mesh

        self.devices = list(devices)
        self.axis = axis_name
        self.mesh = Mesh(np.array(self.devices), (axis_name,))
        self.n = len(self.devices)
        self.interpret = interpret
        self.max_bytes = max_bytes
        self.min_bytes = min_bytes
        self.vmem_max_bytes = vmem_max_bytes
        self.seg_bytes = seg_bytes
        self.bidirectional = bidirectional
        self.wire16 = wire16
        self._jax_array = jax.Array
        self._fallback = None   # resolved at comm_enable

    def comm_enable(self, comm) -> None:
        # next-lower provider of the device-array slots (normally
        # coll/xla): unsupported calls fall through to it
        from ompi_tpu.mca.coll.xla import XlaCollModule

        self._fallback = next(
            (m for m in comm.coll_modules if isinstance(m, XlaCollModule)),
            None)

    # -- helpers ---------------------------------------------------------
    def _delegate(self, name, comm, x, *args):
        if self._fallback is None:
            from ompi_tpu.api.errors import ErrorClass, MpiError

            raise MpiError(ErrorClass.ERR_UNSUPPORTED_OPERATION,
                           f"coll/pallas cannot run {name} and no "
                           "fallback module is present")
        return getattr(self._fallback, name)(comm, x, *args)

    def _place(self, comm, x):
        if isinstance(x, self._jax_array):
            return x
        if self._fallback is not None:
            return self._fallback._check(comm, x)
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(
            np.asarray(x), NamedSharding(self.mesh, P(self.axis)))

    def _size_ok(self, x) -> bool:
        cap = self.max_bytes
        if self.interpret:
            cap = min(cap, _INTERPRET_MAX_BYTES)
        per_rank = x.nbytes // max(1, self.n)
        return self.min_bytes <= per_rank <= cap

    def _supported(self, x) -> bool:
        return x.dtype.kind == "f" and self._size_ok(x)

    def _route(self, x):
        """Pick the accumulator regime from the per-rank payload size:
        fused VMEM kernel below ``vmem_max_bytes``, segmented HBM kernel
        (bounded VMEM window of ``seg_bytes``) above — the selection the
        reference's tuned ladder does between its linear and segmented
        rings (``coll_base_allreduce.c:618``)."""
        per_rank = x.nbytes // max(1, self.n)
        if per_rank > self.vmem_max_bytes:
            seg_elems = max(1, self.seg_bytes // x.dtype.itemsize)
            return (("seg_bidi" if self.bidirectional else "seg"),
                    seg_elems)
        if self.bidirectional:
            return "bidi", None
        return "fused", None

    def _allreduce_variant(self, x, ring_op):
        """ONE routing rule for one-shot AND persistent allreduce (a
        persistent handle must never diverge numerically from the
        one-shot slot it mirrors)."""
        variant, seg_elems = self._route(x)
        if (self.wire16 and ring_op == "sum"
                and str(x.dtype) == "float32" and variant == "fused"):
            # opt-in compressed wire (f32 acc, bf16 bytes); only the
            # fused regime has a wire16 kernel so far
            variant = "wire16"
        return variant, seg_elems

    def _reduce_scatter_variant(self, x, ring_op):
        """ONE routing rule for one-shot AND persistent reduce_scatter
        (same never-diverge contract as ``_allreduce_variant``)."""
        variant, seg_elems = self._route(x)
        if variant == "bidi":        # no bidi reduce-scatter kernel (yet)
            variant, seg_elems = "fused", None
        elif variant == "seg_bidi":  # ...so large payloads keep the
            variant = "seg"          # segmented HBM bound unidirectional
        if (self.wire16 and ring_op == "sum"
                and str(x.dtype) == "float32" and variant == "fused"):
            variant = "wire16"       # same opt-in codec as allreduce
        return variant, seg_elems

    # -- collective slots ------------------------------------------------
    def allreduce_array(self, comm, x, op: op_mod.Op = op_mod.SUM):
        x = self._place(comm, x)
        ring_op = _RING_OPS.get(op.name)
        if ring_op is None or not self._supported(x):
            return self._delegate("allreduce_array", comm, x, op)
        from ompi_tpu.ops import pallas_collectives as pc

        variant, seg_elems = self._allreduce_variant(x, ring_op)
        return pc.all_reduce(x, self.mesh, self.axis, ring_op,
                             interpret=self.interpret, variant=variant,
                             seg_elems=seg_elems)

    def allgather_array(self, comm, x):
        x = self._place(comm, x)
        if not self._supported(x):
            return self._delegate("allgather_array", comm, x)
        from ompi_tpu.ops import pallas_collectives as pc

        # same duplex opt-in as the reduce rings: both ICI directions
        # carry blocks, ceil((n-1)/2) steps instead of n-1
        variant = "bidi" if self.bidirectional else "ring"
        return pc.all_gather(x, self.mesh, self.axis,
                             interpret=self.interpret, variant=variant)

    def reduce_scatter_array(self, comm, x, op: op_mod.Op = op_mod.SUM):
        x = self._place(comm, x)
        ring_op = _RING_OPS.get(op.name)
        if ring_op is None or not self._supported(x):
            return self._delegate("reduce_scatter_array", comm, x, op)
        from ompi_tpu.ops import pallas_collectives as pc

        variant, seg_elems = self._reduce_scatter_variant(x, ring_op)
        return pc.reduce_scatter(x, self.mesh, self.axis, ring_op,
                                 interpret=self.interpret, variant=variant,
                                 seg_elems=seg_elems)

    def alltoall_array(self, comm, x):
        x = self._place(comm, x)
        # pure DMA, no arithmetic: any dtype qualifies — only size and
        # the (n, n, *S) layout gate (a malformed shape must surface as
        # coll/xla's MpiError, not an out-of-bounds remote DMA)
        if (not self._size_ok(x) or x.ndim < 2
                or x.shape[0] != self.n or x.shape[1] != self.n):
            return self._delegate("alltoall_array", comm, x)
        from ompi_tpu.ops import pallas_collectives as pc

        return pc.all_to_all(x, self.mesh, self.axis,
                             interpret=self.interpret)

    def alltoallv_array(self, comm, x, counts):
        """True ragged alltoallv: per-pair explicit chunked DMAs sized
        by the runtime counts table (``ops.pallas_collectives.
        all_to_all_v``) instead of coll/xla's padded all_to_all +
        host-side slicing — wire bytes follow the raggedness, the MoE/
        EP dispatch contract (``coll_base_alltoall.c`` pairwise)."""
        x = self._place(comm, x)
        if (not self._size_ok(x) or x.ndim != 4
                or x.shape[0] != self.n or x.shape[1] != self.n
                or x.shape[3] % 128 != 0):
            return self._delegate("alltoallv_array", comm, x, counts)
        import numpy as np

        if np.asarray(counts).shape != (self.n, self.n):
            # same error contract as coll/xla: malformed counts surface
            # as MpiError, never as a bad SMEM table / IndexError
            from ompi_tpu.api.errors import ErrorClass, MpiError

            raise MpiError(
                ErrorClass.ERR_BUFFER,
                f"alltoallv needs an ({self.n}, {self.n}) counts "
                f"table, got {np.asarray(counts).shape}")
        from ompi_tpu.ops import pallas_collectives as pc

        full = pc.all_to_all_v(x, np.asarray(counts, np.int32),
                               self.mesh, self.axis,
                               interpret=self.interpret)
        # same return contract as coll/xla's alltoallv_array: sliced
        # zero-copy views, out[i][j] = what rank i received from j
        return [[full[i, j, :int(counts[j][i])] for j in range(self.n)]
                for i in range(self.n)]

    def allgatherv_array(self, comm, x, counts):
        """True ragged allgatherv: the ring forwards each block as
        count-sized chunked DMAs (``ops.pallas_collectives.
        all_gather_v``) instead of coll/xla's padded all_gather —
        wire bytes follow the raggedness."""
        x = self._place(comm, x)
        if (not self._size_ok(x) or x.ndim != 3
                or x.shape[0] != self.n or x.shape[2] % 128 != 0):
            return self._delegate("allgatherv_array", comm, x, counts)
        if len(counts) != self.n:
            # coll/xla's error contract (allgatherv_array)
            from ompi_tpu.api.errors import ErrorClass, MpiError

            raise MpiError(
                ErrorClass.ERR_BUFFER,
                f"allgatherv needs {self.n} counts, got {len(counts)}")
        from ompi_tpu.ops import pallas_collectives as pc

        full = pc.all_gather_v(x, list(counts), self.mesh, self.axis,
                               interpret=self.interpret)
        # coll/xla return contract: per-rank views sliced to counts[i]
        return [full[i, :int(counts[i])] for i in range(self.n)]

    def persistent_coll(self, comm, coll: str, template, *args):
        """MPI_*_init analog bound to the CACHED pallas jitted program:
        when this component owns the slot, the persistent handle
        dispatches the explicit-DMA ring, not the coll/xla program.
        Shapes/ops the ring does not serve bind through the fallback
        provider (same per-call delegation discipline as the one-shot
        slots)."""
        from ompi_tpu.mca.coll.xla import PersistentColl

        template = self._place(comm, template)
        op = args[0] if args else op_mod.SUM
        ring_op = _RING_OPS.get(getattr(op, "name", "SUM"))
        supported = (coll in ("allreduce", "reduce_scatter")
                     and ring_op is not None
                     and self._supported(template)) or \
                    (coll == "bcast" and self._size_ok(template)) or \
                    (coll == "allgather" and self._supported(template))
        if not supported:
            return self._delegate("persistent_coll", comm, coll,
                                  template, *args)
        # bind through the PUBLIC wrappers: they own the n==1 fast
        # path, padding, and the lru-cached jitted program (so repeated
        # start() is a cache hit, not a retrace)
        from ompi_tpu.ops import pallas_collectives as pc

        if coll == "allreduce":
            variant, seg_elems = self._allreduce_variant(template,
                                                         ring_op)

            def fn(x, v=variant, s=seg_elems):
                return pc.all_reduce(x, self.mesh, self.axis, ring_op,
                                     interpret=self.interpret,
                                     variant=v, seg_elems=s)
        elif coll == "reduce_scatter":
            variant, seg_elems = self._reduce_scatter_variant(template,
                                                              ring_op)

            def fn(x, v=variant, s=seg_elems):
                return pc.reduce_scatter(x, self.mesh, self.axis,
                                         ring_op,
                                         interpret=self.interpret,
                                         variant=v, seg_elems=s)
        elif coll == "allgather":
            # same routing as the one-shot slot (never-diverge contract)
            variant = "bidi" if self.bidirectional else "ring"

            def fn(x, v=variant):
                return pc.all_gather(x, self.mesh, self.axis,
                                     interpret=self.interpret, variant=v)
        else:   # bcast: root baked into the handle, one shared program
            root = int(args[0]) % self.n if args else 0
            seg_elems = max(1, self.seg_bytes // template.dtype.itemsize)

            def fn(x, r=root, s=seg_elems):
                return pc.bcast(x, self.mesh, self.axis, root=r,
                                interpret=self.interpret, seg_elems=s)
        fn(template)    # build + cache + validate now, not at start()
        return PersistentColl(fn, coll, int(template.nbytes))

    def bcast_array(self, comm, x, root: int = 0):
        x = self._place(comm, x)
        # pure DMA, no arithmetic: any dtype qualifies — only size gates
        if not self._size_ok(x):
            return self._delegate("bcast_array", comm, x, root)
        from ompi_tpu.ops import pallas_collectives as pc

        seg_elems = max(1, self.seg_bytes // x.dtype.itemsize)
        return pc.bcast(x, self.mesh, self.axis, root=root,
                        interpret=self.interpret, seg_elems=seg_elems)

    def psum_scatter_array(self, comm, x):
        # the SUM reduce-scatter by another name (coll/xla parity)
        return self.reduce_scatter_array(comm, x, op_mod.SUM)

    def ppermute_array(self, comm, x, perm):
        perm = tuple((int(s), int(d)) for s, d in perm)
        rot = tuple((i, (i + 1) % self.n) for i in range(self.n))
        x = self._place(comm, x)
        if perm != rot or not self._supported(x):
            return self._delegate("ppermute_array", comm, x, perm)
        from ompi_tpu.ops import pallas_collectives as pc

        return pc.right_permute(x, self.mesh, self.axis,
                                interpret=self.interpret)


class PallasCollComponent(Component):
    name = "pallas"
    priority = 85

    def register_vars(self, fw) -> None:
        self._prio = self.register_var(
            "priority", vtype=VarType.INT, default=85,
            help="Selection priority of coll/pallas (explicit remote-DMA "
                 "ring collectives); raise above coll/xla's 90 to select")
        self._interpret = self.register_var(
            "interpret", vtype=VarType.STRING, default="auto",
            help="Run kernels in Pallas interpreter mode: auto = only off "
                 "real TPU devices, 0/1 to force")
        self._min = self.register_var(
            "min_bytes", vtype=VarType.SIZE, default="0",
            help="Smallest per-rank payload routed to the DMA ring; "
                 "smaller calls fall through to coll/xla (latency-bound "
                 "small collectives are usually better "
                 "compiler-scheduled — derive the crossover from "
                 "LADDER_PROBE.json on real hardware)")
        self._max = self.register_var(
            "max_bytes", vtype=VarType.SIZE, default="1g",
            help="Largest per-rank payload routed to the DMA ring; "
                 "bigger calls fall through to coll/xla.  Large payloads "
                 "use the segmented HBM-resident kernels, so this bounds "
                 "HBM, not VMEM")
        self._vmem_max = self.register_var(
            "vmem_max_bytes", vtype=VarType.SIZE, default="8m",
            help="Per-rank payload crossover from the fused all-VMEM "
                 "ring kernel to the segmented HBM-resident one "
                 "(bounded VMEM window).  The default is the "
                 "Mosaic-measured ceiling: on a v5e-8 topology the "
                 "fused kernel's acc+recv footprint compiles at 8MB "
                 "per-rank payload and is VMEM-exhausted at 16MB "
                 "(pallas_aot round-5 probe)")
        self._seg = self.register_var(
            "seg_bytes", vtype=VarType.SIZE, default="512k",
            help="VMEM window size per buffer for the segmented ring "
                 "kernels (two double-buffered windows this size)")
        self._bidi = self.register_var(
            "bidirectional", vtype=VarType.BOOL, default=False,
            help="Use the bidirectional (duplex) ring schedules: "
                 "all-reduce carries half the payload in each ICI "
                 "direction per step (fused sizes; seg_bidi above the "
                 "VMEM bound), and allgather ships blocks both ways in "
                 "ceil((n-1)/2) steps instead of n-1")
        self._wire16 = self.register_var(
            "wire16", vtype=VarType.BOOL, default=False,
            help="Opt-in wire compression for float32 SUM allreduce: "
                 "f32 accumulation, bf16 bytes on the ICI — halves "
                 "per-step wire time at bf16 value precision "
                 "(bit-identical across ranks; worst-case error "
                 "O(n*2^-8) relative to partial magnitudes).  Changes "
                 "numerics, so never on by default")
        self._axis = self.register_var(
            "axis_name", default="mpi",
            help="Mesh axis name for coll/pallas kernels")

    def _interpret_mode(self, devices) -> bool:
        v = str(self._interpret.value or "auto").strip().lower()
        if v in ("0", "false", "no"):
            return False
        if v in ("1", "true", "yes"):
            return True
        return not all(
            getattr(d, "platform", "") == "tpu" for d in devices)

    def comm_query(self, comm):
        rte = comm.rte
        if rte is None or not rte.is_device_world:
            return None
        try:
            devices = [rte.device_of(r) for r in comm.group.world_ranks]
        except Exception:
            return None
        if not devices or any(d is None for d in devices):
            return None
        return self._prio.value, PallasCollModule(
            comm, devices, self._axis.value,
            self._interpret_mode(devices), int(self._max.value),
            vmem_max_bytes=int(self._vmem_max.value),
            seg_bytes=int(self._seg.value),
            bidirectional=bool(self._bidi.value),
            min_bytes=int(self._min.value),
            wire16=bool(self._wire16.value))


COMPONENT = PallasCollComponent()
