"""coll/tuned — the decision layer picking algorithms from the menu.

Re-design of ``/root/reference/ompi/mca/coll/tuned/``: *fixed rules* =
hardcoded (commutativity, comm_size, message_size) ladders per collective
(``coll_tuned_decision_fixed.c:55-124`` — thresholds there are Ethernet/IB-
derived; the ladders here are re-derived for the host/DCN path of a TPU
deployment and keep the same structure and the same non-commutative
exclusions ``:77-80``), *dynamic rules* = a runtime-loaded rule file
(``coll_tuned_component.c:232-236``), and per-collective force-MCA-vars
(``otpu_coll_tuned_<coll>_algorithm``) overriding both.

Priority 30 — above coll/basic (10) so the tuned ladders own the host
collectives on multi-process communicators, below coll/xla (90) which owns
the device-array path.

Dynamic rule file format (one rule per line, first match wins)::

    # coll  max_comm_size  max_bytes  algorithm  [segsize]
    allreduce  8  4096  recursive_doubling
    allreduce  0  0     ring            # 0 = unbounded
"""
from __future__ import annotations

import numpy as np

from ompi_tpu.api import op as op_mod
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType
from ompi_tpu.mca.coll import algorithms as algs
from ompi_tpu.mca.coll.basic import BasicCollModule

_MENUS = {
    "allreduce": algs.ALLREDUCE,
    "bcast": algs.BCAST,
    "reduce": algs.REDUCE,
    "allgather": algs.ALLGATHER,
    "alltoall": algs.ALLTOALL,
    "barrier": algs.BARRIER,
    "reduce_scatter": algs.REDUCE_SCATTER,
    "gather": algs.GATHER,
    "scatter": algs.SCATTER,
}


def _nbytes(buf) -> int:
    return np.asarray(buf).nbytes


class TunedModule:
    """Per-communicator module: ladder dispatch over the algorithm menu."""

    def __init__(self, component: "TunedCollComponent"):
        self._c = component
        self._basic = BasicCollModule()

    # -- decision machinery ---------------------------------------------
    def _pick(self, coll: str, comm_size: int, nbytes: int,
              default: str) -> str:
        forced = self._c.force_var(coll)
        if forced:
            return forced
        for (rcoll, max_size, max_bytes, alg, _seg) in self._c.rules:
            if rcoll != coll:
                continue
            if max_size and comm_size > max_size:
                continue
            if max_bytes and nbytes > max_bytes:
                continue
            return alg
        return default

    def _run(self, coll: str, alg: str, *args, **kw):
        menu = _MENUS[coll]
        fn = menu.get(alg)
        if fn is None:
            from ompi_tpu.base.output import show_help

            show_help("help-coll-tuned", "unknown-algorithm",
                      coll=coll, alg=alg, known=", ".join(sorted(menu)))
            fn = next(iter(menu.values()))
        return fn(*args, **kw)

    # -- fixed ladders (decision_fixed.c shape, TPU-host re-derivation) --
    def allreduce(self, comm, sendbuf, op=op_mod.SUM):
        nbytes = _nbytes(sendbuf)
        if not op.commute:
            # ring/Rabenseifner reorder operands -> excluded (:77-80)
            alg = "nonoverlapping" if comm.size <= 4 else "recursive_doubling"
        elif nbytes < 4096:
            alg = "recursive_doubling"
        elif nbytes < (512 << 10):
            alg = "rabenseifner"
        elif nbytes < (4 << 20):
            alg = "ring"
        else:
            alg = "ring_segmented"
        alg = self._pick("allreduce", comm.size, nbytes, alg)
        if alg == "ring_segmented":
            return algs.allreduce_ring_segmented(
                comm, sendbuf, op, segsize=self._c.segsize("allreduce"))
        return self._run("allreduce", alg, comm, sendbuf, op)

    def bcast(self, comm, buf, root=0):
        nbytes = _nbytes(buf)
        if nbytes < 2048 or comm.size <= 4:
            alg = "binomial"
        elif nbytes < (1 << 20):
            alg = "scatter_allgather"
        else:
            alg = "chain"
        alg = self._pick("bcast", comm.size, nbytes, alg)
        if alg == "chain":
            return algs.bcast_chain(comm, buf, root,
                                    segsize=self._c.segsize("bcast"))
        return self._run("bcast", alg, comm, buf, root)

    def reduce(self, comm, sendbuf, op=op_mod.SUM, root=0):
        nbytes = _nbytes(sendbuf)
        if not op.commute:
            # binomial reorders; pipeline and linear are rank-ordered
            alg = "linear" if nbytes < (64 << 10) else "pipeline"
        elif nbytes < (64 << 10):
            alg = "binomial"
        else:
            alg = "pipeline"
        alg = self._pick("reduce", comm.size, nbytes, alg)
        if alg == "pipeline":
            return algs.reduce_pipeline(comm, sendbuf, op, root,
                                        segsize=self._c.segsize("reduce"))
        return self._run("reduce", alg, comm, sendbuf, op, root)

    def allgather(self, comm, sendbuf):
        nbytes = _nbytes(sendbuf)
        if comm.size <= 2:
            alg = "linear"
        elif nbytes < 1024:
            alg = "bruck"
        elif nbytes < (512 << 10):
            alg = "recursive_doubling"   # falls back to bruck for non-pof2
        else:
            alg = "neighbor"             # falls back to ring for odd sizes
        alg = self._pick("allgather", comm.size, nbytes, alg)
        return self._run("allgather", alg, comm, sendbuf)

    def alltoall(self, comm, sendbuf):
        stack = np.asarray(sendbuf)
        per_block = stack.nbytes // max(1, stack.shape[0] if stack.ndim else 1)
        if comm.size <= 2:
            alg = "linear"
        elif per_block < 256:
            alg = "bruck"
        else:
            alg = "pairwise"
        alg = self._pick("alltoall", comm.size, int(per_block), alg)
        return self._run("alltoall", alg, comm, sendbuf)

    def barrier(self, comm):
        alg = "recursive_doubling" if not (comm.size & (comm.size - 1)) \
            else "bruck"
        alg = self._pick("barrier", comm.size, 0, alg)
        return self._run("barrier", alg, comm)

    def reduce_scatter(self, comm, sendbuf, recvcounts=None, op=op_mod.SUM):
        nbytes = _nbytes(sendbuf)
        if not op.commute:
            alg = "basic"                # reduce+scatter keeps rank order
        elif nbytes < (64 << 10):
            alg = "recursive_halving"
        else:
            alg = "ring"
        alg = self._pick("reduce_scatter", comm.size, nbytes, alg)
        return self._run("reduce_scatter", alg, comm, sendbuf, recvcounts, op)

    def gather(self, comm, sendbuf, root=0):
        nbytes = _nbytes(sendbuf)
        alg = "binomial" if nbytes < (64 << 10) else "linear"
        alg = self._pick("gather", comm.size, nbytes, alg)
        return self._run("gather", alg, comm, sendbuf, root)

    def scatter(self, comm, sendbuf, root=0):
        nbytes = _nbytes(sendbuf)
        alg = "binomial" if nbytes < (64 << 10) else "linear"
        alg = self._pick("scatter", comm.size, nbytes, alg)
        return self._run("scatter", alg, comm, sendbuf, root)


class TunedCollComponent(Component):
    name = "tuned"
    priority = 30

    def register_vars(self, fw) -> None:
        self._prio = self.register_var(
            "priority", vtype=VarType.INT, default=30,
            help="Selection priority of coll/tuned")
        self._rules_file = self.register_var(
            "dynamic_rules_filename", vtype=VarType.STRING, default="",
            help="Path to a dynamic decision-rule file "
                 "(coll_tuned_component.c:232 equivalent)")
        self._force: dict[str, object] = {}
        self._seg: dict[str, object] = {}
        for coll, menu in _MENUS.items():
            self._force[coll] = self.register_var(
                f"{coll}_algorithm", vtype=VarType.STRING, default="",
                help=f"Force a {coll} algorithm: one of "
                     f"{', '.join(sorted(menu))} (empty = decision ladder)")
        for coll, default in (("allreduce", 1 << 20), ("bcast", 1 << 17),
                              ("reduce", 1 << 17)):
            self._seg[coll] = self.register_var(
                f"{coll}_segsize", vtype=VarType.INT, default=default,
                help=f"Segment size in bytes for segmented {coll} algorithms")
        self.rules: list[tuple] = []

    def open(self) -> bool:
        self.rules = []
        path = (self._rules_file.value or "").strip()
        if path:
            try:
                self.rules = _load_rules(path)
            except OSError as exc:
                from ompi_tpu.base.output import show_help

                show_help("help-coll-tuned", "bad-rules-file",
                          path=path, error=str(exc))
        return True

    def force_var(self, coll: str) -> str:
        v = self._force.get(coll)
        return (v.value or "").strip() if v is not None else ""

    def segsize(self, coll: str) -> int:
        v = self._seg.get(coll)
        return int(v.value) if v is not None else 1 << 20

    def comm_query(self, comm):
        if comm.rte is not None and comm.rte.is_device_world:
            return None   # conductor/xla own the device world
        if comm.size == 1:
            return None
        return self._prio.value, TunedModule(self)


def _load_rules(path: str) -> list[tuple]:
    rules = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            if len(parts) not in (4, 5):
                raise OSError(f"line {lineno}: expected "
                              "'coll max_size max_bytes alg [segsize]'")
            coll, max_size, max_bytes, alg = parts[:4]
            seg = int(parts[4]) if len(parts) == 5 else 0
            if coll not in _MENUS:
                raise OSError(f"line {lineno}: unknown collective {coll!r}")
            if alg not in _MENUS[coll]:
                raise OSError(f"line {lineno}: unknown {coll} algorithm "
                              f"{alg!r}")
            rules.append((coll, int(max_size), int(max_bytes), alg, seg))
    return rules


COMPONENT = TunedCollComponent()

from ompi_tpu.base.output import register_help as _rh

_rh("help-coll-tuned", "unknown-algorithm",
    "coll/tuned was asked for {coll} algorithm {alg!r} but only knows: "
    "{known}; using the first available instead.")
_rh("help-coll-tuned", "bad-rules-file",
    "coll/tuned could not load the dynamic rules file {path!r}: {error}. "
    "Falling back to the fixed decision ladder.")
