"""coll/tuned — the decision layer picking algorithms from the menu.

Re-design of ``/root/reference/ompi/mca/coll/tuned/``: *fixed rules* =
hardcoded (commutativity, comm_size, message_size) ladders per collective
(``coll_tuned_decision_fixed.c:55-124`` — thresholds there are Ethernet/IB-
derived; the ladders here are re-derived for the host/DCN path of a TPU
deployment and keep the same structure and the same non-commutative
exclusions ``:77-80``), *dynamic rules* = a runtime-loaded rule file
(``coll_tuned_component.c:232-236``), and per-collective force-MCA-vars
(``otpu_coll_tuned_<coll>_algorithm``) overriding both.

Priority 30 — above coll/basic (10) so the tuned ladders own the host
collectives on multi-process communicators, below coll/xla (90) which owns
the device-array path.

Dynamic rule file format (one rule per line, first match wins)::

    # coll  max_comm_size  max_bytes  algorithm  [segsize]
    allreduce  8  4096  recursive_doubling
    allreduce  0  0     ring            # 0 = unbounded
"""
from __future__ import annotations

import numpy as np

from ompi_tpu.api import op as op_mod
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType
from ompi_tpu.mca.coll import algorithms as algs
from ompi_tpu.mca.coll import quant as quant_mod
from ompi_tpu.mca.coll.basic import BasicCollModule
from ompi_tpu.runtime import profile, spc
from ompi_tpu.runtime.hotpath import hot_path

_MENUS = {
    "allreduce": algs.ALLREDUCE,
    "bcast": algs.BCAST,
    "reduce": algs.REDUCE,
    "allgather": algs.ALLGATHER,
    "alltoall": algs.ALLTOALL,
    "barrier": algs.BARRIER,
    "reduce_scatter": algs.REDUCE_SCATTER,
    "gather": algs.GATHER,
    "scatter": algs.SCATTER,
}

#: the DEVICE-tier ladder cells (ops/pallas_overlap): communication-
#: fused matmul programs consulted by name from jit-adjacent call sites
#: (the MoE expert FFN, parallel/moe.py).  Deliberately NOT rows in
#: ``_MENUS`` — host menu entries take ``(comm, buf, ...)`` while these
#: take ``(a, b, mesh, axis)`` — but they ride the same component so
#: one force-var surface (``otpu_coll_tuned_fused_cells``) governs both
#: tiers' overrides.
DEVICE_CELLS = ("matmul_allreduce", "matmul_reduce_scatter")


def device_cell(name: str):
    """Resolve a device-tier fused ladder cell, honoring the force-var.

    Returns the ``ops/pallas_overlap`` kernel callable, or None when
    the fused tier is disabled (``fused_cells=off``) or the var forces
    a DIFFERENT cell — the caller then falls back to its unfused
    einsum+psum form, mirroring ``_run``'s safe-default discipline."""
    if name not in DEVICE_CELLS:
        raise KeyError(f"no device ladder cell {name!r} (known: "
                       f"{', '.join(DEVICE_CELLS)})")
    forced = COMPONENT.fused_cells_var()
    if forced == "off" or (forced and forced != name):
        return None
    from ompi_tpu.ops import pallas_overlap

    return getattr(pallas_overlap, name)


def _nbytes(buf) -> int:
    # ndarrays answer .nbytes directly — np.asarray on the hot path
    # costs a dispatch + possible copy for list inputs
    n = getattr(buf, "nbytes", None)
    return n if n is not None else np.asarray(buf).nbytes


def default_algorithm(coll: str, comm_size: int, nbytes: int,
                      commute: bool = True,
                      per_block: int = None) -> str:
    """The fixed decision ladder's pick for one (coll, comm_size,
    nbytes) cell — the ``decision_fixed.c`` tables as a pure function.

    ONE home for the ladder: the per-communicator :class:`TunedModule`
    dispatch methods call it on every invocation, and ``otpu_analyze
    --suggest-ladder`` calls it to name the incumbent algorithm for the
    critical-path-hot cells its draft rules file pins (a rules file
    that disagreed with the ladder it documents would be a lie).

    ``per_block`` is the alltoall per-destination block size (derived
    from ``nbytes / comm_size`` when not supplied — the dispatch method
    passes the exact value).
    """
    if coll == "allreduce":
        if not commute:
            # ring/Rabenseifner reorder operands -> excluded (:77-80)
            return "nonoverlapping" if comm_size <= 4 \
                else "recursive_doubling"
        if nbytes <= 4096:
            # boundary inclusive: rd measured ~1.9x rabenseifner at
            # exactly 4KB on the 4-rank host path (matches the lane)
            return "recursive_doubling"
        if nbytes < (512 << 10):
            return "rabenseifner"
        if nbytes < (4 << 20):
            return "ring"
        return "ring_segmented"
    if coll == "bcast":
        if nbytes < 2048 or comm_size <= 4:
            return "binomial"
        return "scatter_allgather" if nbytes < (1 << 20) else "chain"
    if coll == "reduce":
        if not commute:
            # binomial reorders; pipeline and linear are rank-ordered
            return "linear" if nbytes < (64 << 10) else "pipeline"
        return "binomial" if nbytes < (64 << 10) else "pipeline"
    if coll == "allgather":
        if comm_size <= 2:
            return "linear"
        if nbytes < 1024:
            return "bruck"
        if nbytes < (512 << 10):
            return "recursive_doubling"  # falls to bruck for non-pof2
        return "neighbor"                # falls to ring for odd sizes
    if coll == "alltoall":
        if per_block is None:
            per_block = nbytes // max(1, comm_size)
        if comm_size <= 2:
            return "linear"
        return "bruck" if per_block < 256 else "pairwise"
    if coll == "barrier":
        return "recursive_doubling" \
            if not (comm_size & (comm_size - 1)) else "bruck"
    if coll == "reduce_scatter":
        if not commute:
            return "basic"           # reduce+scatter keeps rank order
        return "recursive_halving" if nbytes < (64 << 10) else "ring"
    if coll in ("gather", "scatter"):
        return "binomial" if nbytes < (64 << 10) else "linear"
    raise KeyError(f"no fixed ladder for collective {coll!r}")


def ladder_rules(coll: str, comm_size: int, cap_bytes: int,
                 commute: bool = True) -> list[tuple[int, str]]:
    """The fixed ladder as ascending ``(max_bytes, algorithm)`` rule
    rows whose first-match-wins evaluation reproduces
    :func:`default_algorithm` EXACTLY for every ``nbytes <= cap_bytes``
    (sizes above the cap fall through the rule list back to the fixed
    ladder itself, which picks the same incumbent — so a rules file
    built from these rows is behavior-identical by construction).

    ``otpu_analyze --suggest-ladder`` uses this: emitting only a hot
    cell's own row would silently extend that cell's pick to every
    smaller message (the grammar has no lower bound); emitting the
    whole breakpoint table keeps the draft honest.

    Thresholds are powers of two in total bytes (``<=`` or ``<``
    style) or per-destination-block bytes (alltoall: pow2 times
    ``comm_size``), so probing each boundary's two sides at ``2^k``
    and ``2^k * comm_size`` finds every breakpoint."""
    probes: set = set()
    n = 1
    while n <= (1 << 40):
        probes.update((n, n + 1, n * max(1, comm_size),
                       n * max(1, comm_size) + 1))
        n <<= 1
    rows: list[tuple[int, str]] = []
    cur = default_algorithm(coll, comm_size, 0, commute)
    last_max = -1
    for probe in sorted(probes):
        if last_max >= cap_bytes:
            break
        alg = default_algorithm(coll, comm_size, probe, commute)
        if alg != cur:
            rows.append((probe - 1, cur))
            last_max = probe - 1
            cur = alg
    if last_max < cap_bytes:
        # close the table at the cap (0 = unbounded, which is exactly
        # right for a size-independent pick like barrier's)
        rows.append((int(cap_bytes), cur))
    return rows


class TunedModule:
    """Per-communicator module: ladder dispatch over the algorithm menu.

    fastpath: the ladders themselves are cheap integer compares; the
    per-call cost a training loop actually replays is building the
    chosen algorithm's peer/segment schedule, which is memoized on
    ``coll/algorithms`` (``_sched_cache`` — SPC
    ``fastpath_sched_{hits,misses}``).  Force-vars and a dynamic-rules
    file stay mutable at runtime through MPI_T: every call re-reads
    them, so a mid-run ``registry.set`` is never masked.
    """

    def __init__(self, component: "TunedCollComponent"):
        self._c = component
        self._basic = BasicCollModule()

    # -- decision machinery ---------------------------------------------
    def _pick(self, coll: str, comm_size: int, nbytes: int,
              default: str, commute: bool = True) -> tuple[str, int]:
        """(algorithm, rule segsize) — segsize 0 means 'use the MCA var'.
        ``nbytes`` is the TOTAL payload per rank for every collective
        (alltoall included), matching the rule file's max_bytes column.

        Dynamic rules apply to COMMUTATIVE reductions only: the rule
        grammar cannot express commutativity, and a measured schedule
        for commutative traffic (ring/Rabenseifner/binomial reorder
        operands) would silently produce wrong answers on a
        non-commutative op — those always take the fixed ladder's
        order-safe picks.  A force-var is the user's explicit override
        and still applies."""
        _pt = profile.now() if profile.enabled else 0
        try:
            forced = self._c.force_var(coll)
            if forced:
                return forced, 0
            if not commute:
                return default, 0
            for (rcoll, max_size, max_bytes, alg, seg) in self._c.rules:
                if rcoll != coll:
                    continue
                if max_size and comm_size > max_size:
                    continue
                if max_bytes and nbytes > max_bytes:
                    continue
                return alg, seg
            return default, 0
        finally:
            if profile.enabled:
                profile.stage_span("coll.decide", _pt)

    def _run(self, coll: str, alg: str, default: str, *args, **kw):
        menu = _MENUS[coll]
        fn = menu.get(alg)
        if fn is None:
            from ompi_tpu.base.output import show_help

            show_help("help-coll-tuned", "unknown-algorithm",
                      coll=coll, alg=alg, known=", ".join(sorted(menu)))
            # fall back to the ladder's own default: unlike an arbitrary
            # menu entry it is always safe for the op at hand
            fn = menu[default]
        _pt = profile.now() if profile.enabled else 0
        try:
            return fn(*args, **kw)
        finally:
            if profile.enabled:
                profile.stage_span("coll.alg", _pt)

    # -- fixed ladders (decision_fixed.c shape, TPU-host re-derivation) --
    @hot_path
    def allreduce(self, comm, sendbuf, op=op_mod.SUM):
        nbytes = _nbytes(sendbuf)
        # SPC-counted small-message eager lane: below the threshold the
        # ladder ALWAYS lands on recursive doubling (for commutative and
        # non-commutative alike — rd keeps rank order), so skip the pick
        # machinery and dispatch straight into the cached-peer-schedule
        # algorithm.  Force-vars and rule files disable the lane so every
        # override still goes through the full decision path.
        if (nbytes <= self._c.eager_lane_max()
                and (op.commute or comm.size > 4)
                and not self._c.rules
                and not self._c.force_var("allreduce")):
            spc.record("fastpath_eager_lane")
            if not profile.enabled:
                return algs.allreduce_recursive_doubling(comm, sendbuf, op)
            _pt = profile.now()
            try:
                return algs.allreduce_recursive_doubling(comm, sendbuf, op)
            finally:
                profile.stage_span("coll.alg", _pt)
        # coll/quant arm of the ladder: the (dtype, size, accuracy_
        # budget) rule key, armed only by an EXPLICIT per-comm budget
        # info key and never for non-commutative ops (pick re-checks) —
        # a force-var stays the user's override and wins outright
        if (op.commute and not self._c.force_var("allreduce")):
            qcodec = quant_mod.pick(comm, "allreduce",
                                    getattr(sendbuf, "dtype", None),
                                    nbytes, op)
            if qcodec is not None:
                _pt = profile.now() if profile.enabled else 0
                try:
                    return quant_mod.allreduce_blockq(comm, sendbuf,
                                                      op, qcodec)
                finally:
                    if profile.enabled:
                        profile.stage_span("coll.alg", _pt)
        default = default_algorithm("allreduce", comm.size, nbytes,
                                    op.commute)
        alg, seg = self._pick("allreduce", comm.size, nbytes, default,
                              commute=op.commute)
        if alg == "ring_segmented":
            return self._run(
                "allreduce", alg, default, comm, sendbuf, op,
                segsize=seg or self._c.segsize("allreduce"))
        return self._run("allreduce", alg, default, comm, sendbuf, op)

    def bcast(self, comm, buf, root=0):
        nbytes = _nbytes(buf)
        default = default_algorithm("bcast", comm.size, nbytes)
        alg, seg = self._pick("bcast", comm.size, nbytes, default)
        if alg == "chain":
            return self._run("bcast", alg, default, comm, buf, root,
                             segsize=seg or self._c.segsize("bcast"))
        return self._run("bcast", alg, default, comm, buf, root)

    def reduce(self, comm, sendbuf, op=op_mod.SUM, root=0):
        nbytes = _nbytes(sendbuf)
        default = default_algorithm("reduce", comm.size, nbytes,
                                    op.commute)
        alg, seg = self._pick("reduce", comm.size, nbytes, default,
                              commute=op.commute)
        if alg == "pipeline":
            return self._run("reduce", alg, default, comm, sendbuf, op,
                             root, segsize=seg or self._c.segsize("reduce"))
        return self._run("reduce", alg, default, comm, sendbuf, op, root)

    def allgather(self, comm, sendbuf):
        nbytes = _nbytes(sendbuf)
        # coll/quant arm (see allreduce): explicit budget only
        if not self._c.force_var("allgather"):
            qcodec = quant_mod.pick(comm, "allgather",
                                    getattr(sendbuf, "dtype", None),
                                    nbytes)
            if qcodec is not None:
                _pt = profile.now() if profile.enabled else 0
                try:
                    return quant_mod.allgather_blockq(comm, sendbuf,
                                                      qcodec)
                finally:
                    if profile.enabled:
                        profile.stage_span("coll.alg", _pt)
        default = default_algorithm("allgather", comm.size, nbytes)
        alg, _ = self._pick("allgather", comm.size, nbytes, default)
        return self._run("allgather", alg, default, comm, sendbuf)

    def alltoall(self, comm, sendbuf):
        stack = np.asarray(sendbuf)
        nbytes = stack.nbytes   # total, like every other collective
        per_block = nbytes // max(1, stack.shape[0] if stack.ndim else 1)
        default = default_algorithm("alltoall", comm.size, nbytes,
                                    per_block=per_block)
        alg, _ = self._pick("alltoall", comm.size, nbytes, default)
        return self._run("alltoall", alg, default, comm, sendbuf)

    def barrier(self, comm):
        default = default_algorithm("barrier", comm.size, 0)
        alg, _ = self._pick("barrier", comm.size, 0, default)
        return self._run("barrier", alg, default, comm)

    def reduce_scatter(self, comm, sendbuf, recvcounts=None, op=op_mod.SUM):
        nbytes = _nbytes(sendbuf)
        default = default_algorithm("reduce_scatter", comm.size, nbytes,
                                    op.commute)
        alg, _ = self._pick("reduce_scatter", comm.size, nbytes,
                            default, commute=op.commute)
        return self._run("reduce_scatter", alg, default,
                         comm, sendbuf, recvcounts, op)

    def gather(self, comm, sendbuf, root=0):
        nbytes = _nbytes(sendbuf)
        default = default_algorithm("gather", comm.size, nbytes)
        alg, _ = self._pick("gather", comm.size, nbytes, default)
        return self._run("gather", alg, default, comm, sendbuf, root)

    def scatter(self, comm, sendbuf, root=0):
        nbytes = _nbytes(sendbuf)
        default = default_algorithm("scatter", comm.size, nbytes)
        alg, _ = self._pick("scatter", comm.size, nbytes, default)
        return self._run("scatter", alg, default, comm, sendbuf, root)


class TunedCollComponent(Component):
    name = "tuned"
    priority = 30

    def register_vars(self, fw) -> None:
        self._prio = self.register_var(
            "priority", vtype=VarType.INT, default=30,
            help="Selection priority of coll/tuned")
        self._rules_file = self.register_var(
            "dynamic_rules_filename", vtype=VarType.STRING, default="",
            help="Path to a dynamic decision-rule file "
                 "(coll_tuned_component.c:232 equivalent)")
        self._force: dict[str, object] = {}
        self._seg: dict[str, object] = {}
        for coll, menu in _MENUS.items():
            self._force[coll] = self.register_var(
                f"{coll}_algorithm", vtype=VarType.STRING, default="",
                help=f"Force a {coll} algorithm: one of "
                     f"{', '.join(sorted(menu))} (empty = decision ladder)")
        for coll, default in (("allreduce", 1 << 20), ("bcast", 1 << 17),
                              ("reduce", 1 << 17)):
            self._seg[coll] = self.register_var(
                f"{coll}_segsize", vtype=VarType.INT, default=default,
                help=f"Segment size in bytes for segmented {coll} algorithms")
        self._fused = self.register_var(
            "fused_cells", vtype=VarType.STRING, default="",
            help="Device-tier fused ladder cells (ops/pallas_overlap) "
                 f"consulted via device_cell(): one of "
                 f"{', '.join(DEVICE_CELLS)} to force that cell only, "
                 "'off' to disable the fused tier (callers fall back to "
                 "unfused einsum+psum), empty = ladder decides")
        self._eager_lane = self.register_var(
            "eager_lane_max", vtype=VarType.SIZE, default="4k",
            help="Allreduces below this take the SPC-counted small-"
                 "message eager lane (straight to the cached recursive-"
                 "doubling schedule, skipping the decision machinery); "
                 "0 disables the lane.  Matches the fixed ladder's "
                 "recursive-doubling threshold")
        self.rules: list[tuple] = []

    def open(self) -> bool:
        self.rules = []
        path = (self._rules_file.value or "").strip()
        if path:
            try:
                self.rules = _load_rules(path)
            except OSError as exc:
                from ompi_tpu.base.output import show_help

                show_help("help-coll-tuned", "bad-rules-file",
                          path=path, error=str(exc))
        return True

    def force_var(self, coll: str) -> str:
        v = self._force.get(coll)
        return (v.value or "").strip() if v is not None else ""

    def segsize(self, coll: str) -> int:
        v = self._seg.get(coll)
        return int(v.value) if v is not None else 1 << 20

    def fused_cells_var(self) -> str:
        v = getattr(self, "_fused", None)
        return (v.value or "").strip() if v is not None else ""

    def eager_lane_max(self) -> int:
        v = getattr(self, "_eager_lane", None)
        return int(v.value) if v is not None else 4096

    def comm_query(self, comm):
        if comm.rte is not None and comm.rte.is_device_world:
            return None   # conductor/xla own the device world
        if comm.size == 1 or comm.is_inter:
            return None   # intercomms take coll/inter's two-group protocol
        return self._prio.value, TunedModule(self)


def _load_rules(path: str) -> list[tuple]:
    rules = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            if len(parts) not in (4, 5):
                raise OSError(f"line {lineno}: expected "
                              "'coll max_size max_bytes alg [segsize]'")
            coll, max_size, max_bytes, alg = parts[:4]
            seg = int(parts[4]) if len(parts) == 5 else 0
            if coll not in _MENUS:
                raise OSError(f"line {lineno}: unknown collective {coll!r}")
            if alg not in _MENUS[coll]:
                raise OSError(f"line {lineno}: unknown {coll} algorithm "
                              f"{alg!r}")
            rules.append((coll, int(max_size), int(max_bytes), alg, seg))
    return rules


COMPONENT = TunedCollComponent()

from ompi_tpu.base.output import register_help as _rh

_rh("help-coll-tuned", "unknown-algorithm",
    "coll/tuned was asked for {coll} algorithm {alg!r} but only knows: "
    "{known}; using the first available instead.")
_rh("help-coll-tuned", "bad-rules-file",
    "coll/tuned could not load the dynamic rules file {path!r}: {error}. "
    "Falling back to the fixed decision ladder.")
