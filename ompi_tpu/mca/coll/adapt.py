"""coll/adapt — event-driven asynchronous bcast/reduce with segmentation.

Re-design of ``/root/reference/ompi/mca/coll/adapt/`` (2,336 LoC): where
libnbc advances fixed round schedules in lockstep, adapt is EVENT-DRIVEN —
a message is split into segments and each segment flows down (bcast) or up
(reduce) a binomial tree the moment it arrives, driven by request
completion callbacks rather than round barriers.  A fast subtree never
waits for a slow sibling's round, which is the component's whole point.

Provides the nonblocking ``ibcast``/``ireduce`` slots (and blocking
wrappers) at priority 28 — above libnbc (25) so its pipelined trees serve
large messages, below the tuned ladders for everything else.
"""
from __future__ import annotations

import threading

import numpy as np

from ompi_tpu.api import op as op_mod
from ompi_tpu.api.request import Request
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType
from ompi_tpu.mca.coll.algorithms import _binomial_tree
from ompi_tpu.mca.coll.basic import coll_tag


_SEG_SLOT = 1 << 22    # segments per collective before tags could wrap


def _seg_tag(tag: int, k: int) -> int:
    """Per-segment tag in a dedicated far-negative range: segment slots
    must not collide with subsequent collectives' base tags (coll_tag
    steps by 1) or any other internal tag space.  Each collective owns a
    2^22-segment slot (a 4 MiB-segment x 16 TiB message before wrap)."""
    return -(1 << 40) + (tag + 16) * _SEG_SLOT - k


class _Latch(Request):
    """A request completing after ``count`` constituent completions.

    The first constituent error is remembered and the latch completes IN
    ERROR, so a peer death or truncation mid-pipeline surfaces from
    ``wait()`` instead of returning partial data as success."""

    def __init__(self, count: int) -> None:
        super().__init__()
        self._remaining = count
        self._first_error = None
        self._latch_lock = threading.Lock()
        if count == 0:
            self.complete()

    def arm(self, req: Request) -> None:
        req.on_complete(self._hit)

    def _hit(self, req: Request) -> None:
        with self._latch_lock:
            if getattr(req, "error", None) is not None \
                    and self._first_error is None:
                self._first_error = req.error
            self._remaining -= 1
            done = self._remaining == 0
            err = self._first_error
        if done:
            self.complete(err)


class AdaptModule:
    def __init__(self, component: "AdaptCollComponent") -> None:
        self._c = component

    def _segments(self, arr: np.ndarray, align: int = 1) -> list:
        seg = max(align, int(self._c.seg_var.value))
        seg -= seg % align     # whole elements per segment
        flat = arr.view(np.uint8).reshape(-1)
        return [flat[i:i + seg] for i in range(0, len(flat), seg)] or [flat]

    # -- event-driven pipelined broadcast --------------------------------
    def ibcast(self, comm, buf, root: int = 0) -> Request:
        tag = coll_tag(comm)
        arr = np.ascontiguousarray(buf)
        parent, children = _binomial_tree(comm.rank, comm.size, root)
        segs = self._segments(arr)
        nseg = len(segs)
        # completions to wait for: my recvs (non-root) + my forwards
        latch = _Latch((0 if parent is None else nseg)
                       + nseg * len(children))
        latch.result = arr
        pml = comm.pml
        if parent is None:
            for k, seg in enumerate(segs):
                for c in children:
                    latch.arm(pml.isend(comm, seg, c, _seg_tag(tag, k)))
        else:
            for k, seg in enumerate(segs):
                rreq = pml.irecv(comm, seg, parent, _seg_tag(tag, k))

                def forward(_r, seg=seg, k=k):
                    # the segment just landed: push it onward NOW —
                    # adapt's event-driven property (no round lockstep).
                    # An errored recv (truncation, dead peer) must NOT be
                    # forwarded: the latch already records the error, and
                    # descendants recover via FT propagation rather than
                    # receiving garbage marked success.
                    if _r.error is not None:
                        return
                    for c in children:
                        latch.arm(pml.isend(comm, seg, c,
                                            _seg_tag(tag, k)))

                rreq.on_complete(forward)
                latch.arm(rreq)
        return latch

    def bcast(self, comm, buf, root: int = 0):
        req = self.ibcast(comm, buf, root)
        req.wait()
        return req.result

    # -- event-driven pipelined reduce -----------------------------------
    def ireduce(self, comm, sendbuf, op: op_mod.Op = op_mod.SUM,
                root: int = 0) -> Request:
        if not op.commute:
            # arrival-order folding needs commutativity; rank-ordered
            # algorithms serve the rest (the reference's exclusion)
            from ompi_tpu.api.request import CompletedRequest
            from ompi_tpu.mca.coll.basic import BasicCollModule

            r = CompletedRequest()
            r.result = BasicCollModule().reduce(comm, sendbuf, op, root)
            return r
        tag = coll_tag(comm)
        arr = np.array(sendbuf, copy=True, order="C")
        dtype, shape = arr.dtype, arr.shape
        parent, children = _binomial_tree(comm.rank, comm.size, root)
        # segments must hold whole elements: the fold views them typed
        segs = self._segments(arr, align=arr.dtype.itemsize)
        nseg = len(segs)
        pml = comm.pml
        # per-segment: wait for each child's contribution, fold it in as
        # it arrives; when all children contributed, forward up
        pending = [len(children) for _ in range(nseg)]
        plock = threading.Lock()
        latch = _Latch(nseg * len(children)
                       + (0 if parent is None else nseg))
        latch.result = None

        def seg_done(k: int) -> None:
            if parent is not None:
                latch.arm(pml.isend(comm, segs[k], parent,
                                    _seg_tag(tag, k)))

        child_bufs = {}
        for k in range(nseg):
            if not children:
                seg_done(k)
                continue
            for c in children:
                cb = np.empty_like(segs[k])
                child_bufs[(c, k)] = cb
                rreq = pml.irecv(comm, cb, c, _seg_tag(tag, k))

                def fold(_r, c=c, k=k):
                    # an errored child recv contributes nothing: folding
                    # the uninitialised buffer would corrupt the segment
                    # and seg_done would ship it upward as success.  The
                    # latch records the error; the op completes in error.
                    if _r.error is not None:
                        return
                    cb = child_bufs[(c, k)]
                    with plock:
                        # the fold itself is inside the lock: completions
                        # can fire on concurrent progress threads, and two
                        # children's read-modify-writes of the same
                        # accumulator segment must not interleave
                        mine = segs[k].view(dtype)
                        op(cb.view(dtype), mine)
                        pending[k] -= 1
                        ready = pending[k] == 0
                    if ready:
                        seg_done(k)

                rreq.on_complete(fold)
                latch.arm(rreq)
        if parent is None:
            latch.result = arr.view(dtype).reshape(shape)
        return latch

    def reduce(self, comm, sendbuf, op: op_mod.Op = op_mod.SUM,
               root: int = 0):
        req = self.ireduce(comm, sendbuf, op, root)
        req.wait()
        return req.result


class AdaptCollComponent(Component):
    name = "adapt"
    priority = 28

    def register_vars(self, fw) -> None:
        self._prio = self.register_var(
            "priority", vtype=VarType.INT, default=-1,
            help="Selection priority of coll/adapt (event-driven "
                 "segmented bcast/reduce); <0 disables, like the "
                 "reference's default")
        self.seg_var = self.register_var(
            "segsize", vtype=VarType.SIZE, default="64k",
            help="Segment size for the pipelined trees")

    def comm_query(self, comm):
        if int(self._prio.value) < 0:
            return None
        if comm.rte is not None and comm.rte.is_device_world:
            return None
        if comm.size < 2 or comm.is_inter:
            return None
        return int(self._prio.value), AdaptModule(self)


COMPONENT = AdaptCollComponent()
