"""coll/sync — periodic barrier injection to bound unexpected messages.

Re-design of ``/root/reference/ompi/mca/coll/sync/`` (895 LoC): on
communicators where one rank races far ahead (e.g. a root spamming bcasts),
unexpected-message queues grow without bound; this interposition component
counts collective operations and injects a barrier every
``otpu_coll_sync_barrier_after`` calls.  Disabled (priority < 0) unless
the count var is set, like the reference.
"""
from __future__ import annotations

from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType


class SyncModule:
    """Wraps the already-selected one-sided-flow collectives with a
    countdown barrier (the reference interposes bcast/reduce/scatter —
    the rooted, non-synchronizing ops)."""

    WRAPPED = ("bcast", "reduce", "scatter", "scatterv", "ibcast", "ireduce")

    def __init__(self, component: "SyncCollComponent") -> None:
        self._c = component
        self._count = 0

    def comm_enable(self, comm) -> None:
        # runs during comm_select AFTER lower-priority modules filled the
        # table (ascending fill order): wrap what they provided
        interval = int(self._c.after_var.value)
        for name in self.WRAPPED:
            fn = comm.c_coll.get(name)
            if fn is None or getattr(fn, "__sync_wrapped__", False):
                continue
            comm.c_coll[name] = self._make(comm, name, fn, interval)

    def _make(self, comm, name, fn, interval):
        def wrapped(comm_arg, *args, **kw):
            self._count += 1
            if self._count % interval == 0:
                barrier = comm_arg.c_coll.get("barrier")
                if barrier is not None:
                    barrier(comm_arg)
            return fn(comm_arg, *args, **kw)

        wrapped.__sync_wrapped__ = True
        wrapped.__self__ = getattr(fn, "__self__", None)
        return wrapped


class SyncCollComponent(Component):
    name = "sync"
    priority = 50      # above the providers it wraps; fills no slot itself

    def register_vars(self, fw) -> None:
        self.after_var = self.register_var(
            "barrier_after", vtype=VarType.INT, default=0,
            help="Inject a barrier every N rooted collectives "
                 "(0 = disabled, the reference's default)")

    def comm_query(self, comm):
        if int(self.after_var.value) <= 0:
            return None
        if comm.size == 1 or comm.is_inter:
            return None
        if comm.rte is not None and comm.rte.is_device_world:
            return None
        return self.priority, SyncModule(self)


COMPONENT = SyncCollComponent()
