"""The collective algorithm library — the menu coll/tuned picks from.

Re-design of ``/root/reference/ompi/mca/coll/base/coll_base_*.c``: the same
algorithm *menus* (allreduce×6 ``coll_base_allreduce.c:53-1245``, bcast
binomial/chain/scatter-allgather ``coll_base_bcast.c``, allgather
bruck/recursive-doubling/ring/neighbor ``coll_base_allgather.c``, alltoall
bruck/pairwise ``coll_base_alltoall.c``, barrier rd/bruck/tree
``coll_base_barrier.c``, reduce binomial/pipeline ``coll_base_reduce.c``,
reduce_scatter recursive-halving/ring ``coll_base_reduce_scatter.c``,
binomial gather/scatter ``coll_base_gather.c``/``coll_base_scatter.c``)
implemented SPMD over the framework's pml p2p — these are the *host/DCN
path* algorithms; the ICI path lowers to XLA collectives in ``coll/xla``
instead of scheduling messages by hand.

Every function takes the communicator first and uses one internal collective
tag per call (``ompi_tpu.mca.coll.basic.coll_tag``), so concurrent
collectives on one comm stay ordered, like the reference's collective
context ids.  Reduction argument order follows the MPI convention
``inout = in (op) inout``; algorithms that cannot preserve rank order
(ring, recursive-halving, Rabenseifner, binomial reduce) are only selected
for commutative ops, mirroring ``coll_tuned_decision_fixed.c:77-80``.
"""
from __future__ import annotations

from functools import lru_cache

import numpy as np

from ompi_tpu.api import op as op_mod
from ompi_tpu.api.request import waitall
from ompi_tpu.mca.coll.basic import BasicCollModule, coll_tag
from ompi_tpu.runtime import spc

_basic = BasicCollModule()


def _sched_cache(fn):
    """``lru_cache`` plus SPC accounting: each lookup records
    ``fastpath_sched_hits`` / ``fastpath_sched_misses``, making the
    schedule reuse of a repeated-collective loop observable (and
    pinnable by the perf guard) without a tracing run."""
    cached = lru_cache(maxsize=1024)(fn)

    def wrapper(*args):
        hits0 = cached.cache_info().hits
        out = cached(*args)
        spc.record("fastpath_sched_hits"
                   if cached.cache_info().hits > hits0
                   else "fastpath_sched_misses")
        return out

    wrapper.cache_info = cached.cache_info
    wrapper.cache_clear = cached.cache_clear
    return wrapper


# ---------------------------------------------------------------------------
# helpers
#
# fastpath: the peer/segment schedules below depend only on small
# integer tuples (comm size, rank, payload length) — a training loop
# replays the SAME collective shape every step, so they are memoized on
# the module (lru_cache) instead of being rebuilt per call.  This is the
# Python analog of the reference caching its binomial/topo trees on the
# communicator (``coll_base_topo.c`` ompi_coll_base_topo_build_*).


def _pof2_floor(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


@_sched_cache
def _blocks(total: int, nblocks: int) -> tuple[tuple[int, int], ...]:
    """(offset, count) decomposition of ``total`` items into nblocks pieces,
    earlier blocks one larger when it doesn't divide (MPI block convention)."""
    base, rem = divmod(total, nblocks)
    out = []
    off = 0
    for i in range(nblocks):
        cnt = base + (1 if i < rem else 0)
        out.append((off, cnt))
        off += cnt
    return tuple(out)


@_sched_cache
def _ring_schedule(size: int, rank: int, total: int) -> tuple:
    """The ring allreduce's full per-step slice schedule for this rank:
    ``(max_block, reduce_steps, gather_steps)`` where each step is
    (send_off, send_cnt, recv_off, recv_cnt)."""
    blocks = _blocks(total, size)
    red = []
    for k in range(size - 1):
        soff, scnt = blocks[(rank - k) % size]
        roff, rcnt = blocks[(rank - k - 1) % size]
        red.append((soff, scnt, roff, rcnt))
    gat = []
    for k in range(size - 1):
        soff, scnt = blocks[(rank + 1 - k) % size]
        roff, rcnt = blocks[(rank - k) % size]
        gat.append((soff, scnt, roff, rcnt))
    return (max(c for _, c in blocks), tuple(red), tuple(gat))


@_sched_cache
def _rd_peers(size: int, newrank: int) -> tuple[int, ...]:
    """Recursive-doubling peer sequence for pof2-participant ``newrank``
    (already folded): one real-rank peer per mask round."""
    pof2 = _pof2_floor(size)
    rem = size - pof2
    peers = []
    mask = 1
    while mask < pof2:
        peers.append(_pof2_real_rank(newrank ^ mask, rem))
        mask <<= 1
    return tuple(peers)


def _pof2_real_rank(newrank: int, rem: int) -> int:
    """Real rank behind pof2-participant virtual rank ``newrank`` after the
    fold phase (odd ranks < 2*rem become newrank rank//2; the rest shift
    down by rem) — the MPICH/reference non-power-of-2 mapping."""
    return newrank * 2 + 1 if newrank < rem else newrank + rem


def _fold_to_pof2(comm, acc: np.ndarray, op, tag: int, rem: int) -> int:
    """Pre-phase of the pof2 algorithms: even ranks < 2*rem send their data
    to the odd neighbor (which folds it, keeping rank order) and sit out.
    Returns this rank's virtual rank, or -1 if it sits out."""
    rank = comm.rank
    if rank < 2 * rem:
        if rank % 2 == 0:
            comm.send(acc, dest=rank + 1, tag=tag)
            return -1
        other = np.empty_like(acc)
        comm.recv(other, source=rank - 1, tag=tag)
        op(other, acc)   # acc = lower-rank (op) acc: rank order kept
        return rank // 2
    return rank - rem


def _unfold_from_pof2(comm, acc: np.ndarray, tag: int, rem: int) -> None:
    """Post-phase: odd ranks < 2*rem return the result to the even
    neighbor that sat out."""
    rank = comm.rank
    if rank < 2 * rem:
        if rank % 2 != 0:
            comm.send(acc, dest=rank - 1, tag=tag)
        else:
            comm.recv(acc, source=rank + 1, tag=tag)


@_sched_cache
def _binomial_tree(rank: int, size: int, root: int):
    """(parent, children) of ``rank`` in the binomial tree rooted at root.

    Virtual rank v = (rank - root) mod size; v's parent clears its lowest
    set bit, its children are v + 2^k for 2^k below that bit (all of them
    for v = 0) — the tree shape of the reference's ``coll_base_topo.c``
    binomial builders.
    """
    vrank = (rank - root) % size
    if vrank == 0:
        parent = None
        limit = size
    else:
        lowbit = vrank & -vrank
        parent = ((vrank - lowbit) + root) % size
        limit = lowbit
    children = []
    mask = 1
    while mask < limit and vrank + mask < size:
        children.append((vrank + mask + root) % size)
        mask <<= 1
    return parent, tuple(children)


# ---------------------------------------------------------------------------
# allreduce menu (coll_base_allreduce.c)


def allreduce_nonoverlapping(comm, sendbuf, op=op_mod.SUM):
    """reduce-to-0 + bcast (``coll_base_allreduce.c:53``).  Order-safe."""
    r = _basic.reduce(comm, sendbuf, op, 0)
    arr = np.ascontiguousarray(sendbuf)
    if comm.rank == 0:
        return _basic.bcast(comm, r, 0)
    return _basic.bcast(comm, np.empty_like(arr), 0)


def allreduce_recursive_doubling(comm, sendbuf, op=op_mod.SUM):
    """Recursive doubling (``coll_base_allreduce.c:130``): lg(p) exchange
    rounds; non-power-of-2 handled by folding the first 2*rem ranks.
    Keeps operands in rank order (contiguous-range invariant), so safe for
    non-commutative ops."""
    size, rank = comm.size, comm.rank
    tag = coll_tag(comm)
    acc = np.array(np.ascontiguousarray(sendbuf), copy=True)
    if size == 1:
        return acc
    pof2 = _pof2_floor(size)
    rem = size - pof2
    newrank = _fold_to_pof2(comm, acc, op, tag, rem)

    if newrank >= 0:
        for peer in _rd_peers(size, newrank):   # cached peer schedule
            other = np.empty_like(acc)
            comm.sendrecv(acc, dest=peer, recvbuf=other, source=peer,
                          sendtag=tag, recvtag=tag)
            if peer < rank:
                op(other, acc)              # acc = theirs (op) mine
            else:
                op(acc, other)              # other = mine (op) theirs
                acc = other

    _unfold_from_pof2(comm, acc, tag, rem)
    return acc


def allreduce_ring(comm, sendbuf, op=op_mod.SUM):
    """Ring allreduce (``coll_base_allreduce.c:341``): p-1 reduce-scatter
    steps + p-1 allgather steps around the ring.  Commutative only —
    bandwidth-optimal, the DP-gradient-sync classic."""
    size, rank = comm.size, comm.rank
    flat = np.ascontiguousarray(sendbuf).reshape(-1)
    if size == 1:
        return np.array(flat, copy=True).reshape(np.asarray(sendbuf).shape)
    if flat.size < size:  # degenerate blocks -> latency algorithm instead
        return allreduce_recursive_doubling(comm, sendbuf, op)
    tag = coll_tag(comm)
    acc = np.array(flat, copy=True)
    right = (rank + 1) % size
    left = (rank - 1) % size
    # cached per-(size, rank, length) slice schedule: a gradient-sync
    # loop replays the same shape every step and pays the block math once
    max_block, red_steps, gat_steps = _ring_schedule(size, rank, acc.size)

    # ONE pooled staging buffer serves every step (grdma-style reuse:
    # repeated 4MB allreduces re-fault fresh np.empty pages per call
    # otherwise); block sizes differ by <=1 element, so slice to fit
    from ompi_tpu.mca.accelerator import jax_acc

    tmp = jax_acc.staging_acquire(max_block, acc.dtype)
    try:
        # reduce-scatter phase: step k sends block (rank-k), recvs (rank-k-1)
        for soff, scnt, roff, rcnt in red_steps:
            inbuf = tmp[:rcnt]
            comm.sendrecv(acc[soff:soff + scnt], dest=right, recvbuf=inbuf,
                          source=left, sendtag=tag, recvtag=tag)
            op(inbuf, acc[roff:roff + rcnt])

        # allgather phase: circulate the completed blocks
        for soff, scnt, roff, rcnt in gat_steps:
            inbuf = tmp[:rcnt]
            comm.sendrecv(acc[soff:soff + scnt], dest=right, recvbuf=inbuf,
                          source=left, sendtag=tag, recvtag=tag)
            acc[roff:roff + rcnt] = inbuf
    finally:
        jax_acc.staging_release(tmp)
    return acc.reshape(np.asarray(sendbuf).shape)


def allreduce_ring_segmented(comm, sendbuf, op=op_mod.SUM,
                             segsize: int = 1 << 20):
    """Segmented ring (``coll_base_allreduce.c:618``): the ring run chunk by
    chunk so pipeline depth is bounded by ``segsize``.  Commutative only."""
    arr = np.ascontiguousarray(sendbuf)
    seg_elems = max(1, segsize // arr.dtype.itemsize)
    flat = arr.reshape(-1)
    chunk_elems = seg_elems * comm.size
    if comm.size == 1 or flat.size <= chunk_elems:
        return allreduce_ring(comm, sendbuf, op)
    out = np.empty_like(flat)
    for off in range(0, flat.size, chunk_elems):
        chunk = flat[off:off + chunk_elems]
        out[off:off + chunk.size] = allreduce_ring(comm, chunk, op)
    return out.reshape(arr.shape)


def allreduce_redscat_allgather(comm, sendbuf, op=op_mod.SUM):
    """Rabenseifner (``coll_base_allreduce.c:970``): recursive-halving
    reduce-scatter + recursive-doubling allgather.  Commutative only;
    bandwidth-optimal with lg(p) latency for large payloads."""
    size, rank = comm.size, comm.rank
    flat = np.ascontiguousarray(sendbuf).reshape(-1)
    shape = np.asarray(sendbuf).shape
    pof2 = _pof2_floor(size)
    if size == 1:
        return np.array(flat, copy=True).reshape(shape)
    if flat.size < pof2:
        return allreduce_recursive_doubling(comm, sendbuf, op)
    tag = coll_tag(comm)
    acc = np.array(flat, copy=True)
    rem = size - pof2
    newrank = _fold_to_pof2(comm, acc, op, tag, rem)

    if newrank >= 0:
        blocks = _blocks(acc.size, pof2)

        def span(lo_b: int, hi_b: int) -> tuple[int, int]:
            """Element range covered by blocks [lo_b, hi_b)."""
            return blocks[lo_b][0], blocks[hi_b - 1][0] + blocks[hi_b - 1][1]

        # recursive halving reduce-scatter: window [lo, hi) of blocks
        lo, hi = 0, pof2
        mask = pof2 // 2
        while mask > 0:
            mid = (lo + hi) // 2
            peer = _pof2_real_rank(newrank ^ mask, rem)
            if newrank < mid:   # keep low half, trade away high half
                keep_lo, keep_hi = span(lo, mid)
                send_lo, send_hi = span(mid, hi)
                new_lo, new_hi = lo, mid
            else:
                keep_lo, keep_hi = span(mid, hi)
                send_lo, send_hi = span(lo, mid)
                new_lo, new_hi = mid, hi
            recv_seg = np.empty(keep_hi - keep_lo, acc.dtype)
            comm.sendrecv(acc[send_lo:send_hi], dest=peer, recvbuf=recv_seg,
                          source=peer, sendtag=tag, recvtag=tag)
            op(recv_seg, acc[keep_lo:keep_hi])
            lo, hi = new_lo, new_hi
            mask //= 2

        # recursive doubling allgather: widen [lo, hi) back to [0, pof2)
        mask = 1
        while mask < pof2:
            peer = _pof2_real_rank(newrank ^ mask, rem)
            width = hi - lo
            if newrank & mask:
                p_lo, p_hi = lo - width, lo
            else:
                p_lo, p_hi = hi, hi + width
            m_lo, m_hi = span(lo, hi)
            q_lo, q_hi = span(p_lo, p_hi)
            recv_seg = np.empty(q_hi - q_lo, acc.dtype)
            comm.sendrecv(acc[m_lo:m_hi], dest=peer, recvbuf=recv_seg,
                          source=peer, sendtag=tag, recvtag=tag)
            acc[q_lo:q_hi] = recv_seg
            lo, hi = min(lo, p_lo), max(hi, p_hi)
            mask <<= 1

    _unfold_from_pof2(comm, acc, tag, rem)
    return acc.reshape(shape)


# ---------------------------------------------------------------------------
# bcast menu (coll_base_bcast.c)


def bcast_binomial(comm, buf, root=0):
    """Binomial-tree bcast: lg(p) depth, the small-message winner."""
    tag = coll_tag(comm)
    arr = np.ascontiguousarray(buf)
    parent, children = _binomial_tree(comm.rank, comm.size, root)
    if parent is not None:
        out = np.empty_like(arr)
        comm.recv(out, source=parent, tag=tag)
        arr = out
    waitall([comm.isend(arr, dest=c, tag=tag) for c in children])
    return arr


def bcast_chain(comm, buf, root=0, segsize: int = 1 << 17):
    """Segmented chain bcast: the message flows vrank→vrank+1 in segments so
    every link carries a segment per step (pipeline fill lg-free)."""
    size, rank = comm.size, comm.rank
    arr = np.ascontiguousarray(buf)
    if size == 1:
        return arr
    tag = coll_tag(comm)
    vrank = (rank - root) % size
    prev = (rank - 1) % size
    nxt = (rank + 1) % size
    flat = (np.array(arr, copy=True).reshape(-1) if rank == root
            else np.empty(arr.size, arr.dtype))
    seg_elems = max(1, segsize // arr.dtype.itemsize)
    nseg = (flat.size + seg_elems - 1) // seg_elems
    reqs = []
    for s in range(nseg):
        sl = flat[s * seg_elems:(s + 1) * seg_elems]
        if vrank != 0:
            comm.recv(sl, source=prev, tag=tag)
        if vrank != size - 1:
            reqs.append(comm.isend(sl, dest=nxt, tag=tag))
    waitall(reqs)
    return flat.reshape(arr.shape)


def bcast_scatter_allgather(comm, buf, root=0):
    """Scatter + ring allgather (bandwidth-optimal large-message bcast)."""
    size, rank = comm.size, comm.rank
    arr = np.ascontiguousarray(buf)
    if size == 1:
        return arr
    if arr.size < size:
        return bcast_binomial(comm, buf, root)
    tag = coll_tag(comm)
    flat = (np.array(arr, copy=True).reshape(-1) if rank == root
            else np.empty(arr.size, arr.dtype))
    blocks = _blocks(flat.size, size)
    if rank == root:
        reqs = []
        for r in range(size):
            if r != root:
                off, cnt = blocks[r]
                reqs.append(comm.isend(flat[off:off + cnt], dest=r, tag=tag))
        waitall(reqs)
    else:
        off, cnt = blocks[rank]
        comm.recv(flat[off:off + cnt], source=root, tag=tag)
    right, left = (rank + 1) % size, (rank - 1) % size
    for k in range(size - 1):
        soff, scnt = blocks[(rank - k) % size]
        roff, rcnt = blocks[(rank - k - 1) % size]
        comm.sendrecv(flat[soff:soff + scnt], dest=right,
                      recvbuf=flat[roff:roff + rcnt], source=left,
                      sendtag=tag, recvtag=tag)
    return flat.reshape(arr.shape)


# ---------------------------------------------------------------------------
# reduce menu (coll_base_reduce.c)


def reduce_binomial(comm, sendbuf, op=op_mod.SUM, root=0):
    """Binomial-tree reduce: lg(p) rounds.  Fold order is tree order, so
    commutative ops only (the reference's in-order binary tree serves the
    non-commutative case; here that role falls to linear ``basic.reduce``)."""
    tag = coll_tag(comm)
    acc = np.array(np.ascontiguousarray(sendbuf), copy=True)
    size = comm.size
    vrank = (comm.rank - root) % size
    mask = 1
    while mask < size:
        if vrank & mask:
            peer = ((vrank - mask) + root) % size
            comm.send(acc, dest=peer, tag=tag)
            break
        peer_v = vrank | mask
        if peer_v < size:
            other = np.empty_like(acc)
            comm.recv(other, source=(peer_v + root) % size, tag=tag)
            op(other, acc)
        mask <<= 1
    return acc if comm.rank == root else None


def reduce_pipeline(comm, sendbuf, op=op_mod.SUM, root=0,
                    segsize: int = 1 << 17):
    """Segmented chain reduce: segments fold from rank p-1 down the chain to
    rank 0, preserving MPI rank order (b0 op (b1 op (… b_{p-1})));
    rank 0 forwards the result to root if different.  Order-safe."""
    size, rank = comm.size, comm.rank
    arr = np.ascontiguousarray(sendbuf)
    if size == 1:
        return np.array(arr, copy=True)
    tag = coll_tag(comm)
    flat = arr.reshape(-1)
    seg_elems = max(1, segsize // arr.dtype.itemsize)
    nseg = (flat.size + seg_elems - 1) // seg_elems
    acc = np.array(flat, copy=True)
    reqs = []
    for s in range(nseg):
        sl = slice(s * seg_elems, (s + 1) * seg_elems)
        if rank < size - 1:
            inbuf = np.empty(acc[sl].size, acc.dtype)
            comm.recv(inbuf, source=rank + 1, tag=tag)
            # inbuf holds the fold of ranks > me; mine is the earlier operand
            op(acc[sl], inbuf)
            acc[sl] = inbuf
        if rank > 0:
            reqs.append(comm.isend(acc[sl], dest=rank - 1, tag=tag))
    waitall(reqs)
    if root != 0:
        if rank == 0:
            comm.send(acc, dest=root, tag=tag)
        elif rank == root:
            comm.recv(acc, source=0, tag=tag)
    return acc.reshape(arr.shape) if rank == root else None


# ---------------------------------------------------------------------------
# allgather menu (coll_base_allgather.c)


def allgather_bruck(comm, sendbuf):
    """Bruck allgather: lg(p) rounds of doubling block exchanges, works for
    any p.  Output is the (size, ...) stack in rank order."""
    size, rank = comm.size, comm.rank
    arr = np.ascontiguousarray(sendbuf)
    out = np.empty((size, *arr.shape), arr.dtype)
    if size == 1:
        out[0] = arr
        return out
    tag = coll_tag(comm)
    # work in vrank space: slot k holds the block of rank (rank + k) % size
    work = np.empty_like(out)
    work[0] = arr
    have = 1
    step = 1
    while step < size:
        dst = (rank - step) % size
        cnt = min(step, size - have)
        sendblk = work[:cnt]
        recvblk = np.empty((cnt, *arr.shape), arr.dtype)
        comm.sendrecv(sendblk, dest=dst, recvbuf=recvblk,
                      source=(rank + step) % size, sendtag=tag, recvtag=tag)
        work[have:have + cnt] = recvblk
        have += cnt
        step <<= 1
    # unshift: slot k is rank (rank + k) % size
    for k in range(size):
        out[(rank + k) % size] = work[k]
    return out


def allgather_recursive_doubling(comm, sendbuf):
    """Recursive-doubling allgather (power-of-2 comms; else bruck)."""
    size, rank = comm.size, comm.rank
    if size & (size - 1):
        return allgather_bruck(comm, sendbuf)
    arr = np.ascontiguousarray(sendbuf)
    out = np.empty((size, *arr.shape), arr.dtype)
    out[rank] = arr
    tag = coll_tag(comm)
    mask = 1
    while mask < size:
        peer = rank ^ mask
        base = rank & ~(mask - 1)          # start of my filled window
        peer_base = peer & ~(mask - 1)
        recvblk = np.empty((mask, *arr.shape), arr.dtype)
        comm.sendrecv(out[base:base + mask], dest=peer, recvbuf=recvblk,
                      source=peer, sendtag=tag, recvtag=tag)
        out[peer_base:peer_base + mask] = recvblk
        mask <<= 1
    return out


def allgather_ring(comm, sendbuf):
    """Ring allgather: p-1 neighbor steps, bandwidth-optimal."""
    size, rank = comm.size, comm.rank
    arr = np.ascontiguousarray(sendbuf)
    out = np.empty((size, *arr.shape), arr.dtype)
    out[rank] = arr
    tag = coll_tag(comm)
    right, left = (rank + 1) % size, (rank - 1) % size
    for k in range(size - 1):
        sb = (rank - k) % size
        rb = (rank - k - 1) % size
        comm.sendrecv(out[sb:sb + 1], dest=right, recvbuf=out[rb:rb + 1],
                      source=left, sendtag=tag, recvtag=tag)
    return out


def allgather_neighbor_exchange(comm, sendbuf):
    """Neighbor-exchange allgather (Chen et al.; even p only, else ring):
    p/2 rounds of pairwise swaps with alternating left/right partners,
    each round forwarding the block pair learned in the previous round
    (``coll_base_allgather.c`` neighbor exchange)."""
    size, rank = comm.size, comm.rank
    if size % 2 or size <= 2:
        return allgather_ring(comm, sendbuf)
    arr = np.ascontiguousarray(sendbuf)
    out = np.empty((size, *arr.shape), arr.dtype)
    out[rank] = arr
    tag = coll_tag(comm)

    def partner(r: int, rnd: int) -> int:
        """Partner of rank r in round rnd (1-based): even ranks pair right
        on odd rounds and left on even rounds; odd ranks mirror."""
        right = (rnd % 2 == 1) if r % 2 == 0 else (rnd % 2 == 0)
        return (r + 1) % size if right else (r - 1) % size

    def pair_sent(r: int, rnd: int) -> tuple[int, int]:
        """Block pair r forwards in round rnd >= 2: its own base pair in
        round 2, afterwards the pair it received the round before."""
        if rnd == 2:
            base = r - (r % 2)
            return base, base + 1
        return pair_sent(partner(r, rnd - 1), rnd - 1)

    # round 1: single-block swap with the immediate partner
    p1 = partner(rank, 1)
    comm.sendrecv(out[rank:rank + 1], dest=p1,
                  recvbuf=out[p1:p1 + 1], source=p1,
                  sendtag=tag, recvtag=tag)
    for rnd in range(2, size // 2 + 1):
        peer = partner(rank, rnd)
        s0, s1 = pair_sent(rank, rnd)
        r0, r1 = pair_sent(peer, rnd)
        sendblk = np.stack([out[s0], out[s1]])
        recvblk = np.empty_like(sendblk)
        comm.sendrecv(sendblk, dest=peer, recvbuf=recvblk, source=peer,
                      sendtag=tag, recvtag=tag)
        out[r0] = recvblk[0]
        out[r1] = recvblk[1]
    return out


# ---------------------------------------------------------------------------
# alltoall menu (coll_base_alltoall.c)


def alltoall_pairwise(comm, sendbuf):
    """Pairwise-exchange alltoall: p-1 sendrecv steps with rotating partners
    (``coll_base_alltoall.c`` pairwise)."""
    size, rank = comm.size, comm.rank
    stack = np.ascontiguousarray(sendbuf)
    if stack.shape[0] != size:
        raise ValueError("alltoall needs a (size, ...) stack per rank")
    out = np.empty_like(stack)
    out[rank] = stack[rank]
    tag = coll_tag(comm)
    for step in range(1, size):
        dst = (rank + step) % size
        src = (rank - step) % size
        comm.sendrecv(stack[dst:dst + 1], dest=dst,
                      recvbuf=out[src:src + 1], source=src,
                      sendtag=tag, recvtag=tag)
    return out


def alltoall_bruck(comm, sendbuf):
    """Bruck alltoall: lg(p) rounds moving packed block sets — the
    small-message latency winner (``coll_base_alltoall.c`` bruck)."""
    size, rank = comm.size, comm.rank
    stack = np.ascontiguousarray(sendbuf)
    if stack.shape[0] != size:
        raise ValueError("alltoall needs a (size, ...) stack per rank")
    if size == 1:
        return np.array(stack, copy=True)
    tag = coll_tag(comm)
    # phase 1: local rotation so slot k targets rank (rank + k) % size
    work = np.array(np.roll(stack, -rank, axis=0), copy=True)
    # phase 2: for each bit, send the slots with that bit set to rank+2^k
    pof2 = 1
    while pof2 < size:
        idx = [k for k in range(size) if k & pof2]
        sendblk = np.stack([work[k] for k in idx])
        recvblk = np.empty_like(sendblk)
        comm.sendrecv(sendblk, dest=(rank + pof2) % size, recvbuf=recvblk,
                      source=(rank - pof2) % size, sendtag=tag, recvtag=tag)
        for j, k in enumerate(idx):
            work[k] = recvblk[j]
        pof2 <<= 1
    # phase 3: inverse rotation + reversal to rank order
    out = np.empty_like(work)
    for k in range(size):
        out[(rank - k) % size] = work[k]
    return out


# ---------------------------------------------------------------------------
# barrier menu (coll_base_barrier.c)


def barrier_recursive_doubling(comm):
    """Recursive-doubling barrier with non-pof2 pre/post folding."""
    size, rank = comm.size, comm.rank
    if size == 1:
        return
    tag = coll_tag(comm)
    token = np.zeros(1, np.uint8)
    scratch = np.zeros(1, np.uint8)
    pof2 = _pof2_floor(size)
    rem = size - pof2
    if rank < 2 * rem:
        if rank % 2 == 0:
            comm.send(token, dest=rank + 1, tag=tag)
            newrank = -1
        else:
            comm.recv(scratch, source=rank - 1, tag=tag)
            newrank = rank // 2
    else:
        newrank = rank - rem
    if newrank >= 0:
        mask = 1
        while mask < pof2:
            peer = _pof2_real_rank(newrank ^ mask, rem)
            comm.sendrecv(token, dest=peer, recvbuf=scratch, source=peer,
                          sendtag=tag, recvtag=tag)
            mask <<= 1
    if rank < 2 * rem:
        if rank % 2 != 0:
            comm.send(token, dest=rank - 1, tag=tag)
        else:
            comm.recv(scratch, source=rank + 1, tag=tag)


def barrier_bruck(comm):
    """Bruck dissemination barrier: ceil(lg p) rounds, any p."""
    size, rank = comm.size, comm.rank
    if size == 1:
        return
    tag = coll_tag(comm)
    token = np.zeros(1, np.uint8)
    scratch = np.zeros(1, np.uint8)
    step = 1
    while step < size:
        comm.sendrecv(token, dest=(rank + step) % size, recvbuf=scratch,
                      source=(rank - step) % size, sendtag=tag, recvtag=tag)
        step <<= 1


def barrier_tree(comm):
    """Binomial fan-in + fan-out barrier."""
    size, rank = comm.size, comm.rank
    if size == 1:
        return
    tag = coll_tag(comm)
    token = np.zeros(1, np.uint8)
    parent, children = _binomial_tree(rank, size, 0)
    for c in children:
        comm.recv(np.zeros(1, np.uint8), source=c, tag=tag)
    if parent is not None:
        comm.send(token, dest=parent, tag=tag)
        comm.recv(np.zeros(1, np.uint8), source=parent, tag=tag)
    waitall([comm.isend(token, dest=c, tag=tag) for c in children])


# ---------------------------------------------------------------------------
# reduce_scatter menu (coll_base_reduce_scatter.c)


def reduce_scatter_recursive_halving(comm, sendbuf, recvcounts=None,
                                     op=op_mod.SUM):
    """Recursive-halving reduce_scatter (commutative, pof2 sizes; otherwise
    falls back to the reduce+scatterv composition)."""
    size, rank = comm.size, comm.rank
    flat = np.ascontiguousarray(sendbuf).reshape(-1)
    if recvcounts is None:
        recvcounts = [cnt for _, cnt in _blocks(flat.size, size)]
    if size & (size - 1) or size == 1 or min(recvcounts) == 0:
        return _basic.reduce_scatter(comm, sendbuf, recvcounts, op)
    tag = coll_tag(comm)
    acc = np.array(flat, copy=True)
    offs = np.concatenate([[0], np.cumsum(recvcounts)]).astype(int)

    lo, hi = 0, size
    mask = size // 2
    while mask > 0:
        mid = (lo + hi) // 2
        peer = rank ^ mask
        if rank < mid:
            keep_lo, keep_hi = offs[lo], offs[mid]
            send_lo, send_hi = offs[mid], offs[hi]
            new_lo, new_hi = lo, mid
        else:
            keep_lo, keep_hi = offs[mid], offs[hi]
            send_lo, send_hi = offs[lo], offs[mid]
            new_lo, new_hi = mid, hi
        recv_seg = np.empty(keep_hi - keep_lo, acc.dtype)
        comm.sendrecv(acc[send_lo:send_hi], dest=peer, recvbuf=recv_seg,
                      source=peer, sendtag=tag, recvtag=tag)
        op(recv_seg, acc[keep_lo:keep_hi])
        lo, hi = new_lo, new_hi
        mask //= 2
    return np.array(acc[offs[rank]:offs[rank + 1]], copy=True)


def reduce_scatter_ring(comm, sendbuf, recvcounts=None, op=op_mod.SUM):
    """Ring reduce_scatter: the reduce-scatter half of the ring allreduce,
    generalized to caller recvcounts.  Commutative only."""
    size, rank = comm.size, comm.rank
    flat = np.ascontiguousarray(sendbuf).reshape(-1)
    if recvcounts is None:
        recvcounts = [cnt for _, cnt in _blocks(flat.size, size)]
    if size == 1:
        return np.array(flat[:recvcounts[0]], copy=True)
    tag = coll_tag(comm)
    acc = np.array(flat, copy=True)
    offs = np.concatenate([[0], np.cumsum(recvcounts)]).astype(int)
    right, left = (rank + 1) % size, (rank - 1) % size
    # schedule shifted one block vs the allreduce ring so the fully-reduced
    # block that lands on each rank is its OWN block, not block rank+1
    for k in range(size - 1):
        sb = (rank - 1 - k) % size
        rb = (rank - 2 - k) % size
        inbuf = np.empty(int(recvcounts[rb]), acc.dtype)
        comm.sendrecv(acc[offs[sb]:offs[sb + 1]], dest=right, recvbuf=inbuf,
                      source=left, sendtag=tag, recvtag=tag)
        op(inbuf, acc[offs[rb]:offs[rb + 1]])
    return np.array(acc[offs[rank]:offs[rank + 1]], copy=True)


# ---------------------------------------------------------------------------
# gather / scatter (binomial trees, coll_base_gather.c / coll_base_scatter.c)


def gather_binomial(comm, sendbuf, root=0):
    """Binomial-tree gather: each subtree root forwards its packed subtree
    block upward; lg(p) depth instead of linear fan-in."""
    size, rank = comm.size, comm.rank
    arr = np.ascontiguousarray(sendbuf)
    tag = coll_tag(comm)
    vrank = (rank - root) % size
    # subtree span in vrank space: [vrank, vrank + span)
    if vrank == 0:
        span = size
    else:
        lowbit = vrank & -vrank
        span = min(lowbit, size - vrank)
    buf = np.empty((span, *arr.shape), arr.dtype)
    buf[0] = arr
    # receive children subtrees (mask ascending = child subtree size)
    mask = 1
    while mask < span:
        child_v = vrank + mask
        if child_v < size:
            child_span = min(mask, size - child_v)
            comm.recv(buf[mask:mask + child_span],
                      source=(child_v + root) % size, tag=tag)
        mask <<= 1
    if vrank != 0:
        parent = ((vrank - (vrank & -vrank)) + root) % size
        comm.send(buf, dest=parent, tag=tag)
        return None
    # root: unrotate from vrank order to rank order
    out = np.empty_like(buf)
    for k in range(size):
        out[(k + root) % size] = buf[k]
    return out


def scatter_binomial(comm, sendbuf, root=0):
    """Binomial-tree scatter: root sends each child its whole subtree block;
    mirror image of gather_binomial."""
    size, rank = comm.size, comm.rank
    tag = coll_tag(comm)
    vrank = (rank - root) % size
    if vrank == 0:
        span = size
    else:
        lowbit = vrank & -vrank
        span = min(lowbit, size - vrank)
    if rank == root:
        stack = np.ascontiguousarray(sendbuf)
        if stack.shape[0] != size:
            raise ValueError("scatter needs (size, ...) on root")
        buf = np.empty_like(stack)
        for k in range(size):           # rotate into vrank order
            buf[k] = stack[(k + root) % size]
    else:
        template = np.ascontiguousarray(sendbuf)
        buf = np.empty((span, *template.shape), template.dtype)
        parent = ((vrank - (vrank & -vrank)) + root) % size
        comm.recv(buf, source=parent, tag=tag)
    # forward child subtree blocks (descending mask so big subtrees go first)
    masks = []
    mask = 1
    while mask < span:
        masks.append(mask)
        mask <<= 1
    reqs = []
    for mask in reversed(masks):
        child_v = vrank + mask
        if child_v < size:
            child_span = min(mask, size - child_v)
            reqs.append(comm.isend(buf[mask:mask + child_span],
                                   dest=(child_v + root) % size, tag=tag))
    waitall(reqs)
    return np.array(buf[0], copy=True)


# registry the tuned component indexes: name -> callable
ALLREDUCE = {
    "nonoverlapping": allreduce_nonoverlapping,
    "recursive_doubling": allreduce_recursive_doubling,
    "ring": allreduce_ring,
    "ring_segmented": allreduce_ring_segmented,
    "rabenseifner": allreduce_redscat_allgather,
    "linear": lambda comm, buf, op=op_mod.SUM: _basic.allreduce(comm, buf, op),
}
BCAST = {
    "binomial": bcast_binomial,
    "chain": bcast_chain,
    "scatter_allgather": bcast_scatter_allgather,
    "linear": lambda comm, buf, root=0: _basic.bcast(comm, buf, root),
}
REDUCE = {
    "binomial": reduce_binomial,
    "pipeline": reduce_pipeline,
    "linear": lambda comm, buf, op=op_mod.SUM, root=0:
        _basic.reduce(comm, buf, op, root),
}
ALLGATHER = {
    "bruck": allgather_bruck,
    "recursive_doubling": allgather_recursive_doubling,
    "ring": allgather_ring,
    "neighbor": allgather_neighbor_exchange,
    "linear": lambda comm, buf: _basic.allgather(comm, buf),
}
ALLTOALL = {
    "bruck": alltoall_bruck,
    "pairwise": alltoall_pairwise,
    "linear": lambda comm, buf: _basic.alltoall(comm, buf),
}
BARRIER = {
    "recursive_doubling": barrier_recursive_doubling,
    "bruck": barrier_bruck,
    "tree": barrier_tree,
    "linear": lambda comm: _basic.barrier(comm),
}
REDUCE_SCATTER = {
    "recursive_halving": reduce_scatter_recursive_halving,
    "ring": reduce_scatter_ring,
    "basic": lambda comm, buf, counts=None, op=op_mod.SUM:
        _basic.reduce_scatter(comm, buf, counts, op),
}
GATHER = {
    "binomial": gather_binomial,
    "linear": lambda comm, buf, root=0: _basic.gather(comm, buf, root),
}
SCATTER = {
    "binomial": scatter_binomial,
    "linear": lambda comm, buf, root=0: _basic.scatter(comm, buf, root),
}
