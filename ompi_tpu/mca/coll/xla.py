"""coll/xla ★ — device-buffer collectives lowering to XLA over the ICI mesh.

The north star (BASELINE.json): MPI_Allreduce / Bcast / Allgather /
Reduce_scatter / Alltoall on TPU-resident buffers lower to ``lax.psum`` /
``ppermute`` / ``all_gather`` / ``psum_scatter`` / ``all_to_all`` inside
``shard_map`` on the communicator's mesh — compiler-scheduled collectives,
no progress engine, no staging.  Slots into the coll framework the way
``coll/cuda``/``coll/hcoll`` do (``/root/reference/ompi/mca/coll/cuda/
coll_cuda_allreduce.c:30-69`` stages D2H→coll→H2D; here the collective runs
ON device instead).

Data model (single-controller SPMD): a communicator of size N over an
N-device mesh; device arrays carry a leading rank axis of global size N
sharded over the mesh axis (``x[i]`` lives on device-rank i's HBM).
Compiled programs are cached per (function, op, shape, dtype, args) — the
trace-time analog of the MCA-selection-at-runtime the reference does per
call (SURVEY.md §7 hard part #1).
"""
from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from ompi_tpu.api import op as op_mod
from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType
from ompi_tpu.runtime import spc


class XlaCollModule:
    def __init__(self, comm, devices, axis_name: str = "mpi") -> None:
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        self.devices = list(devices)
        self.axis = axis_name
        self.mesh = Mesh(np.array(self.devices), (axis_name,))
        self.n = len(self.devices)
        self._cache: dict = {}
        self._lock = threading.Lock()
        self._P = P
        self._sharded = NamedSharding(self.mesh, P(axis_name))
        self._replicated = NamedSharding(self.mesh, P())

    # -- helpers ---------------------------------------------------------
    def _check(self, comm, x):
        import jax

        if not isinstance(x, jax.Array):
            x = self.make_world_array(x)
        if x.shape[0] != self.n:
            raise MpiError(
                ErrorClass.ERR_BUFFER,
                f"device collective needs leading rank axis {self.n}, "
                f"got shape {x.shape}")
        spc.record("device_collectives")
        spc.record("device_bytes", x.nbytes)
        return x

    def make_world_array(self, host_stack):
        """Place a (size, ...) host stack so row i lives on device-rank i."""
        import jax

        arr = np.asarray(host_stack)
        if arr.ndim == 0 or arr.shape[0] != self.n:
            raise MpiError(
                ErrorClass.ERR_BUFFER,
                f"world array needs leading rank axis {self.n}, got shape "
                f"{arr.shape}")
        return jax.device_put(arr, self._sharded)

    def _compiled(self, key, builder):
        with self._lock:
            fn = self._cache.get(key)
            if fn is None:
                fn = builder()
                self._cache[key] = fn
        return fn

    def _shard_map(self, fn, in_specs, out_specs, check_vma: bool = False):
        # check_vma off by default: several collective results (all_gather,
        # gather+fold) are replicated in ways jax 0.9's static varying-mesh-
        # axes checker cannot infer; correctness is covered by tests/test_coll.
        import jax
        from jax import shard_map

        return jax.jit(shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=check_vma))

    def _reduce_in_shard(self, op: op_mod.Op):
        """Per-shard reduction body: native collective or gather+fold."""
        import jax

        ax = self.axis
        if op.jax_reduce == "psum":
            return lambda t: jax.lax.psum(t, ax)
        if op.jax_reduce == "pmax":
            return lambda t: jax.lax.pmax(t, ax)
        if op.jax_reduce == "pmin":
            return lambda t: jax.lax.pmin(t, ax)
        fold = op_mod.jax_fold(op)

        def body(t):
            gathered = jax.lax.all_gather(t, ax)  # (n, *S)
            acc = gathered[0]
            for i in range(1, self.n):
                acc = fold(gathered[i], acc)
            return acc

        return body

    # -- collective slots ------------------------------------------------
    def allreduce_array(self, comm, x, op: op_mod.Op = op_mod.SUM):
        x = self._check(comm, x)
        P = self._P
        key = ("allreduce", op.name, x.shape, str(x.dtype))
        body = self._reduce_in_shard(op)
        # gather+fold lowerings produce replicated values the static checker
        # can't infer; native psum/pmax/pmin pass the check
        fn = self._compiled(key, lambda: self._shard_map(
            lambda t: body(t[0]), P(self.axis), P()))
        return fn(x)

    def reduce_array(self, comm, x, op: op_mod.Op = op_mod.SUM, root: int = 0):
        # on a mesh the reduced value is replicated; root semantics are moot
        return self.allreduce_array(comm, x, op)

    def bcast_array(self, comm, x, root: int = 0):
        """Binomial-tree broadcast: log2(n) ppermute rounds over ICI.

        XLA's CollectivePermute disallows one-to-many pairs, so the tree is
        explicit — the device-native shape of the reference's binomial bcast
        (``coll_base_bcast.c`` binomial algorithm), each round doubling the
        set of devices holding root's data.
        """
        import jax
        import jax.numpy as jnp

        x = self._check(comm, x)
        P = self._P
        n, ax = self.n, self.axis
        key = ("bcast", root, x.shape, str(x.dtype))

        def body(t):  # t: (1, *S)
            me = jax.lax.axis_index(ax)
            rel = (me - root) % n
            cur = t
            k = 1
            while k < n:
                perm = [((root + i) % n, (root + i + k) % n)
                        for i in range(min(k, n - k))]
                recvd = jax.lax.ppermute(cur, ax, perm)
                newly = (rel >= k) & (rel < 2 * k)
                cur = jnp.where(newly, recvd, cur)
                k *= 2
            return cur

        fn = self._compiled(key, lambda: self._shard_map(
            body, P(self.axis), P(self.axis), check_vma=False))
        return fn(x)

    def allgather_array(self, comm, x):
        import jax

        x = self._check(comm, x)
        P = self._P
        key = ("allgather", x.shape, str(x.dtype))
        fn = self._compiled(key, lambda: self._shard_map(
            lambda t: jax.lax.all_gather(t[0], self.axis),
            P(self.axis), P()))
        return fn(x)

    def gather_array(self, comm, x, root: int = 0):
        return self.allgather_array(comm, x)

    def reduce_scatter_array(self, comm, x, op: op_mod.Op = op_mod.SUM):
        """Each rank contributes (n, *S); rank i receives the reduced block i.

        Result: global (n, *S) sharded over the rank axis.
        """
        import jax

        x = self._check(comm, x)
        if x.ndim < 2 or x.shape[1] != self.n:
            raise MpiError(ErrorClass.ERR_BUFFER,
                           f"reduce_scatter needs shape (n, n, ...), got "
                           f"{x.shape}")
        P = self._P
        key = ("reduce_scatter", op.name, x.shape, str(x.dtype))
        if op.jax_reduce == "psum":
            def body(t):  # t: (1, n, *S)
                return jax.lax.psum_scatter(
                    t[0], self.axis, scatter_dimension=0, tiled=False)[None]
        else:
            fold = op_mod.jax_fold(op)
            reduce_body = self._reduce_in_shard(op)

            def body(t):
                full = reduce_body(t[0])          # (n, *S) reduced
                i = jax.lax.axis_index(self.axis)
                return jax.lax.dynamic_index_in_dim(full, i, 0)

        fn = self._compiled(key, lambda: self._shard_map(
            body, P(self.axis), P(self.axis)))
        return fn(x)

    def psum_scatter_array(self, comm, x):
        return self.reduce_scatter_array(comm, x, op_mod.SUM)

    def alltoall_array(self, comm, x):
        """x[i, j] moves to result[j, i] (rank j receives x[:, j])."""
        import jax
        import jax.numpy as jnp

        x = self._check(comm, x)
        if x.ndim < 2 or x.shape[1] != self.n:
            raise MpiError(ErrorClass.ERR_BUFFER,
                           f"alltoall needs shape (n, n, ...), got {x.shape}")
        P = self._P
        key = ("alltoall", x.shape, str(x.dtype))

        def body(t):  # (1, n, *S)
            y = jax.lax.all_to_all(t, self.axis, split_axis=1, concat_axis=0)
            return jnp.swapaxes(y, 0, 1)  # (1, n, *S): row = my received blocks

        fn = self._compiled(key, lambda: self._shard_map(
            body, P(self.axis), P(self.axis)))
        return fn(x)

    def ppermute_array(self, comm, x, perm):
        import jax

        x = self._check(comm, x)
        P = self._P
        perm = tuple((int(s), int(d)) for s, d in perm)
        key = ("ppermute", perm, x.shape, str(x.dtype))
        fn = self._compiled(key, lambda: self._shard_map(
            lambda t: jax.lax.ppermute(t, self.axis, perm),
            P(self.axis), P(self.axis)))
        return fn(x)

    def scatter_array(self, comm, x, root: int = 0):
        """Root's (n, *S) blocks land one per device-rank (a resharding:
        block i moves root→device i over ICI, XLA schedules the moves)."""
        import jax

        x = self._check(comm, x)
        return jax.device_put(x, self._sharded)

    def device_barrier(self, comm) -> None:
        import jax
        import jax.numpy as jnp

        key = ("barrier",)
        P = self._P
        fn = self._compiled(key, lambda: self._shard_map(
            lambda t: jax.lax.psum(t, self.axis),
            P(self.axis), P()))
        tok = self.make_world_array(np.zeros((self.n, 1), np.float32))
        jax.block_until_ready(fn(tok))

    def barrier(self, comm) -> None:
        self.device_barrier(comm)


class XlaCollComponent(Component):
    name = "xla"
    priority = 90

    def register_vars(self, fw) -> None:
        self._prio = self.register_var(
            "priority", vtype=VarType.INT, default=90,
            help="Selection priority of coll/xla (device collectives)")
        self._axis = self.register_var(
            "axis_name", default="mpi",
            help="Mesh axis name used for coll/xla collective programs")

    def comm_query(self, comm):
        rte = comm.rte
        if rte is None or not rte.is_device_world:
            return None
        try:
            devices = [rte.device_of(r) for r in comm.group.world_ranks]
        except Exception:
            return None
        if not devices or any(d is None for d in devices):
            return None
        return self._prio.value, XlaCollModule(comm, devices,
                                               self._axis.value)


COMPONENT = XlaCollComponent()
