"""coll/xla ★ — device-buffer collectives lowering to XLA over the ICI mesh.

The north star (BASELINE.json): MPI_Allreduce / Bcast / Allgather /
Reduce_scatter / Alltoall on TPU-resident buffers lower to ``lax.psum`` /
``ppermute`` / ``all_gather`` / ``psum_scatter`` / ``all_to_all`` inside
``shard_map`` on the communicator's mesh — compiler-scheduled collectives,
no progress engine, no staging.  Slots into the coll framework the way
``coll/cuda``/``coll/hcoll`` do (``/root/reference/ompi/mca/coll/cuda/
coll_cuda_allreduce.c:30-69`` stages D2H→coll→H2D; here the collective runs
ON device instead).

Data model (single-controller SPMD): a communicator of size N over an
N-device mesh; device arrays carry a leading rank axis of global size N
sharded over the mesh axis (``x[i]`` lives on device-rank i's HBM).

Hot-path design: compiled programs are cached per (coll, op, shape, dtype)
— the trace-time analog of per-call MCA selection (SURVEY.md §7 hard part
#1) — and a cache *hit* is one unlocked dict probe + relaxed SPC bump +
the XLA dispatch, nothing else; argument validation is memoized with the
program (same key ⇒ already validated).  ``persistent()`` exposes the
bound compiled program directly — the MPI-4 persistent-collective
(``MPI_Allreduce_init``) analog.
"""
from __future__ import annotations

import threading

import numpy as np

from ompi_tpu.api import op as op_mod
from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.api.request import CompletedRequest
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType
from ompi_tpu.mca.coll import quant as quant_mod
from ompi_tpu.runtime import spc, trace


def _ar_key(x, op):
    """Allreduce program-cache key — the hot-path inline form of
    ``_keyfor("allreduce", ...)``; the two MUST stay in sync."""
    return ("allreduce", op.name, x.shape, x.dtype)


def _traced_dispatch(fn, coll: str, nbytes: int):
    """Wrap a compiled program so its XLA *dispatch* (the async launch,
    not device completion — the stream is the progress engine) appears as
    a ``device`` span.  Only installed while tracing is enabled, so the
    steady-state cache hit stays probe + SPC bump + dispatch."""
    def dispatch(*a):
        t0 = trace.now()
        try:
            return fn(*a)
        finally:
            trace.span(f"xla_{coll}", "device", t0,
                       args={"nbytes": int(nbytes)})
    return dispatch


class PersistentColl:
    """A bound, pre-compiled collective program (MPI_*_init analog).

    ``__call__`` runs it eagerly; ``start`` returns a request completing
    with the result (device dispatch is already asynchronous, so the
    request is born complete — the XLA stream is the progress engine).
    """

    __slots__ = ("fn", "coll", "_nbytes", "_bump")

    def __init__(self, fn, coll: str, nbytes: int) -> None:
        self.fn = fn
        self.coll = coll
        self._nbytes = nbytes
        self._bump = spc.bump_device   # pre-bound: ~sub-µs steady state

    def __call__(self, x):
        self._bump(self._nbytes)
        if trace.enabled:
            return _traced_dispatch(self.fn, self.coll, self._nbytes)(x)
        return self.fn(x)

    def start(self, x):
        spc.bump_device(self._nbytes)
        r = CompletedRequest()
        if trace.enabled:
            r.result = _traced_dispatch(self.fn, self.coll,
                                        self._nbytes)(x)
        else:
            r.result = self.fn(x)
        return r

    def free(self) -> None:
        self.fn = None


class XlaCollModule:
    def __init__(self, comm, devices, axis_name: str = "mpi",
                 bcast_sa_min_bytes: int = 256 << 10) -> None:
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        self.devices = list(devices)
        self.axis = axis_name
        self.mesh = Mesh(np.array(self.devices), (axis_name,))
        self.n = len(self.devices)
        self.bcast_sa_min_bytes = int(bcast_sa_min_bytes)
        self._cache: dict = {}
        self._lock = threading.Lock()
        self._P = P
        self._sharded = NamedSharding(self.mesh, P(axis_name))
        self._replicated = NamedSharding(self.mesh, P())
        self._jax_array = jax.Array   # fast isinstance gate for _fast

    # -- helpers ---------------------------------------------------------
    def _check(self, comm, x, inner_n: bool = False):
        """Validate + place a buffer (slow path, memoized by program key)."""
        import jax

        if not isinstance(x, jax.Array):
            x = self.make_world_array(x)
        if x.shape[0] != self.n:
            raise MpiError(
                ErrorClass.ERR_BUFFER,
                f"device collective needs leading rank axis {self.n}, "
                f"got shape {x.shape}")
        if inner_n and (x.ndim < 2 or x.shape[1] != self.n):
            raise MpiError(
                ErrorClass.ERR_BUFFER,
                f"this collective needs shape (n, n, ...), got {x.shape}")
        return x

    def reshard(self, x):
        """Reshard a device array to the row-per-rank layout (XLA moves)."""
        import jax

        return jax.device_put(x, self._sharded)

    def make_world_array(self, host_stack):
        """Place a (size, ...) host stack so row i lives on device-rank i."""
        import jax

        arr = np.asarray(host_stack)
        if arr.ndim == 0 or arr.shape[0] != self.n:
            raise MpiError(
                ErrorClass.ERR_BUFFER,
                f"world array needs leading rank axis {self.n}, got shape "
                f"{arr.shape}")
        return jax.device_put(arr, self._sharded)

    def _fast(self, key):
        """Steady-state probe: the compiled program under ``key``, or
        None on miss.  Callers gate on ``isinstance(x, self._jax_array)``
        first (host inputs need _check's sharded placement) and dispatch
        the returned fn directly.  Bumps SPC on hit."""
        entry = self._cache.get(key)
        if entry is None:
            return None
        spc.bump_device(entry[1])
        if trace.enabled:
            return _traced_dispatch(entry[0], key[0], entry[1])
        return entry[0]

    def _get(self, comm, key, x, builder, inner_n: bool = False):
        """One-probe fast path; build+validate under the lock on miss.

        Host (numpy) inputs always go through _check for explicit sharded
        placement — a warm cache must not hand a raw host array to the
        compiled program."""
        checked = isinstance(x, np.ndarray)
        if checked:
            x = self._check(comm, x, inner_n)
        entry = self._cache.get(key)
        if entry is None:
            if not checked:
                x = self._check(comm, x, inner_n)
            with self._lock:
                entry = self._cache.get(key)
                if entry is None:
                    entry = (builder(), x.nbytes)
                    self._cache[key] = entry
        fn, nbytes = entry
        spc.bump_device(nbytes)
        if trace.enabled:
            return _traced_dispatch(fn, key[0], nbytes), x
        return fn, x

    def _shard_map(self, fn, in_specs, out_specs, check_vma: bool = False):
        # check_vma off by default: several collective results (all_gather,
        # gather+fold) are replicated in ways jax 0.9's static varying-mesh-
        # axes checker cannot infer; correctness is covered by tests/test_coll.
        import jax

        from ompi_tpu.base.jaxenv import shard_map

        return jax.jit(shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=check_vma))

    def _reduce_in_shard(self, op: op_mod.Op):
        """Per-shard reduction body: native collective or gather+fold."""
        import jax

        ax = self.axis
        if op.jax_reduce == "psum":
            return lambda t: jax.lax.psum(t, ax)
        if op.jax_reduce == "pmax":
            return lambda t: jax.lax.pmax(t, ax)
        if op.jax_reduce == "pmin":
            return lambda t: jax.lax.pmin(t, ax)
        def body(t):
            gathered = jax.lax.all_gather(t, ax)  # (n, *S)
            # fused one-pass stack reduction (pallas on TPU) when a
            # component provides one; else chained folds
            stack = op_mod.jax_stack_reduce(op, t.dtype)
            if stack is not None:
                return stack(gathered)
            fold = op_mod.jax_fold(op, t.dtype)
            acc = gathered[0]
            for i in range(1, self.n):
                acc = fold(gathered[i], acc)
            return acc

        return body

    # -- collective slots ------------------------------------------------
    def allreduce_array(self, comm, x, op: op_mod.Op = op_mod.SUM):
        # coll/quant tier: an EXPLICIT per-comm accuracy budget (the
        # info key) routes eligible (dtype, size) cells onto the
        # block-quantized program.  The `in` probe is one dict get;
        # comms that never declared a budget pay nothing else.
        if quant_mod.BUDGET_KEY in comm.info and op.jax_reduce == "psum":
            codec = quant_mod.pick(comm, "allreduce",
                                   getattr(x, "dtype", None),
                                   int(getattr(x, "nbytes", 0)), op)
            if codec is not None:
                return self._quant_allreduce(comm, x, op, codec)
        # steady-state fast path: one dict probe, then straight into the
        # compiled program
        if isinstance(x, self._jax_array):
            fn = self._fast(_ar_key(x, op))
            if fn is not None:
                return fn(x)
        P = self._P
        fn, x = self._get(
            comm, self._keyfor("allreduce", x, op), x,
            lambda: self._shard_map(
                lambda t: self._reduce_in_shard(op)(t[0]),
                P(self.axis), P()))
        return fn(x)

    def _quant_allreduce(self, comm, x, op: op_mod.Op, codec: str):
        """Block-quantized allreduce: per-shard encode (pallas), gather
        the int8 payloads + per-block scales over the mesh axis, and a
        fused dequant-accumulate kernel folds them — the encoded bytes
        (~3.9x fewer for int8, 2x for bf16) are what cross the links."""
        import jax
        import jax.numpy as jnp

        P = self._P
        ax = self.axis

        def body(t):  # (1, *S) -> (*S), replicated like allreduce
            from ompi_tpu.ops import pallas_quant as pq

            flat = t[0].reshape(-1)
            if codec == "bf16":
                g = jax.lax.all_gather(flat.astype(jnp.bfloat16), ax)
                return jnp.sum(g.astype(jnp.float32),
                               axis=0).reshape(t[0].shape)
            q, s = pq.encode_int8(flat)
            qg = jax.lax.all_gather(q, ax)
            sg = jax.lax.all_gather(s, ax)
            out = pq.dequant_accumulate(qg, sg)
            return out.reshape(-1)[:flat.shape[0]].reshape(t[0].shape)

        # pick() already required a real dtype, so x carries shape/dtype
        fn, x = self._get(
            comm, ("allreduce_quant", codec, op.name, x.shape, x.dtype),
            x, lambda: self._shard_map(body, P(self.axis), P()))
        return fn(x)

    def reduce_array(self, comm, x, op: op_mod.Op = op_mod.SUM,
                     root: int = 0):
        """Reduction lands in root's row; other rows are zero (their
        content is undefined per MPI — zeros make misuse visible).

        Binomial ppermute tree toward root (the device-native shape of
        ``coll_base_reduce.c``'s binomial algorithm): log2(n) halving
        rounds, each sender transmitting its partial exactly once, so
        total wire traffic is (n-1)·S — an allreduce-then-mask would
        move ~2x that and an all_gather construction n²·S."""
        import jax
        import jax.numpy as jnp

        P = self._P
        n, ax = self.n, self.axis
        fold = op_mod.jax_fold(op, None)

        def body(t):  # (1, *S)
            me = jax.lax.axis_index(ax)
            rel = jnp.mod(me - root, n)
            cur = t[0]
            k = 1
            while k < n:           # largest power of two below n
                k *= 2
            k //= 2
            while k >= 1:
                # senders rel in [k, min(2k, n)) -> receivers rel - k;
                # after the round the active set halves to [0, k)
                pairs = [((root + r) % n, (root + r - k) % n)
                         for r in range(k, min(2 * k, n))]
                recvd = jax.lax.ppermute(cur, ax, pairs)
                # ppermute delivers zeros to non-targets: mask the fold
                # (max/min/prod would corrupt on a zero fill)
                is_recv = (rel < k) & (rel + k < n)
                cur = jnp.where(is_recv, fold(cur, recvd), cur)
                k //= 2
            return jnp.where(me == root, cur, jnp.zeros_like(cur))[None]

        fn, x = self._get(
            comm, self._keyfor("reduce", x, op, root), x,
            lambda: self._shard_map(body, P(self.axis), P(self.axis)))
        return fn(x)

    def bcast_array(self, comm, x, root: int = 0):
        """Broadcast with the reference's two-regime selection
        (``coll_base_bcast.c`` + the tuned bcast ladder):

        * small payloads — binomial ppermute tree, log2(n) rounds
          (XLA's CollectivePermute disallows one-to-many pairs, so the
          tree is explicit), latency-optimal;
        * payloads ≥ ``bcast_sa_min_bytes`` — scatter+allgather:
          root's buffer is masked into a psum_scatter (each link
          carries S/n-sized shards, zeros fold in free) and an
          all_gather restores it everywhere — two pipelined ring phases
          moving ~2S/n per link instead of log2(n) serial full-S hops.
        """
        if isinstance(x, self._jax_array):
            fn = self._fast(self._keyfor("bcast", x, root))
            if fn is not None:
                return fn(x)
        import jax
        import jax.numpy as jnp

        P = self._P
        n, ax = self.n, self.axis
        per_payload = (int(np.prod(x.shape[1:])) *
                       np.dtype(x.dtype).itemsize)

        def body_tree(t):  # t: (1, *S)
            me = jax.lax.axis_index(ax)
            rel = (me - root) % n
            cur = t
            k = 1
            while k < n:
                perm = [((root + i) % n, (root + i + k) % n)
                        for i in range(min(k, n - k))]
                recvd = jax.lax.ppermute(cur, ax, perm)
                newly = (rel >= k) & (rel < 2 * k)
                cur = jnp.where(newly, recvd, cur)
                k *= 2
            return cur

        def body_sa(t):  # t: (1, *S)
            me = jax.lax.axis_index(ax)
            contrib = jnp.where(me == root, t[0], jnp.zeros_like(t[0]))
            flat = contrib.reshape(-1)
            size = flat.shape[0]
            blk = -(-size // n)
            if blk * n != size:
                flat = jnp.pad(flat, (0, blk * n - size))
            part = jax.lax.psum_scatter(flat.reshape(n, blk), ax,
                                        scatter_dimension=0,
                                        tiled=False)
            full = jax.lax.all_gather(part, ax)        # (n, blk)
            return full.reshape(-1)[:size].reshape(t.shape)

        body = (body_sa if per_payload >= self.bcast_sa_min_bytes
                else body_tree)
        fn, x = self._get(
            comm, self._keyfor("bcast", x, root), x,
            lambda: self._shard_map(body, P(self.axis), P(self.axis)))
        return fn(x)

    def allgather_array(self, comm, x):
        # coll/quant tier: same explicit-budget gate as allreduce —
        # each rank's block travels encoded and decodes at every
        # receiver (within the codec band)
        if quant_mod.BUDGET_KEY in comm.info:
            codec = quant_mod.pick(comm, "allgather",
                                   getattr(x, "dtype", None),
                                   int(getattr(x, "nbytes", 0)))
            if codec is not None:
                return self._quant_allgather(comm, x, codec)
        if isinstance(x, self._jax_array):
            fn = self._fast(self._keyfor("allgather", x))
            if fn is not None:
                return fn(x)
        import jax

        P = self._P
        fn, x = self._get(
            comm, self._keyfor("allgather", x), x,
            lambda: self._shard_map(
                lambda t: jax.lax.all_gather(t[0], self.axis),
                P(self.axis), P()))
        return fn(x)

    def _quant_allgather(self, comm, x, codec: str):
        """Block-quantized allgather: encode per shard, gather the
        encoded payloads, decode all rows locally (pallas dequant)."""
        import jax
        import jax.numpy as jnp

        P = self._P
        ax = self.axis
        n = self.n

        def body(t):  # (1, *S) -> (n, *S), replicated
            from ompi_tpu.ops import pallas_quant as pq

            flat = t[0].reshape(-1)
            if codec == "bf16":
                g = jax.lax.all_gather(flat.astype(jnp.bfloat16), ax)
                return g.astype(jnp.float32).reshape(
                    (n,) + t[0].shape)
            q, s = pq.encode_int8(flat)
            qg = jax.lax.all_gather(q, ax)        # (n, rows, 128)
            sg = jax.lax.all_gather(s, ax)        # (n, rows, 1)
            dec = pq.decode_int8(qg, sg)          # (n, rows, 128) f32
            return dec.reshape(n, -1)[:, :flat.shape[0]].reshape(
                (n,) + t[0].shape)

        fn, x = self._get(
            comm, ("allgather_quant", codec, x.shape, x.dtype), x,
            lambda: self._shard_map(body, P(self.axis), P()))
        return fn(x)

    def allgatherv_array(self, comm, x, counts):
        """Padded allgatherv: blocks padded to a common (max) first dim.

        Ragged shapes don't exist under XLA's static-shape model, so the
        v-variant is allgather of padded blocks + zero-copy host-side
        views: returns a list of per-rank arrays sliced to ``counts[i]``.
        """
        counts = tuple(int(c) for c in counts)
        if len(counts) != self.n:
            raise MpiError(ErrorClass.ERR_BUFFER,
                           f"allgatherv needs {self.n} counts, got "
                           f"{len(counts)}")
        full = self.allgather_array(comm, x)  # (n, Smax, ...)
        return [full[i, :counts[i]] for i in range(self.n)]

    def gather_array(self, comm, x, root: int = 0):
        """Gathered rows land at root; non-root rows are zero.

        Binomial ppermute tree toward root (``coll_base_gather.c``
        binomial): at round k each sender forwards its accumulated
        k-block subtree window once, so total wire traffic is
        O(n·log n·S/2) — an all_gather-then-mask would move n²·S.  The
        window is a static (k, *S) slice per round (XLA needs static
        shapes); boundary subtrees clamp identically on both sides of a
        pair and the overlap adds zeros, so the add-paste is exact."""
        import jax
        import jax.numpy as jnp

        P = self._P
        n, ax = self.n, self.axis

        def body(t):  # (1, *S) -> (1, n, *S)
            me = jax.lax.axis_index(ax)
            rel = jnp.mod(me - root, n)
            zero_starts = (0,) * (t.ndim - 1)
            buf = jnp.zeros((n,) + t.shape[1:], t.dtype)
            buf = jax.lax.dynamic_update_slice(
                buf, t, (rel,) + zero_starts)   # my block at slot rel
            k = 1
            while k < n:
                # senders rel ≡ k (mod 2k) own the k-block window
                # [rel, rel+k); the receiver rel-k pastes it at the
                # same global slots.  dynamic_slice clamps both sides
                # to n-k in lockstep (receiver start rel+k == sender
                # start), and clamp-overlapped slots are still zero on
                # the sending side, so buf + contrib never collides.
                pairs = [((root + r) % n, (root + r - k) % n)
                         for r in range(k, n, 2 * k)]
                win = jax.lax.dynamic_slice(
                    buf, (rel,) + zero_starts, (k,) + t.shape[1:])
                recvd = jax.lax.ppermute(win, ax, pairs)
                contrib = jax.lax.dynamic_update_slice(
                    jnp.zeros_like(buf), recvd,
                    (rel + k,) + zero_starts)
                buf = buf + contrib   # non-receivers add ppermute zeros
                k *= 2
            out = jnp.roll(buf, root, axis=0)   # slot rel -> rank order
            return jnp.where(me == root, out, jnp.zeros_like(out))[None]

        fn, x = self._get(
            comm, self._keyfor("gather", x, root), x,
            lambda: self._shard_map(body, P(self.axis), P(self.axis)))
        return fn(x)

    def reduce_scatter_array(self, comm, x, op: op_mod.Op = op_mod.SUM):
        """Each rank contributes (n, *S); rank i receives the reduced block i.

        Result: global (n, *S) sharded over the rank axis.
        """
        if isinstance(x, self._jax_array):
            fn = self._fast(self._keyfor("reduce_scatter", x, op))
            if fn is not None:
                return fn(x)
        import jax

        P = self._P
        if op.jax_reduce == "psum":
            def body(t):  # t: (1, n, *S)
                return jax.lax.psum_scatter(
                    t[0], self.axis, scatter_dimension=0, tiled=False)[None]
        else:
            reduce_body = self._reduce_in_shard(op)

            def body(t):
                full = reduce_body(t[0])          # (n, *S) reduced
                i = jax.lax.axis_index(self.axis)
                return jax.lax.dynamic_index_in_dim(full, i, 0)

        fn, x = self._get(
            comm, self._keyfor("reduce_scatter", x, op), x,
            lambda: self._shard_map(body, P(self.axis), P(self.axis)),
            inner_n=True)
        return fn(x)

    def psum_scatter_array(self, comm, x):
        return self.reduce_scatter_array(comm, x, op_mod.SUM)

    def alltoall_array(self, comm, x):
        """x[i, j] moves to result[j, i] (rank j receives x[:, j])."""
        if isinstance(x, self._jax_array):
            fn = self._fast(self._keyfor("alltoall", x))
            if fn is not None:
                return fn(x)
        import jax
        import jax.numpy as jnp

        P = self._P

        def body(t):  # (1, n, *S)
            y = jax.lax.all_to_all(t, self.axis, split_axis=1, concat_axis=0)
            return jnp.swapaxes(y, 0, 1)  # (1, n, *S): row = my received blocks

        fn, x = self._get(
            comm, self._keyfor("alltoall", x), x,
            lambda: self._shard_map(body, P(self.axis), P(self.axis)),
            inner_n=True)
        return fn(x)

    def alltoallv_array(self, comm, x, counts):
        """Padded alltoallv: x (n, n, Smax, ...), counts[i][j] = rows rank j
        receives from rank i.  Returns list-of-lists of sliced views."""
        full = self.alltoall_array(comm, x)  # row i = blocks received by i
        return [[full[i, j, :int(counts[j][i])] for j in range(self.n)]
                for i in range(self.n)]

    def ppermute_array(self, comm, x, perm):
        import jax

        P = self._P
        perm = tuple((int(s), int(d)) for s, d in perm)
        fn, x = self._get(
            comm, self._keyfor("ppermute", x, perm), x,
            lambda: self._shard_map(
                lambda t: jax.lax.ppermute(t, self.axis, perm),
                P(self.axis), P(self.axis)))
        return fn(x)

    def scatter_array(self, comm, x, root: int = 0):
        """Scatter root's buffer: x (n, n, *S) where row root holds
        root's n blocks; rank i receives block i.

        Binomial ppermute tree outward from root — the exact mirror of
        :meth:`gather_array`'s tree (``coll_base_scatter.c`` binomial):
        at round k (descending) each holder forwards the half of its
        subtree window it does not keep, so total wire traffic is
        O(n·log n·S/2) where the previous all_to_all construction moved
        every rank's dead-freight row (n²·S).  Same static-window +
        clamp-lockstep discipline as the gather tree, halving instead
        of doubling."""
        import jax
        import jax.numpy as jnp

        P = self._P
        n, ax = self.n, self.axis
        kmax = 1
        while kmax * 2 < n:
            kmax *= 2

        def body(t):  # (1, n, *S) -> (1, *S)
            me = jax.lax.axis_index(ax)
            rel = jnp.mod(me - root, n)
            blk = t[0]                      # (n, *S); valid at root only
            zero_starts = (0,) * (blk.ndim - 1)
            # slot-rotate so the tree runs in rel space: buf slot s =
            # block of rel s (root holds all, everyone else zeros)
            buf = jnp.where(rel == 0, jnp.roll(blk, -root, axis=0),
                            jnp.zeros_like(blk))
            k = kmax
            while k >= 1:
                # holders rel ≡ 0 (mod 2k) own window [rel, rel+2k);
                # they forward the upper half [rel+k, rel+2k) to rel+k
                pairs = [((root + r) % n, (root + r + k) % n)
                         for r in range(0, n - k, 2 * k)]
                win = jax.lax.dynamic_slice(
                    buf, (rel + k,) + zero_starts,
                    (k,) + blk.shape[1:])
                recvd = jax.lax.ppermute(win, ax, pairs)
                contrib = jax.lax.dynamic_update_slice(
                    jnp.zeros_like(buf), recvd, (rel,) + zero_starts)
                buf = buf + contrib   # non-receivers add ppermute zeros
                k //= 2
            return jax.lax.dynamic_index_in_dim(buf, rel, 0)

        fn, x = self._get(
            comm, self._keyfor("scatter", x, root), x,
            lambda: self._shard_map(body, P(self.axis), P(self.axis)),
            inner_n=True)
        return fn(x)

    def scan_array(self, comm, x, op: op_mod.Op = op_mod.SUM):
        """Inclusive scan over ranks: row i = reduce(rows 0..i)."""
        import jax

        P = self._P

        def body(t):  # (1, *S)
            # scans want a fold XLA can fuse into associative_scan
            fold = op_mod.jax_fold(op, t.dtype, fusable=True)
            g = jax.lax.all_gather(t[0], self.axis)        # (n, *S)
            # fold convention: acc = in (op) acc, rank-ordered
            s = jax.lax.associative_scan(lambda a, b: fold(a, b), g, axis=0)
            i = jax.lax.axis_index(self.axis)
            return jax.lax.dynamic_index_in_dim(s, i, 0)

        fn, x = self._get(
            comm, self._keyfor("scan", x, op), x,
            lambda: self._shard_map(body, P(self.axis), P(self.axis)))
        return fn(x)

    def exscan_array(self, comm, x, op: op_mod.Op = op_mod.SUM):
        """Exclusive scan; rank 0's row is zeros (MPI: undefined)."""
        import jax
        import jax.numpy as jnp

        P = self._P

        def body(t):
            fold = op_mod.jax_fold(op, t.dtype, fusable=True)
            g = jax.lax.all_gather(t[0], self.axis)
            s = jax.lax.associative_scan(lambda a, b: fold(a, b), g, axis=0)
            i = jax.lax.axis_index(self.axis)
            prev = jax.lax.dynamic_index_in_dim(
                s, jnp.maximum(i - 1, 0), 0, keepdims=False)
            return jnp.where(i == 0, jnp.zeros_like(prev), prev)[None]

        fn, x = self._get(
            comm, self._keyfor("exscan", x, op), x,
            lambda: self._shard_map(body, P(self.axis), P(self.axis)))
        return fn(x)

    # -- persistent collectives (MPI_Allreduce_init analog) --------------
    def persistent_coll(self, comm, coll: str, template, *args):
        """Pre-bind a compiled collective for a template buffer.

        Runs the named collective once eagerly (building + caching the
        program, validating the template) and returns a ``PersistentColl``
        whose ``__call__``/``start`` skip everything but the XLA dispatch.
        """
        method = getattr(self, coll + "_array", None)
        if method is None:
            raise MpiError(ErrorClass.ERR_UNSUPPORTED_OPERATION,
                           f"no device collective '{coll}'")
        template = self._check(comm, template)
        method(comm, template, *args)   # build + cache + validate
        fn, nbytes = self._cache[self._keyfor(coll, template, *args)]
        return PersistentColl(fn, coll, nbytes)

    def partitioned_coll(self, comm, coll: str, buckets, *args):
        """Device side of the partitioned persistent collective (MPI-4
        ``Pallreduce_init`` analog, ``api/comm.py pallreduce_init``):
        bind one pre-compiled program PER BUCKET so each ``Pready``
        costs one SPC bump + one async XLA dispatch — bucket i's
        reduction overlaps whatever is still computing bucket i+1."""
        return [self.persistent_coll(comm, coll, b, *args)
                for b in buckets]

    def _keyfor(self, coll: str, x, *args):
        """Single source of truth for program-cache keys (used by the
        *_array methods and persistent_coll alike).  Kept closure-free:
        this runs on every collective call."""
        if coll == "allreduce":
            return _ar_key(x, args[0] if args else op_mod.SUM)
        if coll == "reduce":
            op = args[0] if args else op_mod.SUM
            root = args[1] if len(args) > 1 else 0
            return (coll, op.name, root, x.shape, x.dtype)
        if coll in ("bcast", "gather", "scatter"):
            return (coll, args[0] if args else 0, x.shape, x.dtype)
        if coll in ("reduce_scatter", "scan", "exscan"):
            return (coll, (args[0] if args else op_mod.SUM).name,
                    x.shape, x.dtype)
        if coll in ("allgather", "alltoall"):
            return (coll, x.shape, x.dtype)
        if coll == "ppermute":
            perm = tuple((int(s), int(d)) for s, d in args[0])
            return (coll, perm, x.shape, x.dtype)
        raise MpiError(ErrorClass.ERR_UNSUPPORTED_OPERATION,
                       f"no persistent binding for '{coll}'")

    def device_barrier(self, comm) -> None:
        import jax

        P = self._P
        tok = self.make_world_array(np.zeros((self.n, 1), np.float32))
        fn, tok = self._get(
            comm, ("barrier",), tok,
            lambda: self._shard_map(
                lambda t: jax.lax.psum(t, self.axis), P(self.axis), P()))
        jax.block_until_ready(fn(tok))

    def barrier(self, comm) -> None:
        self.device_barrier(comm)


class XlaMpCollModule:
    """coll/xla for the MULTI-PROCESS device world: the communicator's
    ranks are processes of a ``jax.distributed``-booted job, and one
    compiled program spans every member's devices (the cross-process
    collectives VERDICT round 5 named as the PMIx-shaped hole).

    Data model (multi-controller SPMD — the inverse of the conductor
    model's stacked rows): every member calls the same collective with
    ITS OWN local contribution, no leading rank axis.  The module builds
    a global array whose leading axis is the comm-rank axis — row i
    lives on member i's devices, replicated across that member's local
    shards — and dispatches a jitted ``shard_map`` over a (members ×
    local-devices) mesh that every member executes.  Results of
    allreduce/bcast/allgather are replicated (fully addressable on
    every member); reduce_scatter returns the rank-sharded global array
    (my block is my addressable shard).

    Same hot-path discipline as :class:`XlaCollModule`: compiled
    programs cached per (coll, op/root, shape, dtype); a hit is one
    dict probe + relaxed SPC bump + the per-call row placement + the
    XLA dispatch.
    """

    def __init__(self, comm, rte, axis_name: str = "mpi") -> None:
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        procs = [rte.device_world_process(w)
                 for w in comm.group.world_ranks]
        by_proc: dict = {}
        for d in rte.global_devices:
            by_proc.setdefault(d.process_index, []).append(d)
        rows = [by_proc[p] for p in procs]   # KeyError -> not selectable
        width = min(len(r) for r in rows)
        if width < 1 or any(len(r) != width for r in rows):
            raise MpiError(ErrorClass.ERR_UNSUPPORTED_OPERATION,
                           "uneven per-process device counts")
        self.n = len(procs)
        self.axis = axis_name
        self.mesh = Mesh(np.array([r[:width] for r in rows]),
                         (axis_name, "device"))
        self._P = P
        self._row_sharding = NamedSharding(self.mesh, P(axis_name))
        self._cache: dict = {}
        self._lock = threading.Lock()

    # -- helpers ---------------------------------------------------------
    def make_world_array(self, local):
        """Global (n, *S) array from this member's local contribution:
        my row on my devices (replicated across local shards), every
        other row on its owner's devices."""
        import jax

        arr = np.asarray(local)
        return jax.make_array_from_process_local_data(
            self._row_sharding, arr[None], (self.n,) + arr.shape)

    def _shard_map(self, fn, in_specs, out_specs):
        import jax

        from ompi_tpu.base.jaxenv import shard_map

        return jax.jit(shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))

    def _reduce_body(self, op: op_mod.Op):
        import jax

        ax = self.axis
        if op.jax_reduce == "psum":
            return lambda t: jax.lax.psum(t, ax)
        if op.jax_reduce == "pmax":
            return lambda t: jax.lax.pmax(t, ax)
        if op.jax_reduce == "pmin":
            return lambda t: jax.lax.pmin(t, ax)

        def body(t):
            gathered = jax.lax.all_gather(t, ax)      # (n, *S)
            fold = op_mod.jax_fold(op, t.dtype)
            acc = gathered[0]
            for i in range(1, self.n):
                acc = fold(gathered[i], acc)
            return acc

        return body

    def _get(self, key, builder):
        entry = self._cache.get(key)
        if entry is None:
            with self._lock:
                entry = self._cache.get(key)
                if entry is None:
                    entry = self._cache[key] = builder()
        return entry

    # -- collective slots ------------------------------------------------
    def allreduce_array(self, comm, x, op: op_mod.Op = op_mod.SUM):
        xg = self.make_world_array(x)
        P = self._P
        fn = self._get(
            ("allreduce", op.name, xg.shape, str(xg.dtype)),
            lambda: self._shard_map(
                lambda t: self._reduce_body(op)(t[0]),
                P(self.axis), P()))
        spc.bump_device(xg.nbytes)
        return fn(xg)

    def bcast_array(self, comm, x, root: int = 0):
        import jax
        import jax.numpy as jnp

        xg = self.make_world_array(x)
        P = self._P
        ax = self.axis

        def body(t):   # mask + psum: one ring phase, replicated result
            contrib = jnp.where(jax.lax.axis_index(ax) == root,
                                t[0], jnp.zeros_like(t[0]))
            return jax.lax.psum(contrib, ax)

        fn = self._get(
            ("bcast", int(root), xg.shape, str(xg.dtype)),
            lambda: self._shard_map(body, P(ax), P()))
        spc.bump_device(xg.nbytes)
        return fn(xg)

    def allgather_array(self, comm, x):
        import jax

        xg = self.make_world_array(x)
        P = self._P
        fn = self._get(
            ("allgather", xg.shape, str(xg.dtype)),
            lambda: self._shard_map(
                lambda t: jax.lax.all_gather(t[0], self.axis),
                P(self.axis), P()))
        spc.bump_device(xg.nbytes)
        return fn(xg)

    def reduce_scatter_array(self, comm, x, op: op_mod.Op = op_mod.SUM):
        """Each member contributes (n, *S); the result is the global
        (n, *S) array sharded over members — my reduced block is my
        addressable shard."""
        import jax

        arr = np.asarray(x)
        if arr.ndim < 1 or arr.shape[0] != self.n:
            raise MpiError(
                ErrorClass.ERR_BUFFER,
                f"reduce_scatter needs a leading rank axis {self.n}, "
                f"got shape {arr.shape}")
        xg = self.make_world_array(arr)     # (n, n, *S)
        P = self._P

        if op.jax_reduce == "psum":
            def body(t):
                return jax.lax.psum_scatter(
                    t[0], self.axis, scatter_dimension=0,
                    tiled=False)[None]
        else:
            reduce_body = self._reduce_body(op)

            def body(t):
                full = reduce_body(t[0])
                i = jax.lax.axis_index(self.axis)
                return jax.lax.dynamic_index_in_dim(full, i, 0)

        fn = self._get(
            ("reduce_scatter", op.name, xg.shape, str(xg.dtype)),
            lambda: self._shard_map(body, P(self.axis), P(self.axis)))
        spc.bump_device(xg.nbytes)
        return fn(xg)

    def psum_scatter_array(self, comm, x):
        return self.reduce_scatter_array(comm, x, op_mod.SUM)

    def device_barrier(self, comm) -> None:
        import jax

        tok = self.allreduce_array(
            comm, np.zeros(1, np.float32), op_mod.SUM)
        jax.block_until_ready(tok)


class XlaCollComponent(Component):
    name = "xla"
    priority = 90

    def register_vars(self, fw) -> None:
        self._prio = self.register_var(
            "priority", vtype=VarType.INT, default=90,
            help="Selection priority of coll/xla (device collectives)")
        self._axis = self.register_var(
            "axis_name", default="mpi",
            help="Mesh axis name used for coll/xla collective programs")
        self._bcast_sa = self.register_var(
            "bcast_sa_min_bytes", vtype=VarType.SIZE, default="256k",
            help="Payloads at least this large broadcast via "
                 "scatter+allgather (~2S/n per link, two pipelined ring "
                 "phases) instead of the binomial tree (log2(n) serial "
                 "full-S hops) — the large-message switch of the "
                 "reference's coll_bcast_decision ladder "
                 "(coll_tuned_decision_fixed.c bcast rules)")

    def comm_query(self, comm):
        rte = comm.rte
        if rte is None:
            return None
        if not rte.is_device_world:
            # multi-process device world: comm ranks are processes of a
            # jax.distributed-booted job — select the cross-process
            # module (host colls keep their own slots; this only fills
            # the *_array entry points)
            if not getattr(rte, "device_world_booted", False):
                return None
            if comm.is_inter:
                return None
            try:
                module = XlaMpCollModule(comm, rte, self._axis.value)
            except Exception:
                return None
            return self._prio.value, module
        try:
            devices = [rte.device_of(r) for r in comm.group.world_ranks]
        except Exception:
            return None
        if not devices or any(d is None for d in devices):
            return None
        return self._prio.value, XlaCollModule(
            comm, devices, self._axis.value,
            bcast_sa_min_bytes=int(self._bcast_sa.value))


COMPONENT = XlaCollComponent()
