"""coll/libnbc — nonblocking collectives as progress-driven schedules.

Re-design of ``/root/reference/ompi/mca/coll/libnbc/``: each nonblocking
collective compiles into a **schedule** — an ordered list of rounds, each
holding local compute (OP/COPY) and p2p postings (``nbc_internal.h:149-156``
round/delimiter encoding) — attached to a request that the central progress
engine advances round by round (``opal_progress`` integration).  A round's
local actions run when the round starts; its sends/receives are posted
nonblocking; the round completes when every posted request completes.

Priority 25: above coll/basic (10) so these schedules own the ``i*`` slots
on multi-process communicators, below coll/tuned (30) whose blocking
ladders own the blocking slots (per-function merge in
``coll_base_comm_select.c`` semantics).
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ompi_tpu.api import op as op_mod
from ompi_tpu.api.request import Request
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType
from ompi_tpu.mca.coll.basic import coll_tag
from ompi_tpu.runtime import progress as progress_engine


class Round:
    """One schedule round: local actions, then p2p postings."""

    __slots__ = ("local", "p2p")

    def __init__(self) -> None:
        self.local: list[Callable[[], None]] = []
        self.p2p: list[tuple] = []   # ("send"|"recv", buf, peer, tag)

    def add_local(self, fn: Callable[[], None]) -> "Round":
        self.local.append(fn)
        return self

    def add_send(self, buf, dest: int, tag: int) -> "Round":
        self.p2p.append(("send", buf, dest, tag))
        return self

    def add_recv(self, buf, source: int, tag: int) -> "Round":
        self.p2p.append(("recv", buf, source, tag))
        return self

    @property
    def empty(self) -> bool:
        return not self.local and not self.p2p


class NbcRequest(Request):
    """A collective in flight: advances its schedule from the progress loop."""

    def __init__(self, comm, rounds: list[Round],
                 finish: Optional[Callable[[], object]] = None):
        super().__init__()
        import threading

        self.comm = comm
        self.rounds = [r for r in rounds if not r.empty]
        self._finish = finish
        self.result = None
        self._round_idx = -1
        self._subreqs: list[Request] = []
        # any thread inside the progress loop may drive this schedule;
        # only one may advance it at a time (others simply skip this pass)
        self._adv_lock = threading.Lock()
        progress_engine.register(self._progress_cb)
        self._advance()   # start round 0 immediately (libnbc Sched_commit)

    def _start_round(self, rnd: Round) -> None:
        for fn in rnd.local:
            fn()
        self._subreqs = []
        for kind, buf, peer, tag in rnd.p2p:
            if kind == "send":
                self._subreqs.append(self.comm.isend(buf, dest=peer, tag=tag))
            else:
                self._subreqs.append(self.comm.irecv(buf, source=peer,
                                                     tag=tag))

    def _advance(self) -> int:
        """Move through as many rounds as are already complete."""
        if not self._adv_lock.acquire(blocking=False):
            return 0   # another thread is already advancing this schedule
        try:
            events = 0
            while True:
                if self._round_idx >= 0:
                    if not all(r.complete_flag for r in self._subreqs):
                        return events
                    for r in self._subreqs:
                        if r.error is not None:
                            self._done(error=r.error)
                            return events + 1
                self._round_idx += 1
                if self._round_idx >= len(self.rounds):
                    self._done()
                    return events + 1
                self._start_round(self.rounds[self._round_idx])
                events += 1
        finally:
            self._adv_lock.release()

    def _done(self, error=None) -> None:
        progress_engine.unregister(self._progress_cb)
        if error is None and self._finish is not None:
            self.result = self._finish()
        self.complete(error)

    def _progress_cb(self) -> int:
        if self.complete_flag:
            progress_engine.unregister(self._progress_cb)
            return 0
        return self._advance()


def _completed(result=None) -> NbcRequest:
    class _Trivial(Request):
        pass
    req = _Trivial()
    req.result = result
    req.complete()
    return req


class LibnbcModule:
    """Schedule builders for every nonblocking collective."""

    # -- ibarrier: bruck dissemination (any p) ---------------------------
    def ibarrier(self, comm) -> Request:
        size, rank = comm.size, comm.rank
        if size == 1:
            return _completed()
        tag = coll_tag(comm)
        rounds = []
        step = 1
        while step < size:
            r = Round()
            r.add_send(np.zeros(1, np.uint8), (rank + step) % size, tag)
            r.add_recv(np.zeros(1, np.uint8), (rank - step) % size, tag)
            rounds.append(r)
            step <<= 1
        return NbcRequest(comm, rounds)

    # -- ibcast: binomial tree -------------------------------------------
    def ibcast(self, comm, buf, root=0) -> Request:
        from ompi_tpu.mca.coll.algorithms import _binomial_tree

        arr = np.array(np.ascontiguousarray(buf), copy=True)
        if comm.size == 1:
            return _completed(arr)
        tag = coll_tag(comm)
        parent, children = _binomial_tree(comm.rank, comm.size, root)
        rounds = []
        if parent is not None:
            rounds.append(Round().add_recv(arr, parent, tag))
        if children:
            send_round = Round()
            for c in children:
                send_round.add_send(arr, c, tag)
            rounds.append(send_round)
        return NbcRequest(comm, rounds, finish=lambda: arr)

    # -- ireduce ----------------------------------------------------------
    def ireduce(self, comm, sendbuf, op=op_mod.SUM, root=0) -> Request:
        size, rank = comm.size, comm.rank
        acc = np.array(np.ascontiguousarray(sendbuf), copy=True)
        if size == 1:
            return _completed(acc)
        tag = coll_tag(comm)
        rounds = []
        if not op.commute:
            # linear fan-in at root, folded in rank order
            if rank == root:
                bufs = {r: np.empty_like(acc) for r in range(size)
                        if r != root}
                rnd = Round()
                for r, b in bufs.items():
                    rnd.add_recv(b, r, tag)
                rounds.append(rnd)

                def fold():
                    ordered = [bufs[r] if r != root else acc
                               for r in range(size)]
                    result = ordered[-1].copy()
                    for i in range(size - 2, -1, -1):
                        op(ordered[i], result)
                    acc[...] = result
                rounds.append(Round().add_local(fold))
            else:
                rounds.append(Round().add_send(acc, root, tag))
        else:
            # binomial fan-in (tree order; commutative only)
            vrank = (rank - root) % size
            mask = 1
            while mask < size:
                if vrank & mask:
                    peer = ((vrank - mask) + root) % size
                    rounds.append(Round().add_send(acc, peer, tag))
                    break
                peer_v = vrank | mask
                if peer_v < size:
                    other = np.empty_like(acc)
                    rnd = Round().add_recv(other, (peer_v + root) % size, tag)
                    rounds.append(rnd)
                    rounds.append(Round().add_local(
                        lambda o=other: op(o, acc)))
                mask <<= 1
        return NbcRequest(
            comm, rounds,
            finish=lambda: acc if rank == root else None)

    # -- iallreduce: recursive doubling ----------------------------------
    def iallreduce(self, comm, sendbuf, op=op_mod.SUM) -> Request:
        from ompi_tpu.mca.coll.algorithms import _pof2_floor, _pof2_real_rank

        size, rank = comm.size, comm.rank
        acc = np.array(np.ascontiguousarray(sendbuf), copy=True)
        if size == 1:
            return _completed(acc)
        tag = coll_tag(comm)
        pof2 = _pof2_floor(size)
        rem = size - pof2
        rounds = []

        if rank < 2 * rem:
            if rank % 2 == 0:
                rounds.append(Round().add_send(acc, rank + 1, tag))
                newrank = -1
            else:
                other0 = np.empty_like(acc)
                rounds.append(Round().add_recv(other0, rank - 1, tag))
                rounds.append(Round().add_local(
                    lambda o=other0: op(o, acc)))
                newrank = rank // 2
        else:
            newrank = rank - rem

        if newrank >= 0:
            mask = 1
            while mask < pof2:
                peer = _pof2_real_rank(newrank ^ mask, rem)
                other = np.empty_like(acc)
                rnd = Round()
                rnd.add_send(acc, peer, tag)
                rnd.add_recv(other, peer, tag)
                rounds.append(rnd)

                def combine(o=other, peer=peer):
                    if peer < rank:
                        op(o, acc)          # theirs (op) mine
                    else:
                        tmp = acc.copy()
                        o2 = o.copy()
                        op(tmp, o2)         # mine (op) theirs
                        acc[...] = o2
                rounds.append(Round().add_local(combine))
                mask <<= 1

        if rank < 2 * rem:
            if rank % 2 != 0:
                rounds.append(Round().add_send(acc, rank - 1, tag))
            else:
                rounds.append(Round().add_recv(acc, rank + 1, tag))
        return NbcRequest(comm, rounds, finish=lambda: acc)

    # -- iallgather: bruck ------------------------------------------------
    def iallgather(self, comm, sendbuf) -> Request:
        size, rank = comm.size, comm.rank
        arr = np.ascontiguousarray(sendbuf)
        work = np.empty((size, *arr.shape), arr.dtype)
        work[0] = arr
        if size == 1:
            return _completed(work.copy())
        tag = coll_tag(comm)
        rounds = []
        have, step = 1, 1
        while step < size:
            cnt = min(step, size - have)
            recvblk = np.empty((cnt, *arr.shape), arr.dtype)
            rnd = Round()
            # bruck sends the FIRST cnt slots; they are final by this round
            rnd.add_send(work[:cnt], (rank - step) % size, tag)
            rnd.add_recv(recvblk, (rank + step) % size, tag)
            rounds.append(rnd)
            rounds.append(Round().add_local(
                lambda h=have, c=cnt, rb=recvblk: work.__setitem__(
                    slice(h, h + c), rb)))
            have += cnt
            step <<= 1

        def unshift():
            out = np.empty_like(work)
            for k in range(size):
                out[(rank + k) % size] = work[k]
            return out
        return NbcRequest(comm, rounds, finish=unshift)

    # -- ialltoall: linear, fully overlapped ------------------------------
    def ialltoall(self, comm, sendbuf) -> Request:
        size, rank = comm.size, comm.rank
        stack = np.ascontiguousarray(sendbuf)
        if stack.shape[0] != size:
            raise ValueError("alltoall needs a (size, ...) stack per rank")
        out = np.empty_like(stack)
        out[rank] = stack[rank]
        if size == 1:
            return _completed(out)
        tag = coll_tag(comm)
        rnd = Round()
        for r in range(size):
            if r != rank:
                rnd.add_send(np.ascontiguousarray(stack[r:r + 1]), r, tag)
                rnd.add_recv(out[r:r + 1], r, tag)
        return NbcRequest(comm, [rnd], finish=lambda: out)

    # -- igather / iscatter: linear --------------------------------------
    def igather(self, comm, sendbuf, root=0) -> Request:
        size, rank = comm.size, comm.rank
        arr = np.ascontiguousarray(sendbuf)
        tag = coll_tag(comm)
        if rank == root:
            out = np.empty((size, *arr.shape), arr.dtype)
            out[root] = arr
            if size == 1:
                return _completed(out)
            rnd = Round()
            for r in range(size):
                if r != root:
                    rnd.add_recv(out[r:r + 1], r, tag)
            return NbcRequest(comm, [rnd], finish=lambda: out)
        return NbcRequest(comm, [Round().add_send(arr, root, tag)],
                          finish=lambda: None)

    def iscatter(self, comm, sendbuf, root=0) -> Request:
        size, rank = comm.size, comm.rank
        tag = coll_tag(comm)
        if rank == root:
            stack = np.ascontiguousarray(sendbuf)
            if stack.shape[0] != size:
                raise ValueError("scatter needs (size, ...) on root")
            mine = np.array(stack[root], copy=True)
            if size == 1:
                return _completed(mine)
            rnd = Round()
            for r in range(size):
                if r != root:
                    rnd.add_send(np.ascontiguousarray(stack[r]), r, tag)
            return NbcRequest(comm, [rnd], finish=lambda: mine)
        out = np.empty_like(np.ascontiguousarray(sendbuf))
        return NbcRequest(comm, [Round().add_recv(out, root, tag)],
                          finish=lambda: out)

    # -- ireduce_scatter: reduce-to-0 + scatterv --------------------------
    def ireduce_scatter(self, comm, sendbuf, recvcounts=None,
                        op=op_mod.SUM) -> Request:
        from ompi_tpu.mca.coll.algorithms import _blocks

        size, rank = comm.size, comm.rank
        flat = np.ascontiguousarray(sendbuf).reshape(-1)
        if recvcounts is None:
            recvcounts = [c for _, c in _blocks(flat.size, size)]
        offs = np.concatenate([[0], np.cumsum(recvcounts)]).astype(int)
        if size == 1:
            return _completed(np.array(flat[:recvcounts[0]], copy=True))
        tag = coll_tag(comm)
        acc = np.array(flat, copy=True)
        rounds = []
        if rank == 0:
            bufs = {r: np.empty_like(acc) for r in range(1, size)}
            rnd = Round()
            for r, b in bufs.items():
                rnd.add_recv(b, r, tag)
            rounds.append(rnd)

            def fold():
                ordered = [acc] + [bufs[r] for r in range(1, size)]
                result = ordered[-1].copy()
                for i in range(size - 2, -1, -1):
                    out = result.copy()
                    op(ordered[i], out)
                    result = out
                acc[...] = result
            rounds.append(Round().add_local(fold))
            scatter_rnd = Round()
            for r in range(1, size):
                scatter_rnd.add_send(acc[offs[r]:offs[r + 1]], r, tag)
            rounds.append(scatter_rnd)
            return NbcRequest(
                comm, rounds,
                finish=lambda: np.array(acc[offs[0]:offs[1]], copy=True))
        mine = np.empty(int(recvcounts[rank]), acc.dtype)
        rounds.append(Round().add_send(acc, 0, tag))
        rounds.append(Round().add_recv(mine, 0, tag))
        return NbcRequest(comm, rounds, finish=lambda: mine)

    # -- iscan / iexscan: chain ------------------------------------------
    def iscan(self, comm, sendbuf, op=op_mod.SUM) -> Request:
        size, rank = comm.size, comm.rank
        acc = np.array(np.ascontiguousarray(sendbuf), copy=True)
        if size == 1:
            return _completed(acc)
        tag = coll_tag(comm)
        rounds = []
        if rank > 0:
            prev = np.empty_like(acc)
            rounds.append(Round().add_recv(prev, rank - 1, tag))
            rounds.append(Round().add_local(lambda: op(prev, acc)))
        if rank < size - 1:
            rounds.append(Round().add_send(acc, rank + 1, tag))
        return NbcRequest(comm, rounds, finish=lambda: acc)

    def iexscan(self, comm, sendbuf, op=op_mod.SUM) -> Request:
        size, rank = comm.size, comm.rank
        arr = np.ascontiguousarray(sendbuf)
        out = np.zeros_like(arr)
        if size == 1:
            return _completed(out)
        tag = coll_tag(comm)
        rounds = []
        if rank > 0:
            rounds.append(Round().add_recv(out, rank - 1, tag))
        if rank < size - 1:
            nxt = np.empty_like(arr)

            def make_next():
                if rank == 0:
                    nxt[...] = arr
                else:
                    val = np.array(arr, copy=True)
                    op(out, val)        # val = out (op) arr, rank order
                    nxt[...] = val
            rounds.append(Round().add_local(make_next))
            rounds.append(Round().add_send(nxt, rank + 1, tag))
        return NbcRequest(comm, rounds, finish=lambda: out)


class LibnbcCollComponent(Component):
    name = "libnbc"
    priority = 25

    def register_vars(self, fw) -> None:
        self._prio = self.register_var(
            "priority", vtype=VarType.INT, default=25,
            help="Selection priority of coll/libnbc")

    def comm_query(self, comm):
        if comm.rte is not None and comm.rte.is_device_world:
            return None   # conductor owns the device world
        if comm.size == 1:
            return None
        return self._prio.value, LibnbcModule()


COMPONENT = LibnbcCollComponent()
