"""coll/quant — block-scale quantization: ONE codec, three datapaths.

EQuARX (PAPERS.md, arxiv 2506.17615) shows block-quantized allreduce
buys large speedups at negligible accuracy cost.  This component owns
the shared block-scale codec and the accuracy-budget decision ladder;
three integrations consume it:

* **device** (``coll/xla``): block-scaled allreduce/allgather programs
  built on the ``ops/pallas_quant.py`` encode / dequant-accumulate
  kernels, selected per communicator by :func:`pick` — the
  ``(dtype, size, accuracy_budget)`` rule key, budget read from the
  comm info key :data:`BUDGET_KEY`;
* **host wire** (``btl/tcp``): quantize-on-pack between
  ``Convertor.pack_borrow`` and the tcp out-queue (``otpu_coll_quant_
  wire``), so a 4MB f32 host allreduce moves 2-4x fewer bytes through
  the 0.7 GB/s loopback wire, dequantized on the receive parse;
* **serving KV** (``serving/kv_stream.py``): int8 + per-block-scale KV
  slabs (``otpu_coll_quant_kv_codec``), a direct 2-4x multiplier on
  slots-per-worker.

Codec formats (pure numpy here — the process-stable reference the
Pallas kernels mirror; round-half-even everywhere so every process
encodes IDENTICAL bytes):

* ``int8``: per ``block`` elements one f32 scale ``max(|x|)/127``;
  layout ``[f32 scales x nblocks][int8 q x n]`` — ~3.9x smaller at the
  default block of 128;
* ``bf16``: round-to-nearest-even truncation to the top 16 mantissa/
  exponent bits; layout ``[u16 x n]`` — exactly 2x smaller.

The decision ladder mirrors ``coll/tuned``'s exclusions: quantization
is LOSSY, so it engages only under an EXPLICIT per-communicator
accuracy budget (the info key), never for non-commutative reductions
(the PR 14 dynamic-rule gate: the codec reorders rounding error the
way ring/Rabenseifner reorder operands), and never for exact dtypes —
integer/bool payloads have no error budget to spend (and the codec is
f32-only by construction).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType
from ompi_tpu.mca.coll import algorithms as algs
from ompi_tpu.runtime import profile, spc

#: codec names, and the accuracy band each one charges against the
#: declared budget.  bf16 rounds to 7 stored mantissa bits: per-element
#: relative error <= 2^-8.  int8's single-encode bound is half a step
#: of the block max (0.5/127), but a reduction FOLDS one independent
#: quantization error per rank, so the ladder charges a full step
#: (1/127) of headroom — deeper compression costs a wider band, which
#: is what makes the two rungs distinct.  The ladder admits a codec
#: only when the comm's declared budget covers its band.
CODECS = ("int8", "bf16")
CODEC_BANDS = {"int8": 1.0 / 127.0, "bf16": 2.0 ** -8}
_CODEC_IDS = {"int8": 1, "bf16": 2}
_CODEC_BY_ID = {v: k for k, v in _CODEC_IDS.items()}

#: collectives the quant tier implements (dequant-accumulate fold for
#: the reduction; decode-only for allgather and alltoallv — the latter
#: is the MoE token-dispatch payload, parallel/moe.dispatch_tokens,
#: pure routing with no reduction so commutativity never gates it)
QUANT_COLLS = ("allreduce", "allgather", "alltoallv")

DEFAULT_BLOCK = 128        # elements per scale block (= one lane row)
DEFAULT_MIN_BYTES = 64 << 10

#: the comm info key carrying the accuracy budget (max relative error
#: the application accepts).  Mutable through the budget_key MCA var;
#: this module global IS the current name (consumers read it directly
#: — one dict probe on the device fast path).
BUDGET_KEY = "otpu_quant_budget"

#: THE wire-path guard (trace/telemetry/chaos module-bool discipline):
#: pml/btl hot paths read this bool and branch — nothing else happens
#: while quantize-on-pack is disabled.
wire_enabled = False


def _set_wire(value) -> None:
    global wire_enabled
    wire_enabled = bool(value)


def _set_budget_key(value) -> None:
    global BUDGET_KEY
    BUDGET_KEY = str(value or "otpu_quant_budget")


# -- the shared block-scale codec (numpy reference) ----------------------

def nblocks(nelems: int, block: int) -> int:
    return -(-int(nelems) // int(block))


def encoded_nbytes(nelems: int, codec: str, block: int = None) -> int:
    """Encoded size in bytes of ``nelems`` f32 elements."""
    n = int(nelems)
    if codec == "bf16":
        return 2 * n
    if codec == "int8":
        return n + 4 * nblocks(n, block or block_elems())
    raise KeyError(f"unknown quant codec {codec!r}")


def encode_f32(x, codec: str, block: int = None) -> np.ndarray:
    """Encode an f32 array into the codec's byte layout (owned uint8).

    Deterministic (round-half-even, pure numpy): every process encodes
    identical bytes for identical input — the property the KV prefix
    cache and the wire receive parse rely on."""
    _pt = profile.now() if profile.enabled else 0
    try:
        x = np.ascontiguousarray(x, np.float32).reshape(-1)
        n = x.size
        if codec == "bf16":
            u = x.view(np.uint32)
            # round-to-nearest-even on the dropped 16 bits, in uint64
            # so the carry can never wrap the sign bit.  NaNs bypass
            # the rounding add (it can carry into the exponent and
            # flush a payload NaN to +/-0.0 — silently defeating
            # overflow detection): truncate them and force a mantissa
            # bit so the result stays a NaN.
            rounded = (((u.astype(np.uint64) + 0x7FFF + ((u >> 16) & 1))
                        >> 16).astype(np.uint16))
            nan = ((u & 0x7F800000) == 0x7F800000) \
                & ((u & 0x007FFFFF) != 0)
            out = np.where(nan, ((u >> 16) | 0x0040).astype(np.uint16),
                           rounded).view(np.uint8).copy()
        elif codec == "int8":
            b = int(block or block_elems())
            nb = nblocks(n, b)
            pad = nb * b - n
            xp = (np.pad(x, (0, pad)) if pad else x).reshape(nb, b)
            amax = np.abs(xp).max(axis=1)
            scale = (amax * (1.0 / 127.0)).astype(np.float32)
            inv = np.zeros_like(amax)
            np.divide(127.0, amax, out=inv, where=amax > 0.0)
            q = np.rint(xp * inv[:, None]).astype(np.int8)
            out = np.empty(4 * nb + n, np.uint8)
            out[:4 * nb] = scale.view(np.uint8)
            out[4 * nb:] = q.reshape(-1)[:n].view(np.uint8)
        else:
            raise KeyError(f"unknown quant codec {codec!r}")
        spc.record("quant_encodes")
        return out
    finally:
        if profile.enabled:
            profile.stage_span("quant.encode", _pt)


def decode_f32(buf, codec: str, nelems: int,
               block: int = None) -> np.ndarray:
    """Decode a codec byte layout back to ``nelems`` f32 elements."""
    _pt = profile.now() if profile.enabled else 0
    try:
        n = int(nelems)
        b8 = np.frombuffer(buf, np.uint8) if not isinstance(buf, np.ndarray) \
            else buf.reshape(-1).view(np.uint8)
        want = encoded_nbytes(n, codec, block)
        if b8.size != want:
            raise ValueError(
                f"quant {codec} payload of {b8.size} bytes does not "
                f"match {n} elements (expected {want})")
        if codec == "bf16":
            u16 = np.ascontiguousarray(b8).view(np.uint16)
            out = (u16.astype(np.uint32) << 16).view(np.float32).copy()
        else:
            b = int(block or block_elems())
            nb = nblocks(n, b)
            scale = np.ascontiguousarray(b8[:4 * nb]).view(np.float32)
            q = b8[4 * nb:].view(np.int8)
            pad = nb * b - n
            qp = (np.pad(q, (0, pad)) if pad else q).reshape(nb, b)
            out = (qp.astype(np.float32)
                   * scale[:, None]).reshape(-1)[:n].copy()
        spc.record("quant_decodes")
        return out
    finally:
        if profile.enabled:
            profile.stage_span("quant.decode", _pt)


# -- the (dtype, size, accuracy_budget) decision ladder ------------------

def decide(coll: str, dtype, nbytes: int, budget: Optional[float],
           commute: bool = True, min_bytes: int = None) -> Optional[str]:
    """The quant rule key as a pure function: codec name, or None.

    A cell quantizes only when EVERY gate passes: an explicit positive
    budget, a supported collective, a commutative reduction (the coll/
    tuned non-commutative exclusion — reordered rounding error is an
    operand reorder), an f32 payload (exact dtypes excluded), and a
    message big enough to earn the encode."""
    if not budget or budget <= 0.0:
        return None
    if coll not in QUANT_COLLS or not commute:
        return None
    if dtype is None:
        return None
    try:
        if np.dtype(dtype) != np.float32:
            return None
    except TypeError:
        return None
    if nbytes < (DEFAULT_MIN_BYTES if min_bytes is None else min_bytes):
        return None
    for codec in ("int8", "bf16"):   # deepest compression first
        if budget >= CODEC_BANDS[codec]:
            return codec
    return None


def budget_of(comm) -> Optional[float]:
    """The comm's declared accuracy budget (info key), or None."""
    raw = comm.info.get(BUDGET_KEY)
    if raw is None:
        return None
    try:
        value = float(raw)
    except (TypeError, ValueError):
        from ompi_tpu.base.output import show_help

        show_help("help-coll-quant", "bad-budget",
                  info_key=BUDGET_KEY, value=raw)
        return None
    return value if value > 0.0 else None


def pick(comm, coll: str, dtype, nbytes: int, op=None) -> Optional[str]:
    """Ladder entry for live dispatch sites (tuned / coll/xla): the
    comm's budget + the MCA block/min-bytes config through
    :func:`decide`."""
    budget = budget_of(comm)
    if budget is None:
        return None
    commute = bool(getattr(op, "commute", True)) if op is not None else True
    return decide(coll, dtype, int(nbytes), budget, commute, min_bytes())


# -- host collective variants (the tuned ladder's quant arm) -------------

def allreduce_blockq(comm, sendbuf, op, codec: str):
    """Block-quantized host allreduce: encode once, allgather the
    encoded payloads, dequant-accumulate locally.

    Every rank folds the decoded contributions in RANK ORDER, so all
    ranks compute bit-identical results (the determinism the tolerance
    harness cross-checks); wire traffic is (n-1) ENCODED payloads per
    rank instead of ~2x the raw buffer."""
    arr = np.ascontiguousarray(sendbuf, np.float32)
    b = block_elems()
    enc = encode_f32(arr.reshape(-1), codec, b)
    gathered = algs.allgather_recursive_doubling(comm, enc)
    acc = decode_f32(gathered[0], codec, arr.size, b)
    for r in range(1, comm.size):
        part = decode_f32(gathered[r], codec, arr.size, b)
        acc = op.reduce_arrays(part, acc)
    return acc.reshape(arr.shape)


def allgather_blockq(comm, sendbuf, codec: str):
    """Block-quantized host allgather: each rank's block travels
    encoded and is decoded at every receiver (within the codec band)."""
    arr = np.ascontiguousarray(sendbuf, np.float32)
    b = block_elems()
    enc = encode_f32(arr.reshape(-1), codec, b)
    gathered = algs.allgather_recursive_doubling(comm, enc)
    return np.stack([decode_f32(gathered[r], codec, arr.size,
                                b).reshape(arr.shape)
                     for r in range(comm.size)])


# -- wire codec stage (btl/tcp quantize-on-pack) -------------------------

#: measured wire volume (module ints, bump_device discipline): original
#: vs encoded bytes of every quantized frame this process sent — the
#: bench row's bytes-on-wire evidence.
_wire_orig = 0
_wire_enc = 0


def wire_stats() -> dict:
    return {"orig": _wire_orig, "enc": _wire_enc}


def codec_id(codec: str) -> int:
    return _CODEC_IDS[codec]


def wire_codec_for(convertor, nbytes: int) -> Optional[str]:
    """pml-side eligibility: the codec for this message's fragments, or
    None.  Only contiguous f32 streams qualify — the btl sees opaque
    packed bytes, so the layer that still knows the dtype must stamp
    the fragment."""
    if nbytes < min_bytes():
        return None
    if not getattr(convertor, "_contig", False):
        return None
    try:
        seg_dtype = convertor.datatype.segments[0].dtype
    except (AttributeError, IndexError):
        return None
    if seg_dtype != np.float32:
        return None
    codec = wire_codec_name()
    return codec if codec in CODECS else None


def encode_wire(payload, codec: str) -> Optional[np.ndarray]:
    """The codec stage between pack_borrow and the tcp out-queue: an
    owned encoded payload, or None when this fragment cannot carry the
    codec (element-misaligned split, too small to earn the scales)."""
    global _wire_orig, _wire_enc
    nbytes = len(payload)
    if nbytes % 4 or nbytes < 1024:
        return None
    enc = encode_f32(np.frombuffer(payload, np.float32), codec,
                     block_elems())
    _wire_orig += nbytes
    _wire_enc += enc.nbytes
    spc.record("quant_wire_bytes_saved", nbytes - enc.nbytes)
    return enc


def decode_wire(payload, codec_byte: int, raw_len: int,
                block: int) -> np.ndarray:
    """Receive-parse decode back to the original f32 byte stream.

    Loud on any inconsistency — a quant frame that does not decode
    exactly is wire corruption and must fail like a crc32 mismatch,
    never deliver garbage bytes."""
    codec = _CODEC_BY_ID.get(int(codec_byte))
    if codec is None:
        raise ValueError(f"unknown quant codec id {codec_byte} on the "
                         "wire")
    if raw_len % 4:
        raise ValueError(f"quant frame raw length {raw_len} is not "
                         "f32-aligned")
    out = decode_f32(np.frombuffer(payload, np.uint8) if not
                     isinstance(payload, np.ndarray) else payload,
                     codec, raw_len // 4, int(block))
    return out.view(np.uint8)


# -- MCA component (vars + registry presence) ----------------------------

class QuantCollComponent(Component):
    """Codec/config home.  comm_query answers None: quant is not a
    standalone per-comm module — the tuned ladder, coll/xla, the btl
    wire stage, and the serving KV slabs consume its codec directly."""

    name = "quant"
    priority = 0

    def register_vars(self, fw) -> None:
        self._block = self.register_var(
            "block", vtype=VarType.INT, default=DEFAULT_BLOCK,
            help="Elements per block scale in the int8 codec (128 = "
                 "one device lane row; smaller tracks outliers closer "
                 "at more scale overhead)")
        self._min = self.register_var(
            "min_bytes", vtype=VarType.SIZE, default="64k",
            help="Smallest payload the quant ladder and the wire codec "
                 "stage consider — below this the encode costs more "
                 "than the bytes it saves")
        self._wire = self.register_var(
            "wire", vtype=VarType.BOOL, default=False,
            on_set=_set_wire,
            help="Arm quantize-on-pack for contiguous f32 streams on "
                 "the btl/tcp fastpath (LOSSY within the codec band; "
                 "dequantized on the zero-copy receive parse).  "
                 "Disabled cost is one module-bool check per send")
        self._wire_codec = self.register_var(
            "wire_codec", vtype=VarType.STRING, default="int8",
            help=f"Wire-stage codec: one of {', '.join(CODECS)}")
        self._kv_codec = self.register_var(
            "kv_codec", vtype=VarType.STRING, default="",
            help="Serving KV-slab codec (empty = raw f32 slabs): int8 "
                 "holds ~3.9x more sequences per slab, bf16 2x, within "
                 "the codec band")
        self._budget_key = self.register_var(
            "budget_key", vtype=VarType.STRING,
            default="otpu_quant_budget", on_set=_set_budget_key,
            help="Comm info key read for the per-communicator accuracy "
                 "budget (max relative error) that arms the quant "
                 "decision ladder")

    def comm_query(self, comm):
        return None


COMPONENT = QuantCollComponent()


def block_elems() -> int:
    v = getattr(COMPONENT, "_block", None)
    value = int(v.value) if v is not None and v.value else DEFAULT_BLOCK
    return max(1, value)


def min_bytes() -> int:
    v = getattr(COMPONENT, "_min", None)
    return int(v.value) if v is not None and v.value is not None \
        else DEFAULT_MIN_BYTES


def wire_codec_name() -> str:
    v = getattr(COMPONENT, "_wire_codec", None)
    return str(v.value or "int8") if v is not None else "int8"


def kv_codec() -> str:
    v = getattr(COMPONENT, "_kv_codec", None)
    return str(v.value or "") if v is not None else ""


from ompi_tpu.base.output import register_help as _rh

_rh("help-coll-quant", "bad-budget",
    "The communicator info key {info_key!r} carries {value!r}, which does "
    "not parse as a positive float.  The accuracy budget is the max "
    "relative error the application accepts (>= 1/127 ~ 0.0079 admits "
    "the int8 block codec, >= 2^-8 ~ 0.0039 bf16); quantization stays "
    "OFF for this communicator.")
_rh("help-coll-quant", "wire-frame-bad",
    "A quantized tcp frame from rank {peer} does not decode: {error}. "
    "The frame is treated as wire corruption (the crc32 discipline) "
    "and the job is being aborted — a quant frame must fail loudly, "
    "never deliver garbage bytes.")
