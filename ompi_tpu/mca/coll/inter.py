"""coll/inter — inter-communicator collectives (two-group protocol).

Re-design of ``/root/reference/ompi/mca/coll/inter/`` (1,418 LoC): an
intercommunicator collective involves two groups bridged by p2p between
their leaders — each side runs a LOCAL collective, the leaders exchange
over the bridge, and results fan back out locally.  MPI's intercomm
semantics carry over:

- ``allreduce``/``allgather``: each group receives the reduction /
  concatenation of the OTHER group's contributions.
- ``bcast``/``reduce``: rooted in ONE group — the root passes
  ``ROOT`` (MPI_ROOT), its group peers pass ``PROC_NULL``, and the other
  group passes the root's rank within the root's group.
- ``barrier``: both groups synchronize through the leaders.

Requires the intercomm to carry its local-side collective channel
(``local_comm``, set by dpm at bridge construction), exactly as the
reference requires ``c_local_comm``.
"""
from __future__ import annotations

import numpy as np

from ompi_tpu.api import op as op_mod
from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.api.status import PROC_NULL, ROOT
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType
from ompi_tpu.mca.coll.basic import coll_tag


class InterCollModule:
    def __init__(self) -> None:
        pass

    def _local(self, comm):
        local = getattr(comm, "local_comm", None)
        if local is None:
            raise MpiError(ErrorClass.ERR_COMM,
                           "intercomm has no local collective channel")
        return local

    def barrier(self, comm) -> None:
        tag = coll_tag(comm)
        local = self._local(comm)
        token = np.zeros(1, np.uint8)
        local.barrier()
        if local.rank == 0:
            # leaders handshake over the bridge (both directions)
            req = comm.isend(token, 0, tag)
            comm.recv(np.zeros(1, np.uint8), 0, tag)
            req.wait()
        local.barrier()

    def allreduce(self, comm, sendbuf, op: op_mod.Op = op_mod.SUM):
        """Each group receives the reduction of the OTHER group's data."""
        tag = coll_tag(comm)
        local = self._local(comm)
        arr = np.ascontiguousarray(sendbuf)
        mine = local.reduce(arr, op, root=0) if local.size > 1 else arr
        out = np.empty_like(arr)
        if local.rank == 0:
            req = comm.isend(np.ascontiguousarray(mine), 0, tag)
            comm.recv(out, 0, tag)
            req.wait()
        return np.asarray(local.bcast(out, root=0)).reshape(arr.shape)

    def allgather(self, comm, sendbuf):
        """Each group receives the concatenation of the OTHER group."""
        tag = coll_tag(comm)
        local = self._local(comm)
        arr = np.ascontiguousarray(sendbuf)
        g = local.gather(arr, root=0) if local.size > 1 else arr[None]
        out = np.empty((comm.remote_size, *arr.shape), arr.dtype)
        if local.rank == 0:
            req = comm.isend(np.ascontiguousarray(g), 0, tag)
            comm.recv(out, 0, tag)
            req.wait()
        return np.asarray(local.bcast(out, root=0))

    def bcast(self, comm, buf, root):
        """Rooted: root passes ROOT, root's peers PROC_NULL, the other
        group the root's rank in the remote group."""
        tag = coll_tag(comm)
        local = self._local(comm)
        arr = np.ascontiguousarray(buf)
        if root == PROC_NULL:
            return arr                      # root's group, non-root: no-op
        if root == ROOT:
            # I am the root: ship to the other group's leader
            comm.send(arr, 0, tag)
            return arr
        # receiving group: leader takes the bridge message, local bcast
        if local.rank == 0:
            got = np.empty_like(arr)
            comm.recv(got, root, tag)
        else:
            got = np.empty_like(arr)
        return np.asarray(local.bcast(got, root=0)).reshape(arr.shape)

    def reduce(self, comm, sendbuf, op: op_mod.Op = op_mod.SUM, root=0):
        """Rooted: the root (passing ROOT) receives the reduction of the
        OTHER group's contributions."""
        tag = coll_tag(comm)
        local = self._local(comm)
        arr = np.ascontiguousarray(sendbuf)
        if root == ROOT:
            out = np.empty_like(arr)
            comm.recv(out, 0, tag)          # from the other group's leader
            return out
        if root == PROC_NULL:
            return None
        # contributing group: local reduce, leader ships to the root
        red = local.reduce(arr, op, root=0) if local.size > 1 else arr
        if local.rank == 0:
            comm.send(np.ascontiguousarray(red), root, tag)
        return None


class InterCollComponent(Component):
    name = "inter"
    priority = 45

    def register_vars(self, fw) -> None:
        self._prio = self.register_var(
            "priority", vtype=VarType.INT, default=45,
            help="Selection priority of coll/inter (intercomm collectives)")

    def comm_query(self, comm):
        if not comm.is_inter:
            return None
        if getattr(comm, "local_comm", None) is None:
            return None
        return self._prio.value, InterCollModule()


COMPONENT = InterCollComponent()
