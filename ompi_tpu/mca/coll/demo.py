"""coll/demo — the teaching interposition component.

Re-design of ``/root/reference/ompi/mca/coll/demo/`` (1,675 LoC): a
component that, when enabled, slots in ABOVE the real selection and
announces every collective before delegating to the underlying module —
the minimal example of the interposition pattern that coll/monitoring,
coll/sync, and coll/cuda (here: coll/conductor) are production uses of.

Enable with ``--mca coll_demo_priority 100``; verbosity goes to the
coll framework's output stream (``--mca coll_base_verbose 1``).
"""
from __future__ import annotations

from ompi_tpu.base import output as _output
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType

_WRAPPED = ("barrier", "bcast", "allreduce", "reduce", "allgather",
            "alltoall", "scatter", "gather", "scan", "exscan")


class DemoModule:
    """Wraps the slots already chosen in the comm's c_coll table."""

    def __init__(self, component: "DemoCollComponent") -> None:
        self._c = component

    def comm_enable(self, comm) -> None:
        # runs after the vtable is filled by lower-priority components;
        # re-point each slot at an announcing wrapper around the original
        stream = self._c.framework.stream if self._c.framework else 0
        for name in _WRAPPED:
            inner = comm.c_coll.get(name)
            if inner is None or getattr(inner, "_demo_wrapped", False):
                continue

            def wrapped(comm_arg, *args, _inner=inner, _name=name, **kw):
                _output.output(stream, 1, "demo: %s on %s (rank %d)",
                               _name, comm_arg.name, comm_arg.rank)
                return _inner(comm_arg, *args, **kw)

            wrapped._demo_wrapped = True
            comm.c_coll[name] = wrapped


class DemoCollComponent(Component):
    name = "demo"
    priority = -1          # never selected unless the user asks

    def register_vars(self, fw) -> None:
        self._prio = self.register_var(
            "priority", vtype=VarType.INT, default=-1,
            help="Priority of coll/demo (negative = disabled; set >=100 "
                 "to interpose the announcing wrappers)")

    def open(self) -> bool:
        self.priority = int(self._prio.value)
        return self.priority >= 0

    def comm_query(self, comm):
        return self.priority, DemoModule(self)


COMPONENT = DemoCollComponent()
