"""coll/ftagree — ULFM agreement collective.

Re-design of ``/root/reference/ompi/mca/coll/ftagree/``: provides the
``agree``/``iagree`` slots of the per-comm vtable with a fault-tolerant
consensus (the ERA algorithm, ``coll_ftagree_earlyreturning.c``), selected
at priority above the non-FT fallbacks so agreement keeps working across
failures.  The consensus itself rides the coordination service
(:mod:`ompi_tpu.ft.agreement`).

ULFM semantics (``ompi/mpiext/ftmpi/c/comm_agree.c``): the int flag is
bitwise-ANDed across all live participants; the call is uniform; if a
group member failed and has not been acknowledged via
``Comm.ack_failed``, every survivor raises ``ProcFailedError`` (carrying
the agreed flag) after agreeing — agreement on the error itself.
"""
from __future__ import annotations

from ompi_tpu.api.request import CompletedRequest
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType
from ompi_tpu.ft import state as ft_state


class FtAgreeModule:
    def __init__(self, component: "FtAgreeComponent") -> None:
        self._c = component

    def agree(self, comm, flag: int) -> int:
        from ompi_tpu.api.errors import ProcFailedError
        from ompi_tpu.ft.agreement import agree_kv, agree_p2p, agree_tree

        members = list(comm.group.world_ranks)
        live = [r for r in members if not ft_state.is_failed(r)]
        seq = comm._agree_seq = getattr(comm, "_agree_seq", 0) + 1
        # each participant contributes (flag, its failure knowledge, whether
        # it has group failures it hasn't acknowledged): the AND/union/OR
        # over contributions makes the failed-set AND the error outcome part
        # of the uniform decision (comm_agree.c group-fault sync) — all
        # survivors raise ProcFailedError or none do, never a mix
        acked = getattr(comm, "_acked_failed", frozenset())
        known_failed = ft_state.failed_ranks()
        my_unacked = any(r in known_failed and r not in acked
                         for r in members)
        instance = ("agree", comm.cid, comm.epoch, seq)
        prev = (("agree", comm.cid, comm.epoch, seq - 2)
                if seq > 2 else None)
        combine = lambda a, b: (a[0] & b[0], a[1] | b[1], a[2] or b[2])
        contribution = (int(flag), known_failed, my_unacked)
        alg = (self._c.alg_var.value or "era").strip()
        if alg == "era":
            # coordination-free ERA: decisions never touch the coord
            # server (it stays restricted to wire-up)
            (agreed_flag, agreed_failed, any_unacked), _ = agree_p2p(
                comm, instance, contribution, live, combine,
                prev_instance=prev)
        elif alg == "tree":
            (agreed_flag, agreed_failed, any_unacked), _ = agree_tree(
                comm, instance, contribution, live, combine,
                prev_instance=prev)
        else:
            (agreed_flag, agreed_failed, any_unacked), _ = agree_kv(
                comm.rte, instance, contribution, live, combine,
                prev_instance=prev)
        if any_unacked:
            in_group_failed = [r for r in members if r in agreed_failed]
            err = ProcFailedError(
                f"agreement completed but ranks {in_group_failed} failed "
                f"without all survivors acknowledging",
                tuple(comm.group.rank_of(r) for r in in_group_failed))
            err.flag = agreed_flag
            comm._err(err)  # route through the communicator errhandler
        return agreed_flag

    def iagree(self, comm, flag: int):
        r = CompletedRequest()
        r.result = self.agree(comm, flag)
        return r


class FtAgreeComponent(Component):
    name = "ftagree"
    priority = 30

    def register_vars(self, fw) -> None:
        self._prio = self.register_var(
            "priority", vtype=VarType.INT, default=30,
            help="Selection priority of coll/ftagree")
        self.alg_var = self.register_var(
            "algorithm", vtype=VarType.STRING, default="era",
            help="Agreement algorithm: 'era' (coordination-free p2p "
                 "tree reduce + pledge-guarded takeover, the default), "
                 "'tree' (binomial p2p reduce with KV-anchored uniform "
                 "decision), or 'kv' (coordinator-decides over the "
                 "coordination service)")

    def comm_query(self, comm):
        # the consensus needs the out-of-band KV service: multi-process only
        if comm.rte is None or comm.rte.is_device_world:
            return None
        if getattr(comm.rte, "client", None) is None:
            return None
        if comm.size == 1:
            return None
        return self._prio.value, FtAgreeModule(self)


COMPONENT = FtAgreeComponent()
