"""coll/sm — shared-memory collectives on a mapped segment.

Re-design of ``/root/reference/ompi/mca/coll/sm/`` (2,813 LoC): same-node
ranks of a communicator map one shared segment and run bcast / allreduce /
barrier through it directly — one copy in, one copy out, no per-fragment
pickling through the btl rings.  Synchronization uses monotonically
increasing shared counters (native C++ atomics), so no reset races exist:
round ``k`` of an operation waits for its counter to reach ``k * n``.

Segment layout::

    [ bar_arrive u64 | bc_gen u64 | bc_readers u64 | ar_arrive u64 |
      ar_done u64 | pad to 64 ]
    [ bcast buffer: slot ]
    [ n contribution slots: slot each ]

Payloads larger than the slot (``otpu_coll_sm_coll_slot_size``) fall
through to the next coll module down the comm's stack (normally
coll/tuned's decision ladders; coll/basic only when nothing else is
selected).  Selected between tuned (30) and han (40) when every member
shares this node and the native library is available.
"""
from __future__ import annotations

import os
import time
from multiprocessing import shared_memory
from typing import Optional

import numpy as np

from ompi_tpu.api import op as op_mod
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType
from ompi_tpu.mca.btl.sm import _attach
from ompi_tpu.mca.coll.basic import BasicCollModule

_HDR = 64
_BAR_ARRIVE = 0
_BC_GEN = 8
_BC_READERS = 16
_AR_ARRIVE = 24
_AR_DONE = 32


class SmCollModule:
    def __init__(self, component: "SmCollComponent") -> None:
        self._c = component
        self._fallback = BasicCollModule()
        self._seg = None
        self._addr = 0
        self._slot = int(component.slot_var.value)
        self._rounds = {"bar": 0, "bc": 0, "ar": 0}

    # -- lifecycle -------------------------------------------------------
    def comm_enable(self, comm) -> None:
        from ompi_tpu import native

        self._native = native
        # above-slot fallback: the next provider DOWN the comm's own coll
        # stack (normally coll/tuned's decision ladders — measured ~25%
        # faster than coll/basic at 4MB), honoring the user's component
        # include/exclude instead of hardcoding basic
        try:
            mine = comm.coll_modules.index(self)
            found = next(
                (m for m in reversed(comm.coll_modules[:mine])
                 if hasattr(m, "allreduce") and hasattr(m, "bcast")),
                None)
            if found is not None:
                self._fallback = found
            else:
                from ompi_tpu.base.output import show_help

                show_help("help-coll-sm", "no-fallback", comm=comm.name)
        except (ValueError, AttributeError):
            pass
        n = comm.size
        size = _HDR + self._slot * (n + 1)
        tag = os.environ.get("OTPU_COORD", "l").replace(":", "_") \
            .replace(".", "_")
        rte = comm.rte
        # job-qualified: a spawned job's cid-0 world must not collide with
        # the parent job's
        name = f"otpu_csm_{tag}_{getattr(rte, 'job', '0')}_{comm.cid}"
        try:
            if comm.rank == 0:
                shm = shared_memory.SharedMemory(name=name, create=True,
                                                 size=size)
                shm.buf[:_HDR] = b"\0" * _HDR
                rte.modex_put(f"coll_sm_{comm.cid}", name)
            else:
                # rank 0 publishes during ITS comm_enable; comm creation
                # is collective so the blocking get cannot deadlock
                got = rte.modex_get(comm.group.world_rank(0),
                                    f"coll_sm_{comm.cid}")
                if got is False:
                    raise OSError("peer could not create the segment")
                shm = _attach(got)
        except OSError as exc:
            # constrained /dev/shm (container defaults are as small as
            # 64MB): surrender the slots to the fallback module instead
            # of failing the communicator.  rank 0 publishes False so
            # peers don't block on a name that will never appear.
            if comm.rank == 0:
                rte.modex_put(f"coll_sm_{comm.cid}", False)
            from ompi_tpu.base.output import show_help

            show_help("help-coll-sm", "no-segment", comm=comm.name,
                      error=str(exc))
            shm = None
        # the enable/disable decision must be COLLECTIVE: one rank whose
        # attach failed running message-based collectives while the rest
        # spin on shared counters would hang the communicator.  Vote over
        # the fallback module (comm creation is collective, so everyone
        # is here).
        ok = np.array([1 if shm is not None else 0], np.int64)
        all_ok = int(np.asarray(self._fallback.allreduce(
            comm, ok, op_mod.MIN)).ravel()[0])
        if not all_ok:
            if shm is not None:
                try:
                    shm.close()
                    if comm.rank == 0:
                        shm.unlink()
                except OSError:
                    pass
            self._seg = None
            return
        import ctypes

        self._seg = shm
        self._addr = ctypes.addressof(ctypes.c_char.from_buffer(shm.buf))
        self._buf = np.frombuffer(shm.buf, np.uint8, offset=_HDR)
        self._owner = comm.rank == 0

    def comm_unquery(self, comm) -> None:
        if self._seg is not None:
            try:
                self._buf = None
                self._seg.close()
            except Exception:
                pass
            if self._owner:
                try:
                    self._seg.unlink()
                except Exception:
                    pass
            self._seg = None

    # -- shared-counter helpers ------------------------------------------
    def _wait_at_least(self, off: int, target: int,
                       comm=None) -> None:
        """Spin until the shared counter reaches ``target``; a failed comm
        member turns the wait into ProcFailedError instead of a hang
        (the basic algorithms get this from pml request completion)."""
        from ompi_tpu.ft import state as ft_state
        from ompi_tpu.runtime.progress import progress

        spins = 0
        while self._native.atomic_load_u64(self._addr + off) < target:
            spins += 1
            # keep the transports moving: a peer may be unable to reach
            # this collective until our queued btl output (pending
            # rendezvous frags) drains — spinning without progress would
            # deadlock the pair
            progress()
            if comm is not None and spins % 2048 == 0:
                dead = [r for r in comm.group.world_ranks
                        if ft_state.is_failed(r)]
                if dead:
                    from ompi_tpu.api.errors import ProcFailedError

                    raise ProcFailedError(
                        f"peer(s) {dead} failed during a coll/sm "
                        f"operation", tuple(dead))
            time.sleep(0)

    def _bump(self, off: int) -> None:
        self._native.atomic_add_i64(self._addr + off, 1)

    def _bc_buf(self) -> np.ndarray:
        return self._buf[:self._slot]

    def _slot_buf(self, rank: int) -> np.ndarray:
        start = self._slot * (rank + 1)
        return self._buf[start:start + self._slot]

    # -- collectives ------------------------------------------------------
    def barrier(self, comm) -> None:
        if self._seg is None:
            return self._fallback.barrier(comm)
        self._rounds["bar"] += 1
        self._bump(_BAR_ARRIVE)
        self._wait_at_least(_BAR_ARRIVE, self._rounds["bar"] * comm.size,
                            comm)

    def bcast(self, comm, buf, root=0):
        arr = np.ascontiguousarray(buf)
        if self._seg is None or arr.nbytes > self._slot:
            return self._fallback.bcast(comm, arr, root)
        self._rounds["bc"] += 1
        rnd, n = self._rounds["bc"], comm.size
        if comm.rank == root:
            # previous round's readers must be done before overwriting
            self._wait_at_least(_BC_READERS, (rnd - 1) * (n - 1), comm)
            self._bc_buf()[:arr.nbytes] = arr.view(np.uint8).reshape(-1)
            self._native.atomic_store_u64(self._addr + _BC_GEN, rnd)
            return arr
        self._wait_at_least(_BC_GEN, rnd, comm)
        out = np.empty_like(arr)
        out.view(np.uint8).reshape(-1)[:] = self._bc_buf()[:arr.nbytes]
        self._bump(_BC_READERS)
        return out

    def allreduce(self, comm, sendbuf, op: op_mod.Op = op_mod.SUM):
        arr = np.ascontiguousarray(sendbuf)
        if self._seg is None or arr.nbytes > self._slot:
            return self._fallback.allreduce(comm, arr, op)
        self._rounds["ar"] += 1
        rnd, n = self._rounds["ar"], comm.size
        # everyone from the previous round must have finished reading the
        # slots before this round's writes
        self._wait_at_least(_AR_DONE, (rnd - 1) * n, comm)
        me = self._slot_buf(comm.rank)
        me[:arr.nbytes] = arr.view(np.uint8).reshape(-1)
        self._bump(_AR_ARRIVE)
        self._wait_at_least(_AR_ARRIVE, rnd * n, comm)
        # fold in rank order (non-commutative safe), each rank locally —
        # the coll/sm tradeoff: n-fold small compute for zero messages
        acc = np.array(self._slot_buf(n - 1)[:arr.nbytes]
                       .view(arr.dtype), copy=True)
        for r in range(n - 2, -1, -1):
            contrib = np.array(self._slot_buf(r)[:arr.nbytes]
                               .view(arr.dtype), copy=True)
            op(contrib, acc)
        self._bump(_AR_DONE)
        return acc.reshape(arr.shape)

    def reduce(self, comm, sendbuf, op: op_mod.Op = op_mod.SUM, root=0):
        out = self.allreduce(comm, sendbuf, op)
        return out if comm.rank == root else None


class SmCollComponent(Component):
    name = "sm_coll"
    priority = 35

    def register_vars(self, fw) -> None:
        self._prio = self.register_var(
            "priority", vtype=VarType.INT, default=35,
            help="Selection priority of coll/sm (mapped-segment colls)")
        self.slot_var = self.register_var(
            "slot_size", vtype=VarType.SIZE, default="2m",
            help="Per-rank shared slot size; larger payloads fall through "
                 "to the next coll module (measured crossover vs the "
                 "tuned ring ~2-4MB on the oversubscribed host path)")

    def comm_query(self, comm):
        rte = comm.rte
        if rte is None or rte.is_device_world:
            return None
        if comm.size < 2 or comm.is_inter:
            return None
        if getattr(rte, "client", None) is None:
            return None
        try:
            from ompi_tpu import native

            if not native.available():
                return None
            my_node = rte.node_of(rte.my_world_rank)
            if my_node is None:
                return None
            for w in comm.group.world_ranks:
                if rte.node_of(w) != my_node:
                    return None
        except Exception:
            return None
        return self._prio.value, SmCollModule(self)


COMPONENT = SmCollComponent()

from ompi_tpu.base.output import register_help as _rh

_rh("help-coll-sm", "no-segment",
    "coll/sm on {comm} could not create/attach its shared segment "
    "({error}); mapped-segment collectives are disabled for this "
    "communicator and the next coll module serves everything.")
_rh("help-coll-sm", "no-fallback",
    "coll/sm on {comm}: no other selected coll module provides the "
    "above-slot collectives, so payloads larger than slot_size use the "
    "built-in basic algorithms even if coll/basic was excluded.")
