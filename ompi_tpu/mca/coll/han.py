"""coll/han — hierarchical two-level collectives (ICI-intra × DCN-inter).

Re-design of ``/root/reference/ompi/mca/coll/han/coll_han.h:189-215``: a
communicator spanning multiple nodes is split into a *low* sub-communicator
(ranks sharing a node / ICI domain) and *up* sub-communicators (one per
low-rank, connecting peers across nodes over DCN), and each collective is
composed from sub-collectives on those two levels so the slow inter-node
links carry the minimum number of bytes:

    allreduce = reduce_scatter(low) → allreduce(up) → allgather(low)
                (symmetric fast path; leader reduce/bcast otherwise)
    bcast     = root→node-leader → bcast(leaders) → bcast(low)
    allgather = gather(low) → allgatherv(leaders) → bcast(low)
    barrier   = gather(low) → barrier(leaders) → bcast(low)

The sub-communicators select their own coll modules (tuned ladders), so the
composition reuses the whole algorithm menu per level — exactly the
reference's design where han stores up/low module pairs per collective.

Node identity comes from the RTE modex ("node" key: OTPU_NODE_ID or the
hostname), so `tpurun --fake-nodes K` can exercise the hierarchy on one
host the way the reference tests han with `mpirun --oversubscribe`.

The device-side analog (`XlaHierarchicalColl`) composes the same schedule
at trace time over a 2-D ``jax.sharding.Mesh`` with ('dcn', 'ici') axes:
psum_scatter over the ICI axis, psum over DCN, all_gather over ICI — the
SURVEY §2.6 "per-slice psum + cross-slice DCN allreduce" template.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ompi_tpu.api import op as op_mod
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType
from ompi_tpu.mca.coll.basic import BasicCollModule, coll_tag


class HanModule:
    """Per-communicator hierarchical module (lazy sub-comm construction)."""

    def __init__(self, component: "HanCollComponent", node_of: list):
        self._c = component
        self._node_of = list(node_of)      # comm rank -> node color (int)
        self._low = None                   # intra-node sub-comm
        self._up = None                    # same-low-rank-across-nodes
        self._leaders = None               # low-rank-0 ranks (None elsewhere)
        self._building = False
        self._fallback = BasicCollModule()
        # per-node bookkeeping (computable locally from node_of)
        colors = sorted(set(self._node_of))
        self._ranks_of_node = {c: [r for r, n in enumerate(self._node_of)
                                   if n == c] for c in colors}
        self._node_index = {c: i for i, c in enumerate(colors)}
        self._low_rank_of = {}
        self._leader_of_node = {}
        for c, ranks in self._ranks_of_node.items():
            self._leader_of_node[c] = ranks[0]
            for j, r in enumerate(ranks):
                self._low_rank_of[r] = j
        sizes = {len(v) for v in self._ranks_of_node.values()}
        self._symmetric = len(sizes) == 1

    # -- sub-communicator construction (collective, lazy) ----------------
    def _ready(self, comm) -> bool:
        """Build the sub-comms on first use; False while building.

        Construction itself issues collectives on the parent (split's
        allgather + CID agreement), which route back through this module —
        during that window every slot delegates to the rank-ordered basic
        fallback, identically on all members, so the recursion grounds out.
        """
        if self._building:
            # mid-construction (an earlier split already set _low but the
            # leaders comm may not exist yet): stay on the fallback
            return False
        if self._low is not None:
            return True
        self._building = True
        try:
            me = comm.rank
            my_node = self._node_of[me]
            # low: ranks of my node, ordered by parent rank
            self._low = comm.split(self._node_index[my_node], key=me)
            # up: peers holding my low-rank on every node (DCN plane)
            self._up = comm.split(self._low_rank_of[me], key=me)
            # leaders: one rank per node (low rank 0); None elsewhere
            self._leaders = comm.split(
                0 if self._low_rank_of[me] == 0 else -1, key=me)
        finally:
            self._building = False
        return True

    # leaders-comm rank of a node = position among node colors in index
    # order (leaders split keyed by parent rank; node groups are disjoint
    # but their leader ranks sort by parent rank, not color index)
    def _leaders_rank_of_node(self, node_color) -> int:
        leaders = sorted(self._leader_of_node.values())
        return leaders.index(self._leader_of_node[node_color])

    # -- collectives ------------------------------------------------------
    def allreduce(self, comm, sendbuf, op: op_mod.Op = op_mod.SUM):
        if not self._ready(comm):
            return self._fallback.allreduce(comm, sendbuf, op)
        arr = np.ascontiguousarray(sendbuf)
        low, up = self._low, self._up
        if (op.commute and self._symmetric and arr.size
                and arr.size % low.size == 0):
            # reduce_scatter(low) → allreduce(up) → allgather(low): DCN
            # carries size/low.size elements per node instead of size
            flat = arr.reshape(-1)
            seg = low.reduce_scatter(flat, op=op)
            seg = np.asarray(up.allreduce(seg, op))
            full = np.asarray(low.allgather(seg))
            return full.reshape(arr.shape)
        if not op.commute:
            # node grouping reorders operands; stay rank-ordered
            return self._fallback.allreduce(comm, arr, op)
        red = low.reduce(arr, op, root=0)
        if low.rank == 0:
            red = np.ascontiguousarray(self._leaders.allreduce(red, op))
            return np.asarray(low.bcast(red, root=0)).reshape(arr.shape)
        out = low.bcast(np.empty_like(arr), root=0)
        return np.asarray(out).reshape(arr.shape)

    def bcast(self, comm, buf, root: int = 0):
        tag = coll_tag(comm)
        if not self._ready(comm):
            return self._fallback.bcast(comm, buf, root)
        low = self._low
        arr = np.ascontiguousarray(buf)
        root_node = self._node_of[root]
        leader = self._leader_of_node[root_node]
        data = arr if comm.rank == root else np.empty_like(arr)
        if root != leader:          # hop 0: root → its node's leader
            if comm.rank == root:
                comm.send(arr, leader, tag)
            elif comm.rank == leader:
                comm.recv(data, root, tag)
        if low.rank == 0:           # hop 1: across nodes (DCN)
            data = np.ascontiguousarray(self._leaders.bcast(
                data, root=self._leaders_rank_of_node(root_node)))
        return np.asarray(low.bcast(data, root=0)).reshape(arr.shape)

    def reduce(self, comm, sendbuf, op: op_mod.Op = op_mod.SUM,
               root: int = 0):
        tag = coll_tag(comm)
        if not self._ready(comm):
            return self._fallback.reduce(comm, sendbuf, op, root)
        if not op.commute:
            return self._fallback.reduce(comm, sendbuf, op, root)
        low = self._low
        arr = np.ascontiguousarray(sendbuf)
        root_node = self._node_of[root]
        leader = self._leader_of_node[root_node]
        red = low.reduce(arr, op, root=0)
        if low.rank == 0:
            red = self._leaders.reduce(
                np.ascontiguousarray(red), op,
                root=self._leaders_rank_of_node(root_node))
        if root == leader:
            return red if comm.rank == root else None
        # final hop: root's node leader → root
        if comm.rank == leader:
            comm.send(np.ascontiguousarray(red), root, tag)
            return None
        if comm.rank == root:
            out = np.empty_like(arr)
            comm.recv(out, leader, tag)
            return out
        return None

    def allgather(self, comm, sendbuf):
        if not self._ready(comm):
            return self._fallback.allgather(comm, sendbuf)
        low = self._low
        arr = np.ascontiguousarray(sendbuf)
        g_low = low.gather(arr, root=0)            # (low.size, *S) at leader
        out = np.empty((comm.size, *arr.shape), arr.dtype)
        if low.rank == 0:
            parts = self._leaders.allgatherv(
                np.ascontiguousarray(g_low).reshape(-1))
            # leaders comm ranks sort by parent rank; map back to nodes
            leaders_sorted = sorted(self._leader_of_node.items(),
                                    key=lambda kv: kv[1])
            for (node_color, _), flat in zip(leaders_sorted, parts):
                ranks = self._ranks_of_node[node_color]
                stack = np.asarray(flat).reshape((len(ranks), *arr.shape))
                for j, r in enumerate(ranks):
                    out[r] = stack[j]
        return np.asarray(low.bcast(out, root=0))

    def barrier(self, comm) -> None:
        if not self._ready(comm):
            return self._fallback.barrier(comm)
        low = self._low
        token = np.zeros(1, np.uint8)
        low.gather(token, root=0)
        if low.rank == 0:
            self._leaders.barrier()
        low.bcast(token, root=0)

    def gather(self, comm, sendbuf, root: int = 0):
        tag = coll_tag(comm)
        if not self._ready(comm):
            return self._fallback.gather(comm, sendbuf, root)
        low = self._low
        arr = np.ascontiguousarray(sendbuf)
        root_node = self._node_of[root]
        leader = self._leader_of_node[root_node]
        g_low = low.gather(arr, root=0)
        assembled = None
        if low.rank == 0:
            parts = self._leaders.gatherv(
                np.ascontiguousarray(g_low).reshape(-1),
                root=self._leaders_rank_of_node(root_node))
            if parts is not None:    # I am root's node leader
                assembled = np.empty((comm.size, *arr.shape), arr.dtype)
                leaders_sorted = sorted(self._leader_of_node.items(),
                                        key=lambda kv: kv[1])
                for (node_color, _), flat in zip(leaders_sorted, parts):
                    ranks = self._ranks_of_node[node_color]
                    stack = np.asarray(flat).reshape(
                        (len(ranks), *arr.shape))
                    for j, r in enumerate(ranks):
                        assembled[r] = stack[j]
        if root == leader:
            return assembled if comm.rank == root else None
        if comm.rank == leader:
            comm.send(assembled, root, tag)
            return None
        if comm.rank == root:
            out = np.empty((comm.size, *arr.shape), arr.dtype)
            comm.recv(out, leader, tag)
            return out
        return None

    def scatter(self, comm, sendbuf, root: int = 0):
        tag = coll_tag(comm)
        if not self._ready(comm):
            return self._fallback.scatter(comm, sendbuf, root)
        low = self._low
        my_node = self._node_of[comm.rank]
        if comm.rank == root:
            stack = np.ascontiguousarray(sendbuf)
            if stack.shape[0] != comm.size:
                raise ValueError("scatter needs (size, ...) on root")
            block = np.ascontiguousarray(stack[root])
            sub_for_me = None
            # one message per *node* over DCN, not per rank
            for node_color, ranks in self._ranks_of_node.items():
                sub = np.ascontiguousarray(stack[ranks])
                leader = self._leader_of_node[node_color]
                if leader == root:
                    sub_for_me = sub
                else:
                    comm.send(sub, leader, tag)
        else:
            block = np.ascontiguousarray(sendbuf)  # template: my block shape
            sub_for_me = None
        if low.rank == 0 and sub_for_me is None:
            sub_for_me = np.empty((low.size, *block.shape), block.dtype)
            if self._leader_of_node[my_node] != root:
                comm.recv(sub_for_me, root, tag)
        if low.rank == 0:
            return low.scatter(sub_for_me, root=0)
        return low.scatter(block, root=0)

    # NOTE: han deliberately does NOT provide `agree` — coll/ftagree owns
    # the agreement slot (its failure handling must not be shadowed by a
    # higher-priority non-FT composition).

    def comm_unquery(self, comm) -> None:
        for sub in (self._low, self._up, self._leaders):
            if sub is not None:
                sub.free()
        self._low = self._up = self._leaders = None


class HanCollComponent(Component):
    """Selects only on communicators genuinely spanning >= 2 nodes with
    >= 2 ranks somewhere (``coll_han`` disqualifies itself the same way)."""

    name = "han"
    priority = 40

    def register_vars(self, fw) -> None:
        self._prio = self.register_var(
            "priority", vtype=VarType.INT, default=40,
            help="Selection priority of coll/han (hierarchical collectives)")
        self._node_cache: dict[int, object] = {}

    def _node_of_world_rank(self, rte, w: int):
        # shared cached locality lookup (published before the init fence)
        return rte.node_of(w)

    def comm_query(self, comm):
        rte = comm.rte
        if rte is None or rte.is_device_world or comm.size < 2:
            return None
        if comm.is_inter:
            return None
        try:
            nodes = [self._node_of_world_rank(rte, w)
                     for w in comm.group.world_ranks]
        except Exception:
            return None
        if any(n is None for n in nodes):
            return None
        colors = sorted(set(nodes))
        if len(colors) < 2:
            return None
        by_node = {c: sum(1 for n in nodes if n == c) for c in colors}
        if max(by_node.values()) < 2:
            return None
        node_of = [colors.index(n) for n in nodes]
        return self._prio.value, HanModule(self, node_of)


class XlaHierarchicalColl:
    """Device-side two-level composition over a ('dcn', 'ici') mesh.

    The trace-time analog of HanModule.allreduce's symmetric path:
    ``psum_scatter`` over the ICI axis, ``psum`` over the DCN axis,
    ``all_gather`` over ICI — XLA schedules each phase on its own link
    class.  ``n_up * n_low`` devices; world arrays carry a leading
    device axis of that global size.
    """

    def __init__(self, devices, n_up: int, n_low: int,
                 up_axis: str = "dcn", low_axis: str = "ici") -> None:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        devices = np.asarray(devices).reshape(n_up, n_low)
        self.mesh = Mesh(devices, (up_axis, low_axis))
        self.n_up, self.n_low = n_up, n_low
        self.up_axis, self.low_axis = up_axis, low_axis
        self._P = P
        self._sharded = NamedSharding(self.mesh, P((up_axis, low_axis)))
        self._cache: dict = {}

    def make_world_array(self, host_stack):
        import jax

        arr = np.asarray(host_stack)
        if arr.shape[0] != self.n_up * self.n_low:
            raise ValueError(
                f"world array needs leading axis {self.n_up * self.n_low}")
        return jax.device_put(arr, self._sharded)

    def allreduce(self, x):
        """Hierarchical psum of the world rows of ``x`` (replicated out)."""
        import jax
        from ompi_tpu.base.jaxenv import shard_map

        x = self.make_world_array(x) if not hasattr(x, "sharding") else x
        key = ("hier_allreduce", x.shape, x.dtype)
        fn = self._cache.get(key)
        if fn is None:
            P, up, low = self._P, self.up_axis, self.low_axis
            divisible = (x.shape[1:] and x.shape[1] % self.n_low == 0)

            def body(t):  # t: (1, *S) block per device
                v = t[0]
                if divisible:
                    s = jax.lax.psum_scatter(
                        v, low, scatter_dimension=0, tiled=True)
                    s = jax.lax.psum(s, up)
                    return jax.lax.all_gather(s, low, tiled=True)
                return jax.lax.psum(jax.lax.psum(v, low), up)

            fn = jax.jit(shard_map(
                body, mesh=self.mesh, in_specs=P((up, low)), out_specs=P(),
                check_vma=False))
            self._cache[key] = fn
        return fn(x)

    def reduce_scatter(self, x):
        """World (n, n, *S) → reduced block per device, two-level."""
        import jax
        from ompi_tpu.base.jaxenv import shard_map

        x = self.make_world_array(x) if not hasattr(x, "sharding") else x
        key = ("hier_reduce_scatter", x.shape, x.dtype)
        fn = self._cache.get(key)
        if fn is None:
            P, up, low = self._P, self.up_axis, self.low_axis

            def body(t):  # (1, n, *S)
                # scatter across the local ici group first, then finish
                # the reduction across dcn and scatter the remainder
                v = jax.lax.psum(t[0], low)       # (n, *S) node-reduced
                v = jax.lax.psum(v, up)           # full reduction
                i = (jax.lax.axis_index(up) * self.n_low
                     + jax.lax.axis_index(low))
                return jax.lax.dynamic_index_in_dim(v, i, 0)

            fn = jax.jit(shard_map(
                body, mesh=self.mesh, in_specs=P((up, low)),
                out_specs=P((up, low)), check_vma=False))
            self._cache[key] = fn
        return fn(x)


COMPONENT = HanCollComponent()
