"""coll/self equivalent: trivial collectives for size-1 communicators
(``/root/reference/ompi/mca/coll/self/``)."""
from __future__ import annotations

import numpy as np

from ompi_tpu.api.request import CompletedRequest
from ompi_tpu.base.mca import Component


class SelfCollModule:
    def barrier(self, comm) -> None:
        pass

    def bcast(self, comm, buf, root=0):
        return np.asarray(buf)

    def reduce(self, comm, sendbuf, op, root=0):
        return np.array(np.asarray(sendbuf), copy=True)

    def allreduce(self, comm, sendbuf, op):
        return np.array(np.asarray(sendbuf), copy=True)

    def gather(self, comm, sendbuf, root=0):
        return np.asarray(sendbuf)[None, ...]

    def gatherv(self, comm, sendbuf, root=0):
        return [np.asarray(sendbuf)]

    def scatter(self, comm, sendbuf, root=0):
        return np.asarray(sendbuf)[0]

    def scatterv(self, comm, sendbufs, root=0):
        return np.asarray(sendbufs[0])

    def allgather(self, comm, sendbuf):
        return np.asarray(sendbuf)[None, ...]

    def allgatherv(self, comm, sendbuf):
        return [np.asarray(sendbuf)]

    def alltoall(self, comm, sendbuf):
        return np.array(np.asarray(sendbuf), copy=True)

    def alltoallv(self, comm, sendbufs):
        return [np.asarray(b) for b in sendbufs]

    def reduce_scatter(self, comm, sendbuf, recvcounts, op):
        return np.array(np.asarray(sendbuf), copy=True)

    def scan(self, comm, sendbuf, op):
        return np.array(np.asarray(sendbuf), copy=True)

    def exscan(self, comm, sendbuf, op):
        return np.zeros_like(np.asarray(sendbuf))

    def ibarrier(self, comm):
        return CompletedRequest()

    def ibcast(self, comm, buf, root=0):
        r = CompletedRequest()
        r.result = np.asarray(buf)
        return r

    def iallreduce(self, comm, sendbuf, op):
        r = CompletedRequest()
        r.result = self.allreduce(comm, sendbuf, op)
        return r

    def agree(self, comm, flag: int) -> int:
        return int(flag)


class SelfCollComponent(Component):
    name = "self_coll"
    priority = 75

    def comm_query(self, comm):
        if comm.size == 1 and not comm.is_inter:
            return self.priority, SelfCollModule()
        return None


COMPONENT = SelfCollComponent()
