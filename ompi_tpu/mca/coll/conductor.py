"""coll/conductor — host-buffer collectives for the device-world model.

In the single-controller SPMD world every rank's host contribution already
lives in this process, so host collectives are direct computations — the
honest TPU-native counterpart of running message-passing algorithms between
co-located ranks.  Data model: the leading axis of ``sendbuf`` indexes ranks
(``sendbuf[i]`` is rank i's contribution), matching the single-controller
convention of ``jax.pmap``.  Message-passing algorithm menus (ring,
recursive-doubling, Rabenseifner — ``coll_base_allreduce.c:53-1245``) are
exercised in the multi-process model via coll/basic and coll/tuned.

Device buffers (jax.Array) passed to the *host* entry points are detected
via the accelerator framework and forwarded to the coll/xla module — the
interposition pattern of ``coll/cuda`` (``coll_cuda_allreduce.c:44-69``),
except the collective runs *on* device instead of staging to host.
"""
from __future__ import annotations

import numpy as np

from ompi_tpu.api import op as op_mod
from ompi_tpu.api.request import CompletedRequest
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType


def _fold(op: op_mod.Op, stack: np.ndarray) -> np.ndarray:
    """Reduce over the leading (rank) axis with an MPI op.

    Folds right-to-left: with the op convention inout = in (op) inout this
    yields b0 (op) (b1 (op) (... bn-1)), preserving rank order for
    non-commutative user ops.
    """
    n = stack.shape[0]
    acc = np.array(stack[n - 1], copy=True)
    for i in range(n - 2, -1, -1):
        op(stack[i], acc)
    return acc


class ConductorModule:
    def __init__(self, comm):
        pass

    def _is_device(self, x) -> bool:
        from ompi_tpu.mca.accelerator.jax_acc import is_device_array

        return is_device_array(x)

    # -- blocking host collectives --------------------------------------
    def barrier(self, comm) -> None:
        fn = comm.c_coll.get("device_barrier")
        if fn is not None:
            fn(comm)

    def bcast(self, comm, buf, root=0):
        if self._is_device(buf):
            return comm.c_coll["bcast_array"](comm, buf, root)
        return np.asarray(buf)

    def reduce(self, comm, sendbuf, op, root=0):
        if self._is_device(sendbuf):
            # single-controller: root's recvbuf is this process's result, so
            # the replicated allreduce IS the reduce (root row masking is
            # the multi-rank reduce_array slot's business)
            return comm.c_coll["allreduce_array"](comm, sendbuf, op)
        return _fold(op, self._stack(comm, sendbuf))

    def allreduce(self, comm, sendbuf, op):
        if self._is_device(sendbuf):
            return comm.c_coll["allreduce_array"](comm, sendbuf, op)
        return _fold(op, self._stack(comm, sendbuf))

    def gather(self, comm, sendbuf, root=0):
        if self._is_device(sendbuf):
            # single-controller: the replicated allgather is root's recvbuf
            return comm.c_coll["allgather_array"](comm, sendbuf)
        return np.array(self._stack(comm, sendbuf), copy=True)

    def gatherv(self, comm, sendbuf, root=0):
        return [np.asarray(b) for b in sendbuf]

    def scatter(self, comm, sendbuf, root=0):
        if self._is_device(sendbuf):
            # single-controller: root's (n, *S) buffer scattered over the
            # mesh is exactly a resharding; XLA schedules the ICI moves
            xm = next((m for m in getattr(comm, "coll_modules", ())
                       if hasattr(m, "reshard")), None)
            if xm is None:
                from ompi_tpu.api.errors import ErrorClass, MpiError

                raise MpiError(
                    ErrorClass.ERR_UNSUPPORTED_OPERATION,
                    "device-buffer scatter needs a device coll module")
            return xm.reshard(sendbuf)
        return np.array(self._stack(comm, sendbuf), copy=True)

    def scatterv(self, comm, sendbufs, root=0):
        return [np.asarray(b) for b in sendbufs]

    def allgather(self, comm, sendbuf):
        if self._is_device(sendbuf):
            return comm.c_coll["allgather_array"](comm, sendbuf)
        return np.array(self._stack(comm, sendbuf), copy=True)

    def allgatherv(self, comm, sendbuf):
        return [np.asarray(b) for b in sendbuf]

    def alltoall(self, comm, sendbuf):
        if self._is_device(sendbuf):
            return comm.c_coll["alltoall_array"](comm, sendbuf)
        stack = self._stack(comm, sendbuf)
        if stack.ndim < 2 or stack.shape[1] != comm.size:
            raise ValueError("alltoall needs shape (size, size, ...)")
        return np.array(np.swapaxes(stack, 0, 1), copy=True)

    def alltoallv(self, comm, sendbufs):
        n = comm.size
        return [[np.asarray(sendbufs[j][i]) for j in range(n)]
                for i in range(n)]

    def alltoallw(self, comm, sendbufs, recvtypes=None):
        """Matrix form like alltoallv; ``recvtypes[i]`` retypes rank i's
        received blocks (single dtype or one per source)."""
        out = self.alltoallv(comm, sendbufs)
        if recvtypes is None:
            return out
        typed = []
        for i, row in enumerate(out):
            rt = recvtypes[i]
            per_src = list(rt) if isinstance(rt, (list, tuple)) \
                else [rt] * comm.size
            typed.append([
                np.ascontiguousarray(b).reshape(-1).view(np.uint8)
                .view(np.dtype(per_src[j])) for j, b in enumerate(row)])
        return typed

    def reduce_scatter(self, comm, sendbuf, recvcounts, op):
        if self._is_device(sendbuf):
            return comm.c_coll["reduce_scatter_array"](comm, sendbuf, op)
        stack = self._stack(comm, sendbuf)
        total = _fold(op, stack)
        n = comm.size
        if recvcounts is None:
            return np.array(np.split(total, n), copy=True)
        out, off = [], 0
        for c in recvcounts:
            out.append(np.array(total[off:off + c], copy=True))
            off += c
        return out

    def scan(self, comm, sendbuf, op):
        stack = self._stack(comm, sendbuf)
        out = np.array(stack, copy=True)
        for i in range(1, out.shape[0]):
            op(out[i - 1], out[i])
        return out

    def exscan(self, comm, sendbuf, op):
        inc = self.scan(comm, sendbuf, op)
        out = np.zeros_like(inc)
        out[1:] = inc[:-1]
        return out

    # nonblocking: host computation is immediate in conductor mode -------
    def ibarrier(self, comm):
        self.barrier(comm)
        return CompletedRequest()

    def ibcast(self, comm, buf, root=0):
        r = CompletedRequest()
        r.result = self.bcast(comm, buf, root)
        return r

    def iallreduce(self, comm, sendbuf, op):
        r = CompletedRequest()
        r.result = self.allreduce(comm, sendbuf, op)
        return r

    def iallgather(self, comm, sendbuf):
        r = CompletedRequest()
        r.result = self.allgather(comm, sendbuf)
        return r

    def ialltoall(self, comm, sendbuf):
        r = CompletedRequest()
        r.result = self.alltoall(comm, sendbuf)
        return r

    def ireduce(self, comm, sendbuf, op, root=0):
        r = CompletedRequest()
        r.result = self.reduce(comm, sendbuf, op, root)
        return r

    def agree(self, comm, flag: int) -> int:
        # single controller: agreement over live ranks is local (bitwise AND)
        flags = np.atleast_1d(np.asarray(flag, dtype=np.int64))
        return int(np.bitwise_and.reduce(flags))

    # helpers ------------------------------------------------------------
    def _stack(self, comm, sendbuf) -> np.ndarray:
        arr = np.asarray(sendbuf)
        if arr.ndim == 0 or arr.shape[0] != comm.size:
            raise ValueError(
                f"conductor collectives need a leading rank axis of size "
                f"{comm.size}; got shape {arr.shape}")
        return arr


class ConductorComponent(Component):
    name = "conductor"
    priority = 40

    def register_vars(self, fw) -> None:
        self._prio = self.register_var(
            "priority", vtype=VarType.INT, default=40,
            help="Selection priority of coll/conductor")

    def comm_query(self, comm):
        if comm.rte is None or not comm.rte.is_device_world:
            return None
        if comm.size == 1:
            return None  # self_coll handles it
        return self._prio.value, ConductorModule(comm)


COMPONENT = ConductorComponent()
