"""coll — collectives framework (``/root/reference/ompi/mca/coll/``).

Components compete per-communicator by priority
(``coll_base_comm_select.c:96``); each fills the subset of the per-comm
vtable it implements, highest priority winning per function.  Components:
``xla`` (★ the north star: device buffers → XLA collectives over the ICI
mesh), ``conductor`` (host buffers in the device-world model), ``basic``
(linear algorithms over pml), ``tuned`` (decision ladder), ``libnbc``
(nonblocking schedules), ``han`` (hierarchical), ``self_coll`` (size-1),
``ftagree`` (ULFM agreement), ``sync``, ``monitoring``, ``inter``.
"""
