"""coll/base: per-communicator component selection.

Re-design of ``/root/reference/ompi/mca/coll/base/coll_base_comm_select.c``:
query every available component for this communicator (``:341``), keep those
answering with priority >= 0 (``:412``), sort ascending (``:451``), then fill
the per-comm vtable ``c_coll`` in priority order so the highest-priority
provider of each individual function wins (the reference's
``COPY(module, comm, func)`` loop).  The algorithm library itself
(ring / recursive-doubling / Rabenseifner menus) lives in
``ompi_tpu.mca.coll.algorithms``.
"""
from __future__ import annotations

from typing import Optional

from ompi_tpu.base import mca
from ompi_tpu.base.var import VarType, registry

from ompi_tpu.api.comm import COLL_FUNCTIONS


def coll_framework() -> mca.Framework:
    return mca.framework("coll", "collective operations", multi_select=True)


def comm_select(comm) -> None:
    """Fill ``comm.c_coll`` by priority vote across coll components."""
    fw = coll_framework()
    scored = []
    for comp in fw.select_all():
        query = getattr(comp, "comm_query", None)
        if query is None:
            continue
        try:
            res = query(comm)
        except Exception as exc:
            from ompi_tpu.base import output as _o

            _o.output(fw.stream, 1, "coll %s comm_query failed: %s",
                      comp.name, exc)
            res = None
        if res is None:
            continue
        priority, module = res
        if priority < 0:
            continue
        scored.append((priority, comp.name, module))
    # ascending sort; later (higher-priority) modules overwrite earlier ones
    scored.sort(key=lambda t: (t[0], t[1]))
    comm.c_coll = {}
    comm.coll_modules = [m for _, _, m in scored]
    for _, _, module in scored:
        enable = getattr(module, "comm_enable", None)
        if enable is not None:
            enable(comm)
        for fname in COLL_FUNCTIONS:
            fn = getattr(module, fname, None)
            if fn is not None:
                comm.c_coll[fname] = fn
    if not comm.c_coll:
        from ompi_tpu.base.output import show_help

        show_help("help-coll", "none-available", comm=comm.name)
    # coll/monitoring interposition (records per-collective counters)
    from ompi_tpu.runtime import monitoring

    monitoring.wrap_coll_table(comm)
    # coll/trace interposition (span + log2-size latency histogram per
    # slot — host and device entry points alike).  Installed always;
    # the wrapper's disabled path is one flag check.
    from ompi_tpu.runtime import trace

    trace.wrap_coll_table(comm)


from ompi_tpu.base.output import register_help as _rh

_rh("help-coll", "none-available",
    "No collective component is available for communicator {comm}; "
    "collective operations on it will fail.")
