"""coll/basic — naive linear/log algorithms over pml p2p, always available.

Equivalent of ``/root/reference/ompi/mca/coll/basic/`` (priority 10, the
fallback when nothing better selects): linear fan-in/fan-out algorithms
driven SPMD-style (each process participates with its own call).  Collective
traffic uses the internal (negative) tag space with a per-communicator
sequence so concurrent collectives on different comms can't cross-match —
the role the reference's separate collective context id plays.

Reductions fold in rank order, so non-commutative user ops are safe here
(the property the tuned decision ladder relies on when it excludes ring/
Rabenseifner for non-commutative ops, ``coll_tuned_decision_fixed.c:77-80``).
"""
from __future__ import annotations

import numpy as np

from ompi_tpu.api import op as op_mod
from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType

_TAG_BASE = 16
_TAG_SPACE = 1 << 20


def coll_tag(comm) -> int:
    """Next internal tag for one collective on this comm (ordered calls)."""
    seq = getattr(comm, "_coll_tag_seq", 0)
    comm._coll_tag_seq = seq + 1
    return -(_TAG_BASE + seq % _TAG_SPACE)


class BasicCollModule:
    # -- building blocks -------------------------------------------------
    def barrier(self, comm) -> None:
        tag = coll_tag(comm)
        token = np.zeros(1, np.uint8)
        if comm.rank == 0:
            for r in range(1, comm.size):
                comm.recv(np.zeros(1, np.uint8), source=r, tag=tag)
            for r in range(1, comm.size):
                comm.send(token, dest=r, tag=tag)
        else:
            comm.send(token, dest=0, tag=tag)
            comm.recv(np.zeros(1, np.uint8), source=0, tag=tag)

    def bcast(self, comm, buf, root=0):
        tag = coll_tag(comm)
        arr = np.ascontiguousarray(buf)
        if comm.rank == root:
            for r in range(comm.size):
                if r != root:
                    comm.send(arr, dest=r, tag=tag)
            return arr
        out = np.empty_like(arr)
        comm.recv(out, source=root, tag=tag)
        return out

    def gather(self, comm, sendbuf, root=0):
        tag = coll_tag(comm)
        arr = np.ascontiguousarray(sendbuf)
        if comm.rank == root:
            out = np.empty((comm.size, *arr.shape), arr.dtype)
            out[root] = arr
            for r in range(comm.size):
                if r != root:
                    # out[r:r+1] is always a view; out[r] would be a
                    # detached scalar for 1-elem rows and drop the data
                    comm.recv(out[r:r + 1], source=r, tag=tag)
            return out
        comm.send(arr, dest=root, tag=tag)
        return None

    def gatherv(self, comm, sendbuf, root=0):
        tag = coll_tag(comm)
        arr = np.ascontiguousarray(sendbuf).reshape(-1)
        sizes = self.gather(comm, np.array([arr.size], np.int64), root)
        if comm.rank == root:
            out = []
            for r in range(comm.size):
                if r == root:
                    out.append(arr)
                else:
                    buf = np.empty(int(sizes[r][0]), arr.dtype)
                    comm.recv(buf, source=r, tag=tag)
                    out.append(buf)
            return out
        comm.send(arr, dest=root, tag=tag)
        return None

    def scatter(self, comm, sendbuf, root=0):
        """Root passes the (size, ...) stack; non-roots pass a template
        array with their block's shape/dtype (the recvbuf spec MPI needs)."""
        tag = coll_tag(comm)
        if comm.rank == root:
            stack = np.ascontiguousarray(sendbuf)
            if stack.shape[0] != comm.size:
                raise ValueError("scatter needs (size, ...) on root")
            for r in range(comm.size):
                if r != root:
                    comm.send(np.ascontiguousarray(stack[r]), dest=r, tag=tag)
            return np.array(stack[root], copy=True)
        out = np.empty_like(np.ascontiguousarray(sendbuf))
        comm.recv(out, source=root, tag=tag)
        return out

    def allgather(self, comm, sendbuf):
        g = self.gather(comm, sendbuf, 0)
        if comm.rank == 0:
            return self.bcast(comm, g, 0)
        arr = np.ascontiguousarray(sendbuf)
        return self.bcast(comm, np.empty((comm.size, *arr.shape), arr.dtype), 0)

    def allgatherv(self, comm, sendbuf):
        sizes = self.allgather(comm, np.array([np.asarray(sendbuf).size],
                                              np.int64))
        tag = coll_tag(comm)
        arr = np.ascontiguousarray(sendbuf).reshape(-1)
        out = []
        reqs = []
        for r in range(comm.size):
            if r != comm.rank:
                reqs.append(comm.isend(arr, dest=r, tag=tag))
        for r in range(comm.size):
            if r == comm.rank:
                out.append(arr)
            else:
                buf = np.empty(int(sizes[r][0]), arr.dtype)
                comm.recv(buf, source=r, tag=tag)
                out.append(buf)
        from ompi_tpu.api.request import waitall

        waitall(reqs)
        return out

    def alltoall(self, comm, sendbuf):
        tag = coll_tag(comm)
        stack = np.ascontiguousarray(sendbuf)
        if stack.shape[0] != comm.size:
            raise ValueError("alltoall needs (size, ...) per rank")
        out = np.empty_like(stack)
        out[comm.rank] = stack[comm.rank]
        reqs = []
        for r in range(comm.size):
            if r != comm.rank:
                reqs.append(comm.isend(np.ascontiguousarray(stack[r:r + 1]),
                                       dest=r, tag=tag))
        for r in range(comm.size):
            if r != comm.rank:
                comm.recv(out[r:r + 1], source=r, tag=tag)
        from ompi_tpu.api.request import waitall

        waitall(reqs)
        return out

    def alltoallv(self, comm, sendbufs):
        """Received block from rank r is typed as ``sendbufs[r].dtype``
        — the symmetric-exchange contract every component returns
        (self_coll/conductor keep types trivially; the wire carries
        bytes and this view restores them).  Pairs exchanging DIFFERENT
        dtypes must use ``alltoallw`` with explicit ``recvtypes``, the
        exact split MPI itself makes (``ompi/mpi/c/alltoallw.c``)."""
        tag = coll_tag(comm)
        reqs = []
        for r in range(comm.size):
            if r != comm.rank:
                reqs.append(comm.isend(
                    np.ascontiguousarray(sendbufs[r]), dest=r, tag=tag))
        out = [None] * comm.size
        out[comm.rank] = np.ascontiguousarray(sendbufs[comm.rank])
        for r in range(comm.size):
            if r != comm.rank:
                st = comm.probe(source=r, tag=tag)
                buf = np.empty(st._nbytes, np.uint8)
                comm.recv(buf, source=r, tag=tag)
                dt = np.asarray(sendbufs[r]).dtype
                if buf.nbytes % max(1, dt.itemsize):
                    raise MpiError(
                        ErrorClass.ERR_TYPE,
                        f"alltoallv: peer {r} sent {buf.nbytes} bytes, "
                        f"not a multiple of this rank's send dtype {dt} "
                        f"(itemsize {dt.itemsize}) — alltoallv's contract "
                        "is a symmetric dtype per pair; use alltoallw "
                        "with explicit recvtypes for asymmetric-dtype "
                        "exchanges")
                out[r] = buf.view(dt)
        from ompi_tpu.api.request import waitall

        waitall(reqs)
        return out

    def alltoallw(self, comm, sendbufs, recvtypes=None):
        """``MPI_Alltoallw``: per-peer buffers AND per-peer datatypes.

        ``sendbufs[i]`` (any dtype/shape each) goes to rank i;
        ``recvtypes[i]`` (numpy dtypes) types the block received from
        rank i (default uint8, the wire type).  The v-variant's
        byte-stream exchange already carries arbitrary layouts — the w
        semantics are the per-peer reinterpretation on both ends
        (``ompi/mpi/c/alltoallw.c``)."""
        raw = self.alltoallv(comm, sendbufs)
        if recvtypes is None:
            return raw
        out = []
        for i, b in enumerate(raw):
            arr = np.ascontiguousarray(b).reshape(-1).view(np.uint8)
            out.append(arr.view(np.dtype(recvtypes[i])))
        return out

    def reduce(self, comm, sendbuf, op: op_mod.Op = op_mod.SUM, root=0):
        g = self.gather(comm, sendbuf, root)
        if comm.rank != root:
            return None
        # fold right-to-left so the op convention inout = in (op) inout
        # yields b0 (op) (b1 (op) (... bn-1)) — rank order preserved for
        # non-commutative ops
        acc = np.array(g[comm.size - 1], copy=True)
        for i in range(comm.size - 2, -1, -1):
            op(g[i], acc)
        return acc

    def allreduce(self, comm, sendbuf, op: op_mod.Op = op_mod.SUM):
        r = self.reduce(comm, sendbuf, op, 0)
        if comm.rank == 0:
            return self.bcast(comm, r, 0)
        arr = np.ascontiguousarray(sendbuf)
        return self.bcast(comm, np.empty_like(arr), 0)

    def reduce_scatter(self, comm, sendbuf, recvcounts=None,
                       op: op_mod.Op = op_mod.SUM):
        total = self.allreduce(comm, sendbuf, op)
        n = comm.size
        if recvcounts is None:
            return np.array_split(total, n)[comm.rank]
        off = int(np.sum(recvcounts[:comm.rank]))
        return np.array(total[off:off + recvcounts[comm.rank]], copy=True)

    def scan(self, comm, sendbuf, op: op_mod.Op = op_mod.SUM):
        tag = coll_tag(comm)
        arr = np.array(np.ascontiguousarray(sendbuf), copy=True)
        if comm.rank > 0:
            prev = np.empty_like(arr)
            comm.recv(prev, source=comm.rank - 1, tag=tag)
            op(prev, arr)
        if comm.rank < comm.size - 1:
            comm.send(arr, dest=comm.rank + 1, tag=tag)
        return arr

    def exscan(self, comm, sendbuf, op: op_mod.Op = op_mod.SUM):
        tag = coll_tag(comm)
        arr = np.ascontiguousarray(sendbuf)
        out = np.zeros_like(arr)
        if comm.rank > 0:
            comm.recv(out, source=comm.rank - 1, tag=tag)
        if comm.rank < comm.size - 1:
            if comm.rank == 0:
                nxt = np.array(arr, copy=True)
            else:
                # nxt = out (op) arr, preserving rank order
                nxt = np.array(arr, copy=True)
                op(out, nxt)
            comm.send(nxt, dest=comm.rank + 1, tag=tag)
        return out

    def agree(self, comm, flag: int) -> int:
        out = self.allreduce(comm, np.array([flag], np.int64), op_mod.BAND)
        return int(out[0])

    # nonblocking wrappers (libnbc-style schedules land in coll/libnbc) --
    def ibarrier(self, comm):
        from ompi_tpu.api.request import CompletedRequest

        self.barrier(comm)
        return CompletedRequest()

    def iallreduce(self, comm, sendbuf, op: op_mod.Op = op_mod.SUM):
        from ompi_tpu.api.request import CompletedRequest

        r = CompletedRequest()
        r.result = self.allreduce(comm, sendbuf, op)
        return r

    def ibcast(self, comm, buf, root=0):
        from ompi_tpu.api.request import CompletedRequest

        r = CompletedRequest()
        r.result = self.bcast(comm, buf, root)
        return r


class BasicCollComponent(Component):
    name = "basic"
    priority = 10

    def register_vars(self, fw) -> None:
        self._prio = self.register_var(
            "priority", vtype=VarType.INT, default=10,
            help="Selection priority of coll/basic")

    def comm_query(self, comm):
        if comm.rte is not None and comm.rte.is_device_world:
            return None  # conductor model handles host collectives there
        if comm.size == 1 or comm.is_inter:
            return None  # intercomms take coll/inter's two-group protocol
        return self._prio.value, BasicCollModule()


COMPONENT = BasicCollComponent()
