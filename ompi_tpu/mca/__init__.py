"""MCA component frameworks (plugin points).

Each subpackage is one framework (``coll``, ``pml``, ``btl``, ``osc``, ``io``,
``topo``, ``op``, ``accelerator``, ...); each module inside exports a
``COMPONENT`` object discovered by ``ompi_tpu.base.mca.Framework.discover``,
the analog of the reference's dlopen component repository
(``/root/reference/opal/mca/base/mca_base_component_repository.c:420``).
"""
