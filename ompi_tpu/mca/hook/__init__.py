"""hook — init/finalize interposition framework.

Re-design of ``/root/reference/ompi/mca/hook/`` (the framework whose one
shipping component, ``hook/comm_method``, dumps the selected transport
matrix at init): components register callbacks that the runtime invokes at
well-known points (post-init, pre-finalize).
"""
from __future__ import annotations

from ompi_tpu.base import mca


def hook_framework() -> mca.Framework:
    return mca.framework("hook", "init/finalize interposition",
                         multi_select=True)


def run_hooks(point: str, *args) -> None:
    """Invoke every component's ``at_<point>`` callback."""
    fw = hook_framework()
    for comp in fw.select_all():
        fn = getattr(comp, f"at_{point}", None)
        if fn is not None:
            try:
                fn(*args)
            except Exception as exc:
                from ompi_tpu.base import output as _o

                _o.output(fw.stream, 1, "hook %s/%s failed: %s",
                          comp.name, point, exc)
