"""hook/comm_method — print the selected transport matrix at init.

Re-design of ``/root/reference/ompi/mca/hook/comm_method/`` (1,904 LoC):
when ``otpu_hook_comm_method_display`` is set, each rank (or just rank 0
with the full matrix) reports which BTL reaches every peer — the tool for
answering "is this job actually using sm or falling back to tcp?".
"""
from __future__ import annotations

from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType


class CommMethodComponent(Component):
    name = "comm_method"
    priority = 10

    def register_vars(self, fw) -> None:
        self.display_var = self.register_var(
            "display", vtype=VarType.BOOL, default=False,
            help="Print the per-peer transport (BTL) matrix after init "
                 "(hook/comm_method's mca_hook_comm_method_enable_mpi_init)")

    def at_init(self, world) -> None:
        if not bool(self.display_var.value):
            return
        pml = world.pml
        bml = getattr(pml, "bml", None)
        if bml is None:         # monitoring wrapper interposed
            bml = getattr(getattr(pml, "_inner", None), "bml", None)
        if bml is None:
            return
        me = world.rank
        rte = world.rte
        from ompi_tpu.base import hwloc

        my_node = getattr(rte, "_node", None)
        my_cpus = None
        topo = hwloc.host_topology()
        loc_names = {hwloc.LOC_DIFFERENT_NODE: "inter",
                     hwloc.LOC_SAME_NODE: "node",
                     hwloc.LOC_SAME_NUMA: "numa",
                     hwloc.LOC_SAME_CORE: "core"}
        if hasattr(rte, "modex_get"):
            my_cpus = rte.modex_get(rte.my_world_rank, "cpus", wait=False)
        cells = []
        for r in range(world.size):
            w = world.world_rank(r)
            if w == rte.my_world_rank:
                cells.append("self*")
                continue
            eps = bml.endpoints(w)
            cell = eps[0].btl.name if eps else "none"
            # locality tier from the peer's modexed topology facts
            # (hwloc analog — what the reference reads from PMIx locality)
            if my_node is not None and hasattr(rte, "node_of"):
                peer_node = rte.node_of(w)
                peer_cpus = rte.modex_get(w, "cpus", wait=False) \
                    if hasattr(rte, "modex_get") else None
                tier = hwloc.locality(
                    my_node, peer_node or "?", my_cpus, peer_cpus,
                    topo.numa_nodes, ncpus=topo.ncpus_online)
                cell += f"/{loc_names[tier]}"
            cells.append(cell)
        print(f"[comm_method] rank {me}: " +
              " ".join(f"{r}:{c}" for r, c in enumerate(cells)),
              flush=True)


COMPONENT = CommMethodComponent()
