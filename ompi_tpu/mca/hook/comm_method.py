"""hook/comm_method — print the selected transport matrix at init.

Re-design of ``/root/reference/ompi/mca/hook/comm_method/`` (1,904 LoC):
when ``otpu_hook_comm_method_display`` is set, each rank (or just rank 0
with the full matrix) reports which BTL reaches every peer — the tool for
answering "is this job actually using sm or falling back to tcp?".
"""
from __future__ import annotations

from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType


class CommMethodComponent(Component):
    name = "comm_method"
    priority = 10

    def register_vars(self, fw) -> None:
        self.display_var = self.register_var(
            "display", vtype=VarType.BOOL, default=False,
            help="Print the per-peer transport (BTL) matrix after init "
                 "(hook/comm_method's mca_hook_comm_method_enable_mpi_init)")

    def at_init(self, world) -> None:
        if not bool(self.display_var.value):
            return
        pml = world.pml
        bml = getattr(pml, "bml", None)
        if bml is None:         # monitoring wrapper interposed
            bml = getattr(getattr(pml, "_inner", None), "bml", None)
        if bml is None:
            return
        me = world.rank
        cells = []
        for r in range(world.size):
            w = world.world_rank(r)
            if w == world.rte.my_world_rank:
                cells.append("self*")
                continue
            eps = bml.endpoints(w)
            cells.append(eps[0].btl.name if eps else "none")
        print(f"[comm_method] rank {me}: " +
              " ".join(f"{r}:{c}" for r, c in enumerate(cells)),
              flush=True)


COMPONENT = CommMethodComponent()
