"""osc/pt2pt — active-message RMA over the p2p engine.

Re-design of the reference's one-sided engine (``ompi/mca/osc/rdma/`` with
its BTL active-message fallback, ``osc_rdma_accumulate.c:26-71`` lock-and-
apply path): every process runs one *exposure agent* thread per window,
serving PUT/GET/ACC/GACC/CAS requests and the passive-target lock protocol
on the window's private communicator.  Where the reference gets target-side
progress only when the target enters the MPI library (opal_progress), the
agent thread gives true passive-target progress — the honest equivalent of
hardware RDMA on the host path.  Completion semantics lean on ob1's
per-(source,tag) ordering: requests from one origin are applied in issue
order, so a FLUSH round-trip implies all earlier ops from that origin are
target-complete (the reference's osc_rdma "frag flush + local completion"
argument, inverted for AM).

Protocol (all on the window's dup'd comm):
  REQ_TAG:    pickled request dicts origin→target (fire-and-forget for
              PUT/ACC; round-trip for GET/GACC/CAS/LOCK/FLUSH via a
              per-request reply tag)
  reply tags: REPLY_BASE - seq, unique per outstanding request per origin
"""
from __future__ import annotations

import pickle
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from ompi_tpu.api import op as op_mod
from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.api.status import ANY_SOURCE
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType

REQ_TAG = -(1 << 22)
REPLY_BASE = -(1 << 22) - 16
_REPLY_SPACE = 1 << 20


# Request wire format: ONE self-sized message (pickle bytes), so the
# agent never blocks on a second recv from an origin that died between
# sends — the exact failure window ULFM recovery mode opens.  The matched
# size comes from the improbe status.  Replies keep the
# send_obj/recv_obj two-part format (origin-side, actively waited).
def _send_req(comm, dest: int, req: dict) -> None:
    comm.send(np.frombuffer(pickle.dumps(req), np.uint8), dest, REQ_TAG)


def _send_reply(comm, dest: int, tag: int, obj) -> None:
    comm.send_obj(obj, dest, tag)


def _recv_reply(comm, source: int, tag: int):
    return comm.recv_obj(source, tag)


class _LockState:
    """Per-window target-side reader/writer lock with FIFO fairness."""

    def __init__(self) -> None:
        self.mode: Optional[str] = None  # None | "exclusive" | "shared"
        self.holders: set[int] = set()
        self.queue: deque = deque()      # (origin, reply_tag, lock_type)

    def try_grant(self, origin: int, reply_tag: int, lock_type: str) -> bool:
        if self.mode is None:
            self.mode = lock_type
            self.holders.add(origin)
            return True
        if self.mode == "shared" and lock_type == "shared" and not self.queue:
            # no writer waiting: shared locks pile in (FIFO fairness:
            # a queued exclusive blocks later shared acquisitions)
            self.holders.add(origin)
            return True
        self.queue.append((origin, reply_tag, lock_type))
        return False

    def release(self, origin: int) -> list[tuple[int, int]]:
        """Drop ``origin``'s hold; return [(origin, reply_tag)] to grant."""
        self.holders.discard(origin)
        granted = []
        if self.holders:
            return granted
        self.mode = None
        while self.queue:
            o, rt, lt = self.queue[0]
            if self.mode is None:
                self.mode = lt
                self.holders.add(o)
                granted.append((o, rt))
                self.queue.popleft()
            elif self.mode == "shared" and lt == "shared":
                self.holders.add(o)
                granted.append((o, rt))
                self.queue.popleft()
            else:
                break
        return granted


class Pt2ptModule:
    """One module instance per window (state is per-window)."""

    def __init__(self) -> None:
        self._seq = 0
        self._lock = threading.Lock()
        self._agent: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # target-side state
        self._locks = _LockState()
        self._posts: set[int] = set()          # PSCW: who posted to me
        self._completes: set[int] = set()      # PSCW: who completed to me
        self._pscw_cond = threading.Condition()
        self._start_group: Optional[list] = None

    # -- lifecycle -------------------------------------------------------
    def attach(self, win) -> None:
        self._win = win
        self._agent = threading.Thread(
            target=self._serve, args=(win,),
            name=f"otpu-osc-{win.name}", daemon=True)
        self._agent.start()

    def detach(self, win) -> None:
        self._stop.set()
        if self._agent is not None:
            self._agent.join(timeout=10)

    def _next_reply_tag(self) -> int:
        with self._lock:
            self._seq += 1
            return REPLY_BASE - (self._seq % _REPLY_SPACE)

    # -- origin side -----------------------------------------------------
    def put(self, win, arr, target: int, offset: int) -> None:
        _send_req(win.comm, target,
                  {"kind": "put", "off": offset, "data": arr})

    # -- dynamic-window region RMA (MPI_Win_create_dynamic + attach) -----
    def put_region(self, win, arr, target: int, offset: int,
                   region: int) -> None:
        _send_req(win.comm, target,
                  {"kind": "put", "off": offset, "data": arr,
                   "region": region})

    def get_region(self, win, count: int, target: int, offset: int,
                   region: int) -> np.ndarray:
        rt = self._next_reply_tag()
        _send_req(win.comm, target,
                  {"kind": "get", "off": offset, "count": count, "rt": rt,
                   "region": region})
        out = _recv_reply(win.comm, target, rt)
        if isinstance(out, dict) and out.get("err"):
            from ompi_tpu.api.errors import ErrorClass, MpiError

            raise MpiError(ErrorClass.ERR_RMA_CONFLICT,
                           f"region {region} on rank {target}: {out['err']}")
        return out

    def get(self, win, count: int, target: int, offset: int) -> np.ndarray:
        rt = self._next_reply_tag()
        _send_req(win.comm, target,
                  {"kind": "get", "off": offset, "count": count, "rt": rt})
        return _recv_reply(win.comm, target, rt)

    def accumulate(self, win, arr, target: int, offset: int, op) -> None:
        _send_req(win.comm, target,
                  {"kind": "acc", "off": offset, "data": arr, "op": op.name})

    def get_accumulate(self, win, arr, target: int, offset: int,
                       op) -> np.ndarray:
        rt = self._next_reply_tag()
        _send_req(win.comm, target,
                  {"kind": "gacc", "off": offset, "data": arr,
                   "op": op.name, "rt": rt})
        return _recv_reply(win.comm, target, rt)

    def compare_and_swap(self, win, value, compare, target: int, offset: int):
        rt = self._next_reply_tag()
        _send_req(win.comm, target,
                  {"kind": "cas", "off": offset, "value": value,
                   "compare": compare, "rt": rt})
        return _recv_reply(win.comm, target, rt)

    def flush(self, win, target: int) -> None:
        rt = self._next_reply_tag()
        _send_req(win.comm, target, {"kind": "flush", "rt": rt})
        _recv_reply(win.comm, target, rt)

    def fence(self, win) -> None:
        # close epoch: everything I issued is target-complete, then sync
        for t in range(win.size):
            self.flush(win, t)
        win.comm.barrier()

    def lock(self, win, target: int, lock_type: str) -> None:
        rt = self._next_reply_tag()
        _send_req(win.comm, target,
                  {"kind": "lock", "type": lock_type, "rt": rt})
        _recv_reply(win.comm, target, rt)  # blocks until granted

    def unlock(self, win, target: int) -> None:
        # flush-then-release in one round trip: the UNLOCK ack arrives
        # after all prior ops from this origin were applied (FIFO order)
        rt = self._next_reply_tag()
        _send_req(win.comm, target, {"kind": "unlock", "rt": rt})
        _recv_reply(win.comm, target, rt)

    # PSCW --------------------------------------------------------------
    def post(self, win, group) -> None:
        """Expose my window to the access group (MPI_Win_post)."""
        self._post_group = [win.comm.group.rank_of(r)
                            for r in group.world_ranks]
        for t in self._post_group:
            _send_req(win.comm, t, {"kind": "post"})

    def start(self, win, group) -> None:
        """Open an access epoch: wait for every target's post."""
        targets = [win.comm.group.rank_of(r) for r in group.world_ranks]
        self._start_group = targets
        with self._pscw_cond:
            while not all(t in self._posts for t in targets):
                self._pscw_cond.wait(0.05)
                if self._stop.is_set():
                    return
            for t in targets:
                self._posts.discard(t)

    def complete(self, win) -> None:
        """Close the access epoch (MPI_Win_complete)."""
        targets = self._start_group or []
        for t in targets:
            self.flush(win, t)
            _send_req(win.comm, t, {"kind": "complete"})
        self._start_group = None

    def wait(self, win) -> None:
        """Close the exposure epoch: wait for every access-group member's
        complete (MPI_Win_wait) — expressed over the one-copy
        ``pscw_test`` accounting."""
        while not self.pscw_test(win):
            with self._pscw_cond:
                self._pscw_cond.wait(0.05)
            if self._stop.is_set():
                return

    def pscw_test(self, win) -> bool:
        """Nonblocking ``wait`` (MPI_Win_test)."""
        starters = getattr(self, "_post_group", [])
        with self._pscw_cond:
            if not all(s in self._completes for s in starters):
                return False
            for s in starters:
                self._completes.discard(s)
        self._post_group = []
        return True

    # -- target side (the exposure agent) --------------------------------
    def _serve(self, win) -> None:
        from ompi_tpu.runtime.progress import progress

        comm = win.comm
        while not self._stop.is_set():
            try:
                # the agent IS the passive-target progress thread: pump the
                # progress engine so transport frags reach the matching
                # engine even while the app thread is outside the library
                progress()
                ok, msg = comm.improbe(ANY_SOURCE, REQ_TAG)
            except Exception:
                return  # runtime finalizing under us
            if not ok:
                time.sleep(0.0005)
                continue
            try:
                # single self-sized message: recv of a matched frag cannot
                # block on further traffic from the (possibly dead) origin
                payload = np.zeros(msg.status._nbytes, dtype=np.uint8)
                st = msg.recv(payload)
                self._handle(win, st.source, pickle.loads(payload.tobytes()))
            except Exception:
                if self._stop.is_set():
                    return
                from ompi_tpu.base import output as _o

                import traceback

                _o.output(0, 0, "osc agent error: %s",
                          traceback.format_exc(limit=3))

    def _handle(self, win, source: int, req: dict) -> None:
        kind = req["kind"]
        base = win.local
        if req.get("region") is not None:
            # dynamic window: resolve the attached region by handle.  A
            # detached/unknown handle is erroneous per MPI — gets reply
            # an error marker (origin raises ERR_RMA_RANGE); puts are
            # dropped rather than corrupting win.local
            base = win.regions.get(req["region"])
            if base is None:
                if kind == "get":
                    _send_reply(win.comm, source, req["rt"],
                                {"err": "region detached"})
                return
        if kind == "put":
            data = req["data"]
            base[req["off"]:req["off"] + data.size] = data
        elif kind == "get":
            out = np.array(
                base[req["off"]:req["off"] + req["count"]], copy=True)
            _send_reply(win.comm, source, req["rt"], out)
        elif kind == "acc":
            self._apply(base, req["off"], req["data"], req["op"],
                        win.byte_addressed)
        elif kind == "gacc":
            data = req["data"]
            if win.byte_addressed and data.dtype != base.dtype:
                old = np.array(base[req["off"]:req["off"] + data.nbytes]
                               .view(data.dtype), copy=True)
            else:
                old = np.array(
                    base[req["off"]:req["off"] + data.size], copy=True)
            self._apply(base, req["off"], data, req["op"],
                        win.byte_addressed)
            _send_reply(win.comm, source, req["rt"], old)
        elif kind == "cas":
            value = np.asarray(req["value"])
            if win.byte_addressed and value.dtype != base.dtype:
                # typed CAS on a byte-addressed heap window
                view = base[req["off"]:req["off"] + value.dtype.itemsize] \
                    .view(value.dtype)
                old = view[0]
                if old == req["compare"]:
                    view[0] = value
            else:
                old = base[req["off"]]
                if old == req["compare"]:
                    base[req["off"]] = req["value"]
            _send_reply(win.comm, source, req["rt"], old)
        elif kind == "flush":
            _send_reply(win.comm, source, req["rt"], True)
        elif kind == "lock":
            if self._locks.try_grant(source, req["rt"], req["type"]):
                _send_reply(win.comm, source, req["rt"], True)
        elif kind == "unlock":
            granted = self._locks.release(source)
            _send_reply(win.comm, source, req["rt"], True)
            for origin, rtag in granted:
                _send_reply(win.comm, origin, rtag, True)
        elif kind == "post":
            with self._pscw_cond:
                self._posts.add(source)
                self._pscw_cond.notify_all()
        elif kind == "complete":
            with self._pscw_cond:
                self._completes.add(source)
                self._pscw_cond.notify_all()
        else:
            raise MpiError(ErrorClass.ERR_RMA_SYNC,
                           f"unknown RMA request {kind!r}")

    @staticmethod
    def _apply(base: np.ndarray, off: int, data: np.ndarray,
               op_name: str, byte_addressed: bool = False) -> None:
        op = getattr(op_mod, op_name)
        if byte_addressed and data.dtype != base.dtype:
            # typed accumulate into a byte-addressed heap window: ``off``
            # is a byte offset and the view carries the origin type
            view = base[off:off + data.nbytes].view(data.dtype)
            op(data, view)
        else:
            view = base[off:off + data.size]
            op(data.astype(base.dtype, copy=False), view)


class Pt2ptComponent(Component):
    name = "pt2pt"
    priority = 50

    def register_vars(self, fw) -> None:
        self._prio = self.register_var(
            "priority", vtype=VarType.INT, default=50,
            help="Selection priority of osc/pt2pt")

    def win_query(self, win):
        if win.comm.rte is None or win.comm.rte.is_device_world:
            return None
        return self._prio.value, Pt2ptModule()


COMPONENT = Pt2ptComponent()
