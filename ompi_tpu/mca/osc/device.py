"""osc/device — RMA windows on TPU-resident buffers (HBM windows).

The device half of the one-sided story (SURVEY Phase 4): each rank's
exposure region is a row of a ``jax.Array`` sharded over the communicator's
device mesh, so window memory lives in HBM.  put/get/accumulate are
expressed as XLA updates on the global array — the reference-semantics
implementation whose ops a later Pallas ``make_async_remote_copy`` kernel
can replace one-for-one (the device analog of the BTL put/get the
reference's osc/rdma rides).

Single-controller model: the conductor issues every rank's operations, so
epochs are ordered by construction and fences compile to nothing; what
this module pins down is the *data path* — which buffers constitute the
window, where updates land, and the at-offset update semantics.

Select with ``Win.create(comm, ..., device=True)`` in a device world.
"""
from __future__ import annotations

import numpy as np

from ompi_tpu.api import op as op_mod
from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType


class DeviceModule:
    """Window = (size, n) jax.Array, row r on device-rank r's HBM."""

    def attach(self, win) -> None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        rte = win.comm.rte
        self._mesh = rte.mesh
        self._sharding = NamedSharding(self._mesh, P(self._mesh.axis_names[0]))
        base = np.broadcast_to(np.asarray(win.local),
                               (win.size, win.local.size))
        self._win_array = jax.device_put(np.array(base), self._sharding)
        win.device_array = self._win_array
        # the exposure region lives in HBM: drop the host alias so stores
        # to a stale win.local cannot silently diverge from RMA (put/get
        # are the window API; rdma re-points win.local instead because its
        # mapped memory CAN alias)
        win.local = None

    def detach(self, win) -> None:
        self._win_array = None
        win.device_array = None

    # -- data path (XLA updates; Pallas remote-DMA swap point) -----------
    def put(self, win, arr, target: int, offset: int) -> None:
        import jax.numpy as jnp

        vals = jnp.asarray(np.asarray(arr), self._win_array.dtype)
        self._win_array = self._win_array.at[target,
                                             offset:offset + vals.size
                                             ].set(vals)
        win.device_array = self._win_array

    def get(self, win, count: int, target: int, offset: int) -> np.ndarray:
        return np.asarray(
            self._win_array[target, offset:offset + count])

    def accumulate(self, win, arr, target: int, offset: int, op) -> None:
        import jax.numpy as jnp

        vals = jnp.asarray(np.asarray(arr), self._win_array.dtype)
        sl = (target, slice(offset, offset + vals.size))
        if op is op_mod.SUM:
            self._win_array = self._win_array.at[sl].add(vals)
        elif op is op_mod.MAX:
            self._win_array = self._win_array.at[sl].max(vals)
        elif op is op_mod.MIN:
            self._win_array = self._win_array.at[sl].min(vals)
        elif op is op_mod.PROD:
            self._win_array = self._win_array.at[sl].mul(vals)
        elif op is op_mod.REPLACE:
            self._win_array = self._win_array.at[sl].set(vals)
        else:
            raise MpiError(ErrorClass.ERR_OP,
                           f"device window accumulate: unsupported {op}")
        win.device_array = self._win_array

    def get_accumulate(self, win, arr, target: int, offset: int,
                       op) -> np.ndarray:
        old = self.get(win, np.asarray(arr).size, target, offset)
        self.accumulate(win, arr, target, offset, op)
        return old

    def compare_and_swap(self, win, value, compare, target: int,
                         offset: int):
        old = self.get(win, 1, target, offset)[0]
        if old == compare:
            self.put(win, np.asarray([value]), target, offset)
        return old

    # -- sync: single thread of control orders everything -----------------
    def fence(self, win) -> None:
        pass

    def flush(self, win, target: int) -> None:
        pass

    def lock(self, win, target: int, lock_type: str) -> None:
        pass

    def unlock(self, win, target: int) -> None:
        pass

    def post(self, win, group) -> None:
        pass

    def start(self, win, group) -> None:
        pass

    def complete(self, win) -> None:
        pass

    def wait(self, win) -> None:
        pass


class DeviceOscComponent(Component):
    name = "device"
    priority = 90     # above osc/local: explicit device=True windows only

    def register_vars(self, fw) -> None:
        self._prio = self.register_var(
            "priority", vtype=VarType.INT, default=90,
            help="Selection priority of osc/device (HBM windows)")

    def win_query(self, win):
        rte = win.comm.rte
        if rte is None or not rte.is_device_world:
            return None
        if not getattr(win, "device", False):
            return None
        if rte.mesh is None:
            return None
        return self._prio.value, DeviceModule()


COMPONENT = DeviceOscComponent()
