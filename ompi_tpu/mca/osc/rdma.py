"""osc/rdma ★ — true one-sided RMA over mapped shared-memory windows.

Re-design of ``/root/reference/ompi/mca/osc/rdma/`` (8,125 LoC): where the
reference maps windows for direct BTL put/get and implements locks and
accumulate atomicity via remote atomic CAS
(``osc_rdma_accumulate.c:26-71``), this component backs every rank's
exposure region with a ``multiprocessing.shared_memory`` segment that
same-host peers map directly — put/get are memcpys into the target's
memory with NO target-side agent (the defining one-sided property), and
locks/atomics are shared-memory atomics from the native C++ core
(``ompi_tpu.native``: exclusive/shared lock words, fetch-add, CAS).

Segment layout::

    [ user_lock u64 | acc_lock u64 | post_epoch u64 | complete_cnt u64 ]
    [ data ... ]

``user_lock`` backs MPI_Win_lock/unlock (bit 63 exclusive, low bits shared
readers); ``acc_lock`` serializes accumulates (the reference's dedicated
accumulate lock); the last two words drive PSCW without messages.

Selected above osc/pt2pt when every member of the window's communicator
shares a node and the native library is available; otherwise pt2pt's
active-message path serves (exactly the reference's RDMA-capable /
AM-fallback split).
"""
from __future__ import annotations

import os
import time
from multiprocessing import shared_memory
from typing import Optional

import numpy as np

from ompi_tpu.api import op as op_mod
from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType
from ompi_tpu.mca.btl.sm import _attach

_HDR = 32
_USER_LOCK = 0
_ACC_LOCK = 8
_POST_EPOCH = 16
_COMPLETE_CNT = 24


class _Seg:
    """One rank's mapped window segment (mine or a peer's)."""

    def __init__(self, shm: shared_memory.SharedMemory, dtype,
                 owner: bool) -> None:
        import ctypes

        self.shm = shm
        self.owner = owner
        self.dtype = np.dtype(dtype)
        self.addr = ctypes.addressof(ctypes.c_char.from_buffer(shm.buf))
        self.data = np.frombuffer(shm.buf, np.uint8, offset=_HDR)

    def typed(self) -> np.ndarray:
        n = self.data.nbytes // self.dtype.itemsize
        return self.data[:n * self.dtype.itemsize].view(self.dtype)


class RdmaModule:
    def __init__(self, component: "RdmaOscComponent") -> None:
        self._c = component
        self._segs: dict[int, _Seg] = {}     # comm rank -> mapped segment
        self._post_seen: dict[int, int] = {} # PSCW: last seen post epoch
        self._held: dict[int, str] = {}      # target -> held lock type
        self._start_group: Optional[list] = None
        self._post_group_size = 0

    # -- lifecycle -------------------------------------------------------
    def attach(self, win) -> None:
        from ompi_tpu import native

        self._native = native
        rte = win.comm.rte
        size = win.local.nbytes
        tag = os.environ.get("OTPU_COORD", "l").replace(":", "_") \
            .replace(".", "_")
        name = f"otpu_w{tag}_{win.comm.cid}_{win.comm.rank}_{os.getpid() & 0xffff}"
        shm = shared_memory.SharedMemory(name=name, create=True,
                                         size=_HDR + max(1, size))
        shm.buf[:_HDR] = b"\0" * _HDR
        shm.buf[_HDR:_HDR + size] = win.local.view(np.uint8).tobytes()
        seg = _Seg(shm, win.local.dtype, owner=True)
        self._segs[win.comm.rank] = seg
        # my exposure region IS the mapped data from now on: local loads/
        # stores and peers' RMA see one memory
        win.local = seg.typed()[:size // max(1, seg.dtype.itemsize)]
        rte.modex_put(f"osc_rdma_{win.comm.cid}", name)
        self._win = win

    def detach(self, win) -> None:
        # Win.free barriers before detach, so every peer is done.  close()
        # can fail while user views of the mapped data are still alive
        # (BufferError); the owner must unlink regardless so the segment
        # is reclaimed when the last mapping drops.
        for seg in self._segs.values():
            try:
                seg.data = None     # drop our export before close
                seg.shm.close()
            except BufferError:
                # user still holds views of the mapped data (win.local
                # escaped) — close is impossible until those die, and
                # retrying from SharedMemory.__del__ at interpreter
                # exit would only print "Exception ignored" noise.
                # Swallow ONLY the BufferError on later attempts (not
                # close itself): if the views die first, the __del__
                # retry still releases the fd/mapping instead of
                # leaking it until process exit.
                def _close_quietly(_orig=seg.shm.close):
                    try:
                        _orig()
                    except BufferError:
                        pass

                seg.shm.close = _close_quietly
            except Exception:
                pass
            if seg.owner:
                try:
                    seg.shm.unlink()
                except Exception:
                    pass
        self._segs.clear()

    def _seg(self, win, target: int) -> _Seg:
        seg = self._segs.get(target)
        if seg is None:
            name = win.comm.rte.modex_get(
                win.comm.world_rank(target), f"osc_rdma_{win.comm.cid}")
            seg = _Seg(_attach(name), win.local.dtype, owner=False)
            self._segs[target] = seg
        return seg

    def _view(self, win, target: int, arr_dtype, offset: int, nbytes: int):
        seg = self._seg(win, target)
        base = seg.typed()
        if win.byte_addressed and arr_dtype != base.dtype:
            return seg.data[offset:offset + nbytes].view(arr_dtype)
        count = nbytes // max(1, np.dtype(arr_dtype).itemsize)
        return base[offset:offset + count]

    # -- RMA ops (direct load/store: the one-sided property) -------------
    def put(self, win, arr, target: int, offset: int) -> None:
        view = self._view(win, target, arr.dtype, offset, arr.nbytes)
        view[:] = arr.astype(view.dtype, copy=False).reshape(view.shape)

    def get(self, win, count: int, target: int, offset: int) -> np.ndarray:
        seg = self._seg(win, target)
        base = seg.typed()
        return np.array(base[offset:offset + count], copy=True)

    def _acc_lock(self, seg: _Seg):
        addr = seg.addr + _ACC_LOCK
        while not self._native.lock_excl_try(addr):
            time.sleep(0)          # yield; holder is another process
        return addr

    def accumulate(self, win, arr, target: int, offset: int, op) -> None:
        seg = self._seg(win, target)
        addr = self._acc_lock(seg)
        try:
            view = self._view(win, target, arr.dtype, offset, arr.nbytes)
            op(arr.astype(view.dtype, copy=False)
               if not (win.byte_addressed and arr.dtype != seg.dtype)
               else arr, view)
        finally:
            self._native.lock_excl_release(addr)

    def get_accumulate(self, win, arr, target: int, offset: int,
                       op) -> np.ndarray:
        seg = self._seg(win, target)
        addr = self._acc_lock(seg)
        try:
            view = self._view(win, target, arr.dtype, offset, arr.nbytes)
            old = np.array(view, copy=True)
            op(arr.astype(view.dtype, copy=False)
               if not (win.byte_addressed and arr.dtype != seg.dtype)
               else arr, view)
            return old
        finally:
            self._native.lock_excl_release(addr)

    def compare_and_swap(self, win, value, compare, target: int,
                         offset: int):
        # always under the accumulate lock: MPI requires CAS to be atomic
        # WITH RESPECT TO concurrent accumulates, whose numpy read-modify-
        # write is only protected by that lock (a lock-free native CAS
        # here could land between another rank's read and write)
        seg = self._seg(win, target)
        value = np.asarray(value)
        addr = self._acc_lock(seg)
        try:
            view = self._view(win, target, value.dtype, offset,
                              value.dtype.itemsize)
            old = view[0]
            if old == compare:
                view[0] = value
            return old
        finally:
            self._native.lock_excl_release(addr)

    # -- synchronization --------------------------------------------------
    def fence(self, win) -> None:
        # loads/stores are synchronous in mapped memory; only order ranks
        win.comm.barrier()

    def flush(self, win, target: int) -> None:
        pass                       # direct stores: already complete

    def lock(self, win, target: int, lock_type: str) -> None:
        seg = self._seg(win, target)
        addr = seg.addr + _USER_LOCK
        try_fn = (self._native.lock_excl_try
                  if lock_type == "exclusive"
                  else self._native.lock_shared_try)
        while not try_fn(addr):
            time.sleep(0)
        self._held[target] = lock_type   # per-target: concurrent
        # distinct-target locks are legal MPI

    def unlock(self, win, target: int) -> None:
        seg = self._seg(win, target)
        addr = seg.addr + _USER_LOCK
        lock_type = self._held.pop(target, "exclusive")
        if lock_type == "exclusive":
            self._native.lock_excl_release(addr)
        else:
            self._native.lock_shared_release(addr)

    def sync(self, win) -> None:
        pass

    # -- PSCW via shared counters (no messages) ---------------------------
    def post(self, win, group) -> None:
        """Expose to the access group: bump my post epoch."""
        self._post_group_size = group.size
        seg = self._segs[win.comm.rank]
        cur = self._native.atomic_load_u64(seg.addr + _POST_EPOCH)
        self._native.atomic_store_u64(seg.addr + _POST_EPOCH, cur + 1)

    def start(self, win, group) -> None:
        """Open an access epoch: wait for each target's post."""
        self._start_group = [win.comm.group.rank_of(r)
                             for r in group.world_ranks]
        for t in self._start_group:
            seg = self._seg(win, t)
            seen = self._post_seen.get(t, 0)
            while self._native.atomic_load_u64(
                    seg.addr + _POST_EPOCH) <= seen:
                time.sleep(0)
            self._post_seen[t] = seen + 1

    def complete(self, win) -> None:
        for t in self._start_group or []:
            seg = self._seg(win, t)
            self._native.atomic_add_i64(seg.addr + _COMPLETE_CNT, 1)
        self._start_group = None

    def wait(self, win) -> None:
        while not self.pscw_test(win):
            time.sleep(0)

    def pscw_test(self, win) -> bool:
        """Nonblocking ``wait`` (MPI_Win_test) — the one copy of the
        epoch-close accounting; ``wait`` spins on it."""
        seg = self._segs[win.comm.rank]
        want = self._post_group_size
        if self._native.atomic_load_u64(seg.addr + _COMPLETE_CNT) < want:
            return False
        self._native.atomic_add_i64(seg.addr + _COMPLETE_CNT, -want)
        return True


class RdmaOscComponent(Component):
    name = "rdma"
    priority = 60

    def register_vars(self, fw) -> None:
        self._prio = self.register_var(
            "priority", vtype=VarType.INT, default=60,
            help="Selection priority of osc/rdma (mapped-window RMA)")

    def win_query(self, win):
        rte = win.comm.rte
        if rte is None or rte.is_device_world:
            return None
        if getattr(win, "dynamic", False):
            return None   # dynamic regions need the active-message path
        if getattr(rte, "client", None) is None:
            return None
        try:
            from ompi_tpu import native

            if not native.available():
                return None
        except Exception:
            return None
        # every member must share my node (mapped memory reach)
        my_node = rte.node_of(rte.my_world_rank)
        if my_node is None:
            return None
        for w in win.comm.group.world_ranks:
            if rte.node_of(w) != my_node:
                return None
        return self._prio.value, RdmaModule(self)


COMPONENT = RdmaOscComponent()
