"""osc — one-sided communication framework (``/root/reference/ompi/mca/osc/``).

Components are selected per *window*, the way the reference queries
osc components at ``MPI_Win_create`` (``osc_base_init.c``):

- ``pt2pt`` — active-message RMA over the p2p engine with a per-process
  servicing agent (the re-design of ``osc/rdma``'s AM fallback path,
  ``osc_rdma_accumulate.c:26-71``; true passive-target progress comes from
  the agent thread, which the reference approximates with opal_progress).
- ``local`` — single-controller/device-world windows where every rank's
  exposure region lives in this process.
"""
from __future__ import annotations

from ompi_tpu.base import mca


def osc_framework() -> mca.Framework:
    return mca.framework("osc", "one-sided communication", multi_select=True)


def win_select(win) -> None:
    """Pick the highest-priority osc component claiming this window."""
    fw = osc_framework()
    best = None
    for comp in fw.select_all():
        query = getattr(comp, "win_query", None)
        if query is None:
            continue
        res = query(win)
        if res is None:
            continue
        priority, module = res
        if best is None or priority > best[0]:
            best = (priority, module)
    if best is None:
        from ompi_tpu.api.errors import ErrorClass, MpiError

        raise MpiError(ErrorClass.ERR_WIN,
                       "no osc component available for this window")
    win.module = best[1]
    win.module.attach(win)
