"""osc/local — windows in the single-controller models.

Counterpart of ``osc/sm`` (``/root/reference/ompi/mca/osc/sm/``): when every
rank's exposure region lives in one address space (the device-world
conductor model, or COMM_SELF), RMA is direct memory access.  Each facade
rank registers its base array in a shared per-window table; ops index the
table and apply immediately; all synchronization collapses to no-ops (there
is one thread of control, so epochs are trivially ordered).
"""
from __future__ import annotations

import numpy as np

from ompi_tpu.api import op as op_mod
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType


class LocalModule:
    def attach(self, win) -> None:
        # one region per rank, all hosted here (conductor model)
        self._bases = {r: (np.array(win.local, copy=True) if r != win.rank
                           else win.local)
                       for r in range(win.size)}

    def detach(self, win) -> None:
        self._bases.clear()

    def base_of(self, rank: int) -> np.ndarray:
        return self._bases[rank]

    # -- ops -------------------------------------------------------------
    def put(self, win, arr, target: int, offset: int) -> None:
        self._bases[target][offset:offset + arr.size] = arr

    def get(self, win, count: int, target: int, offset: int) -> np.ndarray:
        return np.array(self._bases[target][offset:offset + count], copy=True)

    def accumulate(self, win, arr, target: int, offset: int, op) -> None:
        base = self._bases[target]
        if win.byte_addressed and arr.dtype != base.dtype:
            # byte-addressed heap window: typed view at byte offset
            view = base[offset:offset + arr.nbytes].view(arr.dtype)
            op(arr, view)
        else:
            view = base[offset:offset + arr.size]
            op(arr.astype(base.dtype, copy=False), view)

    def get_accumulate(self, win, arr, target: int, offset: int,
                       op) -> np.ndarray:
        base = self._bases[target]
        if win.byte_addressed and arr.dtype != base.dtype:
            old = np.array(base[offset:offset + arr.nbytes].view(arr.dtype),
                           copy=True)
        else:
            old = self.get(win, arr.size, target, offset)
        self.accumulate(win, arr, target, offset, op)
        return old

    def compare_and_swap(self, win, value, compare, target: int, offset: int):
        base = self._bases[target]
        value = np.asarray(value)
        if win.byte_addressed and value.dtype != base.dtype:
            view = base[offset:offset + value.dtype.itemsize].view(value.dtype)
            old = view[0]
            if old == compare:
                view[0] = value
            return old
        old = base[offset]
        if old == compare:
            base[offset] = value
        return old

    # -- sync: single thread of control, all trivially ordered ----------
    def flush(self, win, target: int) -> None:
        pass

    def fence(self, win) -> None:
        pass

    def lock(self, win, target: int, lock_type: str) -> None:
        pass

    def unlock(self, win, target: int) -> None:
        pass

    def post(self, win, group) -> None:
        pass

    def start(self, win, group) -> None:
        pass

    def complete(self, win) -> None:
        pass

    def wait(self, win) -> None:
        pass


class LocalComponent(Component):
    name = "local"

    def register_vars(self, fw) -> None:
        self._prio = self.register_var(
            "priority", vtype=VarType.INT, default=80,
            help="Selection priority of osc/local")

    def win_query(self, win):
        if getattr(win, "dynamic", False):
            return None   # region RMA needs the active-message path
        if (win.comm.rte is not None and win.comm.rte.is_device_world) \
                or win.comm.size == 1:
            return self._prio.value, LocalModule()
        return None


COMPONENT = LocalComponent()
