"""pml/ob1 — the default matching & protocol engine over BTLs.

Re-design of ``/root/reference/ompi/mca/pml/ob1/``: MPI matching by
(comm, src, tag) with sender sequence numbers, unexpected-message and
out-of-order queues (``pml_ob1_recvfrag.c:293,831,923``; ooo held by seq,
``:106-147`` — Python's unbounded ints remove the 16-bit rollover dance),
and the eager / rendezvous (RNDV/ACK/FRAG) protocol ladder selected by the
BTL's size limits (``pml_ob1_sendreq.h:375-401``).  The send fast path
(``pml_ob1_isend.c:281`` ``send_inline``) is the eager branch.

Matching state is keyed by (cid, receiver world rank) so a single process
can host every rank of the device-world ("conductor") model — the TPU
equivalent of ``mpirun --oversubscribe`` over btl/self.
"""
from __future__ import annotations

import itertools
import threading
from typing import Optional

import numpy as np

from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.api.request import Request
from ompi_tpu.api.status import ANY_SOURCE, ANY_TAG, Status
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType
from ompi_tpu.datatype import Convertor
from ompi_tpu.mca.bml import Bml
from ompi_tpu.mca.btl.base import ACK, CTL, FRAG, MATCH, RGET, RNDV, Frag
from ompi_tpu.mca.coll import quant as quant_mod
from ompi_tpu.runtime import peruse, profile, spc, trace
from ompi_tpu.runtime.hotpath import hot_path


class SendRequest(Request):
    def __init__(self, pml, comm, buf, dest: int, tag: int):
        super().__init__()
        from ompi_tpu.api.comm import as_buffer

        self.pml = pml
        self.comm = comm
        arr, count, dt = as_buffer(buf)
        self.convertor = Convertor(dt, count, arr)
        self.nbytes = self.convertor.packed_size
        self.dest = dest
        self.tag = tag
        self.req_id = next(pml._req_counter)
        self.acked = False


class RecvRequest(Request):
    def __init__(self, pml, comm, buf, source: int, tag: int):
        super().__init__()
        from ompi_tpu.api.comm import as_buffer

        self.pml = pml
        self.comm = comm
        arr, count, dt = as_buffer(buf)
        self.convertor = Convertor(dt, count, arr)
        self.capacity = self.convertor.packed_size
        self.source = source            # comm rank or ANY_SOURCE
        self.tag = tag
        self.req_id = next(pml._req_counter)
        self.received = 0
        self.total = None               # known after match
        self.matched_src = None
        self._flow = None               # (cid, src, dst, seq) at deliver

    def matches(self, frag: Frag, comm_src: int) -> bool:
        if self.source != ANY_SOURCE and self.source != comm_src:
            return False
        if self.tag == ANY_TAG:
            return frag.tag >= 0        # wildcards never match internal tags
        return self.tag == frag.tag

    def _try_cancel(self) -> bool:
        return self.pml._cancel_recv(self)


class Message:
    """``MPI_Mprobe`` matched-message handle."""

    def __init__(self, pml, comm, frag: Frag, status: Status):
        self._pml = pml
        self._comm = comm
        self._frag = frag
        self.status = status

    def recv(self, buf) -> Status:
        req = RecvRequest(self._pml, self._comm, buf,
                          self.status.source, self.status.tag)
        self._pml._deliver_to_request(req, self._frag)
        return req.wait()

    def irecv(self, buf) -> Request:
        """``MPI_Imrecv``: nonblocking receive of the matched message."""
        req = RecvRequest(self._pml, self._comm, buf,
                          self.status.source, self.status.tag)
        self._pml._deliver_to_request(req, self._frag)
        return req


class _MatchState:
    """Per-(cid, receiver) matching queues."""

    __slots__ = ("posted", "unexpected", "expected_seq", "ooo")

    def __init__(self) -> None:
        self.posted: list[RecvRequest] = []
        self.unexpected: list[Frag] = []
        self.expected_seq: dict[int, int] = {}   # src world rank -> next seq
        self.ooo: dict[int, dict[int, Frag]] = {}


class Ob1Pml:
    """The pml module (one per process)."""

    #: otpu-lint lock-discipline contract: the matching table mutates
    #: only under the pml lock (app threads post/cancel recvs while the
    #: progress thread delivers frags into the same queues)
    _guarded_by = {"_match": "_lock"}

    def __init__(self, component: "Ob1Component", rte) -> None:
        self.component = component
        self.rte = rte
        self._lock = threading.RLock()
        self._match: dict[tuple[int, int], _MatchState] = {}
        self._seq: dict[tuple[int, int, int], itertools.count] = {}
        self._req_counter = itertools.count(1)
        self._send_reqs: dict[int, SendRequest] = {}
        self._recv_reqs: dict[int, RecvRequest] = {}
        self.bml = Bml(rte, self._recv_frag)
        # req_ft.c analog: peer death completes its pending requests in
        # error instead of leaving waiters (e.g. an osc agent mid-rndv)
        # blocked forever
        from ompi_tpu.ft import state as ft_state

        ft_state.on_failure(self._peer_failed)
        register_ctl_handler("ob1_rget_done", self._on_rget_done)
        register_ctl_handler("ob1_rget_pull", self._on_rget_pull)

    # -- framework hooks -------------------------------------------------
    def add_comm(self, comm) -> None:
        with self._lock:
            for r in comm.group.world_ranks:
                self._match.setdefault((comm.cid, r), _MatchState())

    def del_comm(self, comm) -> None:
        """Drop per-comm matching state (``MPI_Comm_free`` teardown)."""
        with self._lock:
            for key in [k for k in self._match if k[0] == comm.cid]:
                del self._match[key]
            for key in [k for k in self._seq if k[0] == comm.cid]:
                del self._seq[key]

    def finalize(self) -> None:
        self.bml.finalize()

    # -- FT request completion (``ompi/request/req_ft.c``) ---------------
    def _peer_failed(self, world_rank: int) -> None:
        """Complete pending requests whose explicit peer died in error.

        ANY_SOURCE recvs are left pending (the reference raises
        ERR_PROC_FAILED_PENDING, a warning, without destroying them).
        """
        from ompi_tpu.api.errors import ProcFailedError

        err = ProcFailedError(f"peer world rank {world_rank} failed",
                              (world_rank,))
        victims = []
        with self._lock:
            for st in self._match.values():
                for req in list(st.posted):
                    if req.source == ANY_SOURCE:
                        continue
                    try:
                        grp = (req.comm.remote_group if req.comm.is_inter
                               else req.comm.group)
                        src_w = grp.world_rank(req.source)
                    except Exception:
                        continue
                    if src_w == world_rank:
                        st.posted.remove(req)
                        victims.append(req)
            for rid, req in list(self._recv_reqs.items()):
                if req.matched_src == world_rank:
                    del self._recv_reqs[rid]
                    victims.append(req)
            for rid, req in list(self._send_reqs.items()):
                try:
                    grp = (req.comm.remote_group if req.comm.is_inter
                           else req.comm.group)
                    if grp.world_rank(req.dest) == world_rank:
                        del self._send_reqs[rid]
                        victims.append(req)
                except Exception:
                    continue
        for req in victims:
            _release_rget(req)   # a dead puller must not leak the segment
            req.complete(err)

    # -- send path (pml_ob1_isend.c:233) --------------------------------
    @hot_path
    def isend(self, comm, buf, dest: int, tag: int,
              sync: bool = False) -> Request:
        """``sync=True`` gives MPI_Ssend semantics: completion only after
        the receiver has matched — implemented by forcing the rendezvous
        protocol, whose sender completion requires the receiver's ACK
        (``pml_ob1_sendreq.h:380`` RNDV; an eager send completes locally
        and cannot observe the match)."""
        spc.record("isend")
        req = SendRequest(self, comm, buf, dest, tag)
        _t0 = trace.now() if trace.enabled else 0
        dst_world = (comm.remote_group if comm.is_inter
                     else comm.group).world_rank(dest)
        src_world = comm.world_rank(comm.rank)
        ep = self.bml.endpoint(dst_world)
        if ep is None:
            raise MpiError(ErrorClass.ERR_INTERN,
                           f"no transport reaches world rank {dst_world}")
        # activate fires only once the request is real (endpoint resolved)
        # so activate/complete pairs always balance
        if peruse.active():
            peruse.fire(peruse.REQ_ACTIVATE, comm.cid, kind="send",
                        dest=dest, tag=tag)
        seq = next(self._seq.setdefault(
            (comm.cid, src_world, dst_world), itertools.count()))
        if trace.enabled:
            # span closes at request completion, whichever protocol leg
            # (eager inline, RNDV ACK, RGET done/pull) completes it.
            # With the flow layer armed the span carries the message's
            # flow key — the (cid, src, dst, per-peer seq) stamped on
            # its btl match header — and emits the flow-arrow start
            # anchored at the span's own end.  The key stays a tuple on
            # this @hot_path (flow_start renders the Chrome id string).
            fkey = ((comm.cid, src_world, dst_world, seq)
                    if trace.flow_enabled else None)

            def _send_span(r, _t0=_t0, fkey=fkey):
                t1 = trace.now()
                eargs = {"nbytes": r.nbytes, "dest": r.dest,
                         "tag": r.tag, "cid": r.comm.cid}
                if fkey is not None:
                    eargs["fid"] = fkey
                trace.span("send", "pml", _t0, t1, args=eargs)
                if fkey is not None:
                    trace.flow_start("pml_msg", fkey, t1)

            req.on_complete(_send_span)
        spc.record("bytes_sent", req.nbytes)
        rget_limit = self.component.rget_limit()
        if (rget_limit and not sync
                and req.nbytes > max(ep.btl.eager_limit, rget_limit)
                and (getattr(ep.btl, "rdma", False)
                     or self.component.rget_emulate())):
            # RGET protocol (pml_ob1_sendreq.h:375-401): expose the packed
            # stream and let the RECEIVER pull it — one one-sided copy
            # into the destination on rdma transports (measured 2.4-3.7x
            # the FRAG stream at 4-16MB on btl/sm).  Like the reference,
            # RGET engages only where the btl has real one-sided get
            # (mca_pml_ob1_rdma_btls): the request/stream pull emulation
            # on non-rdma btls measures ~0.9x FRAG (an extra round-trip,
            # no zero-copy win) and is gated behind rget_emulate
            from ompi_tpu.runtime import memchecker

            memchecker.protect_send(req, buf)
            try:
                self._send_reqs[req.req_id] = req
                spc.record("rget_msgs")
                meta = {"req_id": req.req_id}
                if getattr(ep.btl, "rdma", False):
                    data, _borrowed = req.convertor.pack_borrow()
                    req._rget_key = ep.btl.prepare_src(ep, data)
                    req._rget_btl = ep.btl
                    meta["key"] = req._rget_key
                else:
                    meta["pull"] = True
                frag = Frag(comm.cid, src_world, dst_world, tag, seq, RGET,
                            total_len=req.nbytes, meta=meta)
                ep.btl.send(ep, frag)
            except Exception:
                self._send_reqs.pop(req.req_id, None)
                key = getattr(req, "_rget_key", None)
                if key is not None:
                    ep.btl.release_src(key)
                req.complete(MpiError(ErrorClass.ERR_OTHER,
                                      "rget setup failed"))
                raise
            return req
        if req.nbytes <= ep.btl.eager_limit and not sync:
            # eager: single MATCH fragment, complete immediately.  The
            # payload is a borrowed view when the layout allows it — the
            # btl's wire/ring write is the only copy (send-in-place)
            _pt = profile.now() if profile.enabled else 0
            data, borrowed = req.convertor.pack_borrow()
            if profile.enabled:
                profile.stage_span("send.pack", _pt)
            frag = Frag(comm.cid, src_world, dst_world, tag, seq, MATCH,
                        data, total_len=req.nbytes, borrowed=borrowed,
                        qcodec=quant_mod.wire_codec_for(
                            req.convertor, req.nbytes)
                        if quant_mod.wire_enabled else None)
            ep.btl.send(ep, frag)
            req.complete()
            if peruse.active():
                peruse.fire(peruse.REQ_COMPLETE, comm.cid, kind="send",
                            dest=dest, tag=tag)
        else:
            # rendezvous: RNDV head now, stream on ACK.  The user buffer
            # stays MPI-owned until completion — memchecker freezes it so
            # a racy write fails loudly (memchecker.h:25-52 analog)
            from ompi_tpu.runtime import memchecker

            memchecker.protect_send(req, buf)
            try:
                _pt = profile.now() if profile.enabled else 0
                head, borrowed = req.convertor.pack_borrow(
                    ep.btl.rndv_eager_limit)
                if profile.enabled:
                    profile.stage_span("send.pack", _pt)
                self._send_reqs[req.req_id] = req
                frag = Frag(comm.cid, src_world, dst_world, tag, seq, RNDV,
                            head, total_len=req.nbytes,
                            meta={"req_id": req.req_id}, borrowed=borrowed,
                            qcodec=quant_mod.wire_codec_for(
                                req.convertor, req.nbytes)
                            if quant_mod.wire_enabled else None)
                ep.btl.send(ep, frag)
            except Exception:
                # failed setup: the request will never complete, so the
                # guard's release callback must fire here or the user's
                # buffer stays read-only forever
                self._send_reqs.pop(req.req_id, None)
                req.complete(MpiError(ErrorClass.ERR_OTHER,
                                      "rendezvous setup failed"))
                if peruse.active():
                    peruse.fire(peruse.REQ_COMPLETE, comm.cid, kind="send",
                                dest=dest, tag=tag)
                raise
        return req

    def send(self, comm, buf, dest: int, tag: int) -> None:
        spc.record("send")
        self.isend(comm, buf, dest, tag).wait()

    def _stream_rest(self, req: SendRequest, ack: Frag) -> None:
        """Receiver matched our RNDV: push remaining FRAGs (RPUT analog).

        Multi-rail: FRAG frames are offset-addressed and reassembled by
        req-id at the receiver, so the stream can stripe round-robin
        across EVERY endpoint that reaches the peer, weighted by btl
        bandwidth (``bml_r2.c``'s bandwidth-proportional scheduling /
        btl/tcp link striping).  Eager/RNDV heads stay on the
        lowest-latency rail — order matters only for the matched head.

        fastpath fragment pipelining: ``btl.send`` queues the fragment's
        views and returns after ONE transport attempt (sendmsg/ring
        write), so the pack of fragment n+1 below overlaps the kernel
        draining fragment n — pack and wire move concurrently instead
        of strictly alternating.  On the contiguous path pack_borrow is
        an O(1) slice and the btl sees the user buffer's own memoryview
        (zero payload copies, SPC ``fastpath_payload_copies``); only a
        backpressured remainder is ever owned.
        """
        dst_world, peer_req = ack.src, ack.meta["peer_req"]
        rails = self._stripe_rails(dst_world, req.nbytes)
        conv = req.convertor
        # coll/quant wire stamp, once per stream: the btl's codec stage
        # only sees opaque packed bytes, so the dtype eligibility check
        # must happen here, where the convertor still knows it
        qc = quant_mod.wire_codec_for(conv, req.nbytes) \
            if quant_mod.wire_enabled else None
        if len(rails) == 1:
            # single-rail fast lane: no finish-time bookkeeping at all
            ep = rails[0]
            btl, max_send = ep.btl, rails[0].btl.max_send_size
            while not conv.finished:
                off = conv.position
                _pt = profile.now() if profile.enabled else 0
                data, borrowed = conv.pack_borrow(max_send)
                if profile.enabled:
                    profile.stage_span("send.pack", _pt)
                btl.send(ep, Frag(ack.cid, ack.dst, dst_world,
                                  -1, 0, FRAG, data, total_len=req.nbytes,
                                  offset=off, meta={"req_id": peer_req},
                                  borrowed=borrowed, qcodec=qc))
        else:
            assigned = [0] * len(rails)
            while not conv.finished:
                # finish-time greedy: give the frag to the rail that
                # would complete its assigned bytes soonest — long-run
                # bandwidth-proportional, and a 100x-slower rail never
                # receives a frag a fast rail could finish first
                j = min(range(len(rails)),
                        key=lambda k: (assigned[k]
                                       + rails[k].btl.max_send_size)
                        / max(1, rails[k].btl.bandwidth))
                ep = rails[j]
                off = conv.position
                _pt = profile.now() if profile.enabled else 0
                data, borrowed = conv.pack_borrow(ep.btl.max_send_size)
                if profile.enabled:
                    profile.stage_span("send.pack", _pt)
                assigned[j] += len(data)
                ep.btl.send(ep, Frag(ack.cid, ack.dst, dst_world,
                                     -1, 0, FRAG, data, total_len=req.nbytes,
                                     offset=off, meta={"req_id": peer_req},
                                     borrowed=borrowed, qcodec=qc))
        self._send_reqs.pop(req.req_id, None)
        req.complete()
        if peruse.active():
            peruse.fire(peruse.REQ_COMPLETE, ack.cid, kind="send",
                        dest=req.dest, tag=req.tag)

    def _stripe_rails(self, dst_world: int, nbytes: int) -> list:
        """Endpoints eligible to carry one large transfer's FRAG stream
        (the per-frag schedule itself is finish-time greedy in
        _stream_rest)."""
        eps = self.bml.endpoints(dst_world)
        if (len(eps) < 2 or not self.component.stripe_enabled()
                or nbytes < self.component.stripe_min()):
            return eps[:1] or [self.bml.endpoint(dst_world)]
        spc.record("striped_msgs")
        return list(eps)

    # -- recv path -------------------------------------------------------
    def irecv(self, comm, buf, source: int, tag: int) -> Request:
        spc.record("irecv")
        req = RecvRequest(self, comm, buf, source, tag)
        if trace.enabled:
            _t0 = trace.now()

            def _recv_span(r, _t0=_t0):
                t1 = trace.now()
                eargs = {"nbytes": r.received, "source": r.status.source,
                         "tag": r.tag, "cid": r.comm.cid}
                fl = r._flow
                if fl is not None and trace.flow_enabled:
                    # the sender's stamp rode the match header; closing
                    # the same key here is what lets the merged timeline
                    # draw the send-complete -> recv-delivery arrow
                    eargs["fid"] = fl
                trace.span("recv", "pml", _t0, t1, args=eargs)
                if fl is not None and trace.flow_enabled:
                    trace.flow_finish("pml_msg", fl, t1)

            req.on_complete(_recv_span)
        dst_world = comm.world_rank(comm.rank)
        key = (comm.cid, dst_world)
        if peruse.active():
            peruse.fire(peruse.REQ_ACTIVATE, comm.cid, kind="recv",
                        source=source, tag=tag)
        # PERUSE events observed under self._lock are deferred and fired
        # after release so a callback can never deadlock against the pml
        events: list = []
        with self._lock:
            st = self._match.setdefault(key, _MatchState())
            # check the unexpected queue first (arrival order)
            for i, frag in enumerate(st.unexpected):
                comm_src = (comm.remote_group if comm.is_inter
                            else comm.group).rank_of(frag.src)
                if req.matches(frag, comm_src):
                    st.unexpected.pop(i)
                    if peruse.active():
                        events.append((peruse.REQ_MATCH_UNEX, comm.cid,
                                       dict(source=comm_src, tag=frag.tag,
                                            unex_qlen=len(st.unexpected))))
                    self._deliver_to_request(req, frag, events)
                    break
            else:
                st.posted.append(req)
                if peruse.active():
                    events.append((peruse.REQ_INSERT_IN_POSTED_Q, comm.cid,
                                   dict(source=source, tag=tag,
                                        posted_qlen=len(st.posted))))
        for ev, cid, info in events:
            peruse.fire(ev, cid, **info)
        return req

    def recv(self, comm, buf, source: int, tag: int) -> Status:
        spc.record("recv")
        return self.irecv(comm, buf, source, tag).wait()

    def _probe_liveness(self, comm, source: int, spins: int) -> None:
        """Keep a blocking probe out of the one FT hole request
        completion cannot cover: a probe is never a posted request, so
        ``_peer_failed`` cannot complete it in error — poll the ft
        state like coll/sm's counter waits.  ULFM probe semantics: a
        named failed source raises ERR_PROC_FAILED, a revoked comm
        raises ERR_REVOKED; ANY_SOURCE is left pending (the
        ``_peer_failed`` precedent)."""
        if spins % 2048:
            return
        if comm.is_revoked():
            from ompi_tpu.api.errors import RevokedError

            raise RevokedError(f"{comm.name} revoked during a "
                               "blocking probe")
        if source == ANY_SOURCE:
            return
        from ompi_tpu.ft import state as ft_state

        src_world = (comm.remote_group if comm.is_inter
                     else comm.group).world_rank(source)
        if ft_state.is_failed(src_world):
            from ompi_tpu.api.errors import ProcFailedError

            raise ProcFailedError(
                f"peer world rank {src_world} failed during a "
                "blocking probe", (src_world,))

    def probe(self, comm, source: int, tag: int, blocking: bool):
        spc.record("probe" if blocking else "iprobe")
        from ompi_tpu.runtime.progress import progress

        probe_req = RecvRequest(self, comm, np.empty(0, np.uint8), source, tag)
        dst_world = comm.world_rank(comm.rank)
        key = (comm.cid, dst_world)
        spins = 0
        while True:
            with self._lock:
                st = self._match.setdefault(key, _MatchState())
                for frag in st.unexpected:
                    comm_src = (comm.remote_group if comm.is_inter
                            else comm.group).rank_of(frag.src)
                    if probe_req.matches(frag, comm_src):
                        status = Status(source=comm_src, tag=frag.tag,
                                        _nbytes=frag.total_len or len(frag.data))
                        return status if blocking else (True, status)
            if not blocking:
                progress()
                with self._lock:
                    st = self._match.setdefault(key, _MatchState())
                    for frag in st.unexpected:
                        comm_src = (comm.remote_group if comm.is_inter
                            else comm.group).rank_of(frag.src)
                        if probe_req.matches(frag, comm_src):
                            status = Status(
                                source=comm_src, tag=frag.tag,
                                _nbytes=frag.total_len or len(frag.data))
                            return True, status
                return False, None
            progress()
            spins += 1
            self._probe_liveness(comm, source, spins)

    def mprobe(self, comm, source: int, tag: int, blocking: bool):
        from ompi_tpu.runtime.progress import progress

        probe_req = RecvRequest(self, comm, np.empty(0, np.uint8), source, tag)
        dst_world = comm.world_rank(comm.rank)
        key = (comm.cid, dst_world)
        spins = 0
        while True:
            with self._lock:
                st = self._match.setdefault(key, _MatchState())
                for i, frag in enumerate(st.unexpected):
                    comm_src = (comm.remote_group if comm.is_inter
                            else comm.group).rank_of(frag.src)
                    if probe_req.matches(frag, comm_src):
                        st.unexpected.pop(i)
                        status = Status(source=comm_src, tag=frag.tag,
                                        _nbytes=frag.total_len or len(frag.data))
                        return Message(self, comm, frag, status) if blocking \
                            else (True, Message(self, comm, frag, status))
            if not blocking:
                return False, None
            progress()
            spins += 1
            self._probe_liveness(comm, source, spins)

    def _cancel_recv(self, req: RecvRequest) -> bool:
        with self._lock:
            for st in self._match.values():
                if req in st.posted:
                    st.posted.remove(req)
                    return True
        return False

    # -- fragment delivery (pml_ob1_recvfrag.c:450) ----------------------
    @hot_path
    def _recv_frag(self, frag: Frag) -> None:
        if frag.kind == ACK:
            req = self._send_reqs.get(frag.meta["req_id"])
            if req is not None:
                self._stream_rest(req, frag)
            return
        if frag.kind == FRAG:
            self._recv_data_frag(frag)
            return
        if frag.kind == CTL:
            handler = _ctl_handlers.get(frag.meta.get("proto"))
            if handler is not None:
                frag.own_data()   # handlers may stash the payload
                handler(frag)
            return
        key = (frag.cid, frag.dst)
        events: list = []
        try:
            self._recv_frag_locked(key, frag, events)
        finally:
            for ev, cid, info in events:
                peruse.fire(ev, cid, **info)

    def _recv_frag_locked(self, key, frag: Frag, events: list) -> None:
        with self._lock:
            st = self._match.setdefault(key, _MatchState())
            expected = st.expected_seq.get(frag.src, 0)
            if frag.seq != expected:
                # out-of-order arrival: hold by seq (recvfrag.c:106-147);
                # held data must outlive the sender's btl.send call
                frag.own_data()
                spc.record("out_of_sequence_msgs")
                st.ooo.setdefault(frag.src, {})[frag.seq] = frag
                return
            self._match_one(st, frag, events)
            st.expected_seq[frag.src] = expected + 1
            # drain any now-in-order held frags
            held = st.ooo.get(frag.src, {})
            nxt = st.expected_seq[frag.src]
            while nxt in held:
                self._match_one(st, held.pop(nxt), events)
                nxt += 1
                st.expected_seq[frag.src] = nxt

    def _match_one(self, st: _MatchState, frag: Frag,
                   events: Optional[list] = None) -> None:
        """Match one in-sequence frag against posted recvs (recvfrag.c:831).

        Runs under self._lock; PERUSE events append to ``events`` for the
        caller to fire after release."""
        if events is None:
            events = []
        if peruse.active():
            events.append((peruse.MSG_ARRIVED, frag.cid,
                           dict(source=frag.src, tag=frag.tag)))
        for i, req in enumerate(st.posted):
            comm_src = (req.comm.remote_group if req.comm.is_inter
                    else req.comm.group).rank_of(frag.src)
            if req.matches(frag, comm_src):
                st.posted.pop(i)
                spc.record("matched_msgs")
                if peruse.active():
                    events.append((peruse.MSG_MATCH_POSTED_REQ, frag.cid,
                                   dict(source=frag.src, tag=frag.tag,
                                        posted_qlen=len(st.posted))))
                self._deliver_to_request(req, frag, events)
                return
        spc.record("unexpected_msgs")
        frag.own_data()   # queued past the sender's btl.send call
        st.unexpected.append(frag)
        if peruse.active():
            events.append((peruse.MSG_INSERT_IN_UNEX_Q, frag.cid,
                           dict(source=frag.src, tag=frag.tag,
                                unex_qlen=len(st.unexpected))))

    def _deliver_to_request(self, req: RecvRequest, frag: Frag,
                            events: Optional[list] = None) -> None:
        fire_now = events is None
        if events is None:
            events = []
        _pt = profile.now() if profile.enabled else 0
        comm_src = (req.comm.remote_group if req.comm.is_inter
                    else req.comm.group).rank_of(frag.src)
        req.matched_src = frag.src
        if trace.flow_enabled:
            # the flow key off the match header (MATCH/RNDV/RGET all
            # carry the pml sequence); the recv span closes it
            req._flow = (frag.cid, frag.src, frag.dst, frag.seq)
        req.total = frag.total_len or len(frag.data)
        req.status.source = comm_src
        req.status.tag = frag.tag
        error = None
        if req.total > req.capacity:
            error = MpiError(ErrorClass.ERR_TRUNCATE,
                             f"message of {req.total} bytes into "
                             f"{req.capacity}-byte buffer")
            req.total = req.capacity  # deliver what fits, like the reference
        if frag.kind == RGET:
            self._deliver_rget(req, frag, error, events)
            if fire_now:
                for ev, cid, info in events:
                    peruse.fire(ev, cid, **info)
            return
        n = req.convertor.unpack(frag.data[:max(0, req.capacity)])
        req.received += n
        req.status._nbytes = min(req.total, req.received) if error else req.total
        spc.record("bytes_received", n)
        if profile.enabled:
            profile.stage_span("recv.deliver", _pt)
        done = False
        if frag.kind == RNDV and error is None:
            # register for FRAG continuation and ACK the sender
            self._recv_reqs[req.req_id] = req
            ep = self.bml.endpoint(frag.src)
            ep.btl.send(ep, Frag(frag.cid, frag.dst, frag.src, -1, 0, ACK,
                                 meta={"req_id": frag.meta["req_id"],
                                       "peer_req": req.req_id}))
            if req.received >= req.total:
                self._recv_reqs.pop(req.req_id, None)
                req.status._nbytes = req.received
                done = True
        elif error is not None or req.received >= req.total:
            req.status._nbytes = req.received
            done = True
        if done:
            if peruse.active():
                events.append((peruse.REQ_XFER_END, frag.cid,
                               dict(source=frag.src, tag=req.status.tag,
                                    nbytes=req.received)))
                events.append((peruse.REQ_COMPLETE, frag.cid,
                               dict(kind="recv", source=req.status.source,
                                    tag=req.status.tag)))
            _pt = profile.now() if profile.enabled else 0
            req.complete(error)
            if profile.enabled:
                profile.stage_span("recv.complete", _pt)
        if fire_now:
            for ev, cid, info in events:
                peruse.fire(ev, cid, **info)

    def _deliver_rget(self, req: RecvRequest, frag: Frag,
                      error, events: list) -> None:
        """Receiver side of the RGET protocol (pml_ob1_recvreq.c RGET
        scheduling): pull the exposed region one-sidedly (rdma btl) or
        request a sender-driven stream (pull emulation)."""
        ep = self.bml.endpoint(frag.src)
        if ep is None:
            # sender died and its endpoint is gone: complete in error
            # rather than blowing up the progress engine
            self._rget_fail(req, frag, events)
            return
        key = frag.meta.get("key")
        if error is not None and key is None:
            # truncation on the pull path: tell the sender we're done
            # (it has nothing exposed to release) and error out locally
            ep.btl.send(ep, Frag(frag.cid, frag.dst, frag.src, -1, 0, CTL,
                                 meta={"proto": "ob1_rget_done",
                                       "req_id": frag.meta["req_id"]}))
            req.status._nbytes = 0
            if peruse.active():
                events.append((peruse.REQ_COMPLETE, frag.cid,
                               dict(kind="recv", source=req.status.source,
                                    tag=req.status.tag)))
            req.complete(error)
            return
        if key is not None:
            want = req.total
            view = req.convertor.unpack_view(want)
            try:
                if view is not None:
                    # one-sided landing: peer bytes -> user buffer direct
                    ep.btl.get(ep, view, key)
                else:
                    tmp = np.empty(max(0, want), np.uint8)
                    ep.btl.get(ep, tmp, key)
            except Exception:
                # exposed segment gone (sender died and tore down before
                # detection) or btl without get: fail the recv — and
                # best-effort notify a still-alive sender so its request
                # completes and the exposure is released — instead of
                # killing the progress engine.  Only the btl.get is
                # guarded: a local convertor bug must NOT masquerade as
                # a peer failure.
                try:
                    ep.btl.send(ep, Frag(frag.cid, frag.dst, frag.src,
                                         -1, 0, CTL,
                                         meta={"proto": "ob1_rget_done",
                                               "req_id":
                                                   frag.meta["req_id"]}))
                except Exception:
                    pass
                self._rget_fail(req, frag, events)
                return
            if view is not None:
                req.convertor.advance(len(view))
                n = len(view)
            else:
                n = req.convertor.unpack(tmp)
            req.received = n
            req.status._nbytes = n
            spc.record("bytes_received", n)
            ep.btl.send(ep, Frag(frag.cid, frag.dst, frag.src, -1, 0, CTL,
                                 meta={"proto": "ob1_rget_done",
                                       "req_id": frag.meta["req_id"]}))
            if peruse.active():
                events.append((peruse.REQ_XFER_END, frag.cid,
                               dict(source=frag.src, tag=req.status.tag,
                                    nbytes=n)))
                events.append((peruse.REQ_COMPLETE, frag.cid,
                               dict(kind="recv", source=req.status.source,
                                    tag=req.status.tag)))
            req.complete(error)
            return
        # pull emulation: sender streams FRAGs through the normal
        # continuation machinery (completion in _recv_data_frag)
        self._recv_reqs[req.req_id] = req
        ep.btl.send(ep, Frag(frag.cid, frag.dst, frag.src, -1, 0, CTL,
                             meta={"proto": "ob1_rget_pull",
                                   "req_id": frag.meta["req_id"],
                                   "peer_req": req.req_id}))

    def _rget_fail(self, req: RecvRequest, frag: Frag,
                   events: list) -> None:
        """Complete an RGET recv in error (sender gone / pull failed),
        keeping the PERUSE activate/complete pairing balanced."""
        from ompi_tpu.api.errors import ProcFailedError

        req.status._nbytes = 0
        if peruse.active():
            events.append((peruse.REQ_COMPLETE, frag.cid,
                           dict(kind="recv", source=req.status.source,
                                tag=req.status.tag)))
        req.complete(ProcFailedError(
            f"RGET sender world rank {frag.src} unreachable",
            (frag.src,)))

    def _on_rget_done(self, frag: Frag) -> None:
        """Sender side: receiver finished its pull — release + complete."""
        req = self._send_reqs.pop(frag.meta["req_id"], None)
        if req is None:
            return
        _release_rget(req)
        req.complete()
        if peruse.active():
            peruse.fire(peruse.REQ_COMPLETE, frag.cid, kind="send",
                        dest=req.dest, tag=req.tag)

    def _on_rget_pull(self, frag: Frag) -> None:
        """Sender side of the pull emulation: stream the payload."""
        req = self._send_reqs.get(frag.meta["req_id"])
        if req is not None:
            self._stream_rest(req, frag)

    @hot_path
    def _recv_data_frag(self, frag: Frag) -> None:
        req = self._recv_reqs.get(frag.meta["req_id"])
        if req is None:
            return
        _pt = profile.now() if profile.enabled else 0
        req.convertor.set_position(min(frag.offset, req.capacity))
        n = req.convertor.unpack(frag.data)
        req.received += n
        spc.record("bytes_received", n)
        if profile.enabled:
            profile.stage_span("recv.deliver", _pt)
        if req.received >= min(req.total, req.capacity):
            self._recv_reqs.pop(frag.meta["req_id"], None)
            req.status._nbytes = req.received
            if peruse.active():
                peruse.fire(peruse.REQ_XFER_END, frag.cid,
                            source=req.status.source, tag=req.status.tag,
                            nbytes=req.received)
                peruse.fire(peruse.REQ_COMPLETE, frag.cid, kind="recv",
                            source=req.status.source, tag=req.status.tag)
            _pt = profile.now() if profile.enabled else 0
            req.complete()
            if profile.enabled:
                profile.stage_span("recv.complete", _pt)


def _release_rget(req) -> None:
    """Release an RGET exposure if this send request holds one."""
    key = getattr(req, "_rget_key", None)
    btl = getattr(req, "_rget_btl", None)
    if key is not None and btl is not None:
        try:
            btl.release_src(key)
        except Exception:
            pass
        req._rget_key = None


# control-message protocol handlers (osc / ft register here)
_ctl_handlers: dict[str, callable] = {}


def register_ctl_handler(proto: str, handler) -> None:
    _ctl_handlers[proto] = handler


class Ob1Component(Component):
    name = "ob1"
    priority = 20

    def register_vars(self, fw) -> None:
        self.register_var("priority", vtype=VarType.INT, default=20,
                          help="Selection priority of pml/ob1")
        self._rget_var = self.register_var(
            "rget_limit", vtype=VarType.SIZE, default="512k",
            help="Messages above this (and above the btl eager limit) use "
                 "the receiver-pull RGET protocol "
                 "(pml_ob1_sendreq.h:375-401) on rdma-capable btls; 0 "
                 "disables RGET — measured 3.7x (4MB) / 2.4x (16MB) the "
                 "RNDV FRAG stream's bandwidth over btl/sm "
                 "(BENCH_SWEEP.md rget rows)")
        self._rget_emu_var = self.register_var(
            "rget_emulate", vtype=VarType.BOOL, default=False,
            help="Allow RGET's request/stream pull emulation on btls "
                 "without one-sided get (btl/tcp): measured ~0.9-1.1x "
                 "the FRAG stream across runs (extra round-trip, no "
                 "zero-copy win — parity within noise), so off by "
                 "default — the crossover is the btl rdma flag")
        self._stripe_var = self.register_var(
            "stripe", vtype=VarType.BOOL, default=True,
            help="Stripe large RNDV/pull streams across every btl that "
                 "reaches the peer, bandwidth-weighted (bml/r2 multi-rail)")
        self._stripe_min_var = self.register_var(
            "stripe_min", vtype=VarType.SIZE, default="2m",
            help="Smallest message that stripes across rails")

    def rget_limit(self) -> int:
        var = getattr(self, "_rget_var", None)
        return int(var.value) if var is not None else 512 << 10

    def rget_emulate(self) -> bool:
        var = getattr(self, "_rget_emu_var", None)
        return bool(var.value) if var is not None else False

    def stripe_enabled(self) -> bool:
        var = getattr(self, "_stripe_var", None)
        return bool(var.value) if var is not None else True

    def stripe_min(self) -> int:
        var = getattr(self, "_stripe_min_var", None)
        return int(var.value) if var is not None else 2 << 20

    def get_module(self, rte) -> Ob1Pml:
        self._module = Ob1Pml(self, rte)
        return self._module


COMPONENT = Ob1Component()
