"""pml/v + vprotocol/pessimist — message-event logging for replay FT.

Re-design of ``/root/reference/ompi/mca/pml/v`` (the interposition shell)
and ``ompi/mca/vprotocol/pessimist`` (3,218 LoC): pessimistic message
logging records, to stable storage, every nondeterministic event a rank
observes — most importantly the DELIVERY ORDER of receives (any-source
matches are where replay diverges) — plus send envelopes, so a restarted
rank can be re-driven to its pre-failure state by replaying the log
against re-sent messages.

Enable with ``otpu_vprotocol_pessimist_log=<dir>``: each rank appends
JSONL events to ``<dir>/events.<world_rank>.log``.  Payload hashes make
the log auditable without storing data; ``log_payloads`` stores the bytes
too (full sender-based logging).

The interposition mirrors pml/monitoring: the selected pml module is
wrapped at init, transparently for every caller.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Optional

import numpy as np

from ompi_tpu.base.var import VarType, registry

_log_var = registry.register(
    "vprotocol", "pessimist", "log", vtype=VarType.STRING, default="",
    help="Directory for pessimistic message-event logs (empty = disabled)")
_payload_var = registry.register(
    "vprotocol", "pessimist", "log_payloads", vtype=VarType.BOOL,
    default=False,
    help="Store full payload bytes (sender-based logging), not just hashes")


def enabled() -> bool:
    return bool((_log_var.value or "").strip())


class PessimistPml:
    """Interposition pml recording send envelopes + delivery order."""

    def __init__(self, inner, rte) -> None:
        self._inner = inner
        self._dir = (_log_var.value or "").strip()
        os.makedirs(self._dir, exist_ok=True)
        self._path = os.path.join(self._dir,
                                  f"events.{rte.my_world_rank}.log")
        self._fh = open(self._path, "a", buffering=1)
        self._lock = threading.RLock()   # clock bump + event write nest
        self._seq = 0
        self._payloads = bool(_payload_var.value)
        # per-channel event clocks (the reference's
        # ``vprotocol_pessimist_event.h`` clock stamps): a channel is
        # (peer world rank, cid, tag) — within one, MPI matching is
        # non-overtaking, so the channel sequence number pins each recv
        # to exactly one send even when several comms or tags carry
        # concurrent traffic between the same pair
        self._send_clk: dict[tuple, int] = {}
        self._recv_clk: dict[tuple, int] = {}

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def _event(self, kind: str, **fields) -> None:
        with self._lock:
            self._seq += 1
            fields.update(kind=kind, ev=self._seq)
            self._fh.write(json.dumps(fields) + "\n")

    def _digest(self, buf) -> str:
        try:
            return hashlib.sha1(np.ascontiguousarray(buf)
                                .view(np.uint8)).hexdigest()[:16]
        except Exception:
            return "?"

    # -- send side: envelope (+ payload when sender-based logging) -------
    def _log_send(self, comm, buf, dest, tag) -> None:
        arr = np.asarray(buf)
        grp = comm.remote_group if comm.is_inter else comm.group
        # WORLD ranks in the log: events.<world>.log files are keyed by
        # world rank, so replay's cross-log pairing must be too
        dst = int(grp.world_rank(dest))
        chan = (dst, comm.cid, int(tag))
        rec = dict(cid=comm.cid, dst=dst, tag=int(tag),
                   nbytes=int(arr.nbytes), sha=self._digest(arr))
        if self._payloads:
            rec["payload"] = np.ascontiguousarray(arr).view(np.uint8) \
                .tobytes().hex()
        with self._lock:   # clock bump + write atomic: events must land
            sc = self._send_clk[chan] = \
                self._send_clk.get(chan, -1) + 1   # in sc order per chan
            self._event("send", sc=sc, **rec)

    def send(self, comm, buf, dest, tag, **kw):
        self._log_send(comm, buf, dest, tag)
        return self._inner.send(comm, buf, dest, tag, **kw)

    def isend(self, comm, buf, dest, tag, **kw):
        self._log_send(comm, buf, dest, tag)
        return self._inner.isend(comm, buf, dest, tag, **kw)

    # -- recv side: the nondeterministic event is the MATCH --------------
    def _log_match(self, comm, req) -> None:
        self._log_match_st(comm, req.status)

    def recv(self, comm, buf, source, tag):
        st = self._inner.recv(comm, buf, source, tag)
        self._log_match_st(comm, st)
        return st

    def _log_match_st(self, comm, st) -> None:
        grp = comm.remote_group if comm.is_inter else comm.group
        src = int(grp.world_rank(st.source))
        chan = (src, comm.cid, int(st.tag))
        with self._lock:   # clock bump + write atomic (sc order)
            sc = self._recv_clk[chan] = self._recv_clk.get(chan, -1) + 1
            self._event("recv", cid=comm.cid, src=src, tag=int(st.tag),
                        sc=sc)

    def irecv(self, comm, buf, source, tag):
        req = self._inner.irecv(comm, buf, source, tag)
        req.on_complete(lambda r: self._log_match(comm, r))
        return req

    def finalize(self):
        try:
            self._fh.close()
        except Exception:
            pass
        return self._inner.finalize()


_replay_var = registry.register(
    "vprotocol", "pessimist", "replay", vtype=VarType.STRING, default="",
    help="Replay directory: re-drive this rank's execution from the "
         "pessimist logs (recvs satisfied from logged delivery order, "
         "sends envelope-verified + suppressed when provably delivered), "
         "then fall through to live execution")
_replay_rank_var = registry.register(
    "vprotocol", "pessimist", "replay_rank", vtype=VarType.INT, default=-1,
    help="World rank whose log to replay (default: this process's rank)")


def replay_enabled() -> bool:
    return bool((_replay_var.value or "").strip())


class ReplayDivergence(RuntimeError):
    """The re-executed program issued an operation that does not match
    the logged envelope — the piecewise-deterministic assumption broke."""


class ReplayPml:
    """Re-drive a restarted rank from the pessimist logs.

    The reference's pessimist replay (``ompi/mca/vprotocol/pessimist/``)
    re-delivers logged messages in their logged order until the restarted
    rank catches up, then switches to live execution.  Same model here,
    receiver-pull form over the shared log directory:

    - each **recv** consumes the next logged delivery event: the source
      is pinned to the logged one (the any-source nondeterminism this
      protocol exists to remove), and the payload is pulled from the
      SENDER's log (which is why replay requires
      ``otpu_vprotocol_pessimist_log_payloads=1`` job-wide — full
      sender-based logging);
    - each **send** is verified against the next logged send envelope
      (dst/tag/bytes/sha — a mismatch raises :class:`ReplayDivergence`)
      and then SUPPRESSED iff the receiver's log proves delivery
      (its recv-event count from me covers this send); an in-flight
      send the receiver never matched is re-sent live, so a peer
      resuming just past the crash boundary still receives it;
    - when the log is exhausted every operation passes through to the
      live pml.

    Matching is ORDER-based per rank (the k-th recv of the re-execution
    consumes the k-th logged delivery): the piecewise-deterministic
    execution assumption pessimistic logging is built on.  All log ranks
    are WORLD ranks.

    Payload pairing is by **channel event clock** — a channel is
    (peer, cid, tag) and both sides stamp events with their channel
    sequence number (``sc``), mirroring the reference's per-event clock
    stamps (``vprotocol_pessimist_event.h``): the receiver's k-th
    logged delivery on a channel pairs with the sender's k-th send on
    it, which is exact even when several communicators or tags carry
    concurrent, arbitrarily interleaved traffic between the same pair
    (MPI matching is non-overtaking only WITHIN a channel).  Delivery
    proofs for send suppression are per-channel for the same reason — a
    global count could let another channel's deliveries suppress a send
    that never arrived.
    """

    def __init__(self, inner, rte) -> None:
        self._inner = inner
        self._dir = (_replay_var.value or "").strip()
        rr = int(_replay_rank_var.value)
        self._rank = rr if rr >= 0 else rte.my_world_rank
        events = read_log(self._dir, self._rank)
        self._sends = [e for e in events if e["kind"] == "send"]
        self._recvs = [e for e in events if e["kind"] == "recv"]
        self._si = 0
        self._ri = 0
        # per-source, per-(cid,tag)-channel queues of the sender's
        # logged sends addressed to me (channel-clock pairing)
        self._src_sends: dict[int, dict[tuple, list]] = {}
        # delivery proof per (dst, cid, tag) channel: how many of MY
        # sends on it the dst's log shows matched; sends beyond that
        # are re-sent live
        self._delivered: dict[int, dict[tuple, int]] = {}
        self._sent_to: dict[tuple, int] = {}

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def replay_active(self) -> bool:
        return self._si < len(self._sends) or self._ri < len(self._recvs)

    # -- log plumbing ----------------------------------------------------
    def _sends_from(self, src: int, cid: int, tag: int) -> list:
        chans = self._src_sends.get(src)
        if chans is None:
            chans = {}
            for e in read_log(self._dir, src):
                if e["kind"] == "send" and int(e["dst"]) == self._rank:
                    chans.setdefault(
                        (int(e["cid"]), int(e["tag"])), []).append(e)
            self._src_sends[src] = chans
        return chans.get((int(cid), int(tag)), [])

    def _delivered_count(self, dst: int, cid: int, tag: int) -> int:
        chans = self._delivered.get(dst)
        if chans is None:
            chans = {}
            try:
                for e in read_log(self._dir, dst):
                    if (e["kind"] == "recv"
                            and int(e["src"]) == self._rank):
                        k = (int(e["cid"]), int(e["tag"]))
                        chans[k] = chans.get(k, 0) + 1
            except OSError:
                pass   # peer never logged: nothing provably delivered
            self._delivered[dst] = chans
        return chans.get((int(cid), int(tag)), 0)

    # -- send side -------------------------------------------------------
    def _replay_send(self, comm, buf, dest, tag) -> bool:
        """True when the send was consumed by the log (suppressed or
        re-sent live); False when the log is exhausted."""
        if self._si >= len(self._sends):
            return False
        e = self._sends[self._si]
        arr = np.asarray(buf)
        grp = comm.remote_group if comm.is_inter else comm.group
        dst_world = int(grp.world_rank(dest))
        if (int(e["dst"]) != dst_world or int(e["tag"]) != int(tag)
                or int(e["cid"]) != int(comm.cid)
                or int(e["nbytes"]) != int(arr.nbytes)):
            raise ReplayDivergence(
                f"send #{self._si} diverged: logged (dst={e['dst']} "
                f"cid={e['cid']} tag={e['tag']} nbytes={e['nbytes']}) "
                f"vs replayed (dst={dst_world} cid={comm.cid} tag={tag} "
                f"nbytes={arr.nbytes})")
        sha = hashlib.sha1(np.ascontiguousarray(arr)
                           .view(np.uint8)).hexdigest()[:16]
        if e.get("sha") not in ("?", sha):
            raise ReplayDivergence(
                f"send #{self._si} payload hash diverged "
                f"(logged {e['sha']}, replayed {sha})")
        self._si += 1
        chan = (dst_world, int(e["cid"]), int(tag))
        seq = self._sent_to.get(chan, 0)
        self._sent_to[chan] = seq + 1
        if seq < self._delivered_count(dst_world, e["cid"], tag):
            return True            # provably delivered: suppress
        self._inner.send(comm, buf, dest, tag)   # in-flight at crash
        return True

    def send(self, comm, buf, dest, tag, **kw):
        if self._replay_send(comm, buf, dest, tag):
            return None
        return self._inner.send(comm, buf, dest, tag, **kw)

    def isend(self, comm, buf, dest, tag, **kw):
        from ompi_tpu.api.request import CompletedRequest

        if self._replay_send(comm, buf, dest, tag):
            return CompletedRequest()
        return self._inner.isend(comm, buf, dest, tag, **kw)

    # -- recv side -------------------------------------------------------
    def _replay_recv(self, comm, buf, source, tag):
        from ompi_tpu.api.status import ANY_SOURCE, ANY_TAG, Status

        if self._ri >= len(self._recvs):
            return None
        e = self._recvs[self._ri]
        src = int(e["src"])            # world rank
        grp = comm.remote_group if comm.is_inter else comm.group
        if source != ANY_SOURCE and int(grp.world_rank(source)) != src:
            raise ReplayDivergence(
                f"recv #{self._ri} diverged: logged src world {src}, "
                f"replayed explicit source {source}")
        if tag != ANY_TAG and int(e["tag"]) != int(tag):
            raise ReplayDivergence(
                f"recv #{self._ri} diverged: logged tag {e['tag']}, "
                f"replayed tag {tag}")
        if int(e["cid"]) != int(comm.cid):
            raise ReplayDivergence(
                f"recv #{self._ri} diverged: logged cid {e['cid']}, "
                f"replayed on cid {comm.cid}")
        self._ri += 1
        q = self._sends_from(src, e["cid"], e["tag"])
        if not q:
            raise ReplayDivergence(
                f"recv #{self._ri - 1}: rank {src}'s log has no remaining "
                f"send for me on channel (cid={e['cid']} tag={e['tag']}) "
                f"— was the job run with "
                f"otpu_vprotocol_pessimist_log_payloads=1?")
        se = q.pop(0)
        if "sc" in se and "sc" in e and int(se["sc"]) != int(e["sc"]):
            raise ReplayDivergence(
                f"recv #{self._ri - 1}: channel clock mismatch (sender "
                f"sc={se['sc']}, delivery sc={e['sc']}) — logs are from "
                f"different runs or corrupted")
        if "payload" not in se:
            raise ReplayDivergence(
                f"sender {src} logged no payloads; replay requires "
                "otpu_vprotocol_pessimist_log_payloads=1 job-wide")
        data = bytes.fromhex(se["payload"])
        from ompi_tpu.api.comm import as_buffer
        from ompi_tpu.datatype import Convertor

        arr, count, dt = as_buffer(buf)
        conv = Convertor(dt, count, arr)
        n = conv.unpack(data[:conv.packed_size])
        return Status(source=int(grp.rank_of(src)), tag=int(e["tag"]),
                      _nbytes=n)

    def recv(self, comm, buf, source, tag):
        st = self._replay_recv(comm, buf, source, tag)
        if st is not None:
            return st
        return self._inner.recv(comm, buf, source, tag)

    def irecv(self, comm, buf, source, tag):
        from ompi_tpu.api.request import CompletedRequest

        st = self._replay_recv(comm, buf, source, tag)
        if st is not None:
            return CompletedRequest(st)
        return self._inner.irecv(comm, buf, source, tag)


def maybe_wrap_pml(pml_module, rte):
    if replay_enabled() and getattr(rte, "client", None) is not None:
        # replay takes precedence; live ops after log exhaustion are not
        # re-logged (appending to the consumed log would corrupt it)
        return ReplayPml(pml_module, rte)
    if enabled() and getattr(rte, "client", None) is not None:
        return PessimistPml(pml_module, rte)
    return pml_module


def read_log(directory: str, rank: int) -> list:
    """Parse one rank's event log (the replay driver's input)."""
    path = os.path.join(directory, f"events.{rank}.log")
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]
