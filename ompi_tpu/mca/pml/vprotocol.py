"""pml/v + vprotocol/pessimist — message-event logging for replay FT.

Re-design of ``/root/reference/ompi/mca/pml/v`` (the interposition shell)
and ``ompi/mca/vprotocol/pessimist`` (3,218 LoC): pessimistic message
logging records, to stable storage, every nondeterministic event a rank
observes — most importantly the DELIVERY ORDER of receives (any-source
matches are where replay diverges) — plus send envelopes, so a restarted
rank can be re-driven to its pre-failure state by replaying the log
against re-sent messages.

Enable with ``otpu_vprotocol_pessimist_log=<dir>``: each rank appends
JSONL events to ``<dir>/events.<world_rank>.log``.  Payload hashes make
the log auditable without storing data; ``log_payloads`` stores the bytes
too (full sender-based logging).

The interposition mirrors pml/monitoring: the selected pml module is
wrapped at init, transparently for every caller.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Optional

import numpy as np

from ompi_tpu.base.var import VarType, registry

_log_var = registry.register(
    "vprotocol", "pessimist", "log", vtype=VarType.STRING, default="",
    help="Directory for pessimistic message-event logs (empty = disabled)")
_payload_var = registry.register(
    "vprotocol", "pessimist", "log_payloads", vtype=VarType.BOOL,
    default=False,
    help="Store full payload bytes (sender-based logging), not just hashes")


def enabled() -> bool:
    return bool((_log_var.value or "").strip())


class PessimistPml:
    """Interposition pml recording send envelopes + delivery order."""

    def __init__(self, inner, rte) -> None:
        self._inner = inner
        self._dir = (_log_var.value or "").strip()
        os.makedirs(self._dir, exist_ok=True)
        self._path = os.path.join(self._dir,
                                  f"events.{rte.my_world_rank}.log")
        self._fh = open(self._path, "a", buffering=1)
        self._lock = threading.Lock()
        self._seq = 0
        self._payloads = bool(_payload_var.value)

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def _event(self, kind: str, **fields) -> None:
        with self._lock:
            self._seq += 1
            fields.update(kind=kind, ev=self._seq)
            self._fh.write(json.dumps(fields) + "\n")

    def _digest(self, buf) -> str:
        try:
            return hashlib.sha1(np.ascontiguousarray(buf)
                                .view(np.uint8)).hexdigest()[:16]
        except Exception:
            return "?"

    # -- send side: envelope (+ payload when sender-based logging) -------
    def _log_send(self, comm, buf, dest, tag) -> None:
        arr = np.asarray(buf)
        rec = dict(cid=comm.cid, dst=int(dest), tag=int(tag),
                   nbytes=int(arr.nbytes), sha=self._digest(arr))
        if self._payloads:
            rec["payload"] = np.ascontiguousarray(arr).view(np.uint8) \
                .tobytes().hex()
        self._event("send", **rec)

    def send(self, comm, buf, dest, tag, **kw):
        self._log_send(comm, buf, dest, tag)
        return self._inner.send(comm, buf, dest, tag, **kw)

    def isend(self, comm, buf, dest, tag, **kw):
        self._log_send(comm, buf, dest, tag)
        return self._inner.isend(comm, buf, dest, tag, **kw)

    # -- recv side: the nondeterministic event is the MATCH --------------
    def _log_match(self, comm, req) -> None:
        st = req.status
        self._event("recv", cid=comm.cid, src=int(st.source),
                    tag=int(st.tag))

    def recv(self, comm, buf, source, tag):
        st = self._inner.recv(comm, buf, source, tag)
        self._event("recv", cid=comm.cid, src=int(st.source),
                    tag=int(st.tag))
        return st

    def irecv(self, comm, buf, source, tag):
        req = self._inner.irecv(comm, buf, source, tag)
        req.on_complete(lambda r: self._log_match(comm, r))
        return req

    def finalize(self):
        try:
            self._fh.close()
        except Exception:
            pass
        return self._inner.finalize()


def maybe_wrap_pml(pml_module, rte):
    if enabled() and getattr(rte, "client", None) is not None:
        return PessimistPml(pml_module, rte)
    return pml_module


def read_log(directory: str, rank: int) -> list:
    """Parse one rank's event log (the replay driver's input)."""
    path = os.path.join(directory, f"events.{rank}.log")
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]
