"""pml/template — the teaching skeleton for new messaging engines.

Re-design of ``/root/reference/ompi/mca/pml/example/`` (the commented
stub pml that documents the pml contract without ever being selected):
a minimal but RUNNABLE pml showing exactly what a messaging layer must
provide — the five-method surface ``ompi_mpi_init`` drives
(``add_comm``/``del_comm``/``isend``/``irecv``/``finalize``) plus the
matching rule (communicator, source, tag, arrival order) — so a new
engine (e.g. a matching-offload path or a device-initiated pml) starts
from a working example instead of ob1's full protocol machinery.

What ob1 adds beyond this skeleton, in the order a real engine usually
grows them: eager vs rendezvous protocol selection from btl limits,
unexpected + out-of-order queues keyed by (cid, src) sequence numbers,
RGET receiver-pull for large transfers, probe/mprobe, cancel, multi-
rail striping, PERUSE events.  See ``ob1.py`` for each.

Disabled by default (priority -1, like the reference's example which
is never built into selection); ``--mca pml_template_enable 1`` turns
it into a working single-process loopback pml so framework-level tests
can drive the selection path end-to-end.
"""
from __future__ import annotations

import threading
from collections import deque

from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType


class _Status:
    __slots__ = ("source", "tag", "count", "cancelled")

    def __init__(self, source: int, tag: int, count: int) -> None:
        self.source = source
        self.tag = tag
        self.count = count
        self.cancelled = False

    MPI_SOURCE = property(lambda s: s.source)
    MPI_TAG = property(lambda s: s.tag)


class _ImmediateRequest:
    """The smallest request object the api layer accepts: test/wait.

    A real pml returns requests that complete from the progress engine;
    the loopback completes everything eagerly, which is exactly the
    simplification a skeleton may make (the reference example pml stubs
    its requests the same way)."""

    def __init__(self, status=None):
        self.status = status
        self.complete = True

    def test(self):
        return True, self.status

    def wait(self):
        return self.status

    def cancel(self) -> bool:
        return False

    def free(self) -> None:
        pass


class TemplatePml:
    """1. lifecycle: the runtime calls ``add_comm`` for every new
    communicator and ``finalize`` at teardown.  State here is one
    matching queue per cid — the minimum that honors MPI ordering."""

    def __init__(self, component: "TemplateComponent", rte) -> None:
        self.component = component
        self.rte = rte
        self._lock = threading.Lock()
        self._queues: dict[int, deque] = {}   # cid -> pending frags

    def add_comm(self, comm) -> None:
        with self._lock:
            self._queues.setdefault(comm.cid, deque())

    def del_comm(self, comm) -> None:
        with self._lock:
            self._queues.pop(comm.cid, None)

    def finalize(self) -> None:
        with self._lock:
            self._queues.clear()

    # 2. sending: a real pml resolves the peer through bml/btl and
    #    picks eager/rndv/RGET from the size; the loopback only ever
    #    reaches self-rank, so "the wire" is the local queue.
    def isend(self, comm, buf, dest: int, tag: int, mode: str = "standard"):
        if dest != comm.rank:
            raise RuntimeError(
                "pml/template is a loopback skeleton: it reaches only "
                "the local rank (enable pml/ob1 for real transport)")
        import numpy as np

        payload = np.array(buf, copy=True)
        with self._lock:
            self._queues[comm.cid].append((comm.rank, tag, payload))
        return _ImmediateRequest()

    def send(self, comm, buf, dest: int, tag: int) -> None:
        # MPI_Send IS isend + wait — the skeleton must model the
        # completion contract too, or a pml grown from it returns
        # before the data is safe and drops the request's error
        # (otpu-verify mpi-typestate: discarded-request finding)
        self.isend(comm, buf, dest, tag).wait()

    # 3. receiving + THE MATCHING RULE: first queued frag whose
    #    (source, tag) matches, wildcards allowed, arrival order
    #    breaking ties — the invariant every pml must keep
    #    (``pml.h:498`` recv semantics; ob1 spreads it over three
    #    queues, the skeleton over one).
    def irecv(self, comm, buf, source: int, tag: int):
        status = self.recv(comm, buf, source, tag)
        return _ImmediateRequest(status)

    def recv(self, comm, buf, source: int, tag: int):
        import numpy as np

        with self._lock:
            q = self._queues[comm.cid]
            for i, (src, t, payload) in enumerate(q):
                if source not in (-1, src):   # -1 = ANY_SOURCE
                    continue
                if tag not in (-1, t):        # -1 = ANY_TAG
                    continue
                del q[i]
                out = np.asarray(buf)
                flat = out.reshape(-1)
                flat[:payload.size] = payload.reshape(-1)[:flat.size]
                return _Status(src, t, payload.size)
        raise RuntimeError(
            "pml/template loopback has no matching frag queued "
            "(eager completion means sends must precede receives)")


class TemplateComponent(Component):
    name = "template"
    priority = -1      # never beats ob1; selection requires opt-in

    def register_vars(self, fw) -> None:
        self.register_var("priority", vtype=VarType.INT, default=-1,
                          help="Selection priority of pml/template "
                               "(negative: never auto-selected)")
        self._enable = self.register_var(
            "enable", vtype=VarType.BOOL, default=False,
            help="Enable the template pml (loopback; teaching/testing)")

    def open(self) -> bool:
        return bool(self._enable.value)

    def get_module(self, rte) -> TemplatePml:
        return TemplatePml(self, rte)


COMPONENT = TemplateComponent()
