"""pml — point-to-point messaging layer framework
(``/root/reference/ompi/mca/pml/pml.h:108,498``).  Components: ``ob1`` (the
default matching/protocol engine over BTLs), ``monitoring`` (interposition),
``v`` (message-logging FT interposition).
"""
