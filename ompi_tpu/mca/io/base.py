"""io/base — per-file component selection (``mca/io/base/io_base_file_select.c``)."""
from __future__ import annotations

from ompi_tpu.base import mca


def io_framework() -> mca.Framework:
    return mca.framework("io", "MPI-IO operations", multi_select=True)


def file_select(file) -> None:
    """Pick the highest-priority io module for this file."""
    fw = io_framework()
    best = None
    for comp in fw.select_all():
        query = getattr(comp, "file_query", None)
        if query is None:
            continue
        res = query(file)
        if res is None:
            continue
        priority, module = res
        if priority < 0:
            continue
        if best is None or priority > best[0]:
            best = (priority, module)
    if best is None:
        from ompi_tpu.api.errors import ErrorClass, MpiError

        raise MpiError(ErrorClass.ERR_IO,
                       f"no io component available for {file.filename!r}")
    file.io_module = best[1]
