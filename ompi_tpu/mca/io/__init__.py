"""io — MPI-IO framework (``/root/reference/ompi/mca/io/``).

Components compete per-file the way coll components compete per-comm:
``file_query(file)`` returns ``(priority, module)``; the highest priority
wins and its module serves every I/O operation on that file.  The single
built-in component is ``ompio`` — a re-design of the reference's native
MPI-IO stack (io/ompio + fs + fbtl + fcoll + sharedfp sub-frameworks).
"""
