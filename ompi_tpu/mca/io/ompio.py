"""io/ompio ★ — the native MPI-IO engine.

Re-design of ``/root/reference/ompi/mca/io/ompio/io_ompio.c:1-565`` and its
sub-frameworks, collapsed into three layers:

- **fs** (``ompi/mca/fs/``): file-system ops — open/close/delete/resize via
  the POSIX fd API (the fs/ufs component's role).
- **fbtl** (``ompi/mca/fbtl/posix``): individual strided read/write — the
  file view (disp, etype, filetype) is walked through the datatype engine's
  segment map and each elementary run becomes one ``pread``/``pwrite``.
- **fcoll** (``ompi/mca/fcoll/``): collective two-phase buffering —
  ranks exchange their access extents, the file domain is partitioned
  among aggregator ranks (one per node by default, the ``common/ompio``
  aggregator-selection role), data moves rank→aggregator over pml p2p,
  and each aggregator issues one large sequential I/O per domain
  (read-modify-write when a write domain has holes).  TWO partitioning
  strategies, selected per access pattern like the reference's four
  fcoll components:

  * **static** (``fcoll/vulcan``): even ADDRESS-span stripes — right
    when the job writes a dense region;
  * **dynamic** (``fcoll/dynamic_gen2``): the union of every rank's
    accessed extents is negotiated at runtime and split into
    equal-ACCESSED-BYTE shares, so ragged/clustered patterns (dense
    islands separated by huge holes) still balance real I/O across
    aggregators instead of handing one aggregator all the bytes.

  ``auto`` picks dynamic when the accessed-byte density of the spanned
  region is low (ragged), static when dense; force with the
  ``io_ompio_fcoll`` var.

Shared file pointers (``ompi/mca/sharedfp/``) ride the coordination
service's atomic ``fetch_add`` counter — the TPU-native replacement for the
reference's sm-segment / locked-file implementations.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType
from ompi_tpu.mca.coll.basic import coll_tag
from ompi_tpu.runtime import trace


def _traced_io(name: str, nbytes_of=len):
    """Decorator: run one fbtl/fcoll I/O entry point under an ``io``
    trace span.  ``nbytes_of`` sizes the payload from the last
    positional arg (``len`` for data buffers, ``int`` for byte counts);
    the disabled path is the usual single flag check."""
    def deco(fn):
        def wrapper(self, file, offset, x):
            if not trace.enabled:
                return fn(self, file, offset, x)
            t0 = trace.now()
            try:
                return fn(self, file, offset, x)
            finally:
                trace.span(name, "io", t0,
                           args={"nbytes": int(nbytes_of(x))})
        wrapper.__name__ = fn.__name__.lstrip("_")
        wrapper.__doc__ = fn.__doc__
        wrapper.__wrapped__ = fn
        return wrapper
    return deco


def view_extents(disp: int, filetype, start_byte: int, nbytes: int):
    """Yield ``(file_offset, length)`` runs for the view's data-stream
    range ``[start_byte, start_byte + nbytes)``.

    The filetype's elementary segments (type-map order) are the data
    stream of one *tile*; tiles repeat every ``filetype.extent`` bytes
    starting at ``disp`` (MPI-IO file view semantics).
    """
    segs = filetype.segments
    tile = filetype.size
    if tile == 0 or nbytes <= 0:
        return
    if filetype.is_contiguous:
        # the data stream IS the file stream (minus displacement)
        yield (disp + filetype.lb + start_byte, nbytes)
        return
    ext = filetype.extent
    t, within = divmod(start_byte, tile)
    base = disp + t * ext
    remaining = nbytes
    while remaining > 0:
        for s in segs:
            if within >= s.nbytes:
                within -= s.nbytes
                continue
            take = min(s.nbytes - within, remaining)
            yield (base + s.offset + within, take)
            remaining -= take
            within = 0
            if remaining == 0:
                return
        base += ext
        within = 0


def _coalesce_runs(runs):
    """Merge file-adjacent (offset, length) runs (fewer syscalls)."""
    out = []
    for off, ln in runs:
        if out and out[-1][0] + out[-1][1] == off:
            out[-1][1] += ln
        else:
            out.append([off, ln])
    return out


class OmpioModule:
    """Per-file module: every operation the File object dispatches."""

    def __init__(self, component: "OmpioComponent", file) -> None:
        self._c = component
        self._file = file

    # -- fs layer ---------------------------------------------------------
    def get_size(self, file) -> int:
        return os.fstat(file.fd).st_size

    def set_size(self, file, size: int) -> None:
        os.ftruncate(file.fd, size)

    def preallocate(self, file, size: int) -> None:
        if self.get_size(file) < size:
            os.ftruncate(file.fd, size)

    def sync(self, file) -> None:
        os.fsync(file.fd)

    # -- fbtl layer: individual I/O --------------------------------------
    @_traced_io("io_write_at")
    def write_at(self, file, offset: int, data: bytes) -> int:
        """offset in etype units relative to the view; returns bytes."""
        start = offset * file.etype.size
        pos = 0
        for off, ln in _coalesce_runs(
                view_extents(file.disp, file.filetype, start, len(data))):
            os.pwrite(file.fd, data[pos:pos + ln], off)
            pos += ln
        return pos

    @_traced_io("io_read_at", nbytes_of=int)
    def read_at(self, file, offset: int, nbytes: int) -> bytes:
        start = offset * file.etype.size
        chunks = []
        for off, ln in _coalesce_runs(
                view_extents(file.disp, file.filetype, start, nbytes)):
            got = os.pread(file.fd, ln, off)
            if len(got) < ln:       # short read past EOF: zero-fill
                got = got + b"\0" * (ln - len(got))
            chunks.append(got)
        return b"".join(chunks)

    # -- fcoll layer: two-phase collective I/O ---------------------------
    def _aggregators(self, comm) -> list[int]:
        """Aggregator ranks: one per node when locality is known, else
        ``num_aggregators`` evenly spaced (common/ompio's selection)."""
        forced = int(self._c.num_aggs_var.value)
        if forced > 0:
            n = min(forced, comm.size)
            return [i * comm.size // n for i in range(n)]
        nodes: dict = {}
        rte = comm.rte
        try:
            for r in range(comm.size):
                node = rte.modex_get(comm.world_rank(r), "node") \
                    if rte is not None and not rte.is_device_world else 0
                nodes.setdefault(node, r)
        except Exception:
            return [0]
        return sorted(nodes.values())

    def _my_extents(self, file, offset: int, nbytes: int):
        start = offset * file.etype.size
        return _coalesce_runs(
            view_extents(file.disp, file.filetype, start, nbytes))

    # -- fcoll file-domain partitioning ----------------------------------
    def _file_domains(self, comm, runs):
        """Negotiate the aggregator file domains for this collective op.

        Returns ``(aggs, edges)`` — ``edges`` has ``len(aggs)+1``
        ascending file offsets; aggregator i owns ``[edges[i],
        edges[i+1])`` — or ``None`` when no rank accesses anything.
        One allgatherv carries every rank's coalesced extents (the
        runtime negotiation of ``fcoll/dynamic_gen2``); the strategy is
        picked from the pattern's accessed-byte density unless forced.
        """
        alg = (self._c.fcoll_var.value or "auto").strip().lower()
        if alg not in ("auto", "static", "dynamic"):
            raise MpiError(ErrorClass.ERR_ARG,
                           f"io_ompio_fcoll={alg!r}: expected "
                           "'auto', 'static' or 'dynamic'")
        aggs = self._aggregators(comm)
        k = len(aggs)
        if alg == "static":
            # forced static needs only the global bounds: exchange two
            # ints per rank, not the full extent lists
            lo = runs[0][0] if runs else np.iinfo(np.int64).max
            hi = runs[-1][0] + runs[-1][1] if runs else -1
            bounds = np.asarray(comm.allgather(
                np.array([lo, hi], np.int64))).reshape(comm.size, 2)
            gmin = int(bounds[:, 0].min())
            gmax = int(bounds[:, 1].max())
            if gmax <= gmin:
                return None
            self.last_fcoll_alg = "static"
            stripe = -(-(gmax - gmin) // k)
            edges = [min(gmin + i * stripe, gmax) for i in range(k)]
            edges.append(gmax)
            return aggs, edges
        flat = np.array([v for r in runs for v in r], np.int64)
        gathered = comm.allgatherv(flat)
        intervals = []
        for arr in gathered:
            a = np.asarray(arr, np.int64).reshape(-1, 2)
            intervals.extend((int(o), int(o) + int(ln)) for o, ln in a)
        if not intervals:
            return None
        intervals.sort()
        merged = []                     # interval union across ranks
        for s, e in intervals:
            if merged and s <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], e)
            else:
                merged.append([s, e])
        gmin, gmax = merged[0][0], merged[-1][1]
        total = sum(e - s for s, e in merged)
        if alg == "auto":
            # dense region -> address stripes; ragged (the spanned
            # region is mostly holes) -> balance the actual bytes
            alg = "static" if total * 2 >= (gmax - gmin) else "dynamic"
        self.last_fcoll_alg = alg
        if alg == "static" or k == 1 or total == 0:
            stripe = -(-(gmax - gmin) // k)
            edges = [min(gmin + i * stripe, gmax) for i in range(k)]
        else:
            share = total / k
            edges, acc, nxt = [gmin], 0, 1
            for s, e in merged:
                while nxt < k and acc + (e - s) >= nxt * share:
                    edges.append(s + int(nxt * share - acc))
                    nxt += 1
                acc += e - s
            while len(edges) < k:       # fewer cut points than shares
                edges.append(gmax)
        edges.append(gmax)
        return aggs, edges

    @staticmethod
    def _route(edges, off: int, ln: int):
        """Split ``[off, off+ln)`` at the domain edges: yields
        ``(aggregator_index, piece_offset, piece_length)``."""
        import bisect

        pos, end = off, off + ln
        while pos < end:
            ai = min(max(bisect.bisect_right(edges, pos) - 1, 0),
                     len(edges) - 2)
            take = min(end, max(edges[ai + 1], pos + 1)) - pos
            yield ai, pos, take
            pos += take

    @_traced_io("io_write_at_all")
    def write_at_all(self, file, offset: int, data: bytes) -> int:
        comm = file.comm
        if comm is None or comm.size == 1:
            return self.write_at(file, offset, data)
        tag = coll_tag(comm)
        runs = self._my_extents(file, offset, len(data))
        # phase 0: negotiate the aggregator file domains
        domains = self._file_domains(comm, runs)
        if domains is None:
            return 0
        aggs, edges = domains
        # phase 1: route my pieces to the owning aggregators
        pieces_for: dict[int, list] = {a: [] for a in aggs}
        pos = 0
        for off, ln in runs:
            for ai, poff, take in self._route(edges, off, ln):
                rel = poff - off
                pieces_for[aggs[ai]].append(
                    (poff, data[pos + rel:pos + rel + take]))
            pos += ln
        reqs = []
        for a in aggs:
            if a != comm.rank:
                # nonblocking: two aggregators exchanging pieces must not
                # rendezvous-deadlock on each other's blocking sends
                reqs += comm.isend_obj(pieces_for[a], a, tag)
        # phase 2: aggregators assemble their stripe and write once
        if comm.rank in aggs:
            mine = list(pieces_for[comm.rank])
            for r in range(comm.size):
                if r != comm.rank:
                    mine.extend(comm.recv_obj(r, tag))
            self._rmw_write(file, mine)
        from ompi_tpu.api.request import waitall
        waitall(reqs)
        comm.barrier()      # writes visible before anyone proceeds
        # like write_at: the caller's own contribution, uniformly on all
        # ranks (not the aggregator's assembled-region span)
        return len(data)

    def _rmw_write(self, file, pieces) -> int:
        """One read-modify-write of the region covered by ``pieces``."""
        if not pieces:
            return 0
        pieces.sort(key=lambda p: p[0])
        lo = pieces[0][0]
        hi = max(off + len(b) for off, b in pieces)
        # holes between pieces keep their current file content
        existing = os.pread(file.fd, hi - lo, lo)
        buf = bytearray(existing.ljust(hi - lo, b"\0"))
        for off, b in pieces:
            buf[off - lo:off - lo + len(b)] = b
        os.pwrite(file.fd, bytes(buf), lo)
        return hi - lo

    @_traced_io("io_read_at_all", nbytes_of=int)
    def read_at_all(self, file, offset: int, nbytes: int) -> bytes:
        comm = file.comm
        if comm is None or comm.size == 1:
            return self.read_at(file, offset, nbytes)
        tag = coll_tag(comm)
        runs = self._my_extents(file, offset, nbytes)
        domains = self._file_domains(comm, runs)
        if domains is None:
            return b""
        aggs, edges = domains
        # phase 1: send my wanted runs to the owning aggregators
        want_from: dict[int, list] = {a: [] for a in aggs}
        for off, ln in runs:
            for ai, poff, take in self._route(edges, off, ln):
                want_from[aggs[ai]].append((poff, take))
        reqs = []
        for a in aggs:
            if a != comm.rank:
                reqs += comm.isend_obj(want_from[a], a, tag)
        # phase 2: aggregators read their stripe once and serve pieces
        replies: dict[int, list] = {}
        if comm.rank in aggs:
            wants = {comm.rank: want_from.get(comm.rank, [])}
            for r in range(comm.size):
                if r != comm.rank:
                    wants[r] = comm.recv_obj(r, tag)
            all_runs = [w for lst in wants.values() for w in lst]
            if all_runs:
                rlo = min(o for o, _ in all_runs)
                rhi = max(o + n for o, n in all_runs)
                region = os.pread(file.fd, rhi - rlo, rlo)
                region = region.ljust(rhi - rlo, b"\0")
                for r, lst in wants.items():
                    pieces = [(o, region[o - rlo:o - rlo + n])
                              for o, n in lst]
                    if r == comm.rank:
                        replies[comm.rank] = pieces
                    else:
                        reqs += comm.isend_obj(pieces, r, tag)
            else:
                for r in wants:
                    if r != comm.rank:
                        reqs += comm.isend_obj([], r, tag)
        # phase 3: collect my pieces (from every aggregator I asked)
        got: dict[int, bytes] = {}
        for a in aggs:
            pieces = replies.get(a, None) if a == comm.rank \
                else comm.recv_obj(a, tag)
            for off, b in pieces or []:
                got[off] = b
        from ompi_tpu.api.request import waitall
        waitall(reqs)
        out = bytearray()
        for off, ln in runs:
            taken = 0
            while taken < ln:
                b = got.get(off + taken)
                if b is None:
                    raise MpiError(ErrorClass.ERR_IO,
                                   "collective read assembly hole")
                out += b
                taken += len(b)
        return bytes(out)


class OmpioComponent(Component):
    name = "ompio"
    priority = 30

    def register_vars(self, fw) -> None:
        self._prio = self.register_var(
            "priority", vtype=VarType.INT, default=30,
            help="Selection priority of io/ompio")
        self.num_aggs_var = self.register_var(
            "num_aggregators", vtype=VarType.INT, default=0,
            help="Aggregator count for two-phase collective I/O "
                 "(0 = one per node)")
        self.fcoll_var = self.register_var(
            "fcoll", vtype=VarType.STRING, default="auto",
            help="Collective-buffering file-domain strategy: 'static' "
                 "(even address stripes, fcoll/vulcan), 'dynamic' "
                 "(equal accessed-byte shares negotiated from the "
                 "ranks' extents, fcoll/dynamic_gen2), 'auto' (dynamic "
                 "when the spanned region is mostly holes)")

    def file_query(self, file):
        return self._prio.value, OmpioModule(self, file)


COMPONENT = OmpioComponent()
