"""op/pallas_vpu — Pallas VPU reduction kernels (the op/avx analog).

Reference: ``ompi/mca/op/avx/op_avx_component.c`` registers with a high
priority and per-type flag checks against the host CPU's capabilities;
here the capability check is the jax backend (TPU: compiled Mosaic
kernels; elsewhere the kernels still work via the Pallas interpreter but
plain XLA is just as good, so priority drops below op/xla off-TPU).
"""
from __future__ import annotations

import jax

from ompi_tpu.base import mca
from ompi_tpu.ops import pallas_reduce


class PallasVpuComponent(mca.Component):
    name = "pallas_vpu"
    priority = 50

    def register_vars(self, fw) -> None:
        self._prio_var = self.register_var(
            "priority", vtype=mca.VarType.INT, default=50,
            help="Selection priority of the Pallas VPU reduction kernels")

    def open(self) -> bool:
        self.priority = int(self._prio_var.value)
        if jax.default_backend() != "tpu":
            # interpreter mode works but wins nothing; defer to op/xla
            self.priority = min(self.priority, 5)
        return True

    def close(self) -> None:
        from ompi_tpu.mca.op import base as op_base

        op_base.reset_cache()

    def query_fold(self, op_name: str, dtype, fusable: bool = False):
        if fusable:
            return None  # pallas_call is opaque to XLA fusion
        return pallas_reduce.device_fold(op_name, dtype)

    def query_stack(self, op_name: str, dtype):
        if pallas_reduce.device_fold(op_name, dtype) is None:
            return None
        import functools

        return functools.partial(pallas_reduce.reduce_stack, op_name)


COMPONENT = PallasVpuComponent()
