"""op framework base: per-(op, dtype) kernel selection.

Mirrors ``ompi/mca/op/base/op_base_op_select.c``: every available
component is queried for a fold covering the (op, dtype) pair; the
highest-priority non-None answer wins and is cached (the reference caches
by filling the op's function table once).  Selection honours the usual
``otpu_op`` include/exclude var, so ``--mca op ^pallas_vpu`` forces the
plain-XLA path exactly like ``--mca op ^avx`` in the reference.
"""
from __future__ import annotations

import threading
from typing import Callable, Optional

from ompi_tpu.base import mca

_lock = threading.Lock()
_cache: dict = {}


def _framework() -> mca.Framework:
    fw = mca.framework("op", "reduction kernel components", multi_select=True)
    if not fw.opened:
        fw.open()
    return fw


def select_fold(op_name: str, dtype,
                fusable: bool = False) -> Optional[Callable]:
    """Highest-priority device fold for (op, dtype), or None.

    ``fusable=True`` asks for a fold XLA can fuse into surrounding
    computation (scans, fori bodies) — opaque-kernel components decline.
    """
    key = ("fold", op_name, str(dtype), fusable)
    with _lock:
        if key in _cache:
            return _cache[key]
    fw = _framework()
    best = None
    for comp in sorted(fw.available, key=lambda c: -c.priority):
        fold = comp.query_fold(op_name, dtype, fusable=fusable)
        if fold is not None:
            best = fold
            break
    with _lock:
        _cache[key] = best
    return best


def select_stack(op_name: str, dtype) -> Optional[Callable]:
    """Fused (k, ...)-stack axis-0 reduction for (op, dtype), or None."""
    key = ("stack", op_name, str(dtype))
    with _lock:
        if key in _cache:
            return _cache[key]
    fw = _framework()
    best = None
    for comp in sorted(fw.available, key=lambda c: -c.priority):
        q = getattr(comp, "query_stack", None)
        red = q(op_name, dtype) if q else None
        if red is not None:
            best = red
            break
    with _lock:
        _cache[key] = best
    return best


def reset_cache() -> None:
    with _lock:
        _cache.clear()
