"""MCA ``op`` framework — device reduction-kernel components.

Reference: ``ompi/mca/op/`` — the framework whose components (base C
loops, ``op/avx`` SIMD) compete to fill each ``ompi_op_t``'s per-type
function table at init (``ompi/mca/op/base/op_base_op_select.c``).  Here
components compete to provide the jax-traceable two-operand fold used by
coll/xla's device reductions (tree folds, scan/exscan) for each
(op, dtype).
"""
