"""op/xla — plain-XLA (jnp) reduction folds, the always-available base.

Reference analog: the base C loops every op falls back to when no SIMD
component covers the (op, type) pair (``ompi/mca/op/base``).  XLA fuses
these into surrounding computations, so off-TPU this is also the fastest
choice.
"""
from __future__ import annotations

from ompi_tpu.base import mca


class XlaOpComponent(mca.Component):
    name = "xla"
    priority = 10

    def close(self) -> None:
        from ompi_tpu.mca.op import base as op_base

        op_base.reset_cache()

    def query_fold(self, op_name: str, dtype, fusable: bool = False):
        import jax.numpy as jnp

        table = {
            "SUM": jnp.add,
            "PROD": jnp.multiply,
            "MAX": jnp.maximum,
            "MIN": jnp.minimum,
            "LAND": lambda a, b: (a.astype(bool) & b.astype(bool)
                                  ).astype(a.dtype),
            "LOR": lambda a, b: (a.astype(bool) | b.astype(bool)
                                 ).astype(a.dtype),
            "LXOR": lambda a, b: (a.astype(bool) ^ b.astype(bool)
                                  ).astype(a.dtype),
            "BAND": jnp.bitwise_and,
            "BOR": jnp.bitwise_or,
            "BXOR": jnp.bitwise_xor,
        }
        return table.get(op_name)


COMPONENT = XlaOpComponent()
