"""btl/template — the teaching skeleton for new transports.

Re-design of ``/root/reference/opal/mca/btl/template/`` (1,320 LoC of
commented stubs): a minimal but RUNNABLE btl showing exactly what a
transport must provide — reachability, eager/max limits, ordered frag
delivery, progress-driven receive — so a new DCN transport (RDMA verbs,
gRPC, cloud object relay …) starts from a working example instead of
btl/tcp's full machinery.

Disabled by default (priority -1, like the reference's template which is
never selected); ``--mca btl_template_enable 1`` turns it into a working
intra-process loopback so framework-level tests can exercise bml/pml
against a third transport.
"""
from __future__ import annotations

from ompi_tpu.base.containers import Fifo
from ompi_tpu.base.var import VarType
from ompi_tpu.mca.btl.base import Btl, Endpoint, Frag


class TemplateBtl(Btl):
    # 1. identity + selection: bml orders by latency/bandwidth; negative
    #    priority keeps the template out of real jobs
    name = "template"
    priority = -1
    latency = 1000
    bandwidth = 1

    # 2. protocol limits: pml picks eager vs rendezvous from these
    eager_limit = 4 * 1024
    rndv_eager_limit = 4 * 1024
    max_send_size = 16 * 1024

    def __init__(self) -> None:
        super().__init__()
        self._rte = None
        self._inbox: Fifo = Fifo()

    def register_vars(self, fw) -> None:
        self._enable = self.register_var(
            "enable", vtype=VarType.BOOL, default=False,
            help="Enable the template btl (loopback; testing only)")

    # 3. lifecycle: open() gates availability, setup() binds the RTE,
    #    close() releases resources
    def open(self) -> bool:
        return bool(self._enable.value)

    def setup(self, rte) -> bool:
        self._rte = rte
        return True

    def close(self) -> None:
        self._inbox = Fifo()

    # 4. wiring: which peers can this transport reach?  (A real transport
    #    checks the peer's modexed address; loopback reaches only self-
    #    rank messages the pml would otherwise give btl/self.)
    def reachable(self, world_rank: int, rte):
        if world_rank != rte.my_world_rank:
            return None
        return Endpoint(self, world_rank)

    # 5. send path: enqueue bytes toward the peer.  A real transport
    #    writes a NIC ring / socket here; ordering per (src, dst) is the
    #    btl contract (pml's seq matching relies on it).
    def send(self, ep: Endpoint, frag: Frag) -> None:
        self._inbox.push(frag)

    # 6. progress: drain receives and hand frags to the pml callback.
    #    Called from the global progress engine; must never block.
    def progress(self) -> int:
        made = 0
        while True:
            frag = self._inbox.pop()
            if frag is None:
                break
            self._recv_cb(frag)
            made += 1
        return made


COMPONENT = TemplateBtl()
