"""btl — Byte Transfer Layer framework (``/root/reference/opal/mca/btl/``).

The lowest-level transport abstraction: active-message send, RDMA put/get,
remote atomics (``btl.h:878,949,987,1029``), with eager/rendezvous/max-send
size limits (``btl.h:1162-1180``).  Components: ``self`` (in-process
loopback — which in the device-world SPMD model reaches *every* rank),
``sm`` (shared memory), ``tcp`` (DCN analog).
"""
