"""btl/self — in-process loopback transport.

Equivalent of ``/root/reference/opal/mca/btl/self/`` (684 LoC), widened for
the device-world SPMD model: every rank living in this process (all of them,
in device-world mode; just my own rank in multi-process mode) is
self-reachable, so a single-process N-rank world runs the full pml matching
path the way ``mpirun -n N --oversubscribe`` exercises btl/self+sm on one
node (SURVEY.md §4).
"""
from __future__ import annotations

from typing import Optional

from ompi_tpu.base.containers import Fifo
from ompi_tpu.mca.btl.base import Btl, Endpoint, Frag


class SelfBtl(Btl):
    name = "self"
    priority = 80
    eager_limit = 1 << 62      # in-process: everything is eager
    rndv_eager_limit = 1 << 62
    max_send_size = 1 << 62
    latency = 0                # best possible — bml orders by latency
    bandwidth = 1 << 30

    def __init__(self) -> None:
        super().__init__()
        self._pending = Fifo()

    def register_vars(self, fw) -> None:
        from ompi_tpu.base.var import VarType

        self._eager_var = self.register_var(
            "eager_limit", vtype=VarType.SIZE, default=self.eager_limit,
            help="Maximum eager message size for btl/self")

    def reachable(self, world_rank: int, rte) -> Optional[Endpoint]:
        if rte.is_device_world or world_rank == rte.my_world_rank:
            return Endpoint(self, world_rank)
        return None

    def send(self, ep: Endpoint, frag: Frag) -> None:
        # queue + drain from progress: preserves the asynchronous contract
        # (a blocking recv posted later must still match), while keeping
        # same-call-stack latency low via immediate drain when possible
        self._pending.push(frag)
        self.progress()

    def progress(self) -> int:
        n = 0
        while True:
            frag = self._pending.pop()
            if frag is None:
                break
            if self._recv_cb is not None:
                self._recv_cb(frag)
                n += 1
        return n


COMPONENT = SelfBtl()
