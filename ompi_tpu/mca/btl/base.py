"""BTL interface: fragments, endpoints, module contract.

Mirrors the module struct of ``/root/reference/opal/mca/btl/btl.h:1158`` —
``btl_send``/``btl_sendi`` active messages, ``btl_put``/``btl_get`` RMA,
``btl_register_mem`` — with the descriptor machinery collapsed to a
:class:`Frag` dataclass (Python owns the memory; the native core provides
zero-copy paths for sm).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ompi_tpu.base.mca import Component


def owned_bytes(payload) -> bytes:
    """Owned bytes of any bytes-like payload (ndarray views included) —
    the buffered-descriptor side of the send-in-place vs copy split."""
    import numpy as np

    return payload.tobytes() if isinstance(payload, np.ndarray) \
        else bytes(payload)

# fragment kinds (pml protocol headers ride in ``kind`` + ``meta``)
MATCH = "match"          # eager: full payload, match on arrival
RNDV = "rndv"            # rendezvous first fragment: header + head of data
ACK = "ack"              # receiver matched an rndv: pull the rest
FRAG = "frag"            # rndv continuation fragment
RGET = "rget"            # RDMA-get protocol: sender exposes, receiver pulls
CTL = "ctl"              # control (FT heartbeats, monitoring, osc)


@dataclass
class Frag:
    """One wire fragment. ``data`` is bytes-like; ``meta`` is a small dict
    that must stay picklable (it crosses process boundaries on tcp/sm).

    ``borrowed`` marks ``data`` as a zero-copy view of the SENDER's user
    buffer: valid only within the btl.send call (the wire/ring write is
    the copy).  Anything that outlives the call — queueing, in-process
    loopback delivery — must take ownership first (``own_data``)."""

    cid: int
    src: int              # world rank of sender
    dst: int              # world rank of receiver
    tag: int
    seq: int
    kind: str = MATCH
    data: bytes = b""
    total_len: int = 0    # full message length (rndv)
    offset: int = 0       # stream offset of this fragment (FRAG)
    meta: dict = field(default_factory=dict)
    borrowed: bool = False
    #: coll/quant wire codec this payload may travel under (stamped by
    #: the pml, which still knows the dtype; the btl's codec stage
    #: encodes eligible frames and the receive parse decodes them back
    #: to the ORIGINAL bytes, so total_len/offset stay in raw-stream
    #: units).  None = raw bytes; transports without a codec stage
    #: (sm rings, in-process loopback) ignore it.
    qcodec: "Optional[str]" = None

    def own_data(self) -> None:
        """Replace a borrowed view with an owned copy (idempotent)."""
        if self.borrowed:
            import numpy as np

            self.data = np.array(self.data, copy=True)
            self.borrowed = False


@dataclass
class Endpoint:
    """Per-peer connection state for one BTL."""

    btl: "Btl"
    world_rank: int
    addr: Any = None


class Btl(Component):
    """Base BTL module/component (collapsed, like coll components)."""

    # perf limits (btl.h:1162-1180); subclasses override
    eager_limit: int = 64 * 1024
    rndv_eager_limit: int = 64 * 1024
    max_send_size: int = 128 * 1024
    latency: int = 100        # ordering key for bml (btl.h btl_latency)
    bandwidth: int = 100

    def __init__(self) -> None:
        super().__init__()
        self._recv_cb: Optional[Callable[[Frag], None]] = None

    def set_recv_callback(self, cb: Callable[[Frag], None]) -> None:
        """The pml registers its frag-delivery callback here."""
        self._recv_cb = cb

    def reachable(self, world_rank: int, rte) -> Optional[Endpoint]:
        """Return an endpoint if this BTL can reach the peer, else None."""
        return None

    #: True when this BTL implements the one-sided prepare_src/get/put
    #: RMA triple (``btl.h:949`` btl_put / ``:987`` btl_get); pml/ob1's
    #: RGET protocol engages only on rdma-capable transports and falls
    #: back to pull-streaming emulation elsewhere
    rdma = False

    def send(self, ep: Endpoint, frag: Frag) -> None:
        raise NotImplementedError

    def prepare_src(self, ep: Endpoint, arr) -> Any:
        """Expose a contiguous byte region for one-sided peer access;
        returns a picklable remote key (``btl_register_mem`` +
        descriptor prepare, ``btl.h:1095``)."""
        raise NotImplementedError("this BTL has no RDMA registration")

    def release_src(self, key: Any) -> None:
        """Tear down a prepare_src exposure (deregistration)."""

    def put(self, ep: Endpoint, local, remote_key: Any) -> None:
        """Write ``local`` bytes into the peer region (btl.h:949)."""
        raise NotImplementedError("this BTL has no RDMA put")

    def get(self, ep: Endpoint, local, remote_key: Any) -> None:
        """Read the peer region into ``local`` bytes (btl.h:987)."""
        raise NotImplementedError("this BTL has no RDMA get")

    def progress(self) -> int:
        return 0
