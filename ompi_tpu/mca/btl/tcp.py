"""btl/tcp — socket transport, the DCN analog.

Re-design of ``/root/reference/opal/mca/btl/tcp/`` (5,117 LoC): a listening
socket per process whose address is published through the modex
(``btl_tcp_addr``), lazy connects on first send with a rank handshake,
length-prefixed pickled fragments, and nonblocking IO drained from the
central progress engine (the reference polls through libevent from
``opal_progress``).  Eager/rendezvous thresholds are MCA vars like the
reference's ``btl_tcp_eager_limit`` family (``btl.h:1162-1165``).
"""
from __future__ import annotations

import errno
import pickle
import selectors
import socket
import struct
import threading
import time
from typing import Optional

from ompi_tpu.base.var import VarType
from ompi_tpu.mca.btl.base import Btl, Endpoint, Frag

_LEN = struct.Struct("!I")


class _Conn:
    def __init__(self, sock: socket.socket, rank: Optional[int] = None):
        self.sock = sock
        self.rank = rank
        self.inbuf = bytearray()
        self.outbuf = bytearray()
        # serialises outbuf append+flush: app threads, the progress
        # engine, and the FT detector all send on the same conn, and two
        # concurrent sock.send calls over one outbuf would duplicate the
        # leading bytes and desynchronise the peer's framing
        self.send_lock = threading.Lock()


class TcpBtl(Btl):
    name = "tcp"
    priority = 10
    eager_limit = 64 * 1024
    rndv_eager_limit = 64 * 1024
    max_send_size = 128 * 1024
    latency = 100
    bandwidth = 100

    def __init__(self) -> None:
        super().__init__()
        self._rte = None
        self._listener: Optional[socket.socket] = None
        self._sel = selectors.DefaultSelector()
        # multi-link (btl_tcp_links): several connections per peer, frames
        # round-robined across them — the reference's per-link striping
        self._by_rank: dict[int, list[_Conn]] = {}
        self._rr: dict[int, int] = {}
        self._links = 1
        self._addr_cache: dict[int, tuple] = {}
        self._locks_guard = threading.Lock()
        self._connect_locks: dict[int, threading.Lock] = {}  # per peer
        self._connect_backoff: dict[int, float] = {}   # rank -> retry-after

    def register_vars(self, fw) -> None:
        self.register_var(
            "eager_limit", vtype=VarType.SIZE, default="64k",
            help="Max eager message size over tcp",
            on_set=lambda v: setattr(self, "eager_limit", v))
        self.register_var(
            "max_send_size", vtype=VarType.SIZE, default="128k",
            help="Max fragment size for rendezvous streaming over tcp",
            on_set=lambda v: setattr(self, "max_send_size", v))
        self.register_var(
            "links", vtype=VarType.INT, default=1,
            help="TCP connections per peer; frames stripe round-robin "
                 "across them (btl_tcp_links)",
            on_set=lambda v: setattr(self, "_links", max(1, int(v))))

    # -- lifecycle -------------------------------------------------------
    def setup(self, rte) -> bool:
        """Listen + publish our address (pre-fence).

        Runs even in a 1-rank job: under dpm a singleton spawned job has
        no same-job peers but MUST be reachable from its parent job, and
        tcp is the universal transport that guarantees it.
        """
        if rte.is_device_world:
            return False
        if not hasattr(rte, "modex_put"):
            return False
        if getattr(rte, "client", None) is None:
            return False   # no coord service (singleton): nobody can dial in
        self._rte = rte
        self._listener = socket.create_server(("127.0.0.1", 0), backlog=64)
        self._listener.setblocking(False)
        self._sel.register(self._listener, selectors.EVENT_READ, "listener")
        # idle waiters block on the listener too: an inbound connect (the
        # peer's first message) must wake a sleeping receiver
        from ompi_tpu.runtime import progress as progress_mod

        progress_mod.register_waiter(self._listener)
        rte.modex_put("btl_tcp_addr", self._listener.getsockname())
        return True

    def reachable(self, world_rank: int, rte) -> Optional[Endpoint]:
        if self._rte is None or world_rank == rte.my_world_rank:
            return None
        # cache the peer's address NOW, while the modex is reachable: a
        # lazy lookup at first-send time would make the transport depend
        # on the coordination service staying alive (the FT detector's
        # p2p carrier must work after the coord dies)
        if world_rank not in self._addr_cache:
            try:
                addr = rte.modex_get(world_rank, "btl_tcp_addr", wait=False)
                if addr is not None:
                    self._addr_cache[world_rank] = tuple(addr)
            except Exception:
                pass
        return Endpoint(self, world_rank)

    # -- send path -------------------------------------------------------
    def _connect(self, rank: int, best_effort: bool = False) -> _Conn:
        conns = self._by_rank.get(rank)
        if conns:
            return self._pick(rank, conns)
        with self._locks_guard:
            lock = self._connect_locks.setdefault(rank, threading.Lock())
        with lock:   # one connect round per PEER — peers connect in parallel
            conns = self._by_rank.get(rank)
            if conns:
                return self._pick(rank, conns)
            # failed-connect backoff gates only BEST-EFFORT traffic (FT
            # heartbeats/floods): a dead host blackholes SYNs and a
            # blocking retry per tick would stall the sender for the full
            # connect timeout.  Application sends always attempt the
            # connect — a transient failure must not hard-fail the data
            # path for the backoff window.
            until = self._connect_backoff.get(rank, 0.0)
            if best_effort and time.monotonic() < until:
                raise ConnectionError(
                    f"rank {rank} connect in backoff until {until:.1f}")
            addr = self._addr_cache.get(rank)
            if addr is None:
                addr = self._rte.modex_get(rank, "btl_tcp_addr")
                if addr is not None:
                    self._addr_cache[rank] = tuple(addr)
            if addr is None:
                raise ConnectionError(f"no tcp address for rank {rank}")
            conns = []
            for _link in range(self._links):
                sock = None
                try:
                    sock = socket.create_connection(tuple(addr), timeout=5)
                    sock.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                    # handshake: tell the peer who we are (framed like
                    # any fragment: header pickle + empty payload)
                    hello = pickle.dumps({"rank": self._rte.my_world_rank})
                    sock.sendall(_LEN.pack(_LEN.size + len(hello))
                                 + _LEN.pack(len(hello)) + hello)
                except OSError:
                    if sock is not None:
                        try:
                            sock.close()
                        except OSError:
                            pass
                    if not conns:
                        self._connect_backoff[rank] = \
                            time.monotonic() + 10.0
                        raise
                    break   # some links up: run with what connected
                conn = _Conn(sock, rank)
                sock.setblocking(False)
                self._sel.register(sock, selectors.EVENT_READ, conn)
                from ompi_tpu.runtime import progress as progress_mod

                progress_mod.register_waiter(sock)
                conns.append(conn)
            self._connect_backoff.pop(rank, None)
            # MERGE, never assign: _drain's handshake path may have
            # appended accepted reply rails for this rank concurrently
            self._by_rank.setdefault(rank, []).extend(conns)
            return self._pick(rank, self._by_rank[rank])

    def _pick(self, rank: int, conns: list) -> _Conn:
        """Round-robin link selection (frames are self-contained; pml
        sequence numbers reorder across links at the receiver)."""
        i = self._rr.get(rank, 0)
        self._rr[rank] = i + 1
        try:
            return conns[i % len(conns)]
        except (ZeroDivisionError, IndexError):
            # the progress thread dropped the last link concurrently
            raise ConnectionError(f"no live tcp links to rank {rank}")

    def send(self, ep: Endpoint, frag: Frag) -> None:
        # FT control traffic is best-effort: it honours connect backoff
        # and, when flagged, only rides ALREADY-established connections
        # (a shutdown tombstone flood must not block connecting to a
        # possibly-dead peer)
        meta = frag.meta or {}
        ft = str(meta.get("proto", "")).startswith("ft_")
        if meta.get("est_only"):
            conns = self._by_rank.get(ep.world_rank)
            if not conns:
                raise ConnectionError(
                    f"no established connection to rank {ep.world_rank}")
            conn = self._pick(ep.world_rank, conns)
        else:
            conn = self._connect(ep.world_rank, best_effort=ft)
        # wire format: [u32 frame][u32 hlen][hdr pickle][payload raw] —
        # splitting the payload out of the pickle saves a full-size copy
        # per fragment on both ends (same framing as btl/sm)
        hdr = pickle.dumps(
            (frag.cid, frag.src, frag.dst, frag.tag, frag.seq, frag.kind,
             frag.total_len, frag.offset, frag.meta),
            protocol=pickle.HIGHEST_PROTOCOL)
        # the outbuf append IS the owning copy (and happens synchronously,
        # inside a borrowed view's validity window); memoryview routes an
        # ndarray through the buffer protocol instead of ndarray.__radd__
        payload = frag.data
        if not isinstance(payload, (bytes, bytearray, memoryview)):
            payload = memoryview(payload)
        with conn.send_lock:
            conn.outbuf += _LEN.pack(_LEN.size + len(hdr) + len(payload))
            conn.outbuf += _LEN.pack(len(hdr))
            conn.outbuf += hdr
            conn.outbuf += payload
            self._flush_locked(conn)

    def _flush(self, conn: _Conn) -> None:
        with conn.send_lock:
            self._flush_locked(conn)

    def _flush_locked(self, conn: _Conn) -> None:
        while conn.outbuf:
            try:
                n = conn.sock.send(conn.outbuf)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                # hard error (EPIPE/ECONNRESET): the bytes can never be
                # delivered — drop them so close()'s flush loop terminates
                conn.outbuf.clear()
                self._drop_conn(conn)
                return
            if n == 0:
                return
            del conn.outbuf[:n]

    # -- progress --------------------------------------------------------
    def progress(self) -> int:
        events = 0
        try:
            ready = self._sel.select(timeout=0)
        except OSError:
            return 0
        for key, _ in ready:
            if key.data == "listener":
                try:
                    sock, _ = self._listener.accept()
                except OSError:
                    continue
                sock.setblocking(False)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                conn = _Conn(sock)
                self._sel.register(sock, selectors.EVENT_READ, conn)
                from ompi_tpu.runtime import progress as progress_mod

                progress_mod.register_waiter(sock)
                continue
            conn: _Conn = key.data
            try:
                data = conn.sock.recv(1 << 16)
            except (BlockingIOError, InterruptedError):
                continue
            except OSError:
                data = b""
            if not data:
                from ompi_tpu.runtime import progress as progress_mod

                progress_mod.unregister_waiter(conn.sock)
                try:
                    self._sel.unregister(conn.sock)
                    conn.sock.close()
                except (OSError, KeyError):
                    pass
                self._drop_conn(conn)
                continue
            conn.inbuf += data
            events += self._drain(conn)
        for conn in self._all_conns():
            if conn.outbuf:
                self._flush(conn)
        return events

    def _all_conns(self) -> list:
        return [c for conns in self._by_rank.values() for c in conns]

    def _drop_conn(self, conn: "_Conn") -> None:
        if conn.rank is None:
            return
        conns = self._by_rank.get(conn.rank)
        if conns and conn in conns:
            conns.remove(conn)
            if not conns:
                self._by_rank.pop(conn.rank, None)

    def _drain(self, conn: _Conn) -> int:
        import numpy as np

        events = 0
        while True:
            if len(conn.inbuf) < _LEN.size:
                return events
            (n,) = _LEN.unpack(conn.inbuf[:_LEN.size])
            if len(conn.inbuf) < _LEN.size + n:
                return events
            frame = bytes(conn.inbuf[_LEN.size:_LEN.size + n])
            del conn.inbuf[:_LEN.size + n]
            (hlen,) = _LEN.unpack_from(frame, 0)
            obj = pickle.loads(memoryview(frame)[_LEN.size:_LEN.size + hlen])
            if isinstance(obj, dict) and "rank" in obj and conn.rank is None:
                conn.rank = obj["rank"]
                # accepted links become reply rails for this rank too
                self._by_rank.setdefault(conn.rank, []).append(conn)
                continue
            cid, src, dst, tag, seq, kind, total_len, offset, meta = obj
            frag = Frag(cid, src, dst, tag, seq, kind,
                        np.frombuffer(frame, np.uint8,
                                      offset=_LEN.size + hlen),
                        total_len, offset, meta)
            if self._recv_cb is not None:
                self._recv_cb(frag)
                events += 1

    def close(self) -> None:
        # flush queued outbound bytes before closing (same delivered-but-
        # unsent exit hazard as btl/sm — see its close())
        deadline = time.monotonic() + 30.0
        while (any(c.outbuf for c in self._all_conns())
               and time.monotonic() < deadline):
            for conn in self._all_conns():
                if conn.outbuf:
                    self._flush(conn)
            if any(c.outbuf for c in self._all_conns()):
                time.sleep(0.0005)
        from ompi_tpu.runtime import progress as progress_mod

        # every registered socket — including accepted-but-unhandshaked
        # conns that never made it into _by_rank — must leave the global
        # waiter selector, or their EOF-readable fds make idle_wait()
        # busy-spin forever after this btl is gone
        for key in list(self._sel.get_map().values()):
            if key.data == "listener":
                continue
            progress_mod.unregister_waiter(key.fileobj)
            try:
                self._sel.unregister(key.fileobj)
                key.fileobj.close()
            except (OSError, KeyError):
                pass
        self._by_rank.clear()
        if self._listener is not None:
            progress_mod.unregister_waiter(self._listener)
            try:
                self._sel.unregister(self._listener)
                self._listener.close()
            except (OSError, KeyError):
                pass
            self._listener = None


COMPONENT = TcpBtl()
