"""btl/tcp — socket transport, the DCN analog.

Re-design of ``/root/reference/opal/mca/btl/tcp/`` (5,117 LoC): a listening
socket per process whose address is published through the modex
(``btl_tcp_addr``), lazy connects on first send with a rank handshake,
length-prefixed fragments, and nonblocking IO drained from the central
progress engine (the reference polls through libevent from
``opal_progress``).  Eager/rendezvous thresholds are MCA vars like the
reference's ``btl_tcp_eager_limit`` family (``btl.h:1162-1165``).

**fastpath wire format** (one byte of header-type negotiation per
fragment, so fast and pickle headers coexist on one connection)::

    frame    := [u32 frame_len][u8 htype][header][payload]
    htype 0  := [u32 hlen][pickle header]          (exotic meta, handshake)
    htype 1  := [_FAST struct: cid,src,dst,tag,seq,kind,total,off,req_id]

Both header forms carry the otpu-crit flow key ride-along for free:
``(src, seq)`` together with ``cid``/``dst`` IS the ``cid.src.dst.seq``
message key the pml stamps on its send span and the recv side closes at
delivery (``runtime/trace.py`` FLOW_CATEGORIES) — no extra framing
bytes, the match header always carried it.

The fast header covers the common contiguous-frag cases — eager MATCH
(empty meta) and RNDV-continuation FRAG (``{"req_id": int}``) — which
carry all the payload bytes; anything else (ACK/CTL/RGET metas, FT
protos) falls back to pickle.  The reference's equivalent is the fixed
``mca_btl_tcp_hdr_t`` vs the PML's marshalled headers.

**Zero-copy send path**: the out-queue is a deque of memoryviews drained
by ``socket.sendmsg`` scatter-gather — the sender's payload view rides
to the syscall with no intermediate concatenation (the old bytearray
``outbuf`` re-copied every queued byte per partial send: O(n²) under
backpressure).  Borrowed payload views (``Frag.borrowed``) are only
valid inside ``send``: whatever the first sendmsg cannot hand to the
kernel is copied once (SPC ``fastpath_payload_copies``) so the queue
never aliases user memory; owned payloads queue as views and are never
copied.  Backpressured connections register for EVENT_WRITE and are
drained by the progress loop when the socket turns writable — no
busy-retry.
"""
from __future__ import annotations

import pickle
import selectors
import socket
import struct
import threading
import time
import zlib
from collections import deque
from functools import partial
from typing import Optional

import numpy as np

from ompi_tpu.base.var import VarType
from ompi_tpu.ft import chaos
from ompi_tpu.mca.btl.base import ACK, CTL, FRAG, MATCH, RGET, RNDV, \
    Btl, Endpoint, Frag
from ompi_tpu.mca.coll import quant as quant_mod
from ompi_tpu.runtime import profile, reactor as reactor_mod, \
    sanitizer, spc, trace
from ompi_tpu.runtime.hotpath import hot_path

# reactor record types, bound to locals for the dispatch hot path
_R_RAW = reactor_mod.REC_RAW
_R_FAST = reactor_mod.REC_FAST
_R_EOF = reactor_mod.REC_EOF
_R_ACCEPT = reactor_mod.REC_ACCEPT
_R_WRITABLE = reactor_mod.REC_WRITABLE
_R_OVERSIZE = reactor_mod.REC_OVERSIZE
_R_DESYNC = reactor_mod.REC_DESYNC

_LEN = struct.Struct("!I")
_MAX_FRAME = (1 << 32) - 1          # the !I length prefix's ceiling

# header-type byte (per-fragment negotiation; the bits compose)
_H_PICKLE = 0
_H_FAST = 1
# checksummed variants (htype | _H_CK_BASE): the frame carries a crc32
# of everything after the crc field.  Armed under chaos / OTPU_SANITIZE
# on the SEND side; the receiver verifies whatever arrives checksummed,
# so mixed-arming jobs interoperate.  Silent wire corruption becomes a
# loud, attributed error instead of a downstream mystery.
_H_CK_BASE = 2
_CKSUM = struct.Struct("!I")
# quantized variants (htype | _H_QUANT — the crc32 framing precedent):
# the payload travels through the coll/quant block-scale codec, with a
# small quant sub-header [u8 codec][u32 raw_len][u16 block] between the
# crc (which covers it) and the message header.  Stamped per-fragment
# by the pml (Frag.qcodec — only it still knows the bytes are f32);
# the receive parse decodes back to the ORIGINAL byte stream, so the
# pml's reassembly offsets never see codec bytes.
_H_QUANT = 4
_QHDR = struct.Struct("!BIH")


def _cksum_armed() -> bool:
    """Frame checksumming is opt-in: chaos (corruption is being
    *injected*) or the sanitizer hard-assertion mode arms it; the
    default fast path never pays the crc."""
    return chaos.enabled or sanitizer.enabled

# fast header: cid, src, dst (u32), tag (i32), seq (i64), kind (u8),
# total_len, offset, req_id (i64; req_id -1 = no meta)
_FAST = struct.Struct("!IIIiqBqqq")
_KIND_TO_CODE = {MATCH: 0, RNDV: 1, ACK: 2, FRAG: 3, RGET: 4, CTL: 5}
_CODE_TO_KIND = {v: k for k, v in _KIND_TO_CODE.items()}

#: sendmsg scatter-gather width per syscall (Linux IOV_MAX is 1024;
#: 64 buffers ≈ 16 frames per call, plenty to amortize the syscall)
_IOV_BATCH = 64


def _fast_header(frag: Frag) -> Optional[bytes]:
    """The fixed struct header when ``frag`` fits it, else None.

    Eligible: empty meta or exactly ``{"req_id": int}`` (the FRAG
    continuation case), known kind, and every field within the struct's
    integer ranges — anything else takes the pickle fallback.
    """
    meta = frag.meta
    if meta:
        if len(meta) != 1 or "req_id" not in meta:
            return None
        req_id = meta["req_id"]
        if not isinstance(req_id, int) or not 0 <= req_id < (1 << 63):
            return None
    else:
        req_id = -1
    code = _KIND_TO_CODE.get(frag.kind)
    if code is None:
        return None
    try:
        return _FAST.pack(frag.cid, frag.src, frag.dst, frag.tag,
                          frag.seq, code, frag.total_len, frag.offset,
                          req_id)
    except (struct.error, TypeError):
        return None   # out-of-range field (huge tag, negative rank…)


class _Conn:
    #: per-recv scratch size (recv_into target; frames parse straight
    #: out of it, so bigger = more frames per syscall)
    SCRATCH = 1 << 18

    #: otpu-lint lock-discipline contract: the out-queue and its byte
    #: count mutate only under send_lock (helpers named *_locked run
    #: with it held by the caller)
    _guarded_by = {"outq": "send_lock", "out_bytes": "send_lock"}

    def __init__(self, sock: socket.socket, rank: Optional[int] = None):
        self.sock = sock
        self.rank = rank
        # fd registered with the native reactor (None on the pure-
        # Python selector lane); cleared on EOF teardown
        self.fd: Optional[int] = None
        # holds only the partial TAIL frame split across recv calls;
        # complete frames are parsed zero-copy from the recv scratch
        self.inbuf = bytearray()
        self.scratch = bytearray(self.SCRATCH)
        # out-queue: memoryviews handed to sendmsg in order.  Owned
        # buffers (headers, owned payload arrays) are queued as views —
        # the deque entry keeps them alive; borrowed payload remainders
        # are copied before queueing (see send()).
        self.outq: deque = deque()
        self.out_bytes = 0
        # whether this conn is registered for EVENT_WRITE in the btl
        # selector (set while outq is non-empty, under send_lock)
        self.want_write = False
        # serialises outq append+flush: app threads, the progress
        # engine, and the FT detector all send on the same conn, and two
        # concurrent sendmsg calls over one queue would interleave
        # frames and desynchronise the peer's framing
        self.send_lock = threading.Lock()


def _conn_peer(conn: "Optional[_Conn]") -> int:
    """Attributed rank of a connection (-1: pre-handshake)."""
    return conn.rank if conn is not None and conn.rank is not None \
        else -1


class TcpBtl(Btl):
    name = "tcp"
    priority = 10
    eager_limit = 64 * 1024
    rndv_eager_limit = 64 * 1024
    max_send_size = 128 * 1024
    latency = 100
    bandwidth = 100

    #: otpu-lint lock-discipline contract.  _by_rank is mutated from app
    #: threads (connect, flush hard-error drop), the progress thread
    #: (EOF drop, handshake append), and close(): every mutation takes
    #: _conns_lock — the otpu-lint pass found the unguarded remove/
    #: extend races this declaration now pins.  Reads stay lock-free
    #: snapshots (GIL-atomic dict get; _pick tolerates a concurrently
    #: shrunk list).
    _guarded_by = {"_by_rank": "_conns_lock",
                   "_suspects": "_conns_lock",
                   "_connect_locks": "_locks_guard"}

    def __init__(self) -> None:
        super().__init__()
        self._rte = None
        self._listener: Optional[socket.socket] = None
        self._sel = selectors.DefaultSelector()
        # native-reactor lane: when True the epoll loop in otpu_native
        # owns every socket (drain/framing/parse off-GIL) and progress()
        # only fires deferred suspicions — records arrive through
        # reactor_mod.drain() -> _reactor_event.  _rconns mirrors the
        # reactor's fd registrations for close() teardown.
        self._reactor = False
        self._rconns: dict[int, _Conn] = {}
        # multi-link (btl_tcp_links): several connections per peer, frames
        # round-robined across them — the reference's per-link striping
        self._by_rank: dict[int, list[_Conn]] = {}
        self._conns_lock = threading.Lock()
        self._rr: dict[int, int] = {}
        self._links = 1
        self._addr_cache: dict[int, tuple] = {}
        self._locks_guard = threading.Lock()
        self._connect_locks: dict[int, threading.Lock] = {}  # per peer
        self._connect_backoff: dict[int, float] = {}   # rank -> retry-after
        # peers whose connection died mid-traffic (reset/EOF), pending
        # hand-off to the FT detector as suspicions — filled under
        # _conns_lock in _drop_conn, drained lock-free by send/progress
        self._suspects: list[int] = []
        # live out-queue depth for otpu_top (one dict insert here; the
        # provider runs only on the sampler thread, never on a hot path)
        from ompi_tpu.runtime import telemetry

        telemetry.register_source("tcp", self._telemetry_stats)

    def _telemetry_stats(self) -> dict:
        """Sampler-thread source: aggregate out-queue depth/bytes and
        connection count.  Racy unlocked reads of per-conn counters —
        telemetry is an approximation, and the lock contract only
        covers mutation."""
        frags = qbytes = nconns = 0
        for conns in list(self._by_rank.values()):
            for conn in list(conns):
                nconns += 1
                frags += len(conn.outq)
                qbytes += conn.out_bytes
        return {"outq_frags": frags, "outq_bytes": qbytes,
                "conns": nconns}

    def register_vars(self, fw) -> None:
        self.register_var(
            "eager_limit", vtype=VarType.SIZE, default="64k",
            help="Max eager message size over tcp",
            on_set=lambda v: setattr(self, "eager_limit", v))
        self.register_var(
            "max_send_size", vtype=VarType.SIZE, default="128k",
            help="Max fragment size for rendezvous streaming over tcp",
            on_set=lambda v: setattr(self, "max_send_size", v))
        self.register_var(
            "links", vtype=VarType.INT, default=1,
            help="TCP connections per peer; frames stripe round-robin "
                 "across them (btl_tcp_links)",
            on_set=lambda v: setattr(self, "_links", max(1, int(v))))

    # -- lifecycle -------------------------------------------------------
    def setup(self, rte) -> bool:
        """Listen + publish our address (pre-fence).

        Runs even in a 1-rank job: under dpm a singleton spawned job has
        no same-job peers but MUST be reachable from its parent job, and
        tcp is the universal transport that guarantees it.
        """
        if rte.is_device_world:
            return False
        if not hasattr(rte, "modex_put"):
            return False
        if getattr(rte, "client", None) is None:
            return False   # no coord service (singleton): nobody can dial in
        self._rte = rte
        self._listener = socket.create_server(("127.0.0.1", 0), backlog=64)
        self._listener.setblocking(False)
        # native-reactor lane: hand the listener to the epoll thread as
        # a NOTIFY (oneshot) fd — inbound connects surface as ACCEPT
        # records and the reactor's notify eventfd (a progress waiter)
        # wakes idle sleepers, so neither the selector nor the waiter
        # registry sees this socket at all
        self._reactor = reactor_mod.engage() and reactor_mod.add(
            self._listener.fileno(), reactor_mod.MODE_NOTIFY,
            self._on_accept_record)
        if not self._reactor:
            self._sel.register(self._listener, selectors.EVENT_READ,
                               "listener")
            # idle waiters block on the listener too: an inbound connect
            # (the peer's first message) must wake a sleeping receiver
            from ompi_tpu.runtime import progress as progress_mod

            progress_mod.register_waiter(self._listener)
        rte.modex_put("btl_tcp_addr", self._listener.getsockname())
        return True

    def _register_conn(self, conn: _Conn) -> None:
        """Register a fresh connection for receive progress: with the
        native reactor its fd becomes a STREAM (drain/framing/parse run
        on the epoll thread); otherwise the classic selector + idle-
        waiter pair."""
        if self._reactor:
            fd = conn.sock.fileno()
            if reactor_mod.add(fd, reactor_mod.MODE_STREAM,
                               partial(self._reactor_event, conn)):
                conn.fd = fd
                self._rconns[fd] = conn
                return
        self._sel.register(conn.sock, selectors.EVENT_READ, conn)
        from ompi_tpu.runtime import progress as progress_mod

        progress_mod.register_waiter(conn.sock)

    def reachable(self, world_rank: int, rte) -> Optional[Endpoint]:
        if self._rte is None or world_rank == rte.my_world_rank:
            return None
        # cache the peer's address NOW, while the modex is reachable: a
        # lazy lookup at first-send time would make the transport depend
        # on the coordination service staying alive (the FT detector's
        # p2p carrier must work after the coord dies)
        if world_rank not in self._addr_cache:
            try:
                addr = rte.modex_get(world_rank, "btl_tcp_addr", wait=False)
                if addr is not None:
                    self._addr_cache[world_rank] = tuple(addr)
            except Exception:
                pass
        return Endpoint(self, world_rank)

    # -- send path -------------------------------------------------------
    def _connect(self, rank: int, best_effort: bool = False) -> _Conn:
        conns = self._by_rank.get(rank)
        if conns:
            return self._pick(rank, conns)
        with self._locks_guard:
            lock = self._connect_locks.setdefault(rank, threading.Lock())
        with lock:   # one connect round per PEER — peers connect in parallel
            conns = self._by_rank.get(rank)
            if conns:
                return self._pick(rank, conns)
            # failed-connect backoff gates only BEST-EFFORT traffic (FT
            # heartbeats/floods): a dead host blackholes SYNs and a
            # blocking retry per tick would stall the sender for the full
            # connect timeout.  Application sends always attempt the
            # connect — a transient failure must not hard-fail the data
            # path for the backoff window.
            until = self._connect_backoff.get(rank, 0.0)
            if best_effort and time.monotonic() < until:
                raise ConnectionError(
                    f"rank {rank} connect in backoff until {until:.1f}")
            addr = self._addr_cache.get(rank)
            if addr is None:
                addr = self._rte.modex_get(rank, "btl_tcp_addr")
                if addr is not None:
                    self._addr_cache[rank] = tuple(addr)
            if addr is None:
                raise ConnectionError(f"no tcp address for rank {rank}")
            conns = []
            for _link in range(self._links):
                sock = None
                try:
                    sock = socket.create_connection(tuple(addr), timeout=5)
                    sock.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                    # handshake: tell the peer who we are (framed like
                    # any pickle-header fragment with empty payload)
                    hello = pickle.dumps({"rank": self._rte.my_world_rank})
                    sock.sendall(_LEN.pack(1 + _LEN.size + len(hello))
                                 + bytes((_H_PICKLE,))
                                 + _LEN.pack(len(hello)) + hello)
                except OSError:
                    if sock is not None:
                        try:
                            sock.close()
                        except OSError:
                            pass
                    if not conns:
                        self._connect_backoff[rank] = \
                            time.monotonic() + 10.0
                        raise
                    break   # some links up: run with what connected
                conn = _Conn(sock, rank)
                sock.setblocking(False)
                self._register_conn(conn)
                conns.append(conn)
            self._connect_backoff.pop(rank, None)
            # MERGE, never assign: _drain's handshake path may have
            # appended accepted reply rails for this rank concurrently.
            # Pick from the list captured UNDER the lock — a re-read
            # after release could KeyError if the progress thread
            # dropped the rail (EOF on the fresh socket) in between.
            with self._conns_lock:
                merged = self._by_rank.setdefault(rank, [])
                merged.extend(conns)
            return self._pick(rank, merged)

    def _pick(self, rank: int, conns: list) -> _Conn:
        """Round-robin link selection (frames are self-contained; pml
        sequence numbers reorder across links at the receiver)."""
        i = self._rr.get(rank, 0)
        self._rr[rank] = i + 1
        try:
            return conns[i % len(conns)]
        except (ZeroDivisionError, IndexError):
            # the progress thread dropped the last link concurrently
            raise ConnectionError(f"no live tcp links to rank {rank}")

    @hot_path
    def send(self, ep: Endpoint, frag: Frag) -> None:
        # FT control traffic is best-effort: it honours connect backoff
        # and, when flagged, only rides ALREADY-established connections
        # (a shutdown tombstone flood must not block connecting to a
        # possibly-dead peer)
        meta = frag.meta or {}
        chaos_rule = None
        if chaos.enabled:
            chaos_rule = chaos.wire_send("tcp", frag.kind == CTL)
            if chaos_rule is not None:
                fault = chaos_rule["fault"]
                if fault == "drop":
                    return          # best-effort CTL frame lost
                if fault == "delay":
                    chaos.sleep_ms(chaos_rule)
                    chaos_rule = None
        nbytes = getattr(frag.data, "nbytes", None)
        if nbytes is None:
            nbytes = len(frag.data)
        if nbytes + (1 + _FAST.size + _LEN.size + _CKSUM.size) > _MAX_FRAME:
            # early check on the payload alone so the failure fires
            # before any connect/memoryview work; a pickle header can
            # outgrow the assumed fast-header size, so the built frame
            # is re-checked below
            raise self._frame_too_large(nbytes)
        ft = str(meta.get("proto", "")).startswith("ft_")
        if meta.get("est_only"):
            conns = self._by_rank.get(ep.world_rank)
            if not conns:
                raise ConnectionError(
                    f"no established connection to rank {ep.world_rank}")
            conn = self._pick(ep.world_rank, conns)
        else:
            conn = self._connect(ep.world_rank, best_effort=ft)
        if chaos_rule is not None and chaos_rule["fault"] == "reset":
            # injected connection reset: shutdown (the selector sees a
            # readable EOF and runs the normal teardown, which also
            # routes the reset into the detector as a suspicion).  A
            # best-effort CTL frame is silently lost, exactly like a
            # real reset; application traffic fails loudly.
            try:
                conn.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            self._drop_conn(conn)
            self._drain_suspects()
            if frag.kind == CTL:
                return
            raise ConnectionError(
                f"chaos: injected connection reset to rank "
                f"{ep.world_rank}")
        # payload as a flat byte view — memoryview routes an ndarray
        # through the buffer protocol; .cast("B") flattens multi-dim /
        # non-uint8 views so len() counts bytes
        payload = frag.data
        if not isinstance(payload, (bytes, bytearray, memoryview)):
            payload = memoryview(payload)
        if isinstance(payload, memoryview) and (
                payload.ndim != 1 or payload.itemsize != 1):
            payload = payload.cast("B")
        # coll/quant codec stage: between the convertor's pack and the
        # out-queue.  Runs BEFORE the send.queue stage begin (the
        # encode carries its own quant.encode clock inside encode_wire)
        # and replaces the payload with an OWNED encoded array, so the
        # borrowed-remainder machinery below never runs for it.
        qhdr = b""
        borrowed = frag.borrowed
        qbit = 0
        if quant_mod.wire_enabled and frag.qcodec is not None:
            enc = quant_mod.encode_wire(payload, frag.qcodec)
            if enc is not None:
                qhdr = _QHDR.pack(quant_mod.codec_id(frag.qcodec),
                                  len(payload), quant_mod.block_elems())
                payload = memoryview(enc)
                borrowed = False
                qbit = _H_QUANT
        # stage clock: frame build + enqueue, the wire syscall excluded
        # (that is send.wire, recorded inside _flush_locked)
        _pt = profile.now() if profile.enabled else 0
        hdr = _fast_header(frag)
        if hdr is not None:
            spc.record("fastpath_hdr_fast")
            htype = _H_FAST | qbit
        else:
            spc.record("fastpath_hdr_pickle")
            hdr = pickle.dumps(
                (frag.cid, frag.src, frag.dst, frag.tag, frag.seq,
                 frag.kind, frag.total_len, frag.offset, frag.meta),
                protocol=pickle.HIGHEST_PROTOCOL)
            hdr = _LEN.pack(len(hdr)) + hdr
            htype = _H_PICKLE | qbit
        if _cksum_armed():
            # checksummed variant: [len][htype|2][crc32][qhdr][hdr]
            # [payload], crc over everything after the crc field —
            # the quant sub-header is covered too
            crc = zlib.crc32(payload, zlib.crc32(hdr, zlib.crc32(qhdr)))
            frame_len = 1 + _CKSUM.size + len(qhdr) + len(hdr) \
                + len(payload)
            if frame_len > _MAX_FRAME:
                raise self._frame_too_large(frame_len)
            head = (_LEN.pack(frame_len) + bytes((htype | _H_CK_BASE,))
                    + _CKSUM.pack(crc) + qhdr + hdr)
        else:
            frame_len = 1 + len(qhdr) + len(hdr) + len(payload)
            # re-checked here: a pickle header can outgrow the fast-
            # header size the early payload check assumed — and the
            # check must precede _LEN.pack, which would die on a
            # bare struct.error first
            if frame_len > _MAX_FRAME:
                raise self._frame_too_large(frame_len)
            head = _LEN.pack(frame_len) + bytes((htype,)) + qhdr + hdr
        if chaos_rule is not None and chaos_rule["fault"] == "corrupt":
            # on-the-wire bit rot, injected AFTER the checksum was
            # computed (the armed receiver catches it loudly); flips a
            # header byte so the caller's payload memory stays pristine
            mangled = bytearray(head)
            mangled[-1] ^= 0x01
            head = bytes(mangled)
        with conn.send_lock:
            conn.outq.append(memoryview(head))
            conn.out_bytes += len(head)
            queued = 1
            if len(payload):
                conn.outq.append(payload if isinstance(payload, memoryview)
                                 else memoryview(payload))
                conn.out_bytes += len(payload)
                queued = 2
            if profile.enabled:
                profile.stage_span("send.queue", _pt)
            self._flush_locked(conn)
            if conn.outq and borrowed and queued == 2:
                # whatever the kernel did not take must stop aliasing
                # the caller's buffer before we return (Frag contract:
                # borrowed views die with this call).  Only the queued
                # REMAINDER is copied — the common uncongested case
                # stays zero-copy end to end.
                self._own_queued_locked(conn, queued)
            if sanitizer.enabled and borrowed:
                # ownership tag: after a borrowed send returns, no queue
                # entry may still alias the caller's memory
                owner = payload.obj if isinstance(payload, memoryview) \
                    else payload
                for mv in conn.outq:
                    if getattr(mv, "obj", None) is owner:
                        sanitizer.fail(
                            "btl/tcp out-queue still aliases a borrowed "
                            "payload after send() returned")
        if chaos_rule is not None and chaos_rule["fault"] == "dup":
            # duplicate delivery of a best-effort CTL frame (a framing-
            # level retransmit): the FT protocols riding CTL are
            # idempotent by design, which this proves on demand
            with conn.send_lock:
                conn.outq.append(memoryview(head))
                conn.out_bytes += len(head)
                if len(payload):
                    conn.outq.append(memoryview(bytes(payload)))
                    conn.out_bytes += len(payload)
                self._flush_locked(conn)
        self._drain_suspects()

    def _drain_suspects(self) -> None:
        """Fire deferred wire-reset suspicions (recorded by
        ``_drop_conn`` under ``_conns_lock``, delivered here with no
        lock held: the report floods CTL frags over other conns and
        must not nest under transport locks)."""
        if not self._suspects:
            return
        with self._conns_lock:
            pending, self._suspects = self._suspects, []
        from ompi_tpu.ft import propagator

        for rank in pending:
            propagator.wire_suspicion(rank)

    @staticmethod
    def _frame_too_large(nbytes: int) -> ValueError:
        # the !I length prefix caps one frame at 4GB-1; the pml
        # fragments far below this (max_send_size), so hitting it means
        # a caller bypassed fragmentation — fail loudly rather than
        # silently truncating the length on the wire
        from ompi_tpu.base.output import show_help

        show_help("help-btl-tcp", "frame-too-large",
                  nbytes=nbytes, limit=_MAX_FRAME)
        return ValueError(
            f"tcp frame of {nbytes} bytes exceeds the u32 length-prefix "
            f"limit ({_MAX_FRAME}); fragment the payload below "
            "btl.max_send_size")

    def _own_queued_locked(self, conn: _Conn, tail: int) -> None:
        """Own the newest ``tail`` queue entries (send_lock held).

        Only the fragment queued by the current send can alias its
        caller's buffer — every earlier entry was owned at its own send
        time (or was never borrowed), and the FIFO drain in
        ``_flush_locked`` guarantees the current fragment's remainder is
        the queue's tail.  Copying just that tail keeps the backpressure
        cost O(remainder) instead of re-copying the whole backlog.  The
        SPC counter tracks payload bytes copied because the first
        sendmsg backpressured.
        """
        q = conn.outq
        n = min(len(q), tail)
        if not n:
            return
        spc.record("fastpath_payload_copies")
        owned = [memoryview(bytes(q.pop())) for _ in range(n)]
        q.extend(reversed(owned))

    def _flush(self, conn: _Conn) -> None:
        with conn.send_lock:
            self._flush_locked(conn)

    @hot_path
    def _flush_locked(self, conn: _Conn) -> None:
        """Drain the out-queue with sendmsg scatter-gather; on EAGAIN
        with bytes left, register for writability instead of retrying —
        the progress loop flushes when the socket can take more."""
        q = conn.outq
        while q:
            bufs = []
            for mv in q:
                bufs.append(mv)
                if len(bufs) >= _IOV_BATCH:
                    break
            t0 = time.perf_counter_ns() \
                if (trace.enabled or profile.enabled) else 0
            try:
                n = conn.sock.sendmsg(bufs)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                # hard error (EPIPE/ECONNRESET): the bytes can never be
                # delivered — drop them so close()'s flush loop terminates
                q.clear()
                conn.out_bytes = 0
                self._mark_writable(conn, False)
                self._drop_conn(conn)
                return
            if trace.enabled or profile.enabled:
                t1 = time.perf_counter_ns()
                if trace.enabled:
                    # peer rides along so otpu_analyze's critical-path
                    # wire bucket can attribute syscall time to the
                    # rank the bytes went to (-1: pre-handshake conn)
                    trace.span("btl_sendmsg", "btl", t0, t1,
                               args={"nbytes": n, "iov": len(bufs),
                                     "peer": conn.rank
                                     if conn.rank is not None else -1})
                    trace.hist_record("btl_sendmsg", n, t1 - t0)
                if profile.enabled:
                    profile.stage_span("send.wire", t0, t1)
            spc.record("fastpath_sendmsg")
            if n == 0:
                break
            conn.out_bytes -= n
            while n and q:
                mv = q[0]
                if n >= len(mv):
                    n -= len(mv)
                    q.popleft()
                else:
                    q[0] = mv[n:]
                    n = 0
        self._mark_writable(conn, bool(q))

    def _mark_writable(self, conn: _Conn, want: bool) -> None:
        """(De)register EVENT_WRITE interest for a backpressured conn."""
        if conn.want_write == want:
            return
        if conn.fd is not None:
            # reactor-owned stream: EPOLLOUT interest lives on the epoll
            # thread; the WRITABLE record it emits routes back through
            # _reactor_event -> _flush (interest auto-clears on fire)
            if reactor_mod.want_write(conn.fd, want):
                conn.want_write = want
            return
        events = selectors.EVENT_READ | (selectors.EVENT_WRITE if want
                                         else 0)
        try:
            self._sel.modify(conn.sock, events, conn)
        except (KeyError, ValueError, OSError):
            return   # conn already torn down / never registered
        conn.want_write = want

    # -- native-reactor record dispatch ----------------------------------
    @hot_path
    def _reactor_event(self, conn: _Conn, etype: int, payload) -> int:
        """Handler for one reactor record on this conn's stream.  FAST
        records carry a ready-to-unpack !IIIiqBqqq header + payload (the
        native thread already drained, framed, and lane-routed); the
        payload memoryview is borrowed drain-buffer scratch — valid
        until the next drain, the same contract as recv-scratch frames
        on the selector lane."""
        if etype == _R_FAST:
            if chaos.enabled:
                # recv-side chaos on the fast lane: delay only — corrupt
                # targets checksummed frames, and those never arrive
                # here (htype & _H_CK_BASE diverts to the RAW lane)
                rule = chaos.wire_recv("tcp", False)
                if rule is not None and rule["fault"] == "delay":
                    chaos.sleep_ms(rule)
            _pt = profile.now() if profile.enabled else 0
            (cid, src, dst, tag, seq, code, total_len, offset,
             req_id) = _FAST.unpack_from(payload, 0)
            data = np.frombuffer(payload, np.uint8, offset=_FAST.size)
            frag = Frag(cid, src, dst, tag, seq, _CODE_TO_KIND[code],
                        data, total_len, offset,
                        {} if req_id < 0 else {"req_id": req_id},
                        borrowed=True)
            if profile.enabled:
                profile.stage_span("recv.parse", _pt)
            spc.record("fastpath_native_frags")
            if self._recv_cb is not None:
                self._recv_cb(frag)
                return 1
            return 0
        if etype == _R_RAW:
            return self._reactor_raw(conn, payload)
        if etype == _R_WRITABLE:
            # the epoll thread cleared its EPOLLOUT interest before
            # emitting this record: mirror that here so the flush's
            # _mark_writable re-arms when the queue is still non-empty
            conn.want_write = False
            self._flush(conn)
            return 1
        if etype == _R_EOF:
            self._reactor_eof(conn)
            return 1
        if etype == _R_OVERSIZE:
            return self._reactor_raw(
                conn, memoryview(reactor_mod.take_oversize(conn.fd)))
        if etype == _R_DESYNC:
            self._wire_fault(
                "wire_desync", _conn_peer(conn), 0, "framing desync",
                "btl/tcp framing desync: zero-length frame on the wire "
                "(native reactor)")
        return 0

    @hot_path
    def _reactor_raw(self, conn: _Conn, frame) -> int:
        """Slow-lane record: the native side forwards any frame that is
        not a plain fast header (crc-armed, quantized, pickle,
        handshake, unknown kind byte) VERBATIM, and this feeds it to the
        exact `_parse_frame` the selector lane uses — behavior stays
        bit-identical, including crc verification and the chaos
        recv-side corrupt hook below."""
        if chaos.enabled:
            rule = chaos.wire_recv("tcp", False)
            if rule is not None:
                if rule["fault"] == "delay":
                    chaos.sleep_ms(rule)
                elif rule["fault"] == "corrupt" \
                        and len(frame) > 1 + _CKSUM.size + 1 \
                        and frame[0] & _H_CK_BASE:
                    frame[1 + _CKSUM.size] ^= 0x01
        _pt = profile.now() if profile.enabled else 0
        frag = self._parse_frame(conn, frame, borrowed=True)
        if profile.enabled:
            profile.stage_span("recv.parse", _pt)
        spc.record("fastpath_native_raw")
        if frag is not None and self._recv_cb is not None:
            self._recv_cb(frag)
            return 1
        return 0

    def _on_accept_record(self, etype: int, payload) -> int:
        """NOTIFY record for the listener: accept everything pending,
        register each conn as a reactor stream, then re-arm the oneshot
        registration."""
        if etype != _R_ACCEPT or self._listener is None:
            return 0
        events = 0
        while True:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                break
            sock.setblocking(False)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._register_conn(_Conn(sock))
            events += 1
        reactor_mod.rearm(self._listener.fileno())
        return events

    def _reactor_eof(self, conn: _Conn) -> None:
        """Peer closed (or hard error) on a reactor stream: same
        teardown as the selector lane's zero-byte recv."""
        fd, conn.fd = conn.fd, None
        if fd is not None:
            reactor_mod.remove(fd)
            self._rconns.pop(fd, None)
        try:
            conn.sock.close()
        except OSError:
            pass
        self._drop_conn(conn)

    # -- progress --------------------------------------------------------
    @hot_path
    def progress(self) -> int:
        events = 0
        self._drain_suspects()
        if self._reactor and not self._sel.get_map():
            # native-reactor lane: every socket lives on the epoll
            # thread and completed records arrive via reactor_mod.drain
            # (a sibling progress callback) — nothing to select here.
            # The map check keeps any selector-registered straggler (a
            # reactor add() that failed mid-teardown) progressing.
            return 0
        try:
            ready = self._sel.select(timeout=0)
        except OSError:
            return 0
        for key, mask in ready:
            if key.data == "listener":
                try:
                    sock, _ = self._listener.accept()
                except OSError:
                    continue
                sock.setblocking(False)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                conn = _Conn(sock)
                self._sel.register(sock, selectors.EVENT_READ, conn)
                from ompi_tpu.runtime import progress as progress_mod

                progress_mod.register_waiter(sock)
                continue
            conn: _Conn = key.data
            if mask & selectors.EVENT_WRITE:
                # backpressured conn turned writable: drain the queue
                # (this is the no-busy-spin half of the flush contract)
                self._flush(conn)
                events += 1
            if not mask & selectors.EVENT_READ:
                continue
            try:
                n = conn.sock.recv_into(conn.scratch)
            except (BlockingIOError, InterruptedError):
                continue
            except OSError:
                n = 0
            if not n:
                from ompi_tpu.runtime import progress as progress_mod

                progress_mod.unregister_waiter(conn.sock)
                try:
                    self._sel.unregister(conn.sock)
                    conn.sock.close()
                except (OSError, KeyError):
                    pass
                self._drop_conn(conn)
                continue
            events += self._on_bytes(conn,
                                     memoryview(conn.scratch)[:n])
        return events

    def _all_conns(self) -> list:
        return [c for conns in self._by_rank.values() for c in conns]

    def _drop_conn(self, conn: "_Conn") -> None:
        # under _conns_lock: the app thread (flush hard error) and the
        # progress thread (EOF) can race this remove against _connect's
        # extend / the handshake append — the unguarded list mutation
        # was an otpu-lint lock-discipline finding
        if conn.rank is None:
            return
        with self._conns_lock:
            conns = self._by_rank.get(conn.rank)
            if conns and conn in conns:
                conns.remove(conn)
                if not conns:
                    self._by_rank.pop(conn.rank, None)
            # peer reset / unexpected EOF mid-traffic: recorded as a
            # suspicion for the FT detector (drained outside the lock;
            # no-op in jobs without a detector).  A teardown-time close
            # never reaches here: close() clears _by_rank wholesale.
            self._suspects.append(conn.rank)

    @staticmethod
    def _need(inbuf) -> int:
        """Bytes still missing before the parked frame is complete."""
        if len(inbuf) < _LEN.size:
            return _LEN.size - len(inbuf)
        (fl,) = _LEN.unpack_from(inbuf, 0)
        return max(0, _LEN.size + fl - len(inbuf))

    @hot_path
    def _on_bytes(self, conn: _Conn, view: memoryview) -> int:
        """Parse one recv's worth of stream bytes.

        Complete frames are parsed ZERO-COPY straight out of the recv
        scratch — the delivered Frag is ``borrowed`` (valid until the
        next recv on this conn; the pml owns anything it queues, same
        contract as btl/sm's ring views).  Only a frame split across
        recv boundaries takes the buffered path through ``inbuf``.
        """
        events = 0
        pos, n = 0, len(view)
        try:
            # finish a frame parked split across recvs (two stages: the
            # length prefix itself may be split, so _need grows once the
            # full prefix is known — keep feeding until frame-complete
            # or chunk exhausted)
            while conn.inbuf:
                take = min(self._need(conn.inbuf), n - pos)
                if take:
                    conn.inbuf += view[pos:pos + take]
                    pos += take
                if self._need(conn.inbuf) == 0:
                    events += self._drain(conn)
                elif pos >= n:
                    return events   # chunk exhausted mid-frame
            # fast path: complete frames straight from the scratch view
            while n - pos >= _LEN.size:
                (fl,) = _LEN.unpack_from(view, pos)
                if sanitizer.enabled and fl < 1:
                    sanitizer.fail("btl/tcp framing desync: zero-length "
                                   "frame on the wire")
                if n - pos < _LEN.size + fl:
                    break
                frame = view[pos + _LEN.size:pos + _LEN.size + fl]
                pos += _LEN.size + fl
                if chaos.enabled:
                    # recv-side faults on tcp are delay + corrupt only
                    # (loss_ok=False): the frag class is unknown before
                    # parse, and loss faults on the wire are the SEND
                    # side's job anyway — injecting them here would
                    # count faults that were never applied
                    rule = chaos.wire_recv("tcp", False)
                    if rule is not None:
                        if rule["fault"] == "delay":
                            chaos.sleep_ms(rule)
                        elif rule["fault"] == "corrupt" \
                                and fl > 1 + _CKSUM.size + 1 \
                                and frame[0] & _H_CK_BASE:
                            # pre-verify bit rot in the recv scratch:
                            # only on checksummed frames (an unarmed
                            # sender's frame would corrupt silently —
                            # the exact thing the armed checksum
                            # exists to preclude)
                            frame[1 + _CKSUM.size] ^= 0x01
                _pt = profile.now() if profile.enabled else 0
                frag = self._parse_frame(conn, frame, borrowed=True)
                if profile.enabled:
                    profile.stage_span("recv.parse", _pt)
                if frag is not None and self._recv_cb is not None:
                    self._recv_cb(frag)
                    events += 1
        finally:
            # park the partial tail — and, if a delivery callback raised
            # mid-chunk, the whole unparsed remainder: the scratch is
            # overwritten by the next recv, so anything left in it here
            # would be lost and desynchronize the connection's framing
            if pos < n:
                conn.inbuf += view[pos:]
        return events

    @hot_path
    def _drain(self, conn: _Conn) -> int:
        """Parse complete frames off the in-buffer (split-frame
        reassembly; the streaming path is :meth:`_on_bytes`).  The
        consumed prefix is deleted ONCE after the parse loop — a
        per-frame del memmoves the whole remainder and turns a burst of
        small frames O(n²)."""
        events = 0
        pos = 0
        buf = conn.inbuf
        try:
            while True:
                if len(buf) - pos < _LEN.size:
                    return events
                (n,) = _LEN.unpack_from(buf, pos)
                if sanitizer.enabled and n < 1:
                    sanitizer.fail("btl/tcp framing desync: zero-length "
                                   "frame in the reassembly buffer")
                if len(buf) - pos < _LEN.size + n:
                    return events
                frame = bytes(memoryview(buf)[pos + _LEN.size:
                                              pos + _LEN.size + n])
                pos += _LEN.size + n
                _pt = profile.now() if profile.enabled else 0
                frag = self._parse_frame(conn, frame)
                if profile.enabled:
                    profile.stage_span("recv.parse", _pt)
                if frag is not None and self._recv_cb is not None:
                    self._recv_cb(frag)
                    events += 1
        finally:
            if pos:
                del conn.inbuf[:pos]

    def _parse_frame(self, conn: _Conn, frame,
                     borrowed: bool = False) -> Optional[Frag]:
        """Decode one frame (bytes or memoryview).  ``borrowed`` marks
        the payload as a view of transient recv scratch.  The htype
        bits compose: checksummed frames (``htype & _H_CK_BASE``, armed
        sender) are verified before any parse — a mismatch is a loud,
        attributed error, never a silently-corrupt delivery — and
        quantized frames (``htype & _H_QUANT``) dequantize straight out
        of the recv view into an OWNED array of the original bytes."""
        htype = frame[0]
        off = 1
        if htype & _H_CK_BASE:
            (want,) = _CKSUM.unpack_from(frame, 1)
            off = 1 + _CKSUM.size
            got = zlib.crc32(memoryview(frame)[off:])
            if got != want:
                self._corrupt_frame(conn, len(frame), want, got)
        qmeta = None
        if htype & _H_QUANT:
            qmeta = _QHDR.unpack_from(frame, off)
            off += _QHDR.size
        if htype & _H_FAST:
            (cid, src, dst, tag, seq, code, total_len, offset,
             req_id) = _FAST.unpack_from(frame, off)
            data = np.frombuffer(frame, np.uint8,
                                 offset=off + _FAST.size)
            if qmeta is not None:
                data = self._dequant_payload(conn, data, qmeta)
                borrowed = False
            return Frag(cid, src, dst, tag, seq, _CODE_TO_KIND[code],
                        data, total_len, offset,
                        {} if req_id < 0 else {"req_id": req_id},
                        borrowed=borrowed)
        (hlen,) = _LEN.unpack_from(frame, off)
        obj = pickle.loads(
            memoryview(frame)[off + _LEN.size:off + _LEN.size + hlen])
        if isinstance(obj, dict) and "rank" in obj and conn.rank is None:
            conn.rank = obj["rank"]
            # accepted links become reply rails for this rank too
            with self._conns_lock:
                self._by_rank.setdefault(conn.rank, []).append(conn)
            return None
        cid, src, dst, tag, seq, kind, total_len, offset, meta = obj
        data = np.frombuffer(frame, np.uint8,
                             offset=off + _LEN.size + hlen)
        if qmeta is not None:
            data = self._dequant_payload(conn, data, qmeta)
            borrowed = False
        return Frag(cid, src, dst, tag, seq, kind, data,
                    total_len, offset, meta, borrowed=borrowed)

    def _dequant_payload(self, conn: Optional[_Conn], data, qmeta):
        """Receive side of the codec stage: the quant sub-header names
        the codec/raw-length/block, and the decode MUST be exact — any
        inconsistency is wire corruption and fails as loudly as a crc32
        mismatch (show_help + abort event + SanitizeError), never a
        silently-garbage delivery."""
        try:
            return quant_mod.decode_wire(data, qmeta[0], qmeta[1],
                                         qmeta[2])
        except (ValueError, KeyError) as exc:
            from ompi_tpu.base.output import show_help

            peer = _conn_peer(conn)
            show_help("help-coll-quant", "wire-frame-bad",
                      peer=peer, error=str(exc))
            self._wire_fault(
                "quant_wire_decode_fail", peer, len(data),
                "quant wire frame",
                f"btl/tcp quantized frame from rank {peer} does not "
                f"decode ({exc}): wire corruption detected")

    def _corrupt_frame(self, conn: Optional[_Conn], nbytes: int,
                       want: int, got: int) -> None:
        """A checksummed frame failed verification: silent wire
        corruption made loud and attributed."""
        from ompi_tpu.base.output import show_help

        peer = _conn_peer(conn)
        show_help("help-btl-tcp", "frame-corrupt", peer=peer,
                  nbytes=nbytes, want=want, got=got)
        self._wire_fault(
            "wire_cksum_fail", peer, nbytes, "wire corruption",
            f"btl/tcp frame from rank {peer} failed its crc32 "
            f"({nbytes} bytes, want {want:#x} got {got:#x}): wire "
            "corruption detected")

    def _wire_fault(self, counter: str, peer: int, nbytes: int,
                    why: str, message: str) -> None:
        """Shared tail of a wire-integrity trip (crc mismatch, quant
        frame that does not decode — each under its OWN counter/trace
        name so the two fault classes stay distinguishable): counted,
        trace-instant'ed, abort event posted, SanitizeError raised.
        Raising from the progress thread alone would only unregister
        this btl's callback and turn the job into a hang — the abort
        event (and the progress loop re-raising SanitizeError) lets
        the launcher tear the job down with the diagnostic on record."""
        spc.record(counter)
        if trace.enabled:
            trace.instant(counter, "btl",
                          args={"peer": peer, "nbytes": nbytes})
        if self._rte is not None:
            try:
                self._rte.event_notify("abort", {"code": 1, "why": why})
            except Exception:
                pass
        raise sanitizer.SanitizeError(message)

    def close(self) -> None:
        # a closed btl must stop publishing telemetry: the sampler may
        # outlive this object's usefulness and would report frozen
        # queue depths as live data (chaos.uninstall's discipline)
        from ompi_tpu.runtime import telemetry

        telemetry.unregister_source("tcp")
        # flush queued outbound bytes before closing (same delivered-but-
        # unsent exit hazard as btl/sm — see its close())
        deadline = time.monotonic() + 30.0
        while (any(c.outq for c in self._all_conns())
               and time.monotonic() < deadline):
            for conn in self._all_conns():
                if conn.outq:
                    self._flush(conn)
            if any(c.outq for c in self._all_conns()):
                time.sleep(0.0005)
        from ompi_tpu.runtime import progress as progress_mod

        # reactor-owned fds leave the epoll set before their sockets
        # close (an fd closed while still registered would be silently
        # dropped from epoll and could recycle into a new stream)
        if self._reactor:
            for fd, conn in list(self._rconns.items()):
                reactor_mod.remove(fd)
                self._rconns.pop(fd, None)
                conn.fd = None
                try:
                    conn.sock.close()
                except OSError:
                    pass
            if self._listener is not None:
                reactor_mod.remove(self._listener.fileno())
            self._reactor = False
        # every registered socket — including accepted-but-unhandshaked
        # conns that never made it into _by_rank — must leave the global
        # waiter selector, or their EOF-readable fds make idle_wait()
        # busy-spin forever after this btl is gone
        for key in list(self._sel.get_map().values()):
            if key.data == "listener":
                continue
            progress_mod.unregister_waiter(key.fileobj)
            try:
                self._sel.unregister(key.fileobj)
                key.fileobj.close()
            except (OSError, KeyError):
                pass
        with self._conns_lock:
            self._by_rank.clear()
        if self._listener is not None:
            progress_mod.unregister_waiter(self._listener)
            try:
                self._sel.unregister(self._listener)
                self._listener.close()
            except (OSError, KeyError):
                pass
            self._listener = None


COMPONENT = TcpBtl()

from ompi_tpu.base.output import register_help as _rh

_rh("help-btl-tcp", "frame-too-large",
    "btl/tcp was asked to send a {nbytes}-byte frame, above the u32 "
    "length-prefix limit of {limit} bytes.  Fragment the payload below "
    "btl_tcp_max_send_size instead of sending it whole.")
_rh("help-btl-tcp", "frame-corrupt",
    "btl/tcp received a {nbytes}-byte frame from rank {peer} whose "
    "crc32 does not verify (expected {want}, computed {got}): the "
    "bytes were corrupted on the wire.  The job is being aborted — "
    "silent corruption must never reach the application.  (Checksums "
    "are armed under chaos injection and OTPU_SANITIZE.)")
