"""btl/sm — shared-memory transport for same-host ranks.

Re-design of ``/root/reference/opal/mca/btl/sm/`` (per-peer lock-free FIFOs
over a mapped segment, ``btl_sm_component.c:71-77``): each receiver owns one
SPSC byte ring per sender in a ``multiprocessing.shared_memory`` segment
(layout: head u64 | tail u64 | data[cap]), published through the modex.
Writers append length-prefixed pickled fragments when space allows and queue
the rest for retry from the progress loop; readers drain from progress.
8-byte aligned head/tail updates order the SPSC handoff (x86/ARM64
single-writer semantics; the native C++ core provides the fenced variant).
Latency sits between btl/self and btl/tcp, so bml prefers sm for co-located
peers — the reference's exact ordering.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import numpy as np
from multiprocessing import shared_memory, resource_tracker
from typing import Optional

from ompi_tpu.base.containers import Fifo
from ompi_tpu.base.var import VarType
from ompi_tpu.ft import chaos
from ompi_tpu.mca.btl.base import CTL, Btl, Endpoint, Frag, owned_bytes
from ompi_tpu.runtime import profile, trace
from ompi_tpu.runtime.hotpath import hot_path

_HDR = struct.Struct("<QQ")  # head, tail
_LEN = struct.Struct("<I")
_DATA_OFF = _HDR.size


def _as_u8(payload) -> np.ndarray:
    """Zero-copy uint8 view of any contiguous bytes-like payload."""
    if isinstance(payload, np.ndarray):
        return payload.reshape(-1).view(np.uint8)
    return np.frombuffer(payload, np.uint8)


def _frame_hdr(frag: Frag) -> bytes:
    """Pickle the fragment's metadata WITHOUT the payload: the payload
    rides raw after the header so large messages never pay the pickle
    round trip (2 extra full-size copies at 512KB+)."""
    return pickle.dumps(
        (frag.cid, frag.src, frag.dst, frag.tag, frag.seq, frag.kind,
         frag.total_len, frag.offset, frag.meta),
        protocol=pickle.HIGHEST_PROTOCOL)


def _unframe(buf: np.ndarray) -> Frag:
    """Rebuild a Frag from one popped frame; ``data`` is a zero-copy view
    of the ring's REUSED scratch buffer, so the frag is ``borrowed``:
    valid until the next pop — queue points must call ``own_data()``."""
    (hlen,) = _LEN.unpack_from(buf, 0)
    cid, src, dst, tag, seq, kind, total_len, offset, meta = \
        pickle.loads(memoryview(buf)[_LEN.size:_LEN.size + hlen])
    return Frag(cid, src, dst, tag, seq, kind,
                buf[_LEN.size + hlen:], total_len, offset, meta,
                borrowed=True)


class _Ring:
    """SPSC byte ring over a shared memory buffer.

    push/pop run through the native C++ twins (``ompi_tpu.native``
    ring ops, the ``opal_fifo`` analog) when the library is built; the
    layout is identical either way so mixed processes interoperate.
    """

    def __init__(self, shm: shared_memory.SharedMemory, owner: bool):
        self.shm = shm
        self.owner = owner
        self.cap = len(shm.buf) - _DATA_OFF
        if owner:
            _HDR.pack_into(shm.buf, 0, 0, 0)
        self._addr = None
        self._popbuf = None
        self._framebuf = None
        try:
            from ompi_tpu import native

            if native.available():
                import ctypes

                self._native = native
                self._addr = ctypes.addressof(
                    ctypes.c_char.from_buffer(shm.buf))
        except Exception:
            self._addr = None

    def _load(self) -> tuple[int, int]:
        return _HDR.unpack_from(self.shm.buf, 0)

    def push_frame(self, hdr: bytes, payload) -> bool:
        """Push one [u32 hlen][hdr][payload] frame; payload is any
        bytes-like (ndarray views welcome — the gather-push copies them
        straight into the ring, no Python-side concatenation)."""
        a = _LEN.pack(len(hdr)) + hdr
        if self._addr is not None:
            return self._native.ring_push2(
                self._addr, self.cap, np.frombuffer(a, np.uint8),
                _as_u8(payload))
        return self.push(a + owned_bytes(payload))

    def pop_frame(self) -> Optional[np.ndarray]:
        """Pop one frame into a REUSED scratch buffer; returns a view.

        The view is valid until the next pop on this ring — receivers
        must consume it synchronously or take an owned copy (the popped
        Frag is marked ``borrowed`` accordingly).  Reuse matters: a fresh
        1MB numpy allocation per frame costs more in page faults than the
        copy itself."""
        if self._addr is not None:
            n = self._native.ring_peek_len(self._addr, self.cap)
            if n < 0:
                return None
            buf = self._framebuf
            if buf is None or len(buf) < n:
                buf = self._framebuf = np.empty(
                    max(n, 64 * 1024), np.uint8)
            if self._native.ring_pop(self._addr, self.cap, buf) < 0:
                return None
            return buf[:n]
        payload = self.pop()
        if payload is None:
            return None
        return np.frombuffer(payload, np.uint8)

    def push(self, payload: bytes) -> bool:
        if self._addr is not None:
            return self._native.ring_push(
                self._addr, self.cap,
                np.frombuffer(payload, np.uint8))
        head, tail = self._load()
        need = _LEN.size + len(payload)
        free = self.cap - (tail - head)
        if need > free:
            return False
        frame = _LEN.pack(len(payload)) + payload
        pos = tail % self.cap
        first = min(len(frame), self.cap - pos)
        self.shm.buf[_DATA_OFF + pos:_DATA_OFF + pos + first] = frame[:first]
        if first < len(frame):
            self.shm.buf[_DATA_OFF:_DATA_OFF + len(frame) - first] = \
                frame[first:]
        struct.pack_into("<Q", self.shm.buf, 8, tail + len(frame))
        return True

    def pop(self) -> Optional[bytes]:
        if self._addr is not None:
            if self._popbuf is None:   # lazy: outbound rings never pop
                self._popbuf = np.empty(self.cap, np.uint8)
            n = self._native.ring_pop(self._addr, self.cap, self._popbuf)
            if n < 0:
                return None
            return self._popbuf[:n].tobytes()
        head, tail = self._load()
        if tail - head < _LEN.size:
            return None
        pos = head % self.cap

        def read(off: int, n: int) -> bytes:
            p = (pos + off) % self.cap
            first = min(n, self.cap - p)
            out = bytes(self.shm.buf[_DATA_OFF + p:_DATA_OFF + p + first])
            if first < n:
                out += bytes(self.shm.buf[_DATA_OFF:_DATA_OFF + n - first])
            return out

        (n,) = _LEN.unpack(read(0, _LEN.size))
        if tail - head < _LEN.size + n:
            return None  # writer mid-frame
        payload = read(_LEN.size, n)
        struct.pack_into("<Q", self.shm.buf, 0, head + _LEN.size + n)
        return payload


def _attach(name: str) -> shared_memory.SharedMemory:
    shm = shared_memory.SharedMemory(name=name)
    # CPython's resource tracker would unlink segments we merely attached
    # to; the owner is responsible for cleanup (well-known workaround).
    try:
        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass
    return shm


class SmBtl(Btl):
    name = "sm"
    priority = 50
    # shared memory pays per-handoff (scheduling + matching) cost, not
    # per-byte: with the zero-copy send path a single big eager frame is
    # one ring write, while RNDV costs 3 handoffs — measured ~2x on the
    # 512KB pingpong (see BENCH_SWEEP.md host rows).  The 4MB ring
    # comfortably holds two in-flight 512KB frames per peer.
    eager_limit = 512 * 1024
    rndv_eager_limit = 512 * 1024
    max_send_size = 1024 * 1024
    latency = 10          # below tcp (100), above self (0)
    bandwidth = 10000

    def __init__(self) -> None:
        super().__init__()
        self._rte = None
        self._rings_in: dict[int, _Ring] = {}    # per-sender, I own these
        self._rings_out: dict[int, _Ring] = {}   # per-receiver, attached
        self._pending: dict[int, Fifo] = {}
        self._db_rx: Optional[socket.socket] = None   # my doorbell
        self._db_tx: Optional[socket.socket] = None   # ring peers' bells
        self._db_addr: dict[int, str] = {}            # rank -> bell address
        # node identity, not raw hostname: OTPU_NODE_ID partitions ranks
        # into emulated nodes (tpurun --fake-nodes / multi-host launchers),
        # and shared memory must not be offered across that boundary so
        # inter-node traffic honestly exercises the DCN (tcp) path
        self._hostname = os.environ.get("OTPU_NODE_ID", socket.gethostname())
        self._ring_size = 4 << 20
        # doorbell registered with the native reactor (MODE_DRAIN): the
        # epoll thread consumes the dgrams and its notify eventfd wakes
        # idle_wait — the Python drain loop in progress() is skipped
        self._db_reactor = False

    def _clamped(self, limit: int) -> int:
        """A frame larger than the ring can NEVER be pushed (push would
        retry forever) — bound protocol limits to half the capacity minus
        framing/pickle slack, so two in-flight max frags always fit
        (btl.h's limits are likewise bounded by transport buffer sizes)."""
        return min(int(limit), max(1024, self._ring_size // 2 - 4096))

    def register_vars(self, fw) -> None:
        self.register_var(
            "ring_size", vtype=VarType.SIZE, default="4m",
            help="Per-peer shared-memory FIFO capacity (takes effect at "
                 "setup; rings are not resized after init)",
            on_set=lambda v: setattr(self, "_ring_size", int(v)))
        self.register_var(
            "eager_limit", vtype=VarType.SIZE, default="512k",
            help="Max eager message size over sm",
            on_set=lambda v: setattr(self, "eager_limit", self._clamped(v)))

    def setup(self, rte) -> bool:
        if rte.is_device_world or rte.world_size <= 1:
            return False
        if not hasattr(rte, "modex_put"):
            return False
        self._rte = rte
        self.max_send_size = self._clamped(self.max_send_size)
        self.eager_limit = self._clamped(self.eager_limit)
        self.rndv_eager_limit = self._clamped(self.rndv_eager_limit)
        me = rte.my_world_rank
        job = os.environ.get("OTPU_COORD", "local").replace(":", "_") \
            .replace(".", "_")
        names = {}
        # inbound rings for my job's peers (global ranks under dpm); a
        # cross-job peer has no preallocated ring and `reachable` declines
        # it, falling back to btl/tcp
        for src in getattr(rte, "job_ranks", range(rte.world_size)):
            if src == me:
                continue
            name = f"otpu_{job}_{src}_{me}_{os.getpid() & 0xffff}"
            shm = shared_memory.SharedMemory(
                name=name, create=True, size=self._ring_size + _DATA_OFF)
            self._rings_in[src] = _Ring(shm, owner=True)
            names[src] = name
        # doorbell: an abstract unix dgram socket peers ping after pushing
        # a frame, so an idle receiver blocked in progress.idle_wait wakes
        # immediately instead of sleeping out its backoff (the wakeup role
        # the reference gets from libevent + btl_sm's fifo signalling)
        db_name = None
        try:
            from ompi_tpu.runtime import progress as progress_mod

            db = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
            db.setblocking(False)
            db_name = f"\0otpu_db_{job}_{me}_{os.getpid() & 0xffff}"
            db.bind(db_name)
            self._db_rx = db
            self._db_tx = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
            self._db_tx.setblocking(False)
            from ompi_tpu.runtime import reactor as reactor_mod

            self._db_reactor = reactor_mod.engage() and reactor_mod.add(
                db.fileno(), reactor_mod.MODE_DRAIN,
                self._on_doorbell_record)
            if not self._db_reactor:
                progress_mod.register_waiter(db)
        except OSError:
            self._db_rx = self._db_tx = None
            db_name = None
        rte.modex_put("btl_sm_rings", {"host": self._hostname,
                                       "names": names, "db": db_name})
        return True

    def _ring_doorbell(self, rank: int, info: Optional[dict] = None) -> None:
        if self._db_tx is None:
            return
        db = info.get("db") if info is not None else self._db_addr.get(rank)
        if db is None:
            return
        try:
            self._db_tx.sendto(b"x", db)
        except OSError:
            pass  # full/absent: receiver still polls on its own cadence

    def reachable(self, world_rank: int, rte) -> Optional[Endpoint]:
        if self._rte is None or world_rank == rte.my_world_rank:
            return None
        # non-blocking probe: same-job peers are guaranteed published by
        # the init fence; a peer that hasn't published (a 1-rank dpm job
        # never runs sm setup at all) must not stall the bml — tcp is the
        # universal fallback
        info = rte.modex_get(world_rank, "btl_sm_rings", wait=False)
        if info is None or info["host"] != self._hostname:
            return None
        if rte.my_world_rank not in info["names"]:
            return None   # peer has no inbound ring for me (cross-job)
        return Endpoint(self, world_rank, addr=info)

    def _ring_to(self, rank: int, info: dict) -> _Ring:
        ring = self._rings_out.get(rank)
        if ring is None:
            name = info["names"][self._rte.my_world_rank]
            ring = _Ring(_attach(name), owner=False)
            self._rings_out[rank] = ring
            if info.get("db") is not None:
                self._db_addr[rank] = info["db"]
        return ring

    @hot_path
    def send(self, ep: Endpoint, frag: Frag) -> None:
        chaos_dup = False
        if chaos.enabled:
            rule = chaos.wire_send("sm", frag.kind == CTL)
            if rule is not None:
                fault = rule["fault"]
                if fault == "drop":
                    return          # best-effort CTL frame lost
                if fault == "delay":
                    chaos.sleep_ms(rule)
                chaos_dup = fault == "dup"
        ring = self._ring_to(ep.world_rank, ep.addr)
        # stage clock: header build + enqueue attempt is send.queue;
        # the ring write itself (the sm "wire") is send.wire
        _pt = profile.now() if profile.enabled else 0
        hdr = _frame_hdr(frag)
        if chaos_dup:
            # framing-level duplicate of an idempotent CTL frame
            if not ring.push_frame(hdr, frag.data):
                self._pending.setdefault(ep.world_rank, Fifo()).push(
                    (hdr, owned_bytes(frag.data)))
        if profile.enabled:
            profile.stage_span("send.queue", _pt)
        # the ring write is sm's "wire": traced like tcp's btl_sendmsg
        # so the critical path's wire bucket sees same-host traffic too
        # (the frame header carries the flow key ride-along — the full
        # pickled (src, seq) match header, see _frame_hdr)
        _t0 = trace.now() if (trace.enabled or profile.enabled) else 0
        if not ring.push_frame(hdr, frag.data):
            # defer with an OWNED payload copy: the caller's request may
            # complete (eager) and the user reuse the buffer before the
            # retry fires from the progress loop
            self._pending.setdefault(ep.world_rank, Fifo()).push(
                (hdr, owned_bytes(frag.data)))
        if trace.enabled or profile.enabled:
            t1 = trace.now()
            if trace.enabled:
                nb = getattr(frag.data, "nbytes", None)
                if nb is None:
                    nb = len(frag.data)
                trace.span("btl_ringpush", "btl", _t0, t1,
                           args={"nbytes": int(nb),
                                 "peer": ep.world_rank})
                trace.hist_record("btl_ringpush", int(nb), t1 - _t0)
            if profile.enabled:
                profile.stage_span("send.wire", _t0, t1)
        self._ring_doorbell(ep.world_rank, ep.addr)

    def _on_doorbell_record(self, etype: int, payload) -> int:
        """Reactor DOORBELL record: the dgrams were already consumed on
        the epoll thread and the notify eventfd woke any idle waiter —
        the ring drain below runs on this same progress tick, so there
        is nothing left to do here (the record IS the wakeup)."""
        return 0

    @hot_path
    def progress(self) -> int:
        events = 0
        # drain doorbell pings (edge signal only; frames carry the
        # data); with the reactor engaged the epoll thread consumed them
        if self._db_rx is not None and not self._db_reactor:
            while True:
                try:
                    self._db_rx.recv(512)
                except (BlockingIOError, InterruptedError):
                    break
                except OSError:
                    break
        # drain incoming rings
        for ring in self._rings_in.values():
            while True:
                buf = ring.pop_frame()
                if buf is None:
                    break
                if self._recv_cb is not None:
                    _pt = profile.now() if profile.enabled else 0
                    frag = _unframe(buf)
                    if profile.enabled:
                        profile.stage_span("recv.parse", _pt)
                    if chaos.enabled:
                        rule = chaos.wire_recv("sm", frag.kind == CTL)
                        if rule is not None:
                            fault = rule["fault"]
                            if fault == "delay":
                                chaos.sleep_ms(rule)
                            elif fault == "drop" and frag.kind == CTL:
                                continue   # delivery withheld
                            elif fault == "dup" and frag.kind == CTL:
                                self._recv_cb(_unframe(buf))
                    self._recv_cb(frag)
                    events += 1
        # retry pending writes
        for rank, fifo in self._pending.items():
            ring = self._rings_out.get(rank)
            if ring is None:
                continue
            while len(fifo):
                hdr, payload = fifo.pop()
                if not ring.push_frame(hdr, payload):
                    # put it back at the front by re-queueing a marker fifo
                    newf = Fifo()
                    newf.push((hdr, payload))
                    while len(fifo):
                        newf.push(fifo.pop())
                    self._pending[rank] = newf
                    break
                self._ring_doorbell(rank)
                events += 1
        return events

    # -- one-sided RMA (btl.h:949 put / :987 get) ------------------------
    #
    # Same-host "RDMA" is a mapped-segment copy: prepare_src stages the
    # contiguous bytes into a shared-memory segment (one copy); the peer's
    # get() copies straight into its destination buffer (one copy).  Two
    # copies and ONE ring handoff total — the rendezvous stream costs
    # three copies and a frame per max_send_size.  Segments are POOLED by
    # size class and peers CACHE their attachments (a registration cache,
    # opal rcache's role): creating + faulting a fresh multi-MB mapping
    # per message costs more than the copies themselves.
    rdma = True
    _RMA_POOL_CAP = 8

    def prepare_src(self, ep: Endpoint, arr) -> dict:
        src = _as_u8(arr)
        # pow2 size class with a 64KB floor
        size = 1 << max(16, (int(len(src)) - 1).bit_length())
        pool = getattr(self, "_rma_pool", None)
        if pool is None:
            pool = self._rma_pool = {}
            self._exposed = {}
        shm = None
        free = pool.get(size)
        if free:
            shm = free.pop()
        if shm is None:
            seq = self._expose_seq = getattr(self, "_expose_seq", 0) + 1
            name = (f"otpu_rg_{self._rte.my_world_rank}_"
                    f"{os.getpid() & 0xffff}_{seq}")
            shm = shared_memory.SharedMemory(name=name, create=True,
                                             size=size)
        np.copyto(np.frombuffer(shm.buf, np.uint8, count=len(src)), src)
        self._exposed[shm.name] = shm
        return {"btl": "sm", "seg": shm.name, "size": size,
                "nbytes": int(len(src))}

    def release_src(self, key: dict) -> None:
        shm = getattr(self, "_exposed", {}).pop(key["seg"], None)
        if shm is None:
            return
        pool = self._rma_pool.setdefault(key["size"], [])
        if len(pool) < self._RMA_POOL_CAP:
            pool.append(shm)   # keep warm: name is stable, peers stay
            return             # attached across reuses
        try:
            shm.close()
            shm.unlink()
        except OSError:
            pass

    def _rma_attach(self, name: str) -> shared_memory.SharedMemory:
        cache = getattr(self, "_attached", None)
        if cache is None:
            cache = self._attached = {}
        shm = cache.get(name)
        if shm is None:
            shm = cache[name] = _attach(name)
            while len(cache) > 4 * self._RMA_POOL_CAP:
                oldest = next(iter(cache))   # insertion order: never the
                if oldest == name:           # entry just added
                    break
                old = cache.pop(oldest)
                try:
                    old.close()
                except OSError:
                    pass
        return shm

    def get(self, ep: Endpoint, local, remote_key: dict) -> None:
        dst = _as_u8(local)
        n = min(len(dst), remote_key["nbytes"])
        shm = self._rma_attach(remote_key["seg"])
        np.copyto(dst[:n], np.frombuffer(shm.buf, np.uint8, count=n))

    def put(self, ep: Endpoint, local, remote_key: dict) -> None:
        src = _as_u8(local)
        n = min(len(src), remote_key["nbytes"])
        shm = self._rma_attach(remote_key["seg"])
        np.copyto(np.frombuffer(shm.buf, np.uint8, count=n), src[:n])

    def close(self) -> None:
        # Flush queued writes before teardown: a request may complete once
        # its frags are packed, so exiting with a non-empty pending queue
        # would silently drop delivered-but-unsent data (the receiver is
        # still draining its ring — give it a bounded window).
        import time as _time

        from ompi_tpu.ft import state as _ft_state

        def _undeliverable(rank: int) -> bool:
            return _ft_state.is_failed(rank)

        deadline = _time.monotonic() + 30.0
        while _time.monotonic() < deadline:
            live_pending = {r: f for r, f in self._pending.items()
                            if len(f) and not _undeliverable(r)}
            if not live_pending:
                break
            if self.progress() == 0:
                _time.sleep(0.0005)
        if self._db_rx is not None:
            if self._db_reactor:
                from ompi_tpu.runtime import reactor as reactor_mod

                reactor_mod.remove(self._db_rx.fileno())
                self._db_reactor = False
            else:
                from ompi_tpu.runtime import progress as progress_mod

                progress_mod.unregister_waiter(self._db_rx)
            try:
                self._db_rx.close()
            except OSError:
                pass
            self._db_rx = None
        if self._db_tx is not None:
            try:
                self._db_tx.close()
            except OSError:
                pass
            self._db_tx = None
        for ring in self._rings_out.values():
            try:
                ring.shm.close()
            except Exception:
                pass
        for ring in self._rings_in.values():
            try:
                ring.shm.close()
                ring.shm.unlink()
            except Exception:
                pass
        self._rings_in.clear()
        self._rings_out.clear()
        for shm in getattr(self, "_attached", {}).values():
            try:
                shm.close()
            except OSError:
                pass
        if hasattr(self, "_attached"):
            self._attached.clear()
        pool_segs = [s for segs in getattr(self, "_rma_pool", {}).values()
                     for s in segs]
        for shm in list(getattr(self, "_exposed", {}).values()) + pool_segs:
            try:
                shm.close()
                shm.unlink()
            except OSError:
                pass
        if hasattr(self, "_exposed"):
            self._exposed.clear()
        if hasattr(self, "_rma_pool"):
            self._rma_pool.clear()


COMPONENT = SmBtl()
