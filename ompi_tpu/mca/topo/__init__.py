"""topo — process topologies (``/root/reference/ompi/mca/topo/``).

Cartesian, graph, and distributed-graph topologies attached to
communicators, plus the rank-reordering hook (the reference's
``topo/treematch`` maps ranks onto the hardware tree; TPU-native, the
equivalent is mapping a cartesian grid onto the ICI device mesh so cart
neighbors are one ICI hop apart).
"""
from __future__ import annotations

from ompi_tpu.mca.topo.base import (CartTopo, DistGraphTopo, GraphTopo,
                                    dims_create)

__all__ = ["CartTopo", "GraphTopo", "DistGraphTopo", "dims_create"]
