"""Topology objects + the MPI_Dims_create factorizer.

Re-design of ``/root/reference/ompi/mca/topo/base/`` (cart/graph/
dist_graph machinery: ``topo_base_cart_create.c``, ``topo_base_graph_*``,
``topo_base_dist_graph_*``): topologies are value objects attached to
``comm.topo``; creation routines live on ``Comm`` (``cart_create`` etc).
The TPU angle: a cartesian topology whose dims match the ICI mesh shape
is the natural carrier for mesh-axis collectives — ``cart_shift`` +
``sendrecv`` is exactly ``lax.ppermute`` along one mesh axis, and
``cart_sub`` is a mesh-axis subset.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.api.status import PROC_NULL


def dims_create(nnodes: int, ndims: int,
                dims: Optional[Sequence[int]] = None) -> list[int]:
    """``MPI_Dims_create``: balanced factorization of nnodes over ndims.

    Mirrors ``topo_base_dims_create.c``: fixed (nonzero) entries are
    honored; free (zero) entries get the remaining factors as evenly as
    possible, in decreasing order.
    """
    out = list(dims) if dims is not None else [0] * ndims
    if len(out) != ndims:
        raise MpiError(ErrorClass.ERR_DIMS, "dims length != ndims")
    fixed = 1
    for d in out:
        if d < 0:
            raise MpiError(ErrorClass.ERR_DIMS, f"negative dim {d}")
        if d > 0:
            fixed *= d
    free_idx = [i for i, d in enumerate(out) if d == 0]
    if not free_idx:
        if fixed != nnodes:
            raise MpiError(ErrorClass.ERR_DIMS,
                           f"dims product {fixed} != nnodes {nnodes}")
        return out
    rem, check = divmod(nnodes, fixed)
    if check:
        raise MpiError(ErrorClass.ERR_DIMS,
                       f"nnodes {nnodes} not divisible by fixed dims {fixed}")
    # prime-factorize the remainder, largest factors first, round-robin the
    # smallest current free dim (keeps the grid as square as possible)
    factors = []
    n = rem
    p = 2
    while p * p <= n:
        while n % p == 0:
            factors.append(p)
            n //= p
        p += 1
    if n > 1:
        factors.append(n)
    sizes = [1] * len(free_idx)
    for f in sorted(factors, reverse=True):
        sizes[sizes.index(min(sizes))] *= f
    for i, s in zip(free_idx, sorted(sizes, reverse=True)):
        out[i] = s
    return out


class CartTopo:
    """Cartesian topology (``mca_topo_base_comm_cart_2_2_0_t``)."""

    kind = "cart"

    def __init__(self, dims: Sequence[int], periods: Sequence[bool]) -> None:
        self.dims = list(dims)
        self.periods = list(periods)
        self.ndims = len(self.dims)
        self.size = int(np.prod(self.dims)) if self.dims else 1

    # row-major rank<->coords (reference convention, cart_rank.c)
    def rank_of(self, coords: Sequence[int]) -> int:
        rank = 0
        for dim, period, c in zip(self.dims, self.periods, coords):
            if period:
                c = c % dim
            elif not 0 <= c < dim:
                return PROC_NULL
            rank = rank * dim + c
        return rank

    def coords_of(self, rank: int) -> list[int]:
        coords = []
        for dim in reversed(self.dims):
            coords.append(rank % dim)
            rank //= dim
        return list(reversed(coords))

    def shift(self, rank: int, direction: int, disp: int) -> tuple[int, int]:
        """``MPI_Cart_shift`` → (source, dest) ranks (PROC_NULL at edges)."""
        if not 0 <= direction < self.ndims:
            raise MpiError(ErrorClass.ERR_DIMS,
                           f"invalid direction {direction}")
        here = self.coords_of(rank)
        up = list(here)
        up[direction] += disp
        down = list(here)
        down[direction] -= disp
        return self.rank_of(down), self.rank_of(up)

    def neighbors(self, rank: int) -> tuple[list[int], list[int]]:
        """(sources, destinations) in dimension order, -disp then +disp —
        the neighbor-collective ordering of ``MPI_NEIGHBOR_ALLTOALL`` on
        cartesian comms."""
        srcs, dsts = [], []
        for d in range(self.ndims):
            minus, plus = self.shift(rank, d, 1)
            srcs += [minus, plus]
            dsts += [minus, plus]
        return srcs, dsts


class GraphTopo:
    """Classic graph topology (index/edges arrays, ``MPI_Graph_create``)."""

    kind = "graph"

    def __init__(self, index: Sequence[int], edges: Sequence[int]) -> None:
        self.index = list(index)
        self.edges = list(edges)
        self.size = len(self.index)

    def neighbors_of(self, rank: int) -> list[int]:
        lo = self.index[rank - 1] if rank > 0 else 0
        return self.edges[lo:self.index[rank]]

    def neighbors(self, rank: int) -> tuple[list[int], list[int]]:
        ns = self.neighbors_of(rank)
        return ns, ns


class DistGraphTopo:
    """Distributed graph (``MPI_Dist_graph_create_adjacent``)."""

    kind = "dist_graph"

    def __init__(self, sources: Sequence[int], destinations: Sequence[int],
                 sourceweights=None, destweights=None) -> None:
        self.sources = list(sources)
        self.destinations = list(destinations)
        self.sourceweights = (list(sourceweights) if sourceweights is not None
                              else [1] * len(self.sources))
        self.destweights = (list(destweights) if destweights is not None
                            else [1] * len(self.destinations))

    def neighbors(self, rank: int) -> tuple[list[int], list[int]]:
        return self.sources, self.destinations
