"""MCA ``threads`` framework — the host-path threading substrate.

Reference: ``opal/mca/threads/`` — the pluggable layer (pthreads,
argobots, qthreads) everything above uses for threads, mutexes, and
condition variables, so the whole stack can be rebuilt on a different
concurrency substrate at configure time.

The TPU-native translation: Python-level thread *API* concurrency is
absorbed by :mod:`threading` (and stays GIL-serialised — see
COVERAGE.md), so what this framework actually provides is the part the
GIL takes away: a worker pool executing the host data path's tight
loops (memcpy, datatype pack/unpack, elementwise reduction math) as
pure native code in true parallel.  Components compete to provide the
pool; ``threads/native`` backs it with the C++ pool in
``native/otpu_native.cc``, ``threads/python`` is the degraded but
always-available fallback.
"""
