"""threads/native — the C++ worker pool (true no-GIL parallelism).

Reference analog: ``opal/mca/threads/pthreads`` — the default
OS-thread backend.  Jobs are split into per-worker chunks inside the
native library (``otpu_native.cc``); the submitting ctypes call drops
the GIL, so pack/reduce/copy genuinely overlap Python execution.
"""
from __future__ import annotations

import threading

import numpy as np

from ompi_tpu import native
from ompi_tpu.mca.threads import base


class _NativeWork(base.Work):
    """Completion handle; ``_keep`` pins arrays whose raw pointers the
    queued native chunks still dereference (segment tables)."""

    def __init__(self, ticket: int, keep=()):
        self._ticket = ticket
        self._keep = keep
        self._done = False
        self._lock = threading.Lock()

    def _complete(self) -> None:
        # single pool_wait under the lock: the ticket is freed exactly
        # once even when test() and wait() race from two threads
        with self._lock:
            if not self._done:
                native.pool_wait(self._ticket)
                self._done = True
                self._keep = ()

    def test(self) -> bool:
        # the poll must also run under the lock: a concurrent wait()
        # frees the ticket, and pool_test on a freed ticket is UB
        with self._lock:
            if not self._done and native.pool_test(self._ticket):
                # ticket memory is freed by pool_wait — completion via
                # test() must still run it (it returns immediately)
                native.pool_wait(self._ticket)
                self._done = True
                self._keep = ()
            return self._done

    def wait(self) -> None:
        self._complete()

    def __del__(self):
        # an abandoned handle must still free its ticket; the queued
        # chunks always drain (workers only exit after the queue is
        # empty), so this wait is bounded
        try:
            self._complete()
        except Exception:
            pass   # interpreter teardown: the process is going away


def _addr(a: np.ndarray) -> int:
    if not a.flags.c_contiguous:
        raise ValueError("pool jobs need C-contiguous arrays")
    return a.ctypes.data


class NativePool(base.WorkPool):
    parallel_pack = True

    def __init__(self, nworkers: int):
        self._h = native.pool_create(nworkers)
        self.size = native.pool_size(self._h)

    def memcpy(self, dst, src):
        if dst.nbytes != src.nbytes:
            raise ValueError("memcpy size mismatch")
        # keep=: the queued chunks hold raw buffer addresses — the
        # handle must pin the arrays until the workers ran
        return _NativeWork(native.pool_memcpy(
            self._h, _addr(dst), _addr(src), src.nbytes),
            keep=(dst, src))

    def reduce(self, op, acc, src):
        dt = str(acc.dtype)
        if (op not in native.POOL_OPS or dt not in native.POOL_DTYPES
                or acc.shape != src.shape or src.dtype != acc.dtype):
            raise ValueError(
                f"unsupported reduce: {op} {dt} vs {src.dtype}")
        return _NativeWork(native.pool_reduce(
            self._h, op, dt, _addr(acc), _addr(src), acc.size),
            keep=(acc, src))

    def pack(self, mem, out, seg_off, seg_len, extent, base_offset,
             first_elem, nelem):
        so = np.ascontiguousarray(seg_off, np.int64)
        sl = np.ascontiguousarray(seg_len, np.int64)
        # keep=(so, sl): the queued chunks hold these arrays' raw
        # pointers until the workers ran (conversion may have copied)
        return _NativeWork(native.pool_pack(
            self._h, mem, out, so, sl, extent, base_offset,
            first_elem, nelem), keep=(so, sl, mem, out))

    def unpack(self, mem, chunk, seg_off, seg_len, extent, base_offset,
               first_elem, nelem):
        so = np.ascontiguousarray(seg_off, np.int64)
        sl = np.ascontiguousarray(seg_len, np.int64)
        return _NativeWork(native.pool_unpack(
            self._h, mem, chunk, so, sl, extent, base_offset,
            first_elem, nelem), keep=(so, sl, mem, chunk))

    def close(self) -> None:
        if self._h:
            native.pool_destroy(self._h)
            self._h = 0


def substrate() -> dict:
    """Capability report of the native substrate this component fronts:
    which otpu_native tiers compiled in (worker pool, ring ops, the
    progress reactor) and whether the reactor is live in THIS process.
    Surfaced by ``otpu_info --progress`` and the threads telemetry so a
    slow run can be attributed to a missing toolchain at a glance."""
    from ompi_tpu.runtime import reactor

    return {"available": native.available(),
            "pool": native.available(),
            "reactor": native.reactor_supported(),
            "reactor_active": reactor.active()}


class NativeThreadsComponent(base.ThreadsComponent):
    name = "native"
    priority = 40

    def open(self) -> bool:
        return native.available()

    def make_pool(self, nworkers: int) -> base.WorkPool:
        return NativePool(nworkers)


COMPONENT = NativeThreadsComponent()
