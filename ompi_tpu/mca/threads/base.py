"""threads framework base: the WorkPool contract + process-global pool.

Reference: ``opal/mca/threads/thread.h`` (create/join et al.) collapses
here to one surface — a work pool with typed jobs — because the jobs
the reference spreads across raw threads (progress loops, pack engines,
reduction math) are exactly the typed loops the native core implements.

Jobs return a :class:`Work` handle (``test``/``wait``), mirroring the
request-completion idiom of the rest of the stack so callers can overlap
a background pack with their own work and complete it like any request.
"""
from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from ompi_tpu.base import mca


class Work:
    """Completion handle for one submitted pool job."""

    def test(self) -> bool:  # pragma: no cover - interface
        raise NotImplementedError

    def wait(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class CompletedWork(Work):
    """Already-done job (inline execution paths)."""

    def test(self) -> bool:
        return True

    def wait(self) -> None:
        return


class WorkPool:
    """The substrate contract: typed parallel jobs over ``size`` workers.

    All addresses are raw byte addresses (``ndarray.ctypes.data``);
    arrays passed whole must be C-contiguous.  The caller owns buffer
    lifetimes until ``wait`` returns — the ``memchecker`` freeze idiom
    applies exactly as it does to nonblocking sends.
    """

    size: int = 1
    #: True when pack/unpack actually run as parallel native loops —
    #: the convertor only fans out when the substrate makes it a win
    parallel_pack: bool = False

    def memcpy(self, dst: np.ndarray, src: np.ndarray) -> Work:
        raise NotImplementedError  # pragma: no cover - interface

    def reduce(self, op: str, acc: np.ndarray,
               src: np.ndarray) -> Work:
        """Elementwise ``acc = acc <op> src`` (sum/prod/max/min)."""
        raise NotImplementedError  # pragma: no cover - interface

    def pack(self, mem: np.ndarray, out: np.ndarray, seg_off, seg_len,
             extent: int, base_offset: int, first_elem: int,
             nelem: int) -> Work:
        raise NotImplementedError  # pragma: no cover - interface

    def unpack(self, mem: np.ndarray, chunk: np.ndarray, seg_off,
               seg_len, extent: int, base_offset: int, first_elem: int,
               nelem: int) -> Work:
        raise NotImplementedError  # pragma: no cover - interface

    def close(self) -> None:  # pragma: no cover - hook
        pass


class ThreadsComponent(mca.Component):
    """A threads component builds WorkPools."""

    def make_pool(self, nworkers: int) -> WorkPool:
        raise NotImplementedError  # pragma: no cover - interface


class InlineSerialPool(WorkPool):
    """Threadless fallback handed out after the permanent (finalize)
    ``shutdown_pool``: no new native/OS worker threads may be spawned
    past teardown — the basic jobs execute inline on the caller's
    thread.  ``size == 1`` / ``parallel_pack = False`` keep every
    fan-out site (op host reductions, convertor packs) on its serial
    path, so pack/unpack are never reached and inherit the base
    NotImplementedError."""

    size = 1
    parallel_pack = False

    def memcpy(self, dst: np.ndarray, src: np.ndarray) -> Work:
        if dst.nbytes != src.nbytes:
            raise ValueError("memcpy size mismatch")
        if not (dst.flags.c_contiguous and src.flags.c_contiguous):
            raise ValueError("pool jobs need C-contiguous arrays")
        dst.reshape(-1).view(np.uint8)[:] = src.reshape(-1).view(np.uint8)
        return CompletedWork()

    def reduce(self, op: str, acc: np.ndarray, src: np.ndarray) -> Work:
        ufunc = {"sum": np.add, "prod": np.multiply,
                 "max": np.maximum, "min": np.minimum}.get(op)
        if (ufunc is None or acc.shape != src.shape
                or src.dtype != acc.dtype):
            raise ValueError(f"unsupported reduce: {op}")
        if not acc.flags.c_contiguous:
            raise ValueError("pool jobs need C-contiguous arrays")
        a = acc.reshape(-1)
        ufunc(a, src.reshape(-1), out=a)
        return CompletedWork()


_pool: Optional[WorkPool] = None
_pool_lock = threading.Lock()
_shut_down = False


def framework() -> mca.Framework:
    return mca.framework("threads", "host-path threading substrate")


def default_workers() -> int:
    import os

    var = mca.registry.lookup("otpu_threads_pool_workers")
    if var is not None and int(var.value) > 0:
        return int(var.value)
    # a single-core host gets ONE worker: pool.size==1 makes every
    # fan-out site (convertor packs, host reductions) keep its serial
    # path — steady-state the pool is ~neutral there (bench
    # threads_pool_pack_4MB row: ~0.98x warm), but with no second core
    # there is nothing to win, and the serial path skips worker
    # startup and cross-thread traffic entirely
    return max(1, min(4, os.cpu_count() or 1))


def get_pool() -> WorkPool:
    """Process-global pool from the selected component (lazy).

    After the permanent (finalize) ``shutdown_pool`` callers get an
    inline-serial pool: a host reduction or pack racing finalize must
    not respawn native worker threads the runtime just joined — the
    lazy recreation here used to do exactly that.  A plain
    ``shutdown_pool()`` keeps the lazy rebuild: bench and tests use it
    to reconfigure the worker count."""
    global _pool
    with _pool_lock:
        if _shut_down:
            return InlineSerialPool()
        if _pool is None:
            comp = framework().select()
            if comp is None:  # python component always opens; belt+braces
                from ompi_tpu.mca.threads.python import COMPONENT as comp
            _pool = comp.make_pool(default_workers())
        return _pool


def shutdown_pool(permanent: bool = False) -> None:
    """Close the pool.  ``permanent=True`` (runtime finalize) also bars
    lazy recreation until :func:`reopen_pool` — the next re-init."""
    global _pool, _shut_down
    with _pool_lock:
        if permanent:
            _shut_down = True
        if _pool is not None:
            _pool.close()
            _pool = None


def reopen_pool() -> None:
    """Re-arm lazy pool creation (runtime re-init after a finalize)."""
    global _shut_down
    with _pool_lock:
        _shut_down = False


def _reset_after_fork() -> None:
    # native worker threads do not survive fork(): drop the handle (the
    # child rebuilds lazily) and renew the lock in case the parent held
    # it mid-fork.  The reference's substrate has the same rule — OS
    # threads are per-process (opal/mca/threads).
    global _pool, _pool_lock, _shut_down
    _pool_lock = threading.Lock()
    _pool = None
    _shut_down = False


import os as _os  # noqa: E402  (registration must follow the handler)

if hasattr(_os, "register_at_fork"):
    _os.register_at_fork(after_in_child=_reset_after_fork)


mca.registry.register(
    "threads", "pool", "workers",
    vtype=mca.VarType.INT, default=0,
    help="Worker count for the threads framework's work pool "
         "(0 = auto: min(4, cpu_count))")
