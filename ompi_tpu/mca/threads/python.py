"""threads/python — ThreadPoolExecutor fallback substrate.

Always available; numpy releases the GIL inside its own ufunc/copy
loops, so large jobs still overlap, but chunking and dispatch pay
Python costs the native component doesn't.  Plays the role of the
reference's configure-time fallback when no better substrate exists.
"""
from __future__ import annotations

from concurrent.futures import Future, ThreadPoolExecutor

import numpy as np

from ompi_tpu.mca.threads import base

_UFUNC = {"sum": np.add, "prod": np.multiply,
          "max": np.maximum, "min": np.minimum}


class _FutureWork(base.Work):
    def __init__(self, futures: list[Future]):
        self._futures = futures

    def test(self) -> bool:
        return all(f.done() for f in self._futures)

    def wait(self) -> None:
        for f in self._futures:
            f.result()


class PythonPool(base.WorkPool):
    def __init__(self, nworkers: int):
        self.size = max(1, nworkers)
        self._ex = ThreadPoolExecutor(
            max_workers=self.size, thread_name_prefix="otpu-threads")

    def _spans(self, n: int, grain: int):
        pieces = max(1, min(self.size, n // grain))
        per, rem = divmod(n, pieces)
        at = 0
        for i in range(pieces):
            ln = per + (1 if i < rem else 0)
            yield at, ln
            at += ln

    def memcpy(self, dst, src):
        if dst.nbytes != src.nbytes:
            raise ValueError("memcpy size mismatch")
        if not (dst.flags.c_contiguous and src.flags.c_contiguous):
            # same contract as the native substrate — reshape(-1) on a
            # non-contiguous dst would silently write into a copy
            raise ValueError("pool jobs need C-contiguous arrays")
        d = dst.reshape(-1).view(np.uint8)
        s = src.reshape(-1).view(np.uint8)
        futs = [self._ex.submit(
            lambda a, ln, d=d, s=s: d.__setitem__(
                slice(a, a + ln), s[a:a + ln]), at, ln)
            for at, ln in self._spans(d.nbytes, 1 << 16)]
        return _FutureWork(futs)

    def reduce(self, op, acc, src):
        # same contract as the native substrate (components must be
        # interchangeable): matching shapes AND dtypes only
        if (op not in _UFUNC or acc.shape != src.shape
                or src.dtype != acc.dtype):
            raise ValueError(f"unsupported reduce: {op}")
        if not acc.flags.c_contiguous:
            raise ValueError("pool jobs need C-contiguous arrays")
        uf = _UFUNC[op]
        a = acc.reshape(-1)
        s = src.reshape(-1)
        futs = [self._ex.submit(
            lambda at, ln: uf(a[at:at + ln], s[at:at + ln],
                              out=a[at:at + ln]), at, ln)
            for at, ln in self._spans(a.size, 1 << 14)]
        return _FutureWork(futs)

    def _packish(self, packing, mem, stream, seg_off, seg_len, extent,
                 base_offset, first_elem, nelem):
        seg_off = np.asarray(seg_off, np.int64)
        seg_len = np.asarray(seg_len, np.int64)
        elem_packed = int(seg_len.sum())

        def run(at, ln):
            # per-element segment gather/scatter, one span per worker
            for e in range(first_elem + at, first_elem + at + ln):
                ebase = base_offset + e * extent
                spos = (e - first_elem) * elem_packed
                for off, ln_j in zip(seg_off, seg_len):
                    if packing:
                        stream[spos:spos + ln_j] = \
                            mem[ebase + off:ebase + off + ln_j]
                    else:
                        mem[ebase + off:ebase + off + ln_j] = \
                            stream[spos:spos + ln_j]
                    spos += ln_j

        futs = [self._ex.submit(run, at, ln)
                for at, ln in self._spans(nelem, 64)]
        return _FutureWork(futs)

    def pack(self, mem, out, seg_off, seg_len, extent, base_offset,
             first_elem, nelem):
        return self._packish(True, mem, out, seg_off, seg_len, extent,
                             base_offset, first_elem, nelem)

    def unpack(self, mem, chunk, seg_off, seg_len, extent, base_offset,
               first_elem, nelem):
        return self._packish(False, mem, chunk, seg_off, seg_len, extent,
                             base_offset, first_elem, nelem)

    def close(self) -> None:
        self._ex.shutdown(wait=True)


class PythonThreadsComponent(base.ThreadsComponent):
    name = "python"
    priority = 10

    def make_pool(self, nworkers: int) -> base.WorkPool:
        return PythonPool(nworkers)


COMPONENT = PythonThreadsComponent()
