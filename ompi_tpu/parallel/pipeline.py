"""GPipe-style pipeline over the pp mesh axis, inside shard_map.

Stage-to-stage activation handoff is a ``ppermute`` ring — the device-side
shape of PP's stage-rank send/recv (SURVEY.md §2.6 PP row, reference
``pml_ob1_isend.c:233``).  Microbatches stream through M + pp - 1 steps;
bubble steps compute on masked-out state (standard for static-shape SPMD
pipelines).  Degenerates cleanly to a plain microbatch loop at pp == 1.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def pipeline_apply(stage_fn, stage_params, x_microbatches, *, pp: int,
                   vary_axes: tuple = ("pp",)):
    """Run microbatches through pp stages; returns (M, *mb_shape) outputs.

    ``stage_fn(stage_params, x_mb) -> y_mb`` is this device's stage (its
    shard of the layer stack).  ``x_microbatches``: (M, *mb_shape), only
    read at stage 0; outputs are collected at stage pp-1 and zero elsewhere.

    ``vary_axes``: mesh axes the stage outputs are device-varying over
    beyond the input's own (``pp`` always; add e.g. ``tp`` when stage_fn
    runs tensor-parallel collectives).  The carries are pre-marked with
    ``pcast(to="varying")`` so the scan type-checks under ``check_vma=True`` — which is
    load-bearing: vma tracking is what makes the ppermute/psum
    TRANSPOSES correct, and with it off the pp>=2 backward silently
    computes wrong gradients (caught by test_pp2_matches_pp1_same_model).
    """
    M = x_microbatches.shape[0]
    r = jax.lax.axis_index("pp") if pp > 1 else 0
    perm = [(i, (i + 1) % pp) for i in range(pp)]
    state = jnp.zeros_like(x_microbatches[0])
    outbuf = jnp.zeros_like(x_microbatches)
    from ompi_tpu.base.jaxenv import pcast

    state = pcast(state, vary_axes, to="varying")
    outbuf = pcast(outbuf, vary_axes, to="varying")
    x_microbatches = pcast(x_microbatches, vary_axes, to="varying")

    def body(carry, t):
        state, outbuf = carry
        inp = jax.lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(t, 0, M - 1), 0, keepdims=False)
        cur = jnp.where(r == 0, inp, state)
        valid = (t >= r) & ((t - r) < M)
        y = stage_fn(stage_params, cur)
        y = jnp.where(valid, y, jnp.zeros_like(y))
        oidx = jnp.clip(t - (pp - 1), 0, M - 1)
        collect = (r == pp - 1) & valid
        prev = jax.lax.dynamic_index_in_dim(outbuf, oidx, 0, keepdims=False)
        outbuf = jax.lax.dynamic_update_index_in_dim(
            outbuf, jnp.where(collect, y, prev), oidx, 0)
        if pp > 1:
            state = jax.lax.ppermute(y, "pp", perm)
        else:
            state = y
        return (state, outbuf), None

    (_, outbuf), _ = jax.lax.scan(
        body, (state, outbuf), jnp.arange(M + pp - 1))
    return outbuf
