"""parallel/elastic — the elastic training loop (train-through-failure).

Closes the ULFM recovery loop on the flagship workload: periodic
checkpoints through the existing MPI-IO path
(:mod:`ompi_tpu.parallel.checkpoint`), and on ``ProcFailedError`` /
``RevokedError`` the full forward-recovery sequence —

    detect → revoke → ERA agree on survivors → shrink →
    (optionally) respawn replacements verified against the job pset →
    rebuild for the new world shape → restore → resume

Every phase gets an otpu-trace span (``elastic_revoke`` …
``elastic_restore``, with ``elastic_detect``/``elastic_resume``
instants) and the end-to-end detect→resume latency lands in the
``elastic_recovery`` trace histogram, whose lazily-registered
``*_p50_us``/``*_p99_us`` pvars expose recovery-time percentiles.

**Bit-exactness by construction.**  The training problem is a toy
but *checkable* one (the serving worker's ``toy_kv`` discipline): the
gradient of global sample ``j`` at step ``s`` is an integer field and
the learning rate is a power of two, so every parameter update is an
exact dyadic rational and the global-batch sum is independent of both
summation order and world width.  A run that loses a rank, shrinks to
the ``mpi://surviving`` membership (optionally respawning back to full
width) and restores from the last checkpoint therefore finishes with
parameters **bit-identical** to a failure-free run restored from the
same checkpoint step — the property ``tests/test_elastic.py`` pins
end-to-end under a chaos kill schedule (``kill:rank=2,step=7``).

Replacement ranks run ``python -m ompi_tpu.parallel.elastic <conf>``:
they meet the survivors through ``MPI_Comm_get_parent``, merge
(parents first, so the survivors' comm ranks are stable), restore from
the shared checkpoint directory, and join the training loop
mid-stream.
"""
from __future__ import annotations

import json
import os
import time
from typing import Optional

import numpy as np

from ompi_tpu.api.errhandler import ERRORS_RETURN
from ompi_tpu.runtime import trace
from ompi_tpu.api.errors import (ErrorClass, MpiError, ProcFailedError,
                                 RevokedError)
from ompi_tpu.parallel import checkpoint

#: power-of-two learning rate: updates are exact dyadic rationals
DEFAULT_LR = 2.0 ** -6

_P1, _P2, _P3 = 1_000_003, 7_919, 104_729


def grad_field(step: int, lo: int, hi: int, dims: int,
               seed: int = 0) -> np.ndarray:
    """Summed integer gradient of global samples [lo, hi) at ``step``.

    Values are small integers (|g| <= 8 per sample), so any partition
    of the global batch sums to the same float64 bit pattern — the
    property that makes degraded-width continuation bit-exact."""
    j = np.arange(int(lo), int(hi), dtype=np.int64)[:, None]
    d = np.arange(int(dims), dtype=np.int64)[None, :]
    g = (int(step) * _P1 + j * _P2 + d * _P3 + int(seed) * 13) % 17 - 8
    return g.sum(axis=0).astype(np.float64)


def partition(rank: int, size: int, total: int) -> tuple:
    """Contiguous [lo, hi) split of ``total`` items over ``size`` ranks
    (first ``total % size`` ranks take one extra)."""
    base, rem = divmod(int(total), int(size))
    lo = rank * base + min(rank, rem)
    return lo, lo + base + (1 if rank < rem else 0)


def reference_run(w0: np.ndarray, from_step: int, to_step: int,
                  global_batch: int, lr: float = DEFAULT_LR,
                  seed: int = 0) -> np.ndarray:
    """Failure-free single-process replay from ``w0`` at ``from_step``
    — the oracle the elastic run must match bit-for-bit."""
    w = np.array(w0, dtype=np.float64, copy=True)
    for s in range(int(from_step), int(to_step)):
        w -= lr * grad_field(s, 0, global_batch, w.shape[0], seed)
    return w


class ElasticTrainer:
    """Train-through-failure driver over a host communicator."""

    def __init__(self, comm, ckpt_dir: str, model_size: int = 16,
                 global_batch: int = 32, lr: float = DEFAULT_LR,
                 ckpt_every: int = 5, respawn: bool = False,
                 target_size: Optional[int] = None, seed: int = 0):
        comm.set_errhandler(ERRORS_RETURN)   # ULFM: errors raise
        self.comm = comm
        self.ckpt_dir = str(ckpt_dir)
        self.model_size = int(model_size)
        self.global_batch = int(global_batch)
        self.lr = float(lr)
        self.ckpt_every = max(1, int(ckpt_every))
        self.respawn = bool(respawn)
        self.target_size = int(target_size if target_size is not None
                               else comm.size)
        self.seed = int(seed)
        self.step = 0
        self.w = np.zeros(self.model_size, np.float64)
        self.recoveries: list = []       # one phase-duration dict each
        self._total_steps = 0

    # -- checkpoint ------------------------------------------------------
    def _ckpt_path(self, step: int) -> str:
        return os.path.join(self.ckpt_dir, f"step{int(step):06d}")

    def _checkpoint(self) -> None:
        from ompi_tpu.runtime import trace

        t0 = time.perf_counter_ns()
        path = self._ckpt_path(self.step)
        lo, hi = partition(self.comm.rank, self.comm.size,
                           self.model_size)
        tree = {
            "w": checkpoint.Shard(self.w[lo:hi], [lo],
                                  [self.model_size]),
            "step": np.array([self.step], np.int64),
        }
        checkpoint.save(path, tree, comm=self.comm)
        # completion marker AFTER the collective writes: restore only
        # ever trusts a checkpoint every rank finished (a kill mid-save
        # must not leave a half-written step looking restorable)
        self.comm.barrier()
        if self.comm.rank == 0:
            with open(os.path.join(path, "COMPLETE"), "w") as f:
                f.write(str(self.step))
        if trace.enabled:
            trace.span("elastic_checkpoint", "ft", t0,
                       args={"step": self.step})

    def latest_complete_step(self) -> int:
        """Highest checkpoint step with a completion marker."""
        best = -1
        try:
            names = os.listdir(self.ckpt_dir)
        except OSError:
            names = []
        for name in names:
            if name.startswith("step") and os.path.exists(
                    os.path.join(self.ckpt_dir, name, "COMPLETE")):
                best = max(best, int(name[4:]))
        if best < 0:
            raise MpiError(
                ErrorClass.ERR_INTERN,
                f"no complete checkpoint under {self.ckpt_dir!r} — "
                "cannot recover")
        return best

    def _restore(self, step: int) -> None:
        """Load the dense checkpoint and take this rank's slice under
        the CURRENT world shape — reshard-on-restore is what makes
        checkpoint-level elasticity work."""
        tree = checkpoint.load(self._ckpt_path(step))
        self.w = np.array(tree["w"], np.float64, copy=True)
        self.step = int(np.asarray(tree["step"]).ravel()[0])

    # -- training --------------------------------------------------------
    def _train_step(self) -> None:
        lo, hi = partition(self.comm.rank, self.comm.size,
                           self.global_batch)
        local = grad_field(self.step, lo, hi, self.model_size, self.seed)
        total = np.asarray(self.comm.allreduce(local))
        self.w = self.w - self.lr * total
        self.step += 1

    def train(self, steps: int) -> np.ndarray:
        """Run to ``steps``, recovering from failures on the way."""
        from ompi_tpu.ft import chaos

        self._total_steps = int(steps)
        while self.step < self._total_steps:
            if chaos.enabled:
                # the kill-at-step schedule (tpurun --mca
                # otpu_chaos_spec 'kill:rank=R,step=S') and the
                # designed-straggler pacing point ('delay:ms=8,rank=R,
                # site=step')
                chaos.kill_point("step", n=self.step)
                chaos.pace("step")
            # step window span: the unit otpu_analyze --critical-path
            # attributes (cat "step"; args carry the step index so
            # windows match across ranks even after a ring wrap)
            _t0 = trace.now() if trace.enabled else 0
            _step0 = self.step
            try:
                if self.step % self.ckpt_every == 0:
                    self._checkpoint()
                self._train_step()
            except (ProcFailedError, RevokedError) as exc:
                if trace.enabled:
                    trace.span("step", "step", _t0,
                               args={"step": _step0, "failed": True})
                self._recover(exc)
                continue
            if trace.enabled:
                trace.span("step", "step", _t0, args={"step": _step0})
        return self.w

    # -- recovery --------------------------------------------------------
    def _phase(self, rec: dict, name: str, fn):
        from ompi_tpu.runtime import trace

        t0 = time.perf_counter_ns()
        try:
            return fn()
        finally:
            dur = time.perf_counter_ns() - t0
            rec[name + "_ms"] = dur / 1e6
            if trace.enabled:
                trace.span("elastic_" + name, "ft", t0,
                           args={"step": rec["detect_step"]})

    def _recover(self, exc) -> None:
        from ompi_tpu.ft import state as ft_state
        from ompi_tpu.runtime import trace

        t_detect = time.perf_counter_ns()
        rec = {"detect_step": self.step, "kind": type(exc).__name__,
               "failed": sorted(ft_state.failed_ranks())}
        if trace.enabled:
            trace.instant("elastic_detect", "ft",
                          args={"step": self.step,
                                "kind": rec["kind"]})
        self._phase(rec, "revoke", self._revoke)
        self._phase(rec, "agree", self._agree_survivors)
        self._phase(rec, "shrink", self._shrink)
        if self.respawn and self.comm.size < self.target_size:
            self._phase(rec, "respawn", self._respawn)
        self._phase(rec, "restore",
                    lambda: self._restore(self.latest_complete_step()))
        total_ns = time.perf_counter_ns() - t_detect
        rec["total_ms"] = total_ns / 1e6
        rec["resume_step"] = self.step
        rec["world_size"] = self.comm.size
        self.recoveries.append(rec)
        # detect→resume latency percentile machinery (p50/p99 pvars)
        trace.hist_record("elastic_recovery", 0, total_ns)
        if trace.enabled:
            trace.instant("elastic_resume", "ft",
                          args={"step": self.step,
                                "size": self.comm.size})

    def _revoke(self) -> None:
        # idempotent: the peer that hit the failure first may have
        # revoked already (we then came here via RevokedError)
        if not self.comm.is_revoked():
            self.comm.revoke()

    def _agree_survivors(self) -> None:
        """ERA agreement among the survivors: loops ack+agree until the
        group's failure knowledge is uniform (comm_agree's group-fault
        synchronisation), so shrink starts from one agreed view."""
        while True:
            try:
                self.comm.agree(1)
                return
            except ProcFailedError:
                self.comm.ack_failed()
            except RevokedError:
                # agree rides the CTL carrier below revocation; a
                # revoked comm still reaching here means an older MPI
                # layer check fired — acknowledge and retry once
                self.comm.ack_failed()

    def _shrink(self) -> None:
        new = self.comm.shrink()
        new.set_errhandler(ERRORS_RETURN)
        self.comm = new

    def _conf(self) -> dict:
        return {"ckpt_dir": self.ckpt_dir, "model_size": self.model_size,
                "global_batch": self.global_batch, "lr": self.lr,
                "ckpt_every": self.ckpt_every, "respawn": self.respawn,
                "target_size": self.target_size, "seed": self.seed,
                "steps": self._total_steps}

    def _respawn(self) -> None:
        """Spawn replacements back to ``target_size``, verified against
        the dynamic ``mpi://job/<id>`` pset before the merge — a
        replacement that is not in the launcher's job set must fail
        loudly, not silently join the training comm."""
        import sys

        n = self.target_size - self.comm.size
        argv = [sys.executable, "-m", "ompi_tpu.parallel.elastic",
                json.dumps(self._conf())]
        inter = self.comm.spawn(argv, n, root=0)
        job = getattr(inter, "spawn_job", None)
        client = getattr(self.comm.rte, "client", None)
        if job is not None and client is not None:
            entry = client.pset_get(f"mpi://job/{job}")
            members = set(entry["members"]) if entry else set()
            children = set(inter.remote_group.world_ranks)
            if children != members:
                raise MpiError(
                    ErrorClass.ERR_SPAWN,
                    f"respawned ranks {sorted(children)} do not match "
                    f"the mpi://job/{job} pset {sorted(members)}")
        full = inter.merge(high=False)   # survivors keep low comm ranks
        full.set_errhandler(ERRORS_RETURN)
        self.comm = full

    def report(self) -> dict:
        return {"step": self.step, "world_size": self.comm.size,
                "recoveries": self.recoveries,
                "w": self.w.tolist()}


def replacement_main(argv: Optional[list] = None) -> int:
    """Entry point of a respawned replacement rank (``python -m
    ompi_tpu.parallel.elastic <json-conf>``): merge with the survivors
    (parents first), restore from the shared checkpoint directory, and
    join the training loop mid-stream."""
    import sys

    import ompi_tpu

    args = sys.argv[1:] if argv is None else list(argv)
    conf = json.loads(args[0])
    steps = int(conf.pop("steps"))
    ompi_tpu.init()
    parent = ompi_tpu.get_parent()
    if parent is None:
        raise MpiError(ErrorClass.ERR_SPAWN,
                       "elastic replacement started without a parent "
                       "intercommunicator (run via ElasticTrainer "
                       "respawn, not directly)")
    full = parent.merge(high=True)       # survivors first, then us
    trainer = ElasticTrainer(full, **conf)
    trainer._total_steps = steps
    trainer._restore(trainer.latest_complete_step())
    trainer.train(steps)
    ompi_tpu.finalize()
    return 0


if __name__ == "__main__":
    raise SystemExit(replacement_main())
