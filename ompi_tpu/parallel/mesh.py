"""Device-mesh construction for the (dp, pp, sp, tp[, expert]) axis set."""
from __future__ import annotations

import dataclasses

import numpy as np

AXES = ("dp", "pp", "sp", "tp")

#: the MoE axis name: appended after the dense axes only when the spec
#: asks for expert parallelism (ep > 1), so every dense caller keeps
#: the 4-axis mesh it always had
EXPERT_AXIS = "expert"


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    dp: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1
    # expert-parallel ways (parallel/moe.py); defaulted so every
    # existing MeshSpec(...) construction and equality pin is unchanged
    ep: int = 1

    @property
    def n(self) -> int:
        return self.dp * self.pp * self.sp * self.tp * self.ep

    def sizes(self) -> dict:
        d = {"dp": self.dp, "pp": self.pp, "sp": self.sp, "tp": self.tp}
        if self.ep > 1:
            d["ep"] = self.ep
        return d


def _prime_factors(n: int) -> list:
    fs, d = [], 2
    while d * d <= n:
        while n % d == 0:
            fs.append(d)
            n //= d
        d += 1
    if n > 1:
        fs.append(n)
    return fs


def default_axis_sizes(n_devices: int) -> MeshSpec:
    """Deterministically factor a device count over (tp, sp, dp[, pp]).

    Model-parallel axes want the fastest links, so tp and sp claim factors
    first (they ride ICI neighbours in a real torus); pp only activates at
    >=16 devices, mirroring how pipeline stages only pay off across hosts.
    """
    sizes = {"dp": 1, "pp": 1, "sp": 1, "tp": 1}
    order = ["tp", "sp", "dp", "pp"] if n_devices >= 16 else ["tp", "sp", "dp"]
    for i, f in enumerate(_prime_factors(n_devices)):
        sizes[order[i % len(order)]] *= f
    return MeshSpec(**sizes)


def make_mesh(devices, spec: MeshSpec = None):
    """Build a jax Mesh over the given devices.

    Dense specs (ep == 1) get the exact 4-axis (dp, pp, sp, tp) mesh
    this function always built; an expert-parallel spec appends the
    ``expert`` axis innermost — expert dispatch is the densest
    all-to-all in the program, so it rides the fastest links, the HiCCL
    hierarchical-composition ordering (PAPERS.md arxiv 2408.05962).
    """
    from jax.sharding import Mesh

    devices = list(devices)
    if spec is None:
        spec = default_axis_sizes(len(devices))
    if spec.n != len(devices):
        raise ValueError(f"mesh spec {spec} needs {spec.n} devices, "
                         f"got {len(devices)}")
    if spec.ep > 1:
        grid = np.array(devices).reshape(
            spec.dp, spec.pp, spec.sp, spec.tp, spec.ep)
        return Mesh(grid, AXES + (EXPERT_AXIS,)), spec
    grid = np.array(devices).reshape(spec.dp, spec.pp, spec.sp, spec.tp)
    return Mesh(grid, AXES), spec
