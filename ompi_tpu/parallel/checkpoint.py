"""Sharded checkpoint/restore of jax array pytrees.

The capability SURVEY §5.4 assigns to this framework (the reference's
checkpoint story — BLCR — was removed before v5; ULFM leaves forward
recovery to the application): save a pytree of sharded ``jax.Array``s so a
restarted (possibly re-shaped) job can restore it.

Two paths, matching the two process models:

- **Single-controller (device world)**: the conductor owns every shard;
  each array is written as one dense row-major file through the MPI-IO
  layer plus a JSON manifest of tree structure, shapes, and dtypes.
  Restore places arrays back onto any sharding (same or different mesh) —
  resharding on load is XLA's job, exactly the property that makes
  checkpoint-level elasticity work on TPU pods.
- **Multi-process**: each rank writes only ITS OWN shards through a
  subarray file view with ``write_at_all`` (two-phase collective
  buffering), producing the same dense file — so single- and multi-
  process jobs can restore each other's checkpoints.

Format: ``<dir>/manifest.json`` + one ``<dir>/<name>.bin`` per leaf.
"""
from __future__ import annotations

import json
import os
import re
from typing import Any, Optional

import numpy as np


def op_max():
    from ompi_tpu.api import op as op_mod

    return op_mod.MAX


def _flatten(tree, prefix="") -> list:
    """(path, leaf) pairs in deterministic order (dict keys sorted)."""
    if isinstance(tree, dict):
        out = []
        for k in sorted(tree):
            out.extend(_flatten(tree[k], f"{prefix}{k}/"))
        return out
    if isinstance(tree, (list, tuple)):
        out = []
        for i, v in enumerate(tree):
            out.extend(_flatten(v, f"{prefix}{i}/"))
        return out
    return [(prefix.rstrip("/"), tree)]


def _unflatten(skeleton, values: dict, prefix=""):
    if isinstance(skeleton, dict):
        return {k: _unflatten(skeleton[k], values, f"{prefix}{k}/")
                for k in skeleton}
    if isinstance(skeleton, (list, tuple)):
        seq = [_unflatten(v, values, f"{prefix}{i}/")
               for i, v in enumerate(skeleton)]
        return type(skeleton)(seq)
    return values[prefix.rstrip("/")]


class Shard:
    """A rank's block of a globally-sharded array (multi-process model):
    the caller states where its block sits in the global shape."""

    def __init__(self, block, starts, global_shape) -> None:
        self.block = np.ascontiguousarray(block)
        self.starts = [int(s) for s in starts]
        self.global_shape = list(global_shape)
        self.dtype = self.block.dtype

    @property
    def shape(self):
        return tuple(self.global_shape)


def _fname(path: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", path) + ".bin"


def save(directory: str, tree, comm=None) -> None:
    """Checkpoint a pytree of arrays (jax or numpy) into ``directory``.

    Collective over ``comm`` when given (multi-process: each rank writes
    its shards); conductor-writes-everything otherwise.
    """
    leaves = _flatten(tree)
    rank = comm.rank if comm is not None else 0
    if rank == 0:
        os.makedirs(directory, exist_ok=True)
        manifest = {
            "leaves": {path: {"shape": (leaf.global_shape
                                        if isinstance(leaf, Shard)
                                        else list(np.shape(leaf))),
                              "dtype": str(leaf.dtype
                                           if hasattr(leaf, "dtype")
                                           else np.asarray(leaf).dtype),
                              "file": _fname(path)}
                       for path, leaf in leaves},
            "skeleton": _skeleton(tree),
        }
        with open(os.path.join(directory, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1)
    if (comm is not None and comm.size > 1
            and not (comm.rte is not None and comm.rte.is_device_world)):
        comm.barrier()
        _save_multiprocess(directory, leaves, comm)
    else:
        # single controller (device world included): every shard is
        # addressable here; write each leaf dense
        for path, leaf in leaves:
            arr = leaf.block if isinstance(leaf, Shard) else np.asarray(leaf)
            arr.tofile(os.path.join(directory, _fname(path)))


def _skeleton(tree):
    if isinstance(tree, dict):
        return {k: _skeleton(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return [_skeleton(v) for v in tree]
    return None


def _save_multiprocess(directory: str, leaves, comm) -> None:
    """Each rank collectively writes the shards it owns through subarray
    file views (the fcoll two-phase path aggregates them)."""
    from ompi_tpu.api.file import File
    from ompi_tpu.datatype import core, from_numpy_dtype

    for path, leaf in leaves:
        fpath = os.path.join(directory, _fname(path))
        f = File.open(comm, fpath, "c+")
        if isinstance(leaf, Shard):
            global_shape, blocks = leaf.global_shape, \
                [(leaf.block, leaf.starts)]
        else:
            global_shape, blocks = list(np.shape(leaf)), \
                _my_shards(leaf, comm)
        # dedupe by start indices: replicated jax leaves surface one
        # identical shard per local device — write each block once
        seen: set = set()
        uniq = []
        for block, starts in blocks:
            key = tuple(int(s) for s in starts)
            if key not in seen:
                seen.add(key)
                uniq.append((np.ascontiguousarray(block), starts))
        # a block covering the whole global shape is a replicated leaf:
        # only rank 0 contributes its copy
        uniq = [(b, s) for b, s in uniq
                if list(b.shape) != global_shape or comm.rank == 0]
        # collective-call counts must match across ranks: pad to the max
        et_any = from_numpy_dtype(
            uniq[0][0].dtype if uniq
            else (leaf.dtype if hasattr(leaf, "dtype")
                  else np.asarray(leaf).dtype))
        rounds = int(np.asarray(comm.allreduce(
            np.array([len(uniq)], np.int64), op_max())).ravel()[0])
        for i in range(rounds):
            if i < len(uniq):
                block, starts = uniq[i]
                et = from_numpy_dtype(block.dtype)
                if list(block.shape) == global_shape:
                    f.set_view(0, et, et)
                    f.write_at_all(0, block)
                else:
                    ft = core.subarray(global_shape, list(block.shape),
                                       [int(s) for s in starts],
                                       core.ORDER_C, et)
                    f.set_view(0, et, ft)
                    f.write_at_all(0, block)
            else:
                f.set_view(0, et_any, et_any)
                f.write_at_all(0, np.empty(0, np.uint8))
        f.close()


def _my_shards(leaf, comm) -> list:
    """[(host_block, start_indices)] this rank must write."""
    try:
        import jax

        if isinstance(leaf, jax.Array):
            out = []
            for s in leaf.addressable_shards:
                idx = s.index  # tuple of slices into the global shape
                starts = [sl.start or 0 for sl in idx]
                out.append((np.asarray(s.data), starts))
            return out
    except Exception:
        pass
    # host array: treated as replicated (rank 0 writes)
    return [(np.asarray(leaf), [0] * np.ndim(leaf))]


def load(directory: str, sharding=None, comm=None):
    """Restore the pytree.  ``sharding``: None → numpy arrays; a
    ``jax.sharding.Sharding`` → every leaf placed with it; a callable
    ``path -> Sharding`` → per-leaf placement (resharding is free)."""
    with open(os.path.join(directory, "manifest.json")) as f:
        manifest = json.load(f)
    values = {}
    for path, meta in manifest["leaves"].items():
        arr = np.fromfile(os.path.join(directory, meta["file"]),
                          dtype=np.dtype(meta["dtype"]))
        arr = arr.reshape(meta["shape"])
        if sharding is not None:
            import jax

            sh = sharding(path) if callable(sharding) else sharding
            arr = jax.device_put(arr, sh)
        values[path] = arr
    return _unflatten(manifest["skeleton"], values)
