"""Driver-facing dry run: one full dp/pp/sp/tp(+ep) training step."""
from __future__ import annotations

import numpy as np


def make_step_and_args(devices, spec=None, layers=None):
    """Shared flagship-path setup: (jitted step, (params, x)) on a mesh."""
    from ompi_tpu.parallel.mesh import make_mesh
    from ompi_tpu.parallel.train import (build_train_step, init_params,
                                         model_dims)

    mesh, mspec = make_mesh(devices, spec)
    dims = model_dims(mspec, layers)
    step, place = build_train_step(mesh, mspec, layers=layers)
    rng = np.random.RandomState(1)
    x = rng.normal(0, 1, (dims["batch"], dims["seq"], dims["d"]))
    params, xd = place(init_params(mspec, layers=layers), x)
    return step, (params, xd), mspec


def parse_spec(text: str):
    """'dp=1,pp=2,sp=2,tp=2' -> MeshSpec (the driver/dryrun override)."""
    from ompi_tpu.parallel.mesh import MeshSpec

    sizes = {}
    for part in str(text).split(","):
        k, _, v = part.partition("=")
        sizes[k.strip()] = int(v)
    return MeshSpec(**sizes)


def run_training_step(devices, spec=None) -> float:
    """Jit + run one train step over a mesh of the given devices.

    When no spec override is given and the default mesh leaves the
    pipeline axis inactive (pp only self-activates at >=16 devices), a
    second pp-active step runs on the same devices so every dry run
    validates the composed dp x pp x sp x tp program — the round-2 gap
    where the pp>=2 backward had silently-wrong gradients."""
    from ompi_tpu.parallel.mesh import MeshSpec, default_axis_sizes

    loss = _one_descending_step(devices, spec)
    n = len(devices)
    half = default_axis_sizes(n // 2) if n >= 4 else None
    if (spec is None and half is not None and half.pp == 1
            and default_axis_sizes(n).pp == 1):
        # pp=2 over half the factorization; odd counts drop one device.
        # half.pp must itself be 1 or doubling it would not cover
        # 2*(n//2) devices (e.g. n=33: half=16 already has pp=2)
        sizes = half.sizes()
        sizes["pp"] = 2
        _one_descending_step(devices[:2 * (n // 2)], MeshSpec(**sizes))
    return loss


def run_bucket_overlap_check(devices, spec=None) -> None:
    """Tier-1 coverage of ``parallel_bucket_overlap`` without TPU
    access: one step with the single-psum dp sync and one with the
    bucketed (late-layer-first Pready order) sync must produce
    BIT-IDENTICAL parameters and loss — psum per bucket is elementwise
    the same reduction, so any drift is a real bug."""
    import jax

    from ompi_tpu.base.var import registry
    from ompi_tpu.parallel import train as _train  # registers the var

    var = registry.lookup("otpu_parallel_bucket_overlap")
    old = bool(var.value)
    var.set(False)
    try:
        step, (params, xd), mspec = make_step_and_args(devices, spec)
        base_params, base_loss = step(params, xd)
        jax.block_until_ready(base_params)
        var.set(True)
        step2, (params2, xd2), _ = make_step_and_args(devices, spec)
        new_params, new_loss = step2(params2, xd2)
        jax.block_until_ready(new_params)
    finally:
        var.set(old)
    if float(base_loss) != float(new_loss):
        raise RuntimeError(
            f"bucket-overlap loss diverged: {float(base_loss)!r} vs "
            f"{float(new_loss)!r}")
    for k in base_params:
        a = np.asarray(base_params[k])
        b = np.asarray(new_params[k])
        if a.tobytes() != b.tobytes():
            raise RuntimeError(
                f"bucket-overlap param {k!r} not bit-identical "
                f"(max abs diff {np.max(np.abs(a - b))})")
    print(f"bucket-overlap dryrun ok: mesh={mspec.sizes()} params "
          "bit-identical")


def run_tolerance_check(coll, approx_fn, exact_fn=None,
                        sizes=(1 << 10, 1 << 14), dtypes=("float32",),
                        nranks=4, band=0.02, seed=0) -> dict:
    """Tolerance-band twin of the bit-exactness checks: lossy
    collective tiers (coll/quant) cannot promise bit-identical results,
    so this harness pins them to a RELATIVE-ERROR BAND against the f32
    exact result instead.

    For every (size, dtype) cell: seeded inputs ``(nranks, size)``,
    ``exact_fn(stack)`` (default: the f64-accumulated f32 sum — the
    allreduce reference), ``approx_fn(stack)`` (the path under test),
    and the max absolute deviation normalized by ``max(|exact|)``.
    Returns ``{"coll/size/dtype": rel_error}``; any cell outside the
    band raises a LOUD report naming the failing (coll, size, dtype)
    cell — a tolerance regression must name its cell, not drown in an
    aggregate."""
    report: dict = {}
    failures = []
    for size in sizes:
        for di, dtype in enumerate(dtypes):
            rng = np.random.default_rng([int(seed), int(size), di])
            stack = rng.standard_normal((nranks, int(size))).astype(dtype)
            exact = np.asarray(
                np.sum(stack.astype(np.float64), axis=0).astype(dtype)
                if exact_fn is None else exact_fn(stack))
            approx = np.asarray(approx_fn(stack))
            denom = max(float(np.max(np.abs(exact))), 1e-12)
            rel = float(np.max(np.abs(approx.astype(np.float64)
                                      - exact.astype(np.float64)))
                        / denom)
            report[f"{coll}/{size}/{dtype}"] = rel
            if not np.isfinite(rel) or rel > band:
                failures.append((size, dtype, rel))
    if failures:
        cells = "; ".join(
            f"({coll}, {size}, {dtype}) rel error {rel:.3e} > band "
            f"{band:g}" for size, dtype, rel in failures)
        raise RuntimeError(f"tolerance check FAILED: {cells}")
    worst = max(report.values()) if report else 0.0
    print(f"tolerance dryrun ok: {coll} {len(report)} cells, max rel "
          f"error {worst:.3e} within band {band:g}")
    return report


def run_mp_training_step(spec_text: str = "") -> float:
    """Multi-process dryrun body: one flagship train step over the
    GLOBAL device mesh of a ``tpurun --device-world`` job.

    Runs inside each rank: ``init()`` boots the instance, whose
    device-world wire-up ran ``jax.distributed.initialize`` (coordinator
    address from the coord service), so ``jax.devices()`` spans every
    process — the train step's psums cross real process boundaries.
    """
    import jax

    import ompi_tpu

    w = ompi_tpu.init()
    rte = w.rte
    if not getattr(rte, "device_world_booted", False):
        raise RuntimeError(
            "device world did not boot (launch with tpurun --device-world)")
    if jax.process_count() < 2:
        raise RuntimeError(
            f"expected a multi-process device world, got "
            f"{jax.process_count()} process(es)")
    loss = _one_descending_step(
        jax.devices(), parse_spec(spec_text) if spec_text else None)
    ompi_tpu.finalize()
    return loss


def _one_descending_step(devices, spec) -> float:
    import jax

    step, (params, xd), spec = make_step_and_args(devices, spec)
    new_params, loss = step(params, xd)
    jax.block_until_ready(new_params)
    loss = float(loss)
    if not np.isfinite(loss):
        raise RuntimeError(f"non-finite loss {loss}")
    # one more step on the updated params: SGD must have moved them
    _, loss2 = step(new_params, xd)
    if not float(loss2) < loss:
        raise RuntimeError(
            f"training step did not descend: {loss} -> {float(loss2)}")
    print(f"dryrun ok: mesh={spec.sizes()} loss {loss:.6f} -> "
          f"{float(loss2):.6f}")
    return loss
