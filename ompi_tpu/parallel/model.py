"""Per-device transformer block bodies with explicit mesh collectives.

These run *inside* ``shard_map`` — the MPI-flavoured explicit-SPMD style:
every cross-device exchange is a named collective on a mesh axis, the
device-side mirror of the reference's coll algorithms (ring allreduce
``coll_base_allreduce.c:341``, pairwise alltoall ``coll_base_alltoall.c``,
binomial pipelines) rather than GSPMD auto-propagation.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def rmsnorm(x, eps: float = 1e-6):
    return x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)


def _use_flash_default() -> bool:
    import jax as _jax

    return _jax.default_backend() == "tpu"


def ring_attention(q, k, v, axis: str, n_shards: int, use_flash=None,
                   causal: bool = False):
    """Flash-style ring attention over the sequence-parallel axis.

    q/k/v local: (b, h_local, s_local, hd).  K/V blocks rotate around the
    ``axis`` ring via ``ppermute`` (the CP/ring-attention neighbor exchange,
    SURVEY.md §2.6) while the numerator/denominator accumulate with the
    running-max rescaling, so memory stays O(s_local) regardless of the
    global sequence length — long context is a first-class mesh axis.

    ``causal=True`` applies the autoregressive mask at GLOBAL positions:
    shard i's queries own rows [i*s_local, (i+1)*s_local); the block
    visiting at ring step t originated at shard (i-t) mod n, so an
    additive 0/-inf bias built from the two shard offsets masks exactly
    the future positions.  Step 0 is the diagonal block (every query
    row sees at least its own position), which keeps the running max
    finite before any fully-masked later block arrives.

    The per-step block combine (two MXU matmuls + online-softmax rescale)
    is the hot op: on TPU it drops into the fused Pallas kernel
    (``ompi_tpu/ops/flash_attention.py``); the ring structure itself stays
    at the XLA level so the compiler schedules the ICI ppermute.
    """
    hd = q.shape[-1]
    s_local = q.shape[-2]
    scale = 1.0 / math.sqrt(hd)
    if use_flash is None:
        use_flash = _use_flash_default()
    # derive the accumulator inits FROM q (0*q + const) so they inherit
    # q's varying-manifest axes: fresh jnp.zeros/full would be unvarying
    # and the scan carry would trip the vma checker under check_vma=True
    m0 = q[..., 0] * 0 - jnp.inf
    num0 = q * 0
    den0 = q[..., 0] * 0
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    my = jax.lax.axis_index(axis) if n_shards > 1 else 0

    def step_bias(t):
        # kv block at step t came from shard (my - t) mod n
        src = jax.lax.rem(my - t + n_shards, n_shards)
        qpos = my * s_local + jnp.arange(s_local)[:, None]
        kpos = src * s_local + jnp.arange(s_local)[None, :]
        # q.dtype (not f32): a wider bias would promote the scan
        # carry under bfloat16 compute and break lax.scan's
        # carry-type invariant; the flash kernel upcasts internally
        return jnp.where(qpos >= kpos, 0.0, -jnp.inf).astype(q.dtype)

    def body(carry, t):
        k_blk, v_blk, m, num, den = carry
        bias = step_bias(t) if causal else None
        if use_flash:
            from ompi_tpu.ops.flash_attention import (
                flash_block_update, flash_block_update_biased)

            if causal:
                new_m, num, den = flash_block_update_biased(
                    q, k_blk, v_blk, m, num, den, bias)
            else:
                new_m, num, den = flash_block_update(q, k_blk, v_blk, m,
                                                     num, den)
        else:
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) * scale
            if bias is not None:
                s = s + bias
            new_m = jnp.maximum(m, s.max(axis=-1))
            c = jnp.exp(m - new_m)
            p = jnp.exp(s - new_m[..., None])
            num = num * c[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk)
            den = den * c + p.sum(axis=-1)
        if n_shards > 1:
            k_blk = jax.lax.ppermute(k_blk, axis, perm)
            v_blk = jax.lax.ppermute(v_blk, axis, perm)
        return (k_blk, v_blk, new_m, num, den), None

    (_, _, _, num, den), _ = jax.lax.scan(
        body, (k, v, m0, num0, den0), jnp.arange(n_shards))
    return num / den[..., None]


def ulysses_attention(q, k, v, axis: str, n_shards: int,
                      causal: bool = False):
    """DeepSpeed-Ulysses sequence parallelism: all-to-all head↔sequence
    reshard instead of the ring's K/V rotation.

    q/k/v local: (b, h_local, s_local, hd) with h_local % n_shards == 0.
    One ``all_to_all`` turns the sequence axis local-complete (each shard
    keeps h_local/n_shards heads over the FULL sequence), attention runs
    locally with no inter-step dependency, and the inverse all_to_all
    restores sequence sharding.  Two reshard phases (four ``all_to_all``
    calls: q/k/v scatter + the output inverse) vs the ring's
    n_shards ppermute steps — better for short-ish sequences on fast ICI;
    the ring wins at very long context (O(s_local) memory).  The MoE-
    dispatch-shaped exchange of SURVEY.md §2.6's alltoall row.
    """
    if n_shards == 1:
        return _full_attention(q, k, v, causal)

    def scatter_heads(t):   # (b, h_l, s_l, hd) -> (b, h_l/n, s, hd)
        return jax.lax.all_to_all(t, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    # after the reshard each shard holds the FULL sequence, so the
    # causal mask is the plain global lower-triangle
    o = _full_attention(scatter_heads(q), scatter_heads(k),
                        scatter_heads(v), causal)  # (b, h_l/n, s, hd)
    # inverse reshard: full-sequence heads -> my seq block, all heads
    return jax.lax.all_to_all(o, axis, split_axis=2, concat_axis=1,
                              tiled=True)


def _full_attention(q, k, v, causal: bool = False):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(q.shape[-1])
    if causal:
        sq, skv = s.shape[-2], s.shape[-1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(skv)[None, :]
        s = jnp.where(mask, s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


def attention_block(p, x, *, sp: int, tp: int, n_heads_local: int,
                    sp_impl: str = "ring", causal: bool = False):
    """Sequence-parallel attention with tp-sharded heads; psum output proj.

    x local: (b, s_local, d) replicated over tp.  Head projections are
    column-sharded over tp (h_local = H/tp); the output projection is
    row-sharded, so its partial products combine with a ``psum`` over tp —
    the tensor-parallel allreduce (DP/TP table row, SURVEY.md §2.6).

    ``sp_impl`` picks the context-parallel scheme: "ring" (ppermute K/V
    rotation, O(s_local) memory — long context) or "ulysses" (all-to-all
    head↔seq reshard, 2 collectives — short/medium context on fast ICI).
    """
    b, s_l, d = x.shape
    h = rmsnorm(x)

    def heads(w):
        y = h @ w  # (b, s_l, h_local*hd)
        return y.reshape(b, s_l, n_heads_local, -1).transpose(0, 2, 1, 3)

    q, k, v = heads(p["wq"]), heads(p["wk"]), heads(p["wv"])
    if sp_impl == "ulysses" and sp > 1:
        if n_heads_local % sp:
            # silent ring fallback would invalidate any collective-count
            # comparison the user is running — fail loudly instead
            raise ValueError(
                f"ulysses needs local heads divisible by sp "
                f"({n_heads_local} % {sp}); use sp_impl='ring'")
        o = ulysses_attention(q, k, v, "sp", sp,
                              causal=causal)        # (b, h_l, s_l, hd)
    else:
        o = ring_attention(q, k, v, "sp", sp,
                           causal=causal)           # (b, h_l, s_l, hd)
    o = o.transpose(0, 2, 1, 3).reshape(b, s_l, -1)  # (b, s_l, h_l*hd)
    o = o @ p["wo"]
    if tp > 1:
        o = jax.lax.psum(o, "tp")
    return x + o


def mlp_block(p, x, *, tp: int):
    """Megatron-style tp MLP: column-shard w1, row-shard w2, psum combine."""
    h = rmsnorm(x)
    y = jax.nn.gelu(h @ p["w1"]) @ p["w2"]
    if tp > 1:
        y = jax.lax.psum(y, "tp")
    return x + y


def moe_block(p, x, *, tp: int, n_experts: int, capacity: int):
    """Top-1 MoE with experts sharded over tp (the ep axis) via all_to_all.

    Local tokens are chunked over tp (each tp shard routes its slice),
    dispatched to expert-home shards with ``all_to_all`` (the MoE dispatch
    ≅ pairwise alltoall, SURVEY.md §2.6 EP row), processed by the local
    expert FFNs, returned by the inverse all_to_all, and the chunks
    re-replicated with ``all_gather``.  Static capacity per (expert,
    source-shard); overflow tokens fall through on the residual path.
    """
    b, s_l, d = x.shape
    xf = rmsnorm(x).reshape(b * s_l, d)
    t = xf.shape[0]
    tc = t // tp
    e_l = n_experts // tp
    r = jax.lax.axis_index("tp") if tp > 1 else 0
    chunk = jax.lax.dynamic_slice_in_dim(xf, r * tc, tc, 0)  # (tc, d)

    logits = chunk @ p["wr"]                        # (tc, E)
    probs = jax.nn.softmax(logits, axis=-1)
    eid = jnp.argmax(probs, axis=-1)                # (tc,)
    # routing bookkeeping in f32 ALWAYS: bf16 cumsum cannot count
    # past 256 exactly, silently colliding capacity slots at
    # production token counts (compute_dtype must not leak here)
    oh = jax.nn.one_hot(eid, n_experts, dtype=jnp.float32)       # (tc, E)
    pos = (jnp.cumsum(oh, axis=0) - 1.0) * oh                    # (tc, E)
    keep = oh * (pos < capacity)
    pos_oh = jax.nn.one_hot(
        jnp.clip(pos.astype(jnp.int32), 0, capacity - 1), capacity,
        dtype=xf.dtype)                                          # (tc, E, cap)
    # mask back to compute dtype (exact 0/1): the expert einsums
    # and the residual must stay in compute precision
    disp = (keep[..., None] * pos_oh).astype(xf.dtype)           # (tc, E, cap)

    ex_in = jnp.einsum("tec,td->ecd", disp, chunk)   # (E, cap, d)
    ex_in = ex_in.reshape(tp, e_l, capacity, d)
    if tp > 1:
        ex_in = jax.lax.all_to_all(ex_in, "tp", split_axis=0, concat_axis=0)
    # (tp, e_l, cap, d): leading dim is now source shard
    ex_in = ex_in.transpose(1, 0, 2, 3).reshape(e_l, tp * capacity, d)
    hid = jax.nn.gelu(jnp.einsum("etd,edf->etf", ex_in, p["we1"]))
    ex_out = jnp.einsum("etf,efd->etd", hid, p["we2"])
    ex_out = ex_out.reshape(e_l, tp, capacity, d).transpose(1, 0, 2, 3)
    if tp > 1:
        ex_out = jax.lax.all_to_all(ex_out, "tp", split_axis=0, concat_axis=0)
    ex_out = ex_out.reshape(n_experts, capacity, d)

    gate = jnp.einsum("tec,te->t", disp, probs)      # kept-assignment prob
    out_chunk = jnp.einsum("tec,ecd->td", disp, ex_out) * gate[:, None]
    if tp > 1:
        out = jax.lax.all_gather(out_chunk, "tp", axis=0, tiled=True)  # (t, d)
    else:
        out = out_chunk
    return x + out.reshape(b, s_l, d)


def transformer_block(p, x, *, sp, tp, n_heads_local, n_experts, capacity,
                      sp_impl: str = "ring", causal: bool = False):
    x = attention_block(p, x, sp=sp, tp=tp, n_heads_local=n_heads_local,
                        sp_impl=sp_impl, causal=causal)
    x = mlp_block(p, x, tp=tp)
    x = moe_block(p, x, tp=tp, n_experts=n_experts, capacity=capacity)
    return x
