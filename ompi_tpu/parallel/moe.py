"""parallel/moe — expert parallelism over the ragged tier.

Mixture-of-experts as a *composition* of subsystems this repo already
has, from gating to expert-sharded serving:

- **Gating is a pure function** (:func:`plan_step`): integer hash
  scores, strict top-k with a deterministic tie-break, and
  global-token-order capacity assignment.  Same ``(step, tokens,
  experts, seed)`` ⇒ the same :class:`DispatchPlan` on every process —
  independent of PYTHONHASHSEED, world size, or iteration order.  That
  determinism is load-bearing: the dispatch wire protocol carries NO
  metadata.  A receiver recomputes the sender's plan and knows exactly
  how many rows arrive from each peer and which expert each row feeds.

- **Dispatch/combine ride the ragged collectives**: the host trainer
  (:class:`MoeTrainer`) moves token payloads with ``comm.alltoallv``
  and publishes updated expert slabs with ``comm.allgatherv`` (ranks
  owning no experts contribute zero-length buffers — the edge cases
  ``tests/test_ragged_edge.py`` pins); the device tier
  (:func:`dispatch_tokens`) uses the ``alltoallv_array`` slot over
  ``ops/pallas_collectives.all_to_all_v``, with the PR 15 block-int8
  codec engaged by the same ``otpu_quant_budget`` comm-info key.

- **The expert FFN is expert-sharded** over the ``('expert',)`` mesh
  axis (:func:`moe_ep_block` / :func:`build_moe_train_step`), composed
  with the existing dp layer; the fused matmul+collective tier
  (``ops/pallas_overlap``) is reachable as a coll/tuned DEVICE ladder
  cell (:func:`expert_ffn_fused` → ``tuned.device_cell``).

- **Elastic by inheritance**: :class:`MoeTrainer` subclasses
  ``parallel/elastic.ElasticTrainer``.  Expert ownership is
  ``partition(rank, size, n_experts)`` recomputed from the CURRENT
  comm every step, so a chaos kill + shrink automatically re-shards
  the experts over the survivors; the integer-grad / dyadic-gate
  arithmetic keeps the recovered run bit-identical to
  :func:`reference_moe_run`.
"""
from __future__ import annotations

import json
import math
import os
import time
from dataclasses import dataclass
from typing import NamedTuple, Optional

import numpy as np

from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.base.var import VarType, registry
from ompi_tpu.parallel.elastic import (DEFAULT_LR, ElasticTrainer, _P1, _P2,
                                       _P3, grad_field, partition)
from ompi_tpu.parallel.mesh import EXPERT_AXIS, MeshSpec, make_mesh
from ompi_tpu.runtime import spc, telemetry, trace

_n_experts_var = registry.register(
    "moe", None, "n_experts", vtype=VarType.INT, default=8,
    help="Number of experts in the MoE layer (host trainer default; "
         "the device tier derives it from the mesh spec)")

_top_k_var = registry.register(
    "moe", None, "top_k", vtype=VarType.INT, default=2,
    help="Experts each token routes to; gate weights are the dyadic "
         "ladder 1/2, 1/4, ... with the tail 2^-k folded into the top "
         "expert so they sum to exactly 1 (combines stay bit-exact)")

_capacity_factor_var = registry.register(
    "moe", None, "capacity_factor", vtype=VarType.FLOAT, default=1.25,
    help="Per-expert capacity = ceil(factor * tokens * top_k / "
         "n_experts); tokens routed past a full expert follow "
         "otpu_moe_drop_policy")

_drop_policy_var = registry.register(
    "moe", None, "drop_policy", vtype=VarType.STRING, default="drop",
    enum_values={"drop": 0, "error": 1},
    help="Over-capacity token policy: 'drop' (counted in "
         "moe_dropped_tokens, token keeps its residual path) or "
         "'error' (raise ERR_TRUNCATE — for runs where any drop is a "
         "configuration bug)")

_hot_expert_var = registry.register(
    "moe", None, "hot_expert", vtype=VarType.INT, default=-1,
    help="Designated hot expert for designed-imbalance runs (-1 = "
         "none): tokens selected by otpu_moe_hot_boost route their "
         "top-1 here, skewing load for critical-path/imbalance tests")

_hot_boost_var = registry.register(
    "moe", None, "hot_boost", vtype=VarType.FLOAT, default=0.0,
    help="Fraction (0..1) of tokens deterministically biased toward "
         "otpu_moe_hot_expert")

_pace_var = registry.register(
    "moe", None, "compute_us_per_token", vtype=VarType.INT, default=0,
    help="Host-trainer pacing: microseconds of simulated expert "
         "compute per RECEIVED token, so the hot expert's home rank "
         "is measurably the straggler (otpu_analyze --critical-path "
         "acceptance); 0 disables")


# -- gating: a pure, hash-seeded function of (step, tokens, experts) -----

class Assign(NamedTuple):
    token: int      # global token index
    slot: int       # which of the token's top-k choices this is
    expert: int
    weight: float   # dyadic gate weight (exact in f64)
    pos: int        # row within the expert's capacity buffer


@dataclass(frozen=True)
class DispatchPlan:
    """One step's complete routing decision — identical on every
    process by construction, so it IS the wire protocol (receivers
    recompute it instead of reading per-message metadata)."""
    step: int
    tokens: int
    n_experts: int
    top_k: int
    capacity: int
    kept: tuple         # Assign rows, global (token, slot) order
    dropped: tuple      # (token, expert) pairs past capacity
    loads: tuple        # kept rows per expert

    def imbalance(self) -> float:
        """max-expert-load / mean-load (1.0 = perfectly balanced)."""
        loads = np.asarray(self.loads, np.float64)
        mean = float(loads.mean()) if loads.size else 0.0
        return float(loads.max() / mean) if mean > 0 else 1.0

    def to_json(self) -> str:
        return json.dumps({
            "step": self.step, "capacity": self.capacity,
            "kept": [list(a) for a in self.kept],
            "dropped": [list(p) for p in self.dropped],
            "loads": list(self.loads)})


def gate_weights(top_k: int) -> tuple:
    """Dyadic gate weights: ``2^-(i+1)`` per slot with the tail
    ``2^-k`` folded into slot 0 — they sum to exactly 1 and every
    weighted payload stays an exact dyadic rational in f64."""
    k = int(top_k)
    w = [2.0 ** -(i + 1) for i in range(k)]
    w[0] += 2.0 ** -k
    return tuple(w)


def capacity_for(tokens: int, n_experts: int, top_k: int,
                 factor: float) -> int:
    return max(1, int(math.ceil(
        float(factor) * int(tokens) * int(top_k) / int(n_experts))))


def gate_scores(step: int, tokens: int, n_experts: int, seed: int = 0,
                hot_expert: int = -1, hot_boost: float = 0.0):
    """Integer (tokens, n_experts) score table.  Pure modular
    arithmetic over int64 — no Python ``hash()``, no float ordering —
    so PYTHONHASHSEED and platform cannot perturb routing."""
    t = np.arange(int(tokens), dtype=np.int64)[:, None]
    e = np.arange(int(n_experts), dtype=np.int64)[None, :]
    a = (int(step) * _P1 + (t * n_experts + e) * _P2 + e * _P3
         + int(seed) * 13) % 997
    # quadratic mixing: the linear residue alone leaves per-token
    # expert rankings an arithmetic progression mod 997 (systematic
    # load skew); squaring breaks the linearity while staying exact
    # int64 arithmetic
    s = (a * (a + 7)) % 997
    if hot_expert is not None and 0 <= int(hot_expert) < int(n_experts) \
            and hot_boost > 0:
        boosted = ((t[:, 0] * _P3 + int(seed) * 7) % 1000) \
            < int(round(float(hot_boost) * 1000))
        s[boosted, int(hot_expert)] = 1_000_000
    return s


def plan_step(step: int, tokens: int, n_experts: int, top_k: int,
              capacity_factor: float, seed: int = 0,
              hot_expert: int = -1,
              hot_boost: float = 0.0) -> DispatchPlan:
    """Gate + capacity-assign one step.  Tie-break is total: tokens
    prefer the lower expert id at equal score, and capacity slots fill
    in global (token, slot) order — there is exactly one valid plan."""
    T, E, k = int(tokens), int(n_experts), int(top_k)
    if not 1 <= k <= E:
        raise ValueError(f"top_k={k} must be in [1, {E}]")
    s = gate_scores(step, T, E, seed, hot_expert, hot_boost)
    # one key encodes (score desc, expert-id asc): argsort stays total
    key = s * E + (E - 1 - np.arange(E, dtype=np.int64))[None, :]
    order = np.argsort(-key, axis=1, kind="stable")[:, :k]
    wts = gate_weights(k)
    cap = capacity_for(T, E, k, capacity_factor)
    fill = [0] * E
    kept, dropped = [], []
    for t in range(T):
        for i in range(k):
            e = int(order[t, i])
            if fill[e] < cap:
                kept.append(Assign(t, i, e, wts[i], fill[e]))
                fill[e] += 1
            else:
                dropped.append((t, e))
    return DispatchPlan(step, T, E, k, cap, tuple(kept), tuple(dropped),
                        tuple(fill))


def token_grad(step: int, token: int, dims: int,
               seed: int = 0) -> np.ndarray:
    """Per-token integer gradient row — ``elastic.grad_field`` for the
    single sample [token, token+1), so MoE runs share the dense loop's
    exact-arithmetic discipline."""
    return grad_field(step, token, token + 1, dims, seed)


def reference_moe_run(w0: np.ndarray, from_step: int, to_step: int, *,
                      tokens: int, n_experts: int, expert_dim: int,
                      top_k: int = 2, capacity_factor: float = 1.25,
                      lr: float = DEFAULT_LR, seed: int = 0,
                      hot_expert: int = -1,
                      hot_boost: float = 0.0) -> np.ndarray:
    """Failure-free single-process replay — the oracle a distributed
    (and chaos-recovered, re-sharded) MoE run must match bit-for-bit."""
    w = np.array(w0, np.float64, copy=True).reshape(n_experts, expert_dim)
    for s in range(int(from_step), int(to_step)):
        plan = plan_step(s, tokens, n_experts, top_k, capacity_factor,
                         seed, hot_expert, hot_boost)
        upd = np.zeros_like(w)
        for a in plan.kept:
            upd[a.expert] += token_grad(s, a.token, expert_dim, seed) \
                * a.weight
        w -= lr * upd
    return w.ravel()


# -- telemetry: the "moe" live source ------------------------------------

_TELEM = {"steps": 0, "dispatch_tokens": 0, "dropped_tokens": 0,
          "n_experts": 0, "capacity": 0, "imbalance": 0.0,
          "world_size": 0}


def _telem_snapshot() -> dict:
    return dict(_TELEM)


def _imbalance_high_water(imb: float) -> None:
    """Publish the load-imbalance factor as a monotonic high-water in
    milli-units — the SPC plane is append-only counters, so a gauge is
    expressed as read + delta-record."""
    milli = int(round(float(imb) * 1000))
    cur = spc.read("moe_imbalance_max")
    if milli > cur:
        spc.record("moe_imbalance_max", milli - cur)


# -- the host trainer: expert-sharded, elastic, bit-exact ----------------

class MoeTrainer(ElasticTrainer):
    """Expert-parallel train-through-failure driver.

    The model is ``(n_experts, expert_dim)`` expert weights; every
    rank holds the full (small) table but OWNS the contiguous expert
    range ``partition(rank, size, n_experts)`` — owners apply updates,
    everyone else receives the refreshed slabs through the ragged
    ``allgatherv`` combine.  Ownership is recomputed from the live
    comm each step, so recovery's shrink re-shards the experts over
    the survivors with no extra code path."""

    def __init__(self, comm, ckpt_dir: str, n_experts: int = None,
                 expert_dim: int = 8, tokens_per_step: int = 64,
                 top_k: int = None, capacity_factor: float = None,
                 drop_policy: str = None, lr: float = DEFAULT_LR,
                 ckpt_every: int = 5, seed: int = 0,
                 hot_expert: int = None, hot_boost: float = None,
                 compute_us_per_token: int = None):
        self.n_experts = int(n_experts if n_experts is not None
                             else _n_experts_var.value)
        self.expert_dim = int(expert_dim)
        self.top_k = int(top_k if top_k is not None
                         else _top_k_var.value)
        self.capacity_factor = float(
            capacity_factor if capacity_factor is not None
            else _capacity_factor_var.value)
        self.drop_policy = str(drop_policy if drop_policy is not None
                               else _drop_policy_var.value)
        if self.drop_policy not in ("drop", "error"):
            raise MpiError(ErrorClass.ERR_ARG,
                           f"otpu_moe_drop_policy={self.drop_policy!r} "
                           "(want 'drop' or 'error')")
        self.hot_expert = int(hot_expert if hot_expert is not None
                              else _hot_expert_var.value)
        self.hot_boost = float(hot_boost if hot_boost is not None
                               else _hot_boost_var.value)
        self.compute_us_per_token = int(
            compute_us_per_token if compute_us_per_token is not None
            else _pace_var.value)
        super().__init__(comm, ckpt_dir,
                         model_size=self.n_experts * self.expert_dim,
                         global_batch=int(tokens_per_step), lr=lr,
                         ckpt_every=ckpt_every, respawn=False,
                         seed=seed)
        self.capacity = capacity_for(self.global_batch, self.n_experts,
                                     self.top_k, self.capacity_factor)
        self._dispatched = 0
        self._dropped = 0
        self._imb_max = 0.0
        _TELEM.update(n_experts=self.n_experts, capacity=self.capacity)
        telemetry.register_source("moe", _telem_snapshot)

    # -- expert ownership ------------------------------------------------
    def my_experts(self) -> tuple:
        """[lo, hi) expert range this rank owns under the CURRENT comm
        — the single source of re-shard truth after a shrink."""
        return partition(self.comm.rank, self.comm.size, self.n_experts)

    # -- checkpoint at expert boundaries ---------------------------------
    def _checkpoint(self) -> None:
        from ompi_tpu.parallel import checkpoint

        t0 = time.perf_counter_ns()
        path = self._ckpt_path(self.step)
        elo, ehi = self.my_experts()
        d = self.expert_dim
        tree = {
            "w": checkpoint.Shard(self.w[elo * d:ehi * d], [elo * d],
                                  [self.model_size]),
            "step": np.array([self.step], np.int64),
        }
        checkpoint.save(path, tree, comm=self.comm)
        self.comm.barrier()
        if self.comm.rank == 0:
            with open(os.path.join(path, "COMPLETE"), "w") as f:
                f.write(str(self.step))
        if trace.enabled:
            trace.span("elastic_checkpoint", "ft", t0,
                       args={"step": self.step,
                             "experts": [elo, ehi]})

    # -- one expert-parallel step ----------------------------------------
    def _train_step(self) -> None:
        E, d, k = self.n_experts, self.expert_dim, self.top_k
        T = self.global_batch
        me, size = self.comm.rank, self.comm.size
        plan = plan_step(self.step, T, E, k, self.capacity_factor,
                         self.seed, self.hot_expert, self.hot_boost)
        if plan.dropped and self.drop_policy == "error":
            raise MpiError(
                ErrorClass.ERR_TRUNCATE,
                f"step {self.step}: {len(plan.dropped)} tokens over "
                f"capacity {plan.capacity} with "
                "otpu_moe_drop_policy=error")
        tlo, thi = partition(me, size, T)
        mine = [a for a in plan.kept if tlo <= a.token < thi]
        my_dropped = sum(1 for t, _ in plan.dropped if tlo <= t < thi)
        imb = plan.imbalance()
        spc.record("moe_dispatch_tokens", len(mine))
        if my_dropped:
            spc.record("moe_dropped_tokens", my_dropped)
        _imbalance_high_water(imb)
        self._dispatched += len(mine)
        self._dropped += my_dropped
        self._imb_max = max(self._imb_max, imb)
        _TELEM.update(steps=_TELEM["steps"] + 1,
                      dispatch_tokens=_TELEM["dispatch_tokens"]
                      + len(mine),
                      dropped_tokens=_TELEM["dropped_tokens"]
                      + my_dropped,
                      imbalance=imb, world_size=size)

        # dispatch: weighted token-gradient rows to each expert's home
        # rank, in plan order — NO metadata rides the wire, the
        # receiver recomputes the plan and knows every row's expert
        send = []
        for dest in range(size):
            delo, dehi = partition(dest, size, E)
            rows = [token_grad(self.step, a.token, d, self.seed)
                    * a.weight
                    for a in mine if delo <= a.expert < dehi]
            send.append(np.concatenate(rows) if rows
                        else np.zeros(0, np.float64))
        t0 = trace.now() if trace.enabled else 0
        recv = self.comm.alltoallv(send)
        if trace.enabled:
            trace.span("moe_dispatch", "coll", t0,
                       args={"step": self.step, "rows": len(mine)})

        # owner side: fold received rows into my expert slice, exactly
        elo, ehi = self.my_experts()
        upd = np.zeros((max(0, ehi - elo), d), np.float64)
        n_recv = 0
        for src in range(size):
            slo, shi = partition(src, size, T)
            expected = [a for a in plan.kept
                        if slo <= a.token < shi and elo <= a.expert < ehi]
            blk = np.asarray(recv[src])
            rows = (blk if blk.dtype == np.float64
                    else blk.view(np.float64)).reshape(-1, d)
            if rows.shape[0] != len(expected):
                raise MpiError(
                    ErrorClass.ERR_TRUNCATE,
                    f"step {self.step}: rank {src} sent "
                    f"{rows.shape[0]} rows, plan says {len(expected)} "
                    "— gating diverged across processes")
            for a, row in zip(expected, rows):
                upd[a.expert - elo] += row
            n_recv += len(expected)
        if self.compute_us_per_token and n_recv:
            # simulated expert compute ∝ received load: the hot
            # expert's home rank becomes the designed straggler
            time.sleep(self.compute_us_per_token * n_recv / 1e6)
        we = self.w.reshape(E, d)
        if ehi > elo:
            we[elo:ehi] -= self.lr * upd

        # combine: owners publish refreshed expert slabs; expert-less
        # ranks contribute zero-length buffers (the ragged edge case)
        t0 = trace.now() if trace.enabled else 0
        blocks = self.comm.allgatherv(we[elo:ehi].ravel())
        if trace.enabled:
            trace.span("moe_combine", "coll", t0,
                       args={"step": self.step,
                             "experts": [elo, ehi]})
        for r in range(size):
            rlo, rhi = partition(r, size, E)
            if rhi <= rlo:
                continue
            blk = np.asarray(blocks[r])
            we[rlo:rhi] = (blk if blk.dtype == np.float64
                           else blk.view(np.float64)).reshape(
                rhi - rlo, d)
        self.step += 1

    def report(self) -> dict:
        rep = super().report()
        elo, ehi = self.my_experts()
        rep.update({"n_experts": self.n_experts, "top_k": self.top_k,
                    "capacity": self.capacity, "experts": [elo, ehi],
                    "dispatched": self._dispatched,
                    "dropped": self._dropped,
                    "imbalance_max": round(self._imb_max, 6)})
        return rep


# -- device tier: expert-sharded FFN over the ('expert',) mesh axis ------

def moe_model_dims(spec: MeshSpec, top_k: int = None,
                   capacity_factor: float = None) -> dict:
    """Tracing-scale dims derived from the mesh spec so ep always
    divides the expert count and the per-shard token chunk."""
    ep = spec.ep
    E = 2 * ep
    k = int(top_k if top_k is not None else min(2, E))
    cf = float(capacity_factor if capacity_factor is not None
               else _capacity_factor_var.value)
    tc = 4                       # tokens per expert-shard chunk
    cap = max(1, int(math.ceil(cf * tc * k / E)))
    return dict(d=8, ff=16, n_experts=E, e_local=E // ep, top_k=k,
                capacity=cap, t_local=tc * ep, tokens=tc * ep * spec.dp)


def init_moe_params(spec: MeshSpec, seed: int = 0) -> dict:
    dims = moe_model_dims(spec)
    rng = np.random.RandomState(seed)

    def w(*shape):
        return rng.normal(0, 0.5 / np.sqrt(shape[-2]), shape).astype(
            np.float32)

    return {"wr": w(dims["d"], dims["n_experts"]),
            "we1": w(dims["n_experts"], dims["d"], dims["ff"]),
            "we2": w(dims["n_experts"], dims["ff"], dims["d"])}


def moe_param_specs(P, spec: MeshSpec) -> dict:
    ex = EXPERT_AXIS if spec.ep > 1 else None
    return {"wr": P(None, None),
            "we1": P(ex, None, None), "we2": P(ex, None, None)}


def moe_ep_block(p, x, *, ep: int, n_experts: int, capacity: int,
                 top_k: int):
    """Top-k expert-parallel FFN block (inside shard_map).

    ``x`` is the (t_local, d) token chunk, replicated over the expert
    axis; ``p['we1']/['we2']`` are the (E/ep, ...) local expert shards.
    Generalizes model.py's top-1/tp ``moe_block`` over the dedicated
    ``expert`` axis: routing bookkeeping stays f32 (bf16 cumsum cannot
    count past 256), dispatch/return ride ``lax.all_to_all`` over
    ``expert``, and dropped tokens keep the residual path."""
    import jax
    import jax.numpy as jnp

    t, d = x.shape
    E, cap, k = int(n_experts), int(capacity), int(top_k)
    tc = t // ep
    r = jax.lax.axis_index(EXPERT_AXIS) if ep > 1 else 0
    chunk = jax.lax.dynamic_slice_in_dim(x, r * tc, tc, 0)
    logits = (chunk @ p["wr"]).astype(jnp.float32)        # (tc, E)
    probs = jax.nn.softmax(logits, axis=-1)
    _, idx = jax.lax.top_k(probs, k)        # ties break to lower id
    oh = jnp.sum(jax.nn.one_hot(idx, E, dtype=jnp.float32), axis=1)
    pos = (jnp.cumsum(oh, axis=0) - 1.0) * oh
    keep = oh * (pos < cap)
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), cap,
                            dtype=jnp.float32)
    disp = keep[..., None] * pos_oh                       # (tc, E, cap)
    cf = chunk.astype(jnp.float32)
    ex_in = jnp.einsum("tec,td->ecd", disp, cf)
    e_l = E // ep
    if ep > 1:
        ex_in = ex_in.reshape(ep, e_l, cap, d)
        ex_in = jax.lax.all_to_all(ex_in, EXPERT_AXIS,
                                   split_axis=0, concat_axis=0)
        ex_in = ex_in.transpose(1, 0, 2, 3).reshape(e_l, ep * cap, d)
    else:
        ex_in = ex_in.reshape(e_l, cap, d)
    hid = jax.nn.gelu(jnp.einsum(
        "ncd,ndf->ncf", ex_in, p["we1"].astype(jnp.float32)))
    out = jnp.einsum("ncf,nfd->ncd", hid,
                     p["we2"].astype(jnp.float32))
    if ep > 1:
        out = out.reshape(e_l, ep, cap, d).transpose(1, 0, 2, 3)
        out = jax.lax.all_to_all(out, EXPERT_AXIS,
                                 split_axis=0, concat_axis=0)
    ex_out = out.reshape(E, cap, d)
    gates = probs * keep
    comb = jnp.einsum("tec,ecd,te->td", disp, ex_out, gates)
    if ep > 1:
        comb = jax.lax.all_gather(comb, EXPERT_AXIS, axis=0,
                                  tiled=True)
    return x + comb.astype(x.dtype)


def build_moe_train_step(mesh, spec: MeshSpec, lr: float = 0.02):
    """Return (jitted_step, place): step(params, x) -> (params, loss)
    over the (dp, expert) axes of ``mesh`` (from ``make_mesh`` with
    ``spec.ep > 1``; ep == 1 degrades to plain dp)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ompi_tpu.base.jaxenv import shard_map

    dims = moe_model_dims(spec)
    ep = spec.ep
    axes = ("dp", EXPERT_AXIS) if ep > 1 else ("dp",)
    pspecs = moe_param_specs(P, spec)
    x_spec = P("dp", None)

    def body(params, x):
        def loss_fn(ps):
            y = moe_ep_block(ps, x, ep=ep,
                             n_experts=dims["n_experts"],
                             capacity=dims["capacity"],
                             top_k=dims["top_k"])
            yf = y.astype(jnp.float32)
            local = 0.5 * jnp.sum(yf * yf)
            if ep > 1:
                # y is value-replicated across expert but vma-varying
                # (it rode expert collectives): count replica 0 only,
                # the train.py tp-masking discipline
                local = jnp.where(
                    jax.lax.axis_index(EXPERT_AXIS) == 0, local, 0.0)
            return jax.lax.psum(local, axes)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = jax.tree.map(lambda g: jax.lax.psum(g, "dp"), grads)
        if ep > 1:
            # wr is expert-replicated; its grad arrives per token
            # chunk, one chunk per expert shard — sum them
            grads["wr"] = jax.lax.psum(grads["wr"], EXPERT_AXIS)
        new = jax.tree.map(lambda p_, g: p_ - lr * g, params, grads)
        return new, loss

    step = jax.jit(shard_map(body, mesh=mesh, in_specs=(pspecs, x_spec),
                             out_specs=(pspecs, P()), check_vma=True))

    def place(params, x_np):
        p = {k: jax.device_put(v, NamedSharding(mesh, pspecs[k]))
             for k, v in params.items()}
        x = jax.device_put(np.asarray(x_np, np.float32),
                           NamedSharding(mesh, x_spec))
        return p, x

    return step, place


def run_moe_training_step(devices=None, spec: MeshSpec = None,
                          steps: int = 3) -> list:
    """Dryrun: the expert-parallel step compiles, descends, and is
    BIT-STABLE — two fresh builds produce byte-identical loss curves
    (the dryrun-class check the 2-process acceptance reuses)."""
    import jax

    if devices is None:
        devices = jax.devices()
    if spec is None:
        n = len(devices)
        ep = 4 if n % 4 == 0 else (2 if n % 2 == 0 else 1)
        spec = MeshSpec(dp=n // ep, ep=ep)
    mesh, spec = make_mesh(devices, spec)
    dims = moe_model_dims(spec)
    rng = np.random.RandomState(0)
    x = rng.normal(0, 1.0, (dims["tokens"], dims["d"])).astype(
        np.float32)
    curves = []
    for _trial in range(2):
        step, place = build_moe_train_step(mesh, spec)
        params, xd = place(init_moe_params(spec), x)
        losses = []
        for _s in range(int(steps)):
            params, loss = step(params, xd)
            losses.append(float(loss))
        curves.append(losses)
    if not all(np.isfinite(curves[0])):
        raise RuntimeError(f"moe dryrun loss not finite: {curves[0]}")
    if not curves[0][-1] < curves[0][0]:
        raise RuntimeError(f"moe dryrun loss did not descend: "
                           f"{curves[0]}")
    if curves[0] != curves[1]:
        raise RuntimeError(
            f"moe dryrun loss not bit-stable across builds: "
            f"{curves[0]} vs {curves[1]}")
    print(f"moe dryrun ok: mesh={spec.sizes()} "
          f"experts={dims['n_experts']} cap={dims['capacity']} "
          f"loss {curves[0][0]:.6f} -> {curves[0][-1]:.6f}")
    return curves[0]


def expert_ffn_fused(a, b, mesh, axis: str = EXPERT_AXIS,
                     interpret: bool = True):
    """Expert-sharded GEMM with its reduction epilogue through the
    coll/tuned DEVICE ladder cell (``ops/pallas_overlap``
    ``matmul_allreduce``) when the ladder admits it; otherwise the
    unfused einsum contraction of the same shards.  Top-level API —
    fused cells build their own shard_map, so this cannot be called
    from inside one.  ``a``: (n, M, K/n) expert-sharded activations,
    ``b``: (n, K/n, N) matching weight shards; returns (M, N)."""
    from ompi_tpu.mca.coll import tuned

    cell = tuned.device_cell("matmul_allreduce")
    if cell is not None:
        return cell(a, b, mesh, axis, interpret=interpret)
    import jax.numpy as jnp

    return jnp.einsum("nmk,nko->mo", jnp.asarray(a), jnp.asarray(b))


# -- quantized dispatch: the PR 15 codec on the ragged device slot -------

#: scale lanes appended per row by the int8 dispatch packing (holds up
#: to 128 block scales, i.e. payload widths up to 16384)
_SCALE_PAD = 128


def encode_dispatch_int8(x):
    """Pack f32 token rows for the ragged device slot: per-128-block
    int8 quantization (round-half-even, absmax/127 scales — the
    coll/quant codec layout) with the int8 lanes bitcast 4-per-int32
    and the block scales appended (f32 bits reinterpreted as int32),
    so the payload is a plain int32 slab the ``*v_array`` kernels move
    unchanged.  The wire dtype is INTEGER on purpose: arbitrary int8
    lane groups reinterpreted as f32 form NaN payloads, and any
    transport hop that canonicalizes NaNs silently corrupts lanes.
    (..., R, W) -> (..., R, W/4 + 128); requires W % 512 == 0."""
    import jax.numpy as jnp
    from jax import lax

    x = jnp.asarray(x, jnp.float32)
    lead, (R, W) = x.shape[:-2], x.shape[-2:]
    if W % 512:
        raise ValueError(f"int8 dispatch packing needs width % 512 "
                         f"== 0, got {W}")
    nb = W // 128
    if nb > _SCALE_PAD:
        raise ValueError(f"width {W} exceeds the {_SCALE_PAD}-block "
                         "scale budget")
    blocks = x.reshape(lead + (R, nb, 128))
    amax = jnp.max(jnp.abs(blocks), axis=-1)
    inv = jnp.where(amax > 0, 127.0 / amax, 0.0)
    q = jnp.round(blocks * inv[..., None]).astype(jnp.int8)
    qi = lax.bitcast_convert_type(
        q.reshape(lead + (R, W // 4, 4)), jnp.int32)
    pad = [(0, 0)] * (len(lead) + 1) + [(0, _SCALE_PAD - nb)]
    scales = jnp.pad((amax / 127.0).astype(jnp.float32), pad)
    return jnp.concatenate(
        [qi, lax.bitcast_convert_type(scales, jnp.int32)], axis=-1)


def decode_dispatch_int8(y, width: int):
    """Inverse of :func:`encode_dispatch_int8` for rows of original
    width ``width``; accepts any (..., R', W/4 + 128) slab (R' may be
    a ragged count slice)."""
    import jax.numpy as jnp
    from jax import lax

    y = jnp.asarray(y, jnp.int32)
    W = int(width)
    nb = W // 128
    q = lax.bitcast_convert_type(y[..., :W // 4], jnp.int8)
    q = q.reshape(y.shape[:-1] + (nb, 128))     # (..., W/4, 4) lanes
    scales = lax.bitcast_convert_type(y[..., W // 4:W // 4 + nb],
                                      jnp.float32)
    out = q.astype(jnp.float32) * scales[..., None]
    return out.reshape(y.shape[:-1] + (W,))


def dispatch_tokens(comm, x, counts):
    """MoE token dispatch over the comm's ragged device slot
    (``alltoallv_array`` → ``ops/pallas_collectives.all_to_all_v``).

    When the comm carries an ``otpu_quant_budget`` info key admitting
    int8 (the PR 15 accuracy contract, via ``coll/quant``'s pure
    decision ladder), rows cross the wire block-int8 packed at ~3.5x
    fewer bytes and are decoded on arrival.  Returns ``(outs, codec)``
    where ``outs[i][j]`` is the (counts[j][i], W) f32 block rank i
    received from rank j and ``codec`` is the engaged codec or None."""
    from ompi_tpu.mca.coll import quant as quant_mod

    x = np.asarray(x, np.float32)
    n = x.shape[0]
    R, W = int(x.shape[2]), int(x.shape[3])
    codec = quant_mod.pick(comm, "alltoallv", np.float32, x.nbytes)
    if codec != "int8" or W % 512 or R == 0:
        return comm.alltoallv_array(x, counts), None
    enc = np.asarray(encode_dispatch_int8(x))
    spc.record("quant_encodes", n * n)
    outs = comm.alltoallv_array(enc, counts)
    dec = [[np.asarray(decode_dispatch_int8(np.asarray(outs[i][j]), W))
            for j in range(n)] for i in range(n)]
    spc.record("quant_decodes", n * n)
    return dec, codec


def run_quant_dispatch_check(nranks: int = 4,
                             sizes=(1 << 14, 1 << 16),
                             band: float = None) -> dict:
    """Acceptance for the quantized dispatch: the int8-packed path
    through the REAL ragged device kernel must stay inside the
    declared ``otpu_quant_budget`` band (``dryrun.run_tolerance_check``
    names any failing cell).  The exact reference is the dispatch
    permutation itself — out[j, i] = x[i, j] — which is an involution,
    so one more swap returns to input layout."""
    import jax
    from jax.sharding import Mesh

    from ompi_tpu.mca.coll import quant as quant_mod
    from ompi_tpu.ops import pallas_collectives as pc
    from ompi_tpu.parallel import dryrun

    band = float(band if band is not None
                 else quant_mod.CODEC_BANDS["int8"])
    W = 512
    devs = jax.devices()
    mesh = (Mesh(np.array(devs[:nranks]), ("x",))
            if len(devs) >= nranks else None)

    def exact(stack):
        n, size = stack.shape
        x = stack.reshape(n, n, size // (n * W), W)
        return np.swapaxes(x, 0, 1).reshape(n, size)

    def approx(stack):
        n, size = stack.shape
        R = size // (n * W)
        x = stack.reshape(n, n, R, W).astype(np.float32)
        enc = np.asarray(encode_dispatch_int8(x))
        if mesh is not None:
            out = np.asarray(pc.all_to_all_v(
                enc, np.full((n, n), R, np.int32), mesh, "x"))
        else:
            out = np.swapaxes(enc, 0, 1)
        return np.asarray(decode_dispatch_int8(out, W)).reshape(n, size)

    return dryrun.run_tolerance_check("alltoallv", approx,
                                      exact_fn=exact, sizes=sizes,
                                      nranks=nranks, band=band)


# -- worker entry --------------------------------------------------------

def main(argv: Optional[list] = None) -> int:
    """``python -m ompi_tpu.parallel.moe '<json-conf>'`` — one
    self-contained expert-parallel training rank (tpurun jobs and
    examples/moe_train_demo.py launch these).  Rank 0 prints
    ``MOE <report-json>``."""
    import sys

    import ompi_tpu

    args = sys.argv[1:] if argv is None else list(argv)
    conf = json.loads(args[0]) if args else {}
    steps = int(conf.pop("steps", 8))
    ckpt_dir = conf.pop("ckpt_dir")
    ompi_tpu.init()
    w = ompi_tpu.COMM_WORLD
    trainer = MoeTrainer(w, ckpt_dir, **conf)
    trainer.train(steps)
    if trainer.comm.rank == 0:
        print("MOE " + json.dumps(trainer.report()))
    ompi_tpu.finalize()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
