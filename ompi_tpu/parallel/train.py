"""The flagship training step: dp × pp × sp × tp(+ep) in one shard_map.

Assembles the explicit-SPMD transformer (model.py) and pipeline
(pipeline.py) into a jitted train step over a 4-axis mesh:

- activations sharded (dp: batch, sp: sequence), weights sharded (pp:
  layers, tp: hidden/heads/experts)
- grad sync = ``psum`` over (dp, sp) — the DP allreduce
  (≅ ``coll_base_allreduce.c`` ring; SURVEY.md §2.6)
- loss reduced across the pipeline with a pp-masked psum

Model dims are *derived from the mesh spec* so every axis size divides its
tensor dims — the driver's ``dryrun_multichip`` runs this for arbitrary
device counts.
"""
from __future__ import annotations

import numpy as np

from ompi_tpu.base.var import VarType, registry
from ompi_tpu.parallel.mesh import MeshSpec
from ompi_tpu.parallel.model import transformer_block
from ompi_tpu.parallel.pipeline import pipeline_apply

_sp_impl_var = registry.register(
    "parallel", None, "sp_impl", vtype=VarType.STRING, default="ring",
    enum_values={"ring": 0, "ulysses": 1},
    help="Sequence/context-parallel attention scheme: 'ring' (ppermute "
         "K/V rotation, O(s_local) memory) or 'ulysses' (all-to-all "
         "head<->seq reshard, 2 collectives; local heads must divide sp)")

_causal_var = registry.register(
    "parallel", None, "causal", vtype=VarType.BOOL, default=False,
    help="Autoregressive (causal) attention masking at GLOBAL sequence "
         "positions — ring attention builds the per-step block bias "
         "from the shard offsets; ulysses masks the full sequence "
         "after its reshard")

_remat_var = registry.register(
    "parallel", None, "remat", vtype=VarType.BOOL, default=False,
    help="Rematerialize each transformer block in the backward pass "
         "(jax.checkpoint): activation HBM drops from all layers' "
         "intermediates to one block's, paying ~1/3 more FLOPs — the "
         "standard long-context/deep-stack memory lever")

_zero1_var = registry.register(
    "parallel", None, "zero1", vtype=VarType.BOOL, default=False,
    help="ZeRO-1 distributed optimizer: gradients reduce-scatter over "
         "dp (instead of allreduce), each dp rank updates its 1/dp "
         "parameter slice + momentum shard, and the updated slices "
         "rebuild via an exact masked psum — optimizer state memory "
         "drops by dp")

_bucket_var = registry.register(
    "parallel", None, "bucket_overlap", vtype=VarType.BOOL, default=False,
    help="Bucketed dp-gradient sync (the mca/part Pready schedule "
         "expressed in-jit): one psum per local-layer bucket issued "
         "late-layer-first instead of one whole-tree psum, so XLA can "
         "overlap each bucket's allreduce with work on other buckets — "
         "bit-identical parameters to the single-psum path "
         "(parallel/dryrun.py run_bucket_overlap_check pins it)")

_momentum_var = registry.register(
    "parallel", None, "momentum", vtype=VarType.FLOAT, default=0.0,
    help="SGD momentum for the flagship step (state is dp-sharded "
         "under parallel_zero1)")

_compute_dtype_var = registry.register(
    "parallel", None, "compute_dtype", vtype=VarType.STRING,
    default="float32", enum_values={"float32": 0, "bfloat16": 1},
    help="Block compute precision: bfloat16 runs the MXU at full rate "
         "and halves activation bytes (params stay float32 storage; "
         "cast at block entry, loss/grads accumulate in float32)")


def model_dims(spec: MeshSpec, layers: int = None) -> dict:
    """``layers`` defaults to one per pipeline stage; override (a
    multiple of pp) to hold model depth fixed across mesh specs — the
    pp=2-vs-pp=1 equivalence tests depend on it.

    ``OTPU_MODEL_SCALE`` multiplies the width/sequence dims (default 1:
    the compile-check scale every correctness test uses).  The bench's
    single-chip MFU row raises it so the SAME flagship program is
    measured at MXU-saturating sizes instead of tracing-scale ones."""
    import os

    scale = max(1, int(os.environ.get("OTPU_MODEL_SCALE", "1") or 1))
    tp, sp, dp, pp = spec.tp, spec.sp, spec.dp, spec.pp
    L = pp if layers is None else int(layers)
    if L % pp:
        raise ValueError(f"layers={L} not divisible by pp={pp}")
    d = 8 * scale
    hd = 4 * scale
    n_heads = 2 * tp
    ff = 8 * tp * scale
    n_experts = 2 * tp
    ffe = 4 * scale
    s_local = 4 * scale
    M = 2                      # microbatches
    mb = tp                    # microbatch rows per device (keeps MoE even)
    t_local = mb * s_local     # MoE tokens per device per microbatch
    cap = max(1, (t_local // tp) // n_experts * 2)
    return dict(
        d=d, hd=hd, n_heads=n_heads, h_local=n_heads // tp, ff=ff,
        n_experts=n_experts, ffe=ffe, seq=s_local * sp, s_local=s_local,
        M=M, mb=mb, batch=mb * M * dp, b_local=mb * M, capacity=cap,
        layers=L, layers_local=L // pp,
    )


def init_params(spec: MeshSpec, seed: int = 0, layers: int = None) -> dict:
    dims = model_dims(spec, layers)
    rng = np.random.RandomState(seed)
    d, L = dims["d"], dims["layers"]
    hh = dims["n_heads"] * dims["hd"]

    def w(*shape):
        return rng.normal(0, 0.5 / np.sqrt(shape[-2]), shape).astype(
            np.float32)

    return {
        "wq": w(L, d, hh), "wk": w(L, d, hh), "wv": w(L, d, hh),
        "wo": w(L, hh, d),
        "w1": w(L, d, dims["ff"]), "w2": w(L, dims["ff"], d),
        "wr": w(L, d, dims["n_experts"]),
        "we1": w(L, dims["n_experts"], d, dims["ffe"]),
        "we2": w(L, dims["n_experts"], dims["ffe"], d),
    }


def param_specs(P) -> dict:
    return {
        "wq": P("pp", None, "tp"), "wk": P("pp", None, "tp"),
        "wv": P("pp", None, "tp"), "wo": P("pp", "tp", None),
        "w1": P("pp", None, "tp"), "w2": P("pp", "tp", None),
        "wr": P("pp", None, None),
        "we1": P("pp", "tp", None, None), "we2": P("pp", "tp", None, None),
    }


def build_train_step(mesh, spec: MeshSpec, lr: float = 1e-4,
                     layers: int = None):
    """Return (jitted_step, place) where step(params, x) -> (params, loss).

    ``place(params, x_np)`` device_puts globals with the right shardings.
    """
    import jax
    import jax.numpy as jnp
    from ompi_tpu.base.jaxenv import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    dims = model_dims(spec, layers)
    tp, sp_n, pp = spec.tp, spec.sp, spec.pp
    M, mb, s_l, d = dims["M"], dims["mb"], dims["s_local"], dims["d"]
    sp_impl = str(_sp_impl_var.value)
    causal = bool(_causal_var.value)

    compute_dtype = jnp.dtype(str(_compute_dtype_var.value))

    def apply_block(layer, x_mb):
        if compute_dtype != jnp.float32:
            # bf16 compute: params cast per block (storage stays f32 —
            # the master-weights discipline), activations stay bf16
            # across the stack; the f32 loss/grad path upcasts at exit
            layer = jax.tree.map(
                lambda a: a.astype(compute_dtype)
                if jnp.issubdtype(a.dtype, jnp.floating) else a, layer)
        out = transformer_block(
            layer, x_mb, sp=sp_n, tp=tp,
            n_heads_local=dims["h_local"],
            n_experts=dims["n_experts"], capacity=dims["capacity"],
            sp_impl=sp_impl, causal=causal)
        return out

    if bool(_remat_var.value):
        # recompute the block in the backward instead of storing its
        # activations — the jax.checkpoint form of the trade every
        # deep/long-context stack makes on HBM-bound chips
        # prevent_cse=False: apply_block runs inside pipeline_apply's
        # scan, which already provides the CSE barrier — the default
        # setting would only add optimization barriers on the hot path
        apply_block = jax.checkpoint(apply_block, prevent_cse=False)

    def stage_fn(stage_params, x_mb):
        for i in range(dims["layers_local"]):
            layer = jax.tree.map(lambda a: a[i], stage_params)
            x_mb = apply_block(layer, x_mb)
        return x_mb

    zero1 = bool(_zero1_var.value)
    mu = float(_momentum_var.value)
    if mu and not zero1:
        raise ValueError(
            "parallel_momentum is implemented by the ZeRO-1 sharded "
            "optimizer state — set --mca parallel_zero1 1 with it "
            "(a silently momentum-free run would corrupt comparisons)")
    bucket_overlap = bool(_bucket_var.value)
    if bucket_overlap and zero1:
        raise ValueError(
            "parallel_bucket_overlap buckets the dp ALLREDUCE; ZeRO-1 "
            "already reduce-scatters the dp sum — the combination is "
            "unsupported (a silent fallback would corrupt comparisons)")
    dp = spec.dp

    def bucketed_dp_sync(g):
        """Per-local-layer psum buckets, LATE layer first — the Pready
        release order of a backward pass (the last layer's gradient is
        finished first).  Elementwise psum over the same replica set
        makes each bucket bit-identical to its slice of the whole-leaf
        psum; jnp.stack restores the leaf."""
        parts = [jax.lax.psum(g[i], ("dp", "sp"))
                 for i in range(g.shape[0] - 1, -1, -1)]
        return jnp.stack(parts[::-1], axis=0)

    def body(state, x):
        if zero1:
            params, carry_m = state
        else:
            params, carry_m = state, None

        def loss_fn(ps):
            # activations enter the pipeline in compute_dtype so the
            # scan carries / ppermute handoffs stay half-width too
            xmb = x.reshape(M, mb, s_l, d).astype(compute_dtype)
            y = pipeline_apply(stage_fn, ps, xmb, pp=pp,
                               vary_axes=("pp", "tp"))
            # pipeline_apply outputs are zero off the last pp stage, so
            # the psum over pp collects exactly the last stage's loss.
            # y is value-replicated across tp but vma-varying (it came
            # through tp collectives): count the tp=0 replica only, so
            # the psum over ALL axes is both value-correct and provably
            # unvarying — gradients to the other tp shards still flow
            # through the block's internal tp-psum transposes
            yf = y.astype(jnp.float32)     # f32 loss accumulation
            local = 0.5 * jnp.sum(yf * yf)
            local = jnp.where(jax.lax.axis_index("tp") == 0, local, 0.0)
            return jax.lax.psum(local, ("dp", "pp", "sp", "tp"))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        if not zero1:
            sync = bucketed_dp_sync if bucket_overlap else \
                (lambda g: jax.lax.psum(g, ("dp", "sp")))
            grads = jax.tree.map(sync, grads)
            if tp > 1:
                grads["wr"] = jax.lax.psum(grads["wr"], "tp")
            new = jax.tree.map(lambda p, g: p - lr * g, params, grads)
            return new, loss
        # ZeRO-1: the dp sum rides a reduce-scatter (same bytes as the
        # allreduce it replaces), each dp rank owns 1/dp of the flat
        # parameter/momentum state, and the updated slices all-gather
        # back — the FSDP/ZeRO optimizer-state sharding pattern in
        # psum_scatter + all_gather form
        from jax.flatten_util import ravel_pytree

        grads = jax.tree.map(lambda g: jax.lax.psum(g, "sp"), grads)
        if tp > 1:
            grads["wr"] = jax.lax.psum(grads["wr"], "tp")
        # grads and params share one pytree structure: a single ravel
        # provides both the flat vector and the shared unravel
        gflat, unravel = ravel_pytree(grads)
        total = gflat.shape[0]
        chunk = -(-total // dp)
        gpad = jnp.pad(gflat, (0, chunk * dp - total))
        gsl = jax.lax.psum_scatter(gpad.reshape(dp, chunk), "dp",
                                   scatter_dimension=0, tiled=False)
        m = carry_m
        m_new = mu * m + gsl
        r = jax.lax.axis_index("dp")
        # rebuild via masked psum, NOT all_gather: psum's output is
        # provably dp-INVARIANT under the vma checker (all_gather's
        # equal-by-construction result still types as varying), so the
        # replicated param out_specs hold without weakening check_vma
        contrib = jax.lax.dynamic_update_slice(
            jnp.zeros((chunk * dp,), gsl.dtype), -lr * m_new,
            (r * chunk,))
        delta_flat = jax.lax.psum(contrib, "dp")[:total]
        dtree = unravel(delta_flat)
        # leaves REPLICATED over tp (wr): the flat state mixes
        # tp-sharded leaves, so their delta types tp-varying even
        # though its value is identical on every tp shard — one exact
        # masked psum (only shard 0 contributes) restores provable
        # tp-invariance with zero fp perturbation.  UNCONDITIONAL:
        # m_spec carries "tp" even at axis size 1
        tpi = jax.lax.axis_index("tp")
        for k, sspec in pspecs.items():
            if "tp" not in tuple(sspec):
                dtree[k] = jax.lax.psum(
                    jnp.where(tpi == 0, dtree[k],
                              jnp.zeros_like(dtree[k])), "tp")
        new = jax.tree.map(lambda p_, d_: p_ + d_, params, dtree)
        return (new, m_new), loss

    pspecs = param_specs(P)
    # check_vma=True is LOAD-BEARING for correctness, not just a lint:
    # the varying-manifest tracking is what makes the ppermute/psum
    # transposes in the pp>=2 backward correct.  With it off the
    # composed step compiles and descends — with silently wrong
    # pipeline gradients (caught by test_pp2_matches_pp1_same_model).
    if zero1:
        # momentum shard: one (chunk,) block per (dp, pp, tp) shard of
        # the flat local parameter vector — a 1-D array sharded over
        # all three axes (sp replicates: grads are sp-summed first)
        m_spec = P(("dp", "pp", "tp"))
        state_specs = ((pspecs, m_spec), P("dp", "sp", None))
        out_state_specs = ((pspecs, m_spec), P())
    else:
        state_specs = (pspecs, P("dp", "sp", None))
        out_state_specs = (pspecs, P())
    step = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=state_specs,
        out_specs=out_state_specs,
        check_vma=True))

    def place(params, x_np):
        p = {k: jax.device_put(v, NamedSharding(mesh, pspecs[k]))
             for k, v in params.items()}
        x = jax.device_put(
            np.asarray(x_np, np.float32),
            NamedSharding(mesh, P("dp", "sp", None)))
        if zero1:
            # local flat size: each leaf's global shape divided by the
            # MESH size of every axis its spec shards it over — the
            # same division shard_map applies, so body's traced
            # ravel_pytree total always agrees (axis sizes come from
            # mesh.shape, never a hand-maintained map)
            sizes = 0
            for k, v in params.items():
                shp = list(np.asarray(v).shape)
                for dim, ax in enumerate(pspecs[k]):
                    if ax is None:
                        continue
                    for a in (ax if isinstance(ax, tuple) else (ax,)):
                        shp[dim] //= mesh.shape[a]
                sizes += int(np.prod(shp))
            chunk = -(-sizes // spec.dp)
            m0 = np.zeros(chunk * spec.dp * spec.pp * spec.tp,
                          np.float32)
            mdev = jax.device_put(m0, NamedSharding(mesh, m_spec))
            return (p, mdev), x
        return p, x

    return step, place
