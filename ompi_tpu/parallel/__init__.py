"""ompi_tpu.parallel — first-class ML-parallelism toolkit over the mesh.

The reference is the communication substrate *under* ML parallelism
(SURVEY.md §2.6): DP/TP/PP/SP/EP are what users build on MPI.  Here they
are first-class: a 4-axis ``Mesh`` (dp, pp, sp, tp) with

- **dp**  — data parallel gradient sync (``psum`` ≅ allreduce ring,
  ``coll_base_allreduce.c:341``)
- **pp**  — pipeline stage handoff (``ppermute`` ≅ pml send/recv between
  stage ranks, ``pml_ob1_isend.c:233``)
- **sp**  — sequence/context parallelism: ring attention over a
  ``ppermute`` ring (the segmented-ring pipeline shape,
  ``coll_base_allreduce.c:618``)
- **tp**  — tensor parallel matmuls with ``psum`` combine; the same axis
  carries **ep** (MoE expert parallel) via ``all_to_all`` dispatch
  (≅ ``coll_base_alltoall.c`` pairwise exchange)
"""
from ompi_tpu.parallel.mesh import MeshSpec, make_mesh, default_axis_sizes
from ompi_tpu.parallel.train import build_train_step, init_params, model_dims

__all__ = [
    "MeshSpec", "make_mesh", "default_axis_sizes",
    "build_train_step", "init_params", "model_dims",
]
