"""Runtime environment (RTE): launch, wire-up, coordination.

The PMIx/PRRTE-equivalent layer (``/root/reference/ompi/runtime/ompi_rte.c``
+ external OpenPMIx): process naming, modex KV exchange, fences, event bus,
spawn.  Two first-class process models:

- **device-world** (TPU-native SPMD): one controller process, ranks are the
  devices of a ``jax.sharding.Mesh``; collectives are XLA programs over ICI.
- **multi-process**: classic MPI ranks launched by ``tpurun``, wired up
  through the coordination service (``ompi_tpu.rte.coord``).
"""
