"""Coordination service — the PMIx/PRRTE-equivalent wire-up server.

Plays the role OpenPMIx plays for the reference (``ompi/runtime/
ompi_rte.c:568`` ``PMIx_Init``; ``PMIx_Fence`` modex at
``ompi_mpi_init.c:682-701``; PMIx events for ULFM): a small TCP server owned
by the launcher (``tpurun``) providing the job KV space (modex), fences,
pub/sub events (failure notification rides here), and job control (abort).
Protocol: length-prefixed pickle frames (trusted within one job, like PMIx's
unix-socket wire protocol).

**Self-healing client**: every FT path in the stack leans on this
connection, so a single TCP reset during a fence must not kill the rank.
Each request carries an idempotent id (client uuid + monotonic rid); on a
connection error the client reconnects with exponential backoff + jitter
and retries the SAME request.  The server keeps a small per-client replay
cache — a request whose processing completed before the reset is answered
from the cache, one still in flight is adopted (the retry waits for the
original's result) — so a fence or fetch_add interrupted mid-RPC is
applied exactly once.  Timeouts are MCA vars (``otpu_coord_*``) and expire
with a loud ``show_help`` naming the rank, the op, and how long it waited
— never a bare socket timeout or an indefinite hang.
"""
from __future__ import annotations

import contextlib
import os
import pickle
import random
import socket
import struct
import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Any, Optional

from ompi_tpu.base.var import VarType, registry

_LEN = struct.Struct("!I")

_connect_timeout_var = registry.register(
    "coord", None, "connect_timeout", vtype=VarType.FLOAT, default=120.0,
    help="Seconds a rank waits dialing (or re-dialing) the coordination "
         "service before the attempt counts as failed")
_rpc_timeout_var = registry.register(
    "coord", None, "rpc_timeout", vtype=VarType.FLOAT, default=120.0,
    help="Socket-level ceiling on one coordination RPC (fences block "
         "server-side, so this bounds how long a rank may sit inside "
         "one); expiry is a loud show_help error naming rank and op")
_get_timeout_var = registry.register(
    "coord", None, "get_timeout", vtype=VarType.FLOAT, default=60.0,
    help="Default server-side wait for a blocking KV get (modex key "
         "not yet published)")
_final_timeout_var = registry.register(
    "coord", None, "final_timeout", vtype=VarType.FLOAT, default=10.0,
    help="Timeout of the one-shot finalize fence's dedicated "
         "connection — a peer that exited without fencing costs at "
         "most this long")
_retry_max_var = registry.register(
    "coord", None, "retry_max", vtype=VarType.INT, default=8,
    help="Reconnect-and-retry attempts after a connection error before "
         "the RPC fails loudly (0 disables self-healing: components "
         "with their own fallback carrier — detector, event poller — "
         "opt out so a dead coord never stalls them)")
_backoff_var = registry.register(
    "coord", None, "retry_backoff", vtype=VarType.FLOAT, default=0.05,
    help="Base of the reconnect exponential backoff in seconds "
         "(doubled per attempt, jittered, capped at 2s)")
_recovery_retry_max_var = registry.register(
    "coord", None, "recovery_retry_max", vtype=VarType.INT, default=24,
    help="Reconnect-and-retry budget for RPCs issued inside a recovery "
         "scope (ULFM shrink / agreement rounds): every survivor slams "
         "the coordination server at once right after a failure, so "
         "recovery RPCs get a longer ladder than the steady-state "
         "otpu_coord_retry_max instead of flaking the whole shrink.  "
         "0 inherits otpu_coord_retry_max")
_recovery_rpc_timeout_var = registry.register(
    "coord", None, "recovery_rpc_timeout", vtype=VarType.FLOAT,
    default=0.0,
    help="Socket-level ceiling on one coordination RPC while inside a "
         "recovery scope; 0 (the default) inherits "
         "otpu_coord_rpc_timeout")


def _send_frame(sock: socket.socket, obj: Any) -> None:
    payload = pickle.dumps(obj)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> Any:
    hdr = b""
    while len(hdr) < _LEN.size:
        chunk = sock.recv(_LEN.size - len(hdr))
        if not chunk:
            raise ConnectionError("coordination peer closed")
        hdr += chunk
    (n,) = _LEN.unpack(hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 16, n - len(buf)))
        if not chunk:
            raise ConnectionError("coordination peer closed")
        buf += chunk
    return pickle.loads(bytes(buf))


class CoordServer:
    """Job-wide KV + fence + event service (runs inside the launcher)."""

    #: otpu-lint lock-discipline contract: each service table mutates
    #: only under its condition/lock.  The declaration also arms the
    #: no-blocking-under-lock check: per-connection replies
    #: (``_send_frame`` = blocking sendall) must never run while a
    #: condition is held — one slow-reading client would stall every
    #: fence/KV/event operation job-wide (helpers named *_locked run
    #: with the lock held by the caller).
    _guarded_by = {
        "_kv": "_kv_cond", "_psets": "_kv_cond",
        "_next_rank": "_kv_cond", "_spawn_seq": "_kv_cond",
        "_fence_ranks": "_fence_cond", "_fence_gen": "_fence_cond",
        "_fence_done": "_fence_cond", "_fence_expect": "_fence_cond",
        "_failed": "_fence_cond",
        "_events": "_event_cond", "_event_seq": "_event_cond",
        "_event_times": "_event_cond",
        "_conns": "_conns_lock",
        "_rpc_cache": "_rpc_cond", "_inflight": "_rpc_cond",
    }

    #: replay-cache depth per client: the client serializes requests, so
    #: only the newest rid can be retried — a couple of spares absorb
    #: the abandoned-timeout-then-reset corner without unbounded growth
    _REPLAY_DEPTH = 4

    def __init__(self, nprocs: int, host: str = "127.0.0.1", port: int = 0):
        self.nprocs = nprocs
        self._kv: dict[tuple, Any] = {}
        self._kv_cond = threading.Condition()
        self._fence_ranks: dict[str, set] = {}
        self._fence_gen: dict[str, int] = {}
        self._fence_cond = threading.Condition()
        self._events: list[tuple[int, str, Any]] = []
        self._event_seq = 0
        # wall-clock stamp per event seq — the poll wire format stays
        # (seq, name, payload); the flight-recorder bundle reads the
        # times through flight_view() instead
        self._event_times: dict[int, float] = {}
        self._event_cond = threading.Condition()
        self._aborted: Optional[int] = None
        self._failed: set[int] = set()
        # process-set registry (MPI-4 psets; the PMIx_Get PMIX_PSET_NAMES
        # role): name -> {"members": [ranks], "source": str}.  The
        # launcher publishes mpi://WORLD / per-host / user sets at job
        # start; spawn and failure events update dynamic sets.
        self._psets: dict[str, dict] = {}
        self._fence_expect: dict[str, tuple] = {}
        self._fence_done: set[str] = set()
        self._next_rank = nprocs          # global rank allocator (dpm spawn)
        self._spawn_handler = None        # set by the launcher (tpurun)
        self._spawn_seq = 0
        # idempotent-retry replay cache: client uuid -> {rid: response}.
        # A retried rid already processed is answered from here; one
        # still being processed is adopted (the retry thread waits for
        # the original's stored result instead of re-applying the op).
        self._rpc_cache: "OrderedDict[str, OrderedDict]" = OrderedDict()
        self._inflight: dict[str, int] = {}
        self._rpc_cond = threading.Condition()
        self._srv = socket.create_server((host, port))
        self.addr = self._srv.getsockname()
        self._threads: list[threading.Thread] = []
        self._conns: list[socket.socket] = []
        self._conns_lock = threading.Lock()
        self._accepting = True
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    # -- server internals ------------------------------------------------
    def _accept_loop(self) -> None:
        while self._accepting:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            with self._conns_lock:
                if not self._accepting:
                    # raced shutdown: a connection accepted while close()
                    # ran must not be left alive past it
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return
                self._conns.append(conn)
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        try:
            self._serve_loop(conn)
        finally:
            with self._conns_lock:
                try:
                    self._conns.remove(conn)   # prune on disconnect
                except ValueError:
                    pass

    def _serve_loop(self, conn: socket.socket) -> None:
        try:
            while True:
                req = _recv_frame(conn)
                cid = req.get("_cid")
                rid = req.get("_rid")
                if cid is not None and rid is not None:
                    resp = self._replay_or_claim(cid, rid)
                    if resp is None:
                        try:
                            self._maybe_stall(req)
                            resp = self._handle(req, conn)
                        except Exception as exc:
                            # a malformed/version-skewed request must
                            # not strand its in-flight claim (a retry
                            # would spin on it forever) — store a loud
                            # error response instead
                            resp = {"ok": False,
                                    "error": f"server error: {exc!r}"}
                        self._store_reply(cid, rid, resp)
                else:
                    # legacy/anonymous request: process directly
                    resp = self._handle(req, conn)
                _send_frame(conn, resp)
        except (ConnectionError, OSError):
            return

    def _replay_or_claim(self, cid: str, rid: int) -> Optional[dict]:
        """Duplicate-safe entry: a cached rid replays its stored
        response; an in-flight rid is adopted (wait for the original
        thread's result); a fresh rid is claimed for processing
        (returns None)."""
        with self._rpc_cond:
            while True:
                cached = self._rpc_cache.get(cid)
                if cached is not None and rid in cached:
                    return cached[rid]
                if self._inflight.get(cid) != rid:
                    self._inflight[cid] = rid
                    return None
                self._rpc_cond.wait(0.5)

    def _store_reply(self, cid: str, rid: int, resp: dict) -> None:
        with self._rpc_cond:
            cache = self._rpc_cache.get(cid)
            if cache is None:
                cache = self._rpc_cache[cid] = OrderedDict()
            cache[rid] = resp
            while len(cache) > self._REPLAY_DEPTH:
                cache.popitem(last=False)
            if self._inflight.get(cid) == rid:
                del self._inflight[cid]
            # bound the per-client table count too (dead clients):
            # move-to-end keeps live clients out of the eviction edge
            self._rpc_cache.move_to_end(cid)
            while len(self._rpc_cache) > 4096:
                self._rpc_cache.popitem(last=False)
            self._rpc_cond.notify_all()

    def _maybe_stall(self, req: dict) -> None:
        """Chaos seam: a ``stall`` rule armed IN THIS PROCESS delays the
        server's processing of a fresh (non-replayed) request — the
        overloaded-coord model the client's timeout-retry path is
        regression-tested against.  Real multi-process jobs arm chaos in
        the ranks, never in the launcher, so this is inert there; only
        an in-process chaos-armed test reaches it.  Consulted AFTER the
        replay-cache claim: an adopted retry must not burn a firing."""
        from ompi_tpu.ft import chaos

        if not chaos.enabled:
            return
        rule = chaos.coord_stall("server:" + str(req.get("op")))
        if rule is not None:
            chaos.sleep_ms(rule)

    def _handle(self, req: dict, conn: socket.socket) -> dict:
        """Process one request; returns the response frame.  Replies are
        sent by the caller, never from under a service condition."""
        op = req["op"]
        if op == "put":
            with self._kv_cond:
                self._kv[(req["rank"], req["key"])] = req["value"]
                self._kv_cond.notify_all()
            return {"ok": True}
        if op == "del":
            with self._kv_cond:
                self._kv.pop((req["rank"], req["key"]), None)
            return {"ok": True}
        if op == "put_new":
            # atomic put-if-absent: first writer wins, everyone gets
            # the winning value back (consensus decision slots)
            with self._kv_cond:
                k = (req["rank"], req["key"])
                if k not in self._kv:
                    self._kv[k] = req["value"]
                    self._kv_cond.notify_all()
                val = self._kv[k]
            return {"ok": True, "value": val}
        if op == "fetch_add":
            # atomic counter (shared file pointers, spawn ids):
            # returns the PRE-add value, like MPI_Fetch_and_op SUM
            with self._kv_cond:
                k = (req["rank"], req["key"])
                old = self._kv.get(k, 0)
                self._kv[k] = old + req["delta"]
                self._kv_cond.notify_all()
            return {"ok": True, "value": old}
        if op == "get":
            deadline = time.monotonic() + req.get("timeout", 60.0)
            with self._kv_cond:
                while (req["rank"], req["key"]) not in self._kv:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not req.get("wait", True):
                        break
                    self._kv_cond.wait(min(remaining, 1.0))
                val = self._kv.get((req["rank"], req["key"]))
            return {"ok": True, "value": val}
        if op == "fence":
            fid = req["id"]
            with self._fence_cond:
                if "expect" in req and req["expect"] is not None:
                    self._fence_expect.setdefault(
                        fid, tuple(req["expect"]))
                # per-rank contribution tracking: a fence completes
                # when every rank has either arrived or died — a
                # dead rank's earlier arrival must not release the
                # fence while a live survivor is still outside it
                oneshot = bool(req.get("oneshot"))
                if oneshot and fid in self._fence_done:
                    # late arrival to a completed one-shot round:
                    # fall through to the reply OUTSIDE the cond —
                    # otpu-lint found the blocking sendall here
                    # while _fence_cond was held, where one
                    # slow-reading late client stalled every
                    # fence/failure operation job-wide
                    pass
                else:
                    arrived = self._fence_ranks.setdefault(
                        fid, set())
                    arrived.add(req.get("rank", -1))
                    if self._fence_satisfied(fid):
                        self._complete_fence_locked(fid, oneshot)
                    else:
                        gen = self._fence_gen.get(fid, 0)
                        while self._fence_gen.get(fid, 0) == gen:
                            self._fence_cond.wait(1.0)
                            if self._aborted is not None:
                                break
                            # a failure may have lowered the bar
                            if self._fence_satisfied(fid):
                                self._complete_fence_locked(
                                    fid, oneshot)
                                break
            return {"ok": True}
        if op == "event_pub":
            # routed through publish() so in-band failure reports
            # (heartbeat detector) also update fence bookkeeping
            self.publish(req["name"], req["payload"])
            return {"ok": True}
        if op == "event_poll":
            since = req["since"]
            with self._event_cond:
                out = [e for e in self._events if e[0] > since]
            return {"ok": True, "events": out}
        if op == "abort":
            self._aborted = req.get("code", 1)
            with self._fence_cond:
                self._fence_cond.notify_all()
            return {"ok": True}
        if op == "spawn":
            # MPI_Comm_spawn's PMIx_Spawn analog: allocate fresh
            # global ranks, hand the launch to the launcher's
            # registered handler (it owns process management)
            if self._spawn_handler is None:
                return {"ok": False,
                        "error": "no spawn support (launcher too old?)"}
            n = int(req["n"])
            with self._kv_cond:
                ranks = list(range(self._next_rank,
                                   self._next_rank + n))
                self._next_rank += n
                self._spawn_seq += 1
                job = f"job{self._spawn_seq}"
            try:
                self._spawn_handler(
                    req["cmd"], ranks, job,
                    req.get("env") or {})
                # dynamic pset: the new job is addressable by
                # name before it builds any communicator
                self.publish_pset(f"mpi://job/{job}", ranks,
                                  source="spawn")
                return {"ok": True, "ranks": ranks, "job": job}
            except Exception as exc:
                return {"ok": False, "error": str(exc)}
        if op == "pset_pub":
            self.publish_pset(req["name"], req["members"],
                              req.get("source", "user"))
            return {"ok": True}
        if op == "pset_list":
            with self._kv_cond:
                rows = [{"name": n, "size": len(e["members"]),
                         "source": e["source"]}
                        for n, e in sorted(self._psets.items())]
            return {"ok": True, "psets": rows}
        if op == "pset_get":
            with self._kv_cond:
                entry = self._psets.get(req["name"])
            return {"ok": True, "pset": entry}
        if op == "ping":
            # "time" is the server's wall clock: ranks estimate
            # their offset to it (min-RTT, mpisync estimator) so
            # per-rank trace timelines share one timebase
            return {"ok": True, "nprocs": self.nprocs,
                    "aborted": self._aborted, "time": time.time()}
        return {"ok": False, "error": f"bad op {op}"}

    def _fence_satisfied(self, fid: str) -> bool:
        # caller holds _fence_cond
        arrived = self._fence_ranks.get(fid, set())
        expected = self._fence_expect.get(fid, range(self.nprocs))
        return all(r in arrived or r in self._failed for r in expected)

    def _complete_fence_locked(self, fid: str, oneshot: bool = False) -> None:
        # caller holds _fence_cond.  One-shot fences (finalize) record
        # completion permanently: a rank arriving LATE — released peers
        # treated it as failed (e.g. its heartbeats stopped but the
        # process lives) — must pass instead of waiting forever on peers
        # that already left.  Normal fences keep per-round generations so
        # re-used ids (runtime re-init) still synchronise.
        if oneshot:
            self._fence_done.add(fid)
        self._fence_ranks[fid] = set()
        self._fence_gen[fid] = self._fence_gen.get(fid, 0) + 1
        self._fence_cond.notify_all()

    def set_spawn_handler(self, fn) -> None:
        """Launcher registers how to exec spawned ranks:
        ``fn(cmd, global_ranks, job_id, extra_env)``."""
        self._spawn_handler = fn

    def publish_pset(self, name: str, members, source: str = "launcher") -> None:
        """(Re)publish a named process set — launcher-side at job start,
        server-side for dynamic sets (spawn/failure)."""
        with self._kv_cond:
            self._psets[str(name)] = {
                "members": [int(m) for m in members],
                "source": str(source)}
            self._kv_cond.notify_all()

    def kv_put(self, rank: int, key: str, value: Any) -> None:
        """Launcher-side KV injection (e.g. the jax coordinator address
        ranks fetch before their first backend touch)."""
        with self._kv_cond:
            self._kv[(rank, key)] = value
            self._kv_cond.notify_all()

    def publish(self, name: str, payload: Any) -> None:
        """Server-side event injection (launcher-detected failures)."""
        if name == "proc_failed":
            with self._fence_cond:
                self._failed.add(int(payload["rank"]))
                failed_now = set(self._failed)
                # a pending fence may now be satisfiable by the survivors
                for fid in list(self._fence_ranks):
                    if self._fence_ranks[fid] and self._fence_satisfied(fid):
                        self._complete_fence_locked(fid)
            # dynamic pset: the named surviving set the ULFM recovery
            # loop rebuilds from (world minus every known failure)
            with self._kv_cond:
                world = self._psets.get("mpi://WORLD", {}).get(
                    "members", list(range(self.nprocs)))
            self.publish_pset(
                "mpi://surviving",
                [r for r in world if r not in failed_now],
                source="dynamic")
        with self._event_cond:
            self._event_seq += 1
            self._events.append((self._event_seq, name, payload))
            self._event_times[self._event_seq] = time.time()
            self._event_cond.notify_all()

    @property
    def aborted(self) -> Optional[int]:
        return self._aborted

    def flight_view(self) -> dict:
        """The coord service's own post-mortem view — timestamped event
        log, known-failed ranks, advertised psets — merged into the
        flight-recorder bundle next to the per-rank dumps."""
        with self._event_cond:
            events = [{"seq": s, "name": n, "payload": p,
                       "t": self._event_times.get(s)}
                      for s, n, p in self._events]
        with self._fence_cond:
            failed = sorted(self._failed)
        with self._kv_cond:
            psets = {n: e["members"] for n, e in self._psets.items()}
        return {"events": events, "failed": failed, "psets": psets,
                "nprocs": self.nprocs, "aborted": self._aborted,
                "t": time.time()}

    def collect(self, key: str) -> dict:
        """{rank: value} of every KV entry published under ``key`` — the
        launcher-side gather of per-rank payloads (trace timelines)."""
        with self._kv_cond:
            return {r: v for (r, k), v in self._kv.items() if k == key}

    def close(self) -> None:
        """Full stop: the listener AND every live client connection.
        (A close that leaves established connections serving would make
        the service look alive to already-wired clients — the FT tests
        kill the coord to prove detection doesn't depend on it.)"""
        with self._conns_lock:
            self._accepting = False       # no new conns past this point
            conns = list(self._conns)
            self._conns.clear()
        try:
            self._srv.close()
        except OSError:
            pass
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass


class CoordClient:
    """Per-process client (the PMIx client analog) with idempotent
    reconnect-retry (see module docstring).

    ``retries``: reconnect attempts after a connection error; None takes
    ``otpu_coord_retry_max``.  Components with their OWN fallback
    carrier (heartbeat detector, event poller) pass 0 — a dead coord
    must fail them fast, not stall their loops through a backoff ladder.
    """

    def __init__(self, addr: Optional[tuple] = None,
                 timeout: Optional[float] = None,
                 retries: Optional[int] = None):
        if addr is None:
            spec = os.environ["OTPU_COORD"]
            host, port = spec.rsplit(":", 1)
            addr = (host, int(port))
        self._addr = (addr[0], int(addr[1]))
        # an explicit timeout overrides BOTH the connect and RPC vars
        # (fence_final's throwaway short-timeout connection)
        self._connect_timeout = (float(timeout) if timeout is not None
                                 else float(_connect_timeout_var.value))
        self._rpc_timeout = (float(timeout) if timeout is not None
                             else float(_rpc_timeout_var.value))
        self._retry_max = (int(retries) if retries is not None
                           else int(_retry_max_var.value or 0))
        self._backoff = float(_backoff_var.value or 0.05)
        self._rank_label = os.environ.get("OTPU_RANK", "?")
        #: >0 while inside recovery_scope(): RPCs take the recovery
        #: retry/timeout budget instead of the steady-state one (plain
        #: int under the GIL; scopes nest)
        self._recovery_depth = 0
        self._jitter = random.Random(f"coord-jitter:{self._rank_label}")
        self._cid = uuid.uuid4().hex      # idempotent-retry identity
        self._rid = 0
        self._closed = False
        self._applied_rto = self._rpc_timeout
        self._sock: Optional[socket.socket] = self._dial()
        self._lock = threading.Lock()
        self._event_since = 0
        # rolling last-N RPC ring for the flight recorder: (wall time,
        # op, rid, ok) — one deque append per RPC, read at crash time
        self._recent: deque = deque(maxlen=64)

    def _dial(self) -> socket.socket:
        sock = socket.create_connection(self._addr,
                                        timeout=self._connect_timeout)
        sock.settimeout(self._rpc_timeout)
        return sock

    def _rpc(self, **req) -> dict:
        with self._lock:
            self._rid += 1
            req["_cid"] = self._cid
            req["_rid"] = self._rid
            try:
                resp = self._rpc_locked(req)
            except BaseException:
                self._recent.append((time.time(), str(req.get("op")),
                                     self._rid, False))
                raise
            self._recent.append((time.time(), str(req.get("op")),
                                 self._rid, bool(resp.get("ok"))))
        if not resp.get("ok"):
            raise RuntimeError(f"coordination error: {resp.get('error')}")
        return resp

    def recent_rpcs(self) -> list:
        """Last-N completed/failed RPCs as ``[t_wall, op, rid, ok]``
        rows (the flight recorder's coord-activity tail)."""
        return [list(e) for e in self._recent]

    @contextlib.contextmanager
    def recovery_scope(self):
        """RPCs issued inside take the recovery budget
        (``otpu_coord_recovery_retry_max`` /
        ``otpu_coord_recovery_rpc_timeout``) instead of the
        steady-state one.  The recovery paths (shrink agreement
        rounds) wrap their coord traffic in this: right after a
        failure every survivor hits the server at once, and the
        steady-state ladder was measured too short for that burst
        (the documented fleet-soak coord-timeout flake).  Scopes
        nest; the budget reverts when the outermost exits."""
        self._recovery_depth += 1
        try:
            yield self
        finally:
            self._recovery_depth -= 1

    def _effective_retry_max(self) -> int:
        if self._recovery_depth > 0:
            rec = int(_recovery_retry_max_var.value or 0)
            if rec > 0:
                # never SHORTER than steady state: a caller that tuned
                # retry_max up keeps at least that much in recovery
                return max(rec, self._retry_max)
        return self._retry_max

    def _effective_rpc_timeout(self) -> float:
        if self._recovery_depth > 0:
            rto = float(_recovery_rpc_timeout_var.value or 0.0)
            if rto > 0.0:
                return rto
        return self._rpc_timeout

    def _rpc_locked(self, req: dict) -> dict:
        """One idempotent RPC round: send → (maybe injected fault) →
        recv; connection errors reconnect with exponential backoff +
        jitter and retry the SAME request (the server's replay cache
        makes the retry duplicate-safe)."""
        from ompi_tpu.base.output import show_help
        from ompi_tpu.ft import chaos
        from ompi_tpu.runtime import spc

        op = str(req.get("op"))
        attempts = 0
        while True:
            dialing = self._sock is None
            try:
                if dialing:
                    # reconnect: dial failures (refused, connect
                    # timeout) take the backoff ladder below, never the
                    # rpc-timeout path — the server may be restarting
                    self._sock = self._dial()
                    spc.record("coord_reconnects")
                    # past here a timeout is an RPC timeout again: the
                    # dial succeeded, the server is reachable
                    dialing = False
                    self._applied_rto = self._rpc_timeout
                rto = self._effective_rpc_timeout()
                if rto != self._applied_rto:
                    # recovery scope widens the per-RPC ceiling (and the
                    # first post-recovery RPC narrows it back)
                    self._sock.settimeout(rto)
                    self._applied_rto = rto
                if chaos.enabled:
                    rule = chaos.coord_stall(op)
                    if rule is not None:
                        chaos.sleep_ms(rule)
                _send_frame(self._sock, req)
                if chaos.enabled and chaos.coord_disconnect(op):
                    # injected mid-RPC reset: the request reached the
                    # server, the reply is lost — the retry below must
                    # be answered duplicate-safe from the replay cache
                    try:
                        self._sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    self._sock.close()
                return _recv_frame(self._sock)
            except TimeoutError:
                if not dialing:
                    # The socket is CLOSED first — the server's handler
                    # may still be blocked inside the op, and a later
                    # RPC on this client must not queue behind it (or
                    # mis-read the stale reply as its own: replies
                    # carry no correlation on the stream itself)
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
                    # a fence that never finished is a PEER problem
                    # (someone this fence waits on is hung without
                    # having died): loud, never retried — retrying a
                    # stuck fence would just wait again.  Any OTHER op
                    # is server-side-instantaneous, so expiry means the
                    # coord was too LOADED to answer in time (the
                    # fleet-soak shrink-path flake): retry within
                    # otpu_coord_retry_max — the replay cache keeps the
                    # retry exactly-once (a completed original replays,
                    # an in-flight one is adopted and its result
                    # awaited) — and only an exhausted ladder is loud
                    if op == "fence" \
                            or attempts >= self._effective_retry_max():
                        show_help("help-coord", "rpc-timeout",
                                  rank=self._rank_label, op=op,
                                  seconds=self._applied_rto)
                        raise RuntimeError(
                            f"coordination RPC {op!r} timed out after "
                            f"{self._applied_rto:g}s at rank "
                            f"{self._rank_label} (otpu_coord_rpc_timeout)")
                self._retry_or_raise(op, attempts)
                attempts += 1
            except (ConnectionError, OSError):
                self._retry_or_raise(op, attempts)
                attempts += 1

    def _retry_or_raise(self, op: str, attempts: int) -> None:
        """Connection-error path: close, back off (exponential +
        deterministic jitter), let the caller retry — or fail loudly
        once the ladder (otpu_coord_retry_max, or the recovery-scope
        budget otpu_coord_recovery_retry_max) is exhausted."""
        from ompi_tpu.base.output import show_help
        from ompi_tpu.runtime import spc

        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = None
        budget = self._effective_retry_max()
        if self._closed or attempts >= budget:
            if budget > 0 and not self._closed:
                # only the self-healing path announces exhaustion;
                # retries=0 components (detector, poller, finalize
                # fence) opted out and handle the error themselves
                show_help("help-coord", "reconnect-failed",
                          rank=self._rank_label, op=op,
                          attempts=attempts)
            raise
        spc.record("coord_rpc_retries")
        delay = min(self._backoff * (1 << attempts), 2.0)
        time.sleep(delay * (0.5 + self._jitter.random()))

    def put(self, rank: int, key: str, value: Any) -> None:
        self._rpc(op="put", rank=rank, key=key, value=value)

    def put_new(self, rank: int, key: str, value: Any) -> Any:
        """Atomic put-if-absent; returns the winning (stored) value."""
        return self._rpc(op="put_new", rank=rank, key=key,
                         value=value)["value"]

    def fetch_add(self, rank: int, key: str, delta: int) -> int:
        """Atomic fetch-and-add on a coord counter; returns the old value."""
        return self._rpc(op="fetch_add", rank=rank, key=key,
                         delta=delta)["value"]

    def delete(self, rank: int, key: str) -> None:
        self._rpc(op="del", rank=rank, key=key)

    def get(self, rank: int, key: str, wait: bool = True,
            timeout: Optional[float] = None) -> Any:
        if timeout is None:
            timeout = float(_get_timeout_var.value)
        return self._rpc(op="get", rank=rank, key=key, wait=wait,
                         timeout=timeout)["value"]

    def pset_publish(self, name: str, members, source: str = "user") -> None:
        """Publish/replace a named process set (dynamic psets)."""
        self._rpc(op="pset_pub", name=name, members=[int(m) for m in members],
                  source=source)

    def pset_list(self) -> list:
        """[{name, size, source}] of every advertised process set."""
        return self._rpc(op="pset_list")["psets"]

    def pset_get(self, name: str) -> Optional[dict]:
        """{members, source} of a named pset, or None when unknown."""
        return self._rpc(op="pset_get", name=name)["pset"]

    def spawn(self, cmd: list, n: int, env: Optional[dict] = None) -> tuple:
        """Ask the launcher to start ``n`` new ranks; returns
        (global_ranks, job_id)."""
        r = self._rpc(op="spawn", cmd=list(cmd), n=n, env=env or {})
        return list(r["ranks"]), r["job"]

    def fence(self, fence_id: str, *, rank: int, expect=None) -> None:
        """Enter a named fence as ``rank``.

        ``rank`` is mandatory: the server's completion rule is per-rank
        arrival-or-death, so an anonymous contribution can never satisfy it.
        """
        if rank < 0:
            raise ValueError("fence requires the caller's world rank")
        self._rpc(op="fence", id=fence_id, rank=rank, expect=expect)

    def fence_oneshot(self, fence_id: str, *, rank: int,
                      expect=None) -> None:
        """A fence whose completion is remembered: a rank arriving after
        the round completed (peers were released by its presumed failure)
        passes instead of waiting for ranks that already left.  Used for
        the finalize fence — normal fences keep strict per-round
        semantics."""
        if rank < 0:
            raise ValueError("fence requires the caller's world rank")
        self._rpc(op="fence", id=fence_id, rank=rank, expect=expect,
                  oneshot=True)

    def event_publish(self, name: str, payload: Any) -> None:
        self._rpc(op="event_pub", name=name, payload=payload)

    def event_poll(self) -> list[tuple[int, str, Any]]:
        resp = self._rpc(op="event_poll", since=self._event_since)
        events = resp["events"]
        if events:
            self._event_since = events[-1][0]
        return events

    def server_time(self) -> float:
        """The coord server's wall clock (one ping round-trip) — feed
        into ``mpisync.estimate_offset`` for clock alignment."""
        return float(self._rpc(op="ping")["time"])

    def abort(self, code: int = 1) -> None:
        self._rpc(op="abort", code=code)

    def close(self) -> None:
        self._closed = True      # no reconnect ladder during teardown
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass


from ompi_tpu.base.output import register_help as _rh

_rh("help-coord", "rpc-timeout",
    "Coordination RPC {op!r} at rank {rank} expired after {seconds}s "
    "(otpu_coord_rpc_timeout).  The coordination service is alive but "
    "the operation never completed — a peer this fence/get waits on is "
    "probably hung without having died.")
_rh("help-coord", "reconnect-failed",
    "Rank {rank} lost its coordination-service connection during "
    "{op!r} and could not re-establish it after {attempts} "
    "reconnect attempt(s) (otpu_coord_retry_max).  The launcher (and "
    "its coordination service) is gone; out-of-band operations cannot "
    "continue.")
