"""Coordination service — the PMIx/PRRTE-equivalent wire-up server.

Plays the role OpenPMIx plays for the reference (``ompi/runtime/
ompi_rte.c:568`` ``PMIx_Init``; ``PMIx_Fence`` modex at
``ompi_mpi_init.c:682-701``; PMIx events for ULFM): a small TCP server owned
by the launcher (``tpurun``) providing the job KV space (modex), fences,
pub/sub events (failure notification rides here), and job control (abort).
Protocol: length-prefixed pickle frames (trusted within one job, like PMIx's
unix-socket wire protocol).
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
from typing import Any, Optional

_LEN = struct.Struct("!I")


def _send_frame(sock: socket.socket, obj: Any) -> None:
    payload = pickle.dumps(obj)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> Any:
    hdr = b""
    while len(hdr) < _LEN.size:
        chunk = sock.recv(_LEN.size - len(hdr))
        if not chunk:
            raise ConnectionError("coordination peer closed")
        hdr += chunk
    (n,) = _LEN.unpack(hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 16, n - len(buf)))
        if not chunk:
            raise ConnectionError("coordination peer closed")
        buf += chunk
    return pickle.loads(bytes(buf))


class CoordServer:
    """Job-wide KV + fence + event service (runs inside the launcher)."""

    #: otpu-lint lock-discipline contract: each service table mutates
    #: only under its condition/lock.  The declaration also arms the
    #: no-blocking-under-lock check: per-connection replies
    #: (``_send_frame`` = blocking sendall) must never run while a
    #: condition is held — one slow-reading client would stall every
    #: fence/KV/event operation job-wide (helpers named *_locked run
    #: with the lock held by the caller).
    _guarded_by = {
        "_kv": "_kv_cond", "_psets": "_kv_cond",
        "_next_rank": "_kv_cond", "_spawn_seq": "_kv_cond",
        "_fence_ranks": "_fence_cond", "_fence_gen": "_fence_cond",
        "_fence_done": "_fence_cond", "_fence_expect": "_fence_cond",
        "_failed": "_fence_cond",
        "_events": "_event_cond", "_event_seq": "_event_cond",
        "_conns": "_conns_lock",
    }

    def __init__(self, nprocs: int, host: str = "127.0.0.1", port: int = 0):
        self.nprocs = nprocs
        self._kv: dict[tuple, Any] = {}
        self._kv_cond = threading.Condition()
        self._fence_ranks: dict[str, set] = {}
        self._fence_gen: dict[str, int] = {}
        self._fence_cond = threading.Condition()
        self._events: list[tuple[int, str, Any]] = []
        self._event_seq = 0
        self._event_cond = threading.Condition()
        self._aborted: Optional[int] = None
        self._failed: set[int] = set()
        # process-set registry (MPI-4 psets; the PMIx_Get PMIX_PSET_NAMES
        # role): name -> {"members": [ranks], "source": str}.  The
        # launcher publishes mpi://WORLD / per-host / user sets at job
        # start; spawn and failure events update dynamic sets.
        self._psets: dict[str, dict] = {}
        self._fence_expect: dict[str, tuple] = {}
        self._fence_done: set[str] = set()
        self._next_rank = nprocs          # global rank allocator (dpm spawn)
        self._spawn_handler = None        # set by the launcher (tpurun)
        self._spawn_seq = 0
        self._srv = socket.create_server((host, port))
        self.addr = self._srv.getsockname()
        self._threads: list[threading.Thread] = []
        self._conns: list[socket.socket] = []
        self._conns_lock = threading.Lock()
        self._accepting = True
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    # -- server internals ------------------------------------------------
    def _accept_loop(self) -> None:
        while self._accepting:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            with self._conns_lock:
                if not self._accepting:
                    # raced shutdown: a connection accepted while close()
                    # ran must not be left alive past it
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return
                self._conns.append(conn)
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        try:
            self._serve_loop(conn)
        finally:
            with self._conns_lock:
                try:
                    self._conns.remove(conn)   # prune on disconnect
                except ValueError:
                    pass

    def _serve_loop(self, conn: socket.socket) -> None:
        try:
            while True:
                req = _recv_frame(conn)
                op = req["op"]
                if op == "put":
                    with self._kv_cond:
                        self._kv[(req["rank"], req["key"])] = req["value"]
                        self._kv_cond.notify_all()
                    _send_frame(conn, {"ok": True})
                elif op == "del":
                    with self._kv_cond:
                        self._kv.pop((req["rank"], req["key"]), None)
                    _send_frame(conn, {"ok": True})
                elif op == "put_new":
                    # atomic put-if-absent: first writer wins, everyone gets
                    # the winning value back (consensus decision slots)
                    with self._kv_cond:
                        k = (req["rank"], req["key"])
                        if k not in self._kv:
                            self._kv[k] = req["value"]
                            self._kv_cond.notify_all()
                        val = self._kv[k]
                    _send_frame(conn, {"ok": True, "value": val})
                elif op == "fetch_add":
                    # atomic counter (shared file pointers, spawn ids):
                    # returns the PRE-add value, like MPI_Fetch_and_op SUM
                    with self._kv_cond:
                        k = (req["rank"], req["key"])
                        old = self._kv.get(k, 0)
                        self._kv[k] = old + req["delta"]
                        self._kv_cond.notify_all()
                    _send_frame(conn, {"ok": True, "value": old})
                elif op == "get":
                    deadline = time.monotonic() + req.get("timeout", 60.0)
                    with self._kv_cond:
                        while (req["rank"], req["key"]) not in self._kv:
                            remaining = deadline - time.monotonic()
                            if remaining <= 0 or not req.get("wait", True):
                                break
                            self._kv_cond.wait(min(remaining, 1.0))
                        val = self._kv.get((req["rank"], req["key"]))
                    _send_frame(conn, {"ok": True, "value": val})
                elif op == "fence":
                    fid = req["id"]
                    with self._fence_cond:
                        if "expect" in req and req["expect"] is not None:
                            self._fence_expect.setdefault(
                                fid, tuple(req["expect"]))
                        # per-rank contribution tracking: a fence completes
                        # when every rank has either arrived or died — a
                        # dead rank's earlier arrival must not release the
                        # fence while a live survivor is still outside it
                        oneshot = bool(req.get("oneshot"))
                        if oneshot and fid in self._fence_done:
                            # late arrival to a completed one-shot round:
                            # fall through to the reply OUTSIDE the cond —
                            # otpu-lint found the blocking sendall here
                            # while _fence_cond was held, where one
                            # slow-reading late client stalled every
                            # fence/failure operation job-wide
                            pass
                        else:
                            arrived = self._fence_ranks.setdefault(
                                fid, set())
                            arrived.add(req.get("rank", -1))
                            if self._fence_satisfied(fid):
                                self._complete_fence_locked(fid, oneshot)
                            else:
                                gen = self._fence_gen.get(fid, 0)
                                while self._fence_gen.get(fid, 0) == gen:
                                    self._fence_cond.wait(1.0)
                                    if self._aborted is not None:
                                        break
                                    # a failure may have lowered the bar
                                    if self._fence_satisfied(fid):
                                        self._complete_fence_locked(
                                            fid, oneshot)
                                        break
                    _send_frame(conn, {"ok": True})
                elif op == "event_pub":
                    # routed through publish() so in-band failure reports
                    # (heartbeat detector) also update fence bookkeeping
                    self.publish(req["name"], req["payload"])
                    _send_frame(conn, {"ok": True})
                elif op == "event_poll":
                    since = req["since"]
                    with self._event_cond:
                        out = [e for e in self._events if e[0] > since]
                    _send_frame(conn, {"ok": True, "events": out})
                elif op == "abort":
                    self._aborted = req.get("code", 1)
                    with self._fence_cond:
                        self._fence_cond.notify_all()
                    _send_frame(conn, {"ok": True})
                elif op == "spawn":
                    # MPI_Comm_spawn's PMIx_Spawn analog: allocate fresh
                    # global ranks, hand the launch to the launcher's
                    # registered handler (it owns process management)
                    if self._spawn_handler is None:
                        _send_frame(conn, {"ok": False,
                                           "error": "no spawn support "
                                                    "(launcher too old?)"})
                        continue
                    n = int(req["n"])
                    with self._kv_cond:
                        ranks = list(range(self._next_rank,
                                           self._next_rank + n))
                        self._next_rank += n
                        self._spawn_seq += 1
                        job = f"job{self._spawn_seq}"
                    try:
                        self._spawn_handler(
                            req["cmd"], ranks, job,
                            req.get("env") or {})
                        # dynamic pset: the new job is addressable by
                        # name before it builds any communicator
                        self.publish_pset(f"mpi://job/{job}", ranks,
                                          source="spawn")
                        _send_frame(conn, {"ok": True, "ranks": ranks,
                                           "job": job})
                    except Exception as exc:
                        _send_frame(conn, {"ok": False, "error": str(exc)})
                elif op == "pset_pub":
                    self.publish_pset(req["name"], req["members"],
                                      req.get("source", "user"))
                    _send_frame(conn, {"ok": True})
                elif op == "pset_list":
                    with self._kv_cond:
                        rows = [{"name": n, "size": len(e["members"]),
                                 "source": e["source"]}
                                for n, e in sorted(self._psets.items())]
                    _send_frame(conn, {"ok": True, "psets": rows})
                elif op == "pset_get":
                    with self._kv_cond:
                        entry = self._psets.get(req["name"])
                    _send_frame(conn, {"ok": True, "pset": entry})
                elif op == "ping":
                    # "time" is the server's wall clock: ranks estimate
                    # their offset to it (min-RTT, mpisync estimator) so
                    # per-rank trace timelines share one timebase
                    _send_frame(conn, {"ok": True, "nprocs": self.nprocs,
                                       "aborted": self._aborted,
                                       "time": time.time()})
                else:
                    _send_frame(conn, {"ok": False, "error": f"bad op {op}"})
        except (ConnectionError, OSError):
            return

    def _fence_satisfied(self, fid: str) -> bool:
        # caller holds _fence_cond
        arrived = self._fence_ranks.get(fid, set())
        expected = self._fence_expect.get(fid, range(self.nprocs))
        return all(r in arrived or r in self._failed for r in expected)

    def _complete_fence_locked(self, fid: str, oneshot: bool = False) -> None:
        # caller holds _fence_cond.  One-shot fences (finalize) record
        # completion permanently: a rank arriving LATE — released peers
        # treated it as failed (e.g. its heartbeats stopped but the
        # process lives) — must pass instead of waiting forever on peers
        # that already left.  Normal fences keep per-round generations so
        # re-used ids (runtime re-init) still synchronise.
        if oneshot:
            self._fence_done.add(fid)
        self._fence_ranks[fid] = set()
        self._fence_gen[fid] = self._fence_gen.get(fid, 0) + 1
        self._fence_cond.notify_all()

    def set_spawn_handler(self, fn) -> None:
        """Launcher registers how to exec spawned ranks:
        ``fn(cmd, global_ranks, job_id, extra_env)``."""
        self._spawn_handler = fn

    def publish_pset(self, name: str, members, source: str = "launcher") -> None:
        """(Re)publish a named process set — launcher-side at job start,
        server-side for dynamic sets (spawn/failure)."""
        with self._kv_cond:
            self._psets[str(name)] = {
                "members": [int(m) for m in members],
                "source": str(source)}
            self._kv_cond.notify_all()

    def kv_put(self, rank: int, key: str, value: Any) -> None:
        """Launcher-side KV injection (e.g. the jax coordinator address
        ranks fetch before their first backend touch)."""
        with self._kv_cond:
            self._kv[(rank, key)] = value
            self._kv_cond.notify_all()

    def publish(self, name: str, payload: Any) -> None:
        """Server-side event injection (launcher-detected failures)."""
        if name == "proc_failed":
            with self._fence_cond:
                self._failed.add(int(payload["rank"]))
                failed_now = set(self._failed)
                # a pending fence may now be satisfiable by the survivors
                for fid in list(self._fence_ranks):
                    if self._fence_ranks[fid] and self._fence_satisfied(fid):
                        self._complete_fence_locked(fid)
            # dynamic pset: the named surviving set the ULFM recovery
            # loop rebuilds from (world minus every known failure)
            with self._kv_cond:
                world = self._psets.get("mpi://WORLD", {}).get(
                    "members", list(range(self.nprocs)))
            self.publish_pset(
                "mpi://surviving",
                [r for r in world if r not in failed_now],
                source="dynamic")
        with self._event_cond:
            self._event_seq += 1
            self._events.append((self._event_seq, name, payload))
            self._event_cond.notify_all()

    @property
    def aborted(self) -> Optional[int]:
        return self._aborted

    def collect(self, key: str) -> dict:
        """{rank: value} of every KV entry published under ``key`` — the
        launcher-side gather of per-rank payloads (trace timelines)."""
        with self._kv_cond:
            return {r: v for (r, k), v in self._kv.items() if k == key}

    def close(self) -> None:
        """Full stop: the listener AND every live client connection.
        (A close that leaves established connections serving would make
        the service look alive to already-wired clients — the FT tests
        kill the coord to prove detection doesn't depend on it.)"""
        with self._conns_lock:
            self._accepting = False       # no new conns past this point
            conns = list(self._conns)
            self._conns.clear()
        try:
            self._srv.close()
        except OSError:
            pass
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass


class CoordClient:
    """Per-process client (the PMIx client analog)."""

    def __init__(self, addr: Optional[tuple] = None,
                 timeout: float = 120.0):
        if addr is None:
            spec = os.environ["OTPU_COORD"]
            host, port = spec.rsplit(":", 1)
            addr = (host, int(port))
        self._sock = socket.create_connection(addr, timeout=timeout)
        self._lock = threading.Lock()
        self._event_since = 0

    def _rpc(self, **req) -> dict:
        with self._lock:
            _send_frame(self._sock, req)
            resp = _recv_frame(self._sock)
        if not resp.get("ok"):
            raise RuntimeError(f"coordination error: {resp.get('error')}")
        return resp

    def put(self, rank: int, key: str, value: Any) -> None:
        self._rpc(op="put", rank=rank, key=key, value=value)

    def put_new(self, rank: int, key: str, value: Any) -> Any:
        """Atomic put-if-absent; returns the winning (stored) value."""
        return self._rpc(op="put_new", rank=rank, key=key,
                         value=value)["value"]

    def fetch_add(self, rank: int, key: str, delta: int) -> int:
        """Atomic fetch-and-add on a coord counter; returns the old value."""
        return self._rpc(op="fetch_add", rank=rank, key=key,
                         delta=delta)["value"]

    def delete(self, rank: int, key: str) -> None:
        self._rpc(op="del", rank=rank, key=key)

    def get(self, rank: int, key: str, wait: bool = True,
            timeout: float = 60.0) -> Any:
        return self._rpc(op="get", rank=rank, key=key, wait=wait,
                         timeout=timeout)["value"]

    def pset_publish(self, name: str, members, source: str = "user") -> None:
        """Publish/replace a named process set (dynamic psets)."""
        self._rpc(op="pset_pub", name=name, members=[int(m) for m in members],
                  source=source)

    def pset_list(self) -> list:
        """[{name, size, source}] of every advertised process set."""
        return self._rpc(op="pset_list")["psets"]

    def pset_get(self, name: str) -> Optional[dict]:
        """{members, source} of a named pset, or None when unknown."""
        return self._rpc(op="pset_get", name=name)["pset"]

    def spawn(self, cmd: list, n: int, env: Optional[dict] = None) -> tuple:
        """Ask the launcher to start ``n`` new ranks; returns
        (global_ranks, job_id)."""
        r = self._rpc(op="spawn", cmd=list(cmd), n=n, env=env or {})
        return list(r["ranks"]), r["job"]

    def fence(self, fence_id: str, *, rank: int, expect=None) -> None:
        """Enter a named fence as ``rank``.

        ``rank`` is mandatory: the server's completion rule is per-rank
        arrival-or-death, so an anonymous contribution can never satisfy it.
        """
        if rank < 0:
            raise ValueError("fence requires the caller's world rank")
        self._rpc(op="fence", id=fence_id, rank=rank, expect=expect)

    def fence_oneshot(self, fence_id: str, *, rank: int,
                      expect=None) -> None:
        """A fence whose completion is remembered: a rank arriving after
        the round completed (peers were released by its presumed failure)
        passes instead of waiting for ranks that already left.  Used for
        the finalize fence — normal fences keep strict per-round
        semantics."""
        if rank < 0:
            raise ValueError("fence requires the caller's world rank")
        self._rpc(op="fence", id=fence_id, rank=rank, expect=expect,
                  oneshot=True)

    def event_publish(self, name: str, payload: Any) -> None:
        self._rpc(op="event_pub", name=name, payload=payload)

    def event_poll(self) -> list[tuple[int, str, Any]]:
        resp = self._rpc(op="event_poll", since=self._event_since)
        events = resp["events"]
        if events:
            self._event_since = events[-1][0]
        return events

    def server_time(self) -> float:
        """The coord server's wall clock (one ping round-trip) — feed
        into ``mpisync.estimate_offset`` for clock alignment."""
        return float(self._rpc(op="ping")["time"])

    def abort(self, code: int = 1) -> None:
        self._rpc(op="abort", code=code)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
