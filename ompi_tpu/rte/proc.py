"""ProcRte — the multi-process RTE (one MPI rank per OS process).

The classic Open MPI process model: ``tpurun`` launches N processes, each
connecting back to the coordination service for identity, modex, and fences
(the ``PMIx_Init`` path of ``ompi_rte.c:528-568``).  Device resources in
this model are per-process (multi-controller JAX: each process owns its
local TPU chips; cross-process device collectives ride DCN via
``jax.distributed`` — wired in the parallel layer).
"""
from __future__ import annotations

import os
import socket
from typing import Any, Optional

from ompi_tpu.rte.base import Rte
from ompi_tpu.rte.coord import CoordClient


class ProcRte(Rte):
    is_device_world = False

    #: multi-process device world (set by the instance layer when it
    #: boots jax.distributed): the global device list spans every
    #: process of the job, local_devices are this process's shards
    device_world_booted = False
    global_devices = None
    local_devices = None

    def device_world_process(self, world_rank: int) -> int:
        """jax process index of a world rank — the ``process_id`` map
        used at ``jax.distributed.initialize`` (job-local position)."""
        return self.job_ranks.index(int(world_rank))

    def __init__(self) -> None:
        self.my_world_rank = int(os.environ["OTPU_RANK"])
        self.world_size = int(os.environ["OTPU_NPROCS"])
        # arm deterministic fault injection BEFORE the first coord RPC:
        # a chaos spec must cover the wire-up fences too, not just the
        # post-boot steady state (no-op when otpu_chaos_spec is empty)
        from ompi_tpu.ft import chaos

        chaos.install(rank=self.my_world_rank)
        # dpm job identity: a spawned job has its own COMM_WORLD built from
        # GLOBAL ranks allocated by the coord server (OTPU_JOB_RANKS); the
        # primary job is job "0" with ranks 0..nprocs-1
        self.job = os.environ.get("OTPU_JOB", "0")
        jr = os.environ.get("OTPU_JOB_RANKS", "")
        self.job_ranks = ([int(x) for x in jr.split(",")] if jr
                          else list(range(self.world_size)))
        pr = os.environ.get("OTPU_PARENT_RANKS", "")
        self.parent_ranks = [int(x) for x in pr.split(",")] if pr else None
        self.parent_cid = int(os.environ.get("OTPU_PARENT_CID", "-1"))
        self.client = CoordClient()
        self._hostname = socket.gethostname()
        # node identity for the hierarchy (coll/han): hostname by default,
        # OTPU_NODE_ID when the launcher partitions ranks into fake nodes
        # (tpurun --fake-nodes) or a multi-host launcher names slices
        self._node = os.environ.get("OTPU_NODE_ID", self._hostname)
        self.modex_put("hostname", self._hostname)
        self.modex_put("node", self._node)
        if self.job != "0":
            # dpm join handshake: a spawned rank announces it reached the
            # runtime as soon as the coord connection is up, so the
            # parent's MPI_Comm_spawn can distinguish "children booting"
            # from "a child died during join" (ERR_SPAWN) instead of
            # hanging on a half-built intercommunicator
            self.modex_put(f"__spawn_join__:{self.job}", 1)
        self._fence_counter = 0

    def modex_put(self, key: str, value: Any) -> None:
        self.client.put(self.my_world_rank, key, value)

    def modex_get(self, rank: int, key: str, wait: bool = True) -> Any:
        return self.client.get(rank, key, wait=wait)

    def fence(self) -> None:
        self._fence_counter += 1
        # fence ids are job-scoped and carry explicit membership so a
        # spawned job's fences never collide with the primary job's
        self.client.fence(f"{self.job}:f{self._fence_counter}",
                          rank=self.my_world_rank, expect=self.job_ranks)

    def fence_final(self, timeout: Optional[float] = None) -> None:
        """Pre-teardown synchronisation (ompi_mpi_finalize's barrier).

        One-shot semantics (a rank arriving after peers were released by
        its presumed failure passes immediately) on a DEDICATED short-
        timeout connection: a peer that exited without fencing must cost
        at most ``otpu_coord_final_timeout`` seconds and must not
        desynchronise the shared client's request/reply stream — the
        throwaway connection is closed either way.  No reconnect ladder:
        at teardown a dead coord means the job is ending anyway."""
        from ompi_tpu.rte.coord import CoordClient, _final_timeout_var

        if timeout is None:
            timeout = float(_final_timeout_var.value)
        c = CoordClient(timeout=timeout, retries=0)
        try:
            c.fence_oneshot(f"{self.job}:final", rank=self.my_world_rank,
                            expect=self.job_ranks)
        finally:
            try:
                c.close()
            except Exception:
                pass

    def locality_color(self, split_type: str) -> int:
        # 'shared' → same node (the sm/ICI domain).  Stable cross-process
        # hash: builtin hash() is PYTHONHASHSEED-randomised per process,
        # which would give same-node ranks different colors
        import zlib

        return zlib.crc32(self._node.encode()) % (1 << 30)

    def node_of(self, world_rank: int):
        """Cached node identity of a peer (published at its init)."""
        if world_rank == self.my_world_rank:
            return self._node
        cache = getattr(self, "_node_cache", None)
        if cache is None:
            cache = self._node_cache = {}
        if world_rank not in cache:
            try:
                val = self.modex_get(world_rank, "node", wait=False)
            except Exception:
                return None
            if val is None:
                return None     # not cached: may appear later
            cache[world_rank] = val
        return cache[world_rank]

    def event_notify(self, event: str, payload: Any) -> None:
        self.client.event_publish(event, payload)

    def event_poll(self):
        return self.client.event_poll()

    def finalize(self) -> None:
        self.client.close()
