"""ProcRte — the multi-process RTE (one MPI rank per OS process).

The classic Open MPI process model: ``tpurun`` launches N processes, each
connecting back to the coordination service for identity, modex, and fences
(the ``PMIx_Init`` path of ``ompi_rte.c:528-568``).  Device resources in
this model are per-process (multi-controller JAX: each process owns its
local TPU chips; cross-process device collectives ride DCN via
``jax.distributed`` — wired in the parallel layer).
"""
from __future__ import annotations

import os
import socket
from typing import Any, Optional

from ompi_tpu.rte.base import Rte
from ompi_tpu.rte.coord import CoordClient


class ProcRte(Rte):
    is_device_world = False

    def __init__(self) -> None:
        self.my_world_rank = int(os.environ["OTPU_RANK"])
        self.world_size = int(os.environ["OTPU_NPROCS"])
        self.client = CoordClient()
        self._hostname = socket.gethostname()
        # node identity for the hierarchy (coll/han): hostname by default,
        # OTPU_NODE_ID when the launcher partitions ranks into fake nodes
        # (tpurun --fake-nodes) or a multi-host launcher names slices
        self._node = os.environ.get("OTPU_NODE_ID", self._hostname)
        self.modex_put("hostname", self._hostname)
        self.modex_put("node", self._node)
        self._fence_counter = 0

    def modex_put(self, key: str, value: Any) -> None:
        self.client.put(self.my_world_rank, key, value)

    def modex_get(self, rank: int, key: str, wait: bool = True) -> Any:
        return self.client.get(rank, key, wait=wait)

    def fence(self) -> None:
        self._fence_counter += 1
        self.client.fence(f"f{self._fence_counter}", rank=self.my_world_rank)

    def locality_color(self, split_type: str) -> int:
        # 'shared' → same node (the sm/ICI domain)
        return abs(hash(self._node)) % (1 << 30)

    def event_notify(self, event: str, payload: Any) -> None:
        self.client.event_publish(event, payload)

    def event_poll(self):
        return self.client.event_poll()

    def finalize(self) -> None:
        self.client.close()
