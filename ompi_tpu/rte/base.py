"""RTE adapters: the process-model abstraction under the runtime.

Equivalent of the PMIx client surface used by the reference
(``ompi/runtime/ompi_rte.c:568`` ``PMIx_Init``; modex put/get; fences;
events): an Rte provides identity (rank/size), the wire-up KV space, barriers
outside MPI, locality, and — TPU-native — the device mesh that the coll/xla
component compiles against.
"""
from __future__ import annotations

import os
import socket
import threading
from typing import Any, Optional

import numpy as np


class Rte:
    """Interface. ``my_world_rank``/``world_size`` are process identity."""

    my_world_rank: int = 0
    world_size: int = 1
    is_device_world: bool = False

    def modex_put(self, key: str, value: Any) -> None:
        raise NotImplementedError

    def modex_get(self, rank: int, key: str, wait: bool = True) -> Any:
        """Fetch a peer's modexed value; ``wait=False`` returns None
        instead of blocking when the key hasn't been published yet."""
        raise NotImplementedError

    def fence(self) -> None:
        """Out-of-band barrier + modex publication (``PMIx_Fence``)."""
        raise NotImplementedError

    def locality_color(self, split_type: str) -> int:
        return 0  # single host / single slice

    def node_of(self, world_rank: int) -> Optional[Any]:
        """Node identity of a peer (None if unknown) — the shared
        locality lookup han/coll-sm/osc-rdma/treematch all need."""
        return None

    def event_notify(self, event: str, payload: Any) -> None:
        pass

    def finalize(self) -> None:
        pass

    # device resources ---------------------------------------------------
    @property
    def mesh(self):
        return None

    def device_of(self, world_rank: int):
        return None


class DeviceWorldRte(Rte):
    """TPU-native SPMD world: ranks = devices of a 1-D mesh in one process.

    The controller drives all ranks ("conductor" model): host p2p between
    device-ranks runs through the in-process matching engine, device
    collectives compile to one XLA program over the ICI mesh axis.  This is
    the analog of `mpirun -n N --oversubscribe` on one node (every BTL is
    btl/self-reachable) but with the ranks being real accelerator devices.
    """

    is_device_world = True

    def __init__(self, devices=None, axis_name: str = "world") -> None:
        from ompi_tpu.base.jaxenv import apply_platform_env

        apply_platform_env()
        import jax

        if devices is None:
            devices = jax.devices()
            if len(devices) == 1 and devices[0].platform != "cpu":
                pass  # single real chip: world of 1 device-rank
        self.devices = list(devices)
        self.axis_name = axis_name
        self.world_size = len(self.devices)
        self.my_world_rank = 0  # the conductor acts for every rank
        from jax.sharding import Mesh

        self._mesh = Mesh(np.array(self.devices), (axis_name,))
        self._kv: dict[tuple[int, str], Any] = {}
        self._lock = threading.Lock()

    @property
    def mesh(self):
        return self._mesh

    def device_of(self, world_rank: int):
        return self.devices[world_rank]

    def modex_put(self, key: str, value: Any, rank: Optional[int] = None) -> None:
        with self._lock:
            self._kv[(self.my_world_rank if rank is None else rank, key)] = value

    def modex_get(self, rank: int, key: str, wait: bool = True) -> Any:
        # wait is part of the modex signature (ProcRte blocks on missing
        # keys); in-process KV has nothing to wait for
        with self._lock:
            return self._kv.get((rank, key))

    def fence(self) -> None:
        pass  # single process: nothing to synchronize out-of-band

    def locality_color(self, split_type: str) -> int:
        return 0


class SingletonRte(Rte):
    """Size-1 world with no devices (COMM_SELF-only / pure host usage)."""

    def __init__(self) -> None:
        self._kv: dict[tuple[int, str], Any] = {}

    def modex_put(self, key: str, value: Any) -> None:
        self._kv[(0, key)] = value

    def modex_get(self, rank: int, key: str, wait: bool = True) -> Any:
        return self._kv.get((rank, key))

    def fence(self) -> None:
        pass


def detect() -> Rte:
    """Pick the RTE for this process (``ompi_rte_init`` equivalent).

    Launched under ``tpurun`` (OTPU_RANK/OTPU_NPROCS in env) → the
    multi-process ProcRte (``ompi_tpu.rte.proc``).  Otherwise the
    device-world SPMD model over local jax devices.
    """
    if "OTPU_RANK" in os.environ and "OTPU_NPROCS" in os.environ:
        from ompi_tpu.rte.proc import ProcRte

        return ProcRte()
    try:
        return DeviceWorldRte()
    except Exception:
        return SingletonRte()
