"""@hot_path — the allocation-budget tag for runtime hot functions.

The decorator is IDENTITY at runtime: it records the function's qualified
name in a registry (decoration-time cost only) and returns the function
object unchanged, so a tagged hot loop carries zero wrapper overhead —
pinned by ``test_perf_guard.test_sanitizer_off_zero_overhead``.

Its value is static: ``otpu-lint``'s hot-path pass checks every tagged
function against the allocation budget (no pickle / format-string /
list-concat, no bare ``struct.error``), and the registry lets tooling
(``otpu_info --lint``, debuggers) enumerate what the project considers
hot.  Tag the functions that run per message or per progress tick:
progress-loop drain, btl send/recv/framing, convertor pack, coll
dispatch, staging checkout.
"""
from __future__ import annotations

from typing import Callable

_REGISTRY: dict[str, str] = {}   # qualified name -> defining module


def hot_path(fn: Callable) -> Callable:
    """Tag ``fn`` as a runtime hot path (identity; see module docstring)."""
    _REGISTRY[f"{fn.__module__}.{fn.__qualname__}"] = fn.__module__
    return fn


def registered() -> dict[str, str]:
    """{qualified name: module} of every imported @hot_path function."""
    return dict(_REGISTRY)
