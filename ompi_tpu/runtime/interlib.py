"""interlib — coordination between multiple MPI-using libraries.

Re-design of ``/root/reference/ompi/interlib/interlib.c``: when two
independent libraries in one process both use the framework, neither may
tear it down while the other still needs it, and the effective thread
level is the strongest any registrant asked for.  The reference tracks
this with a refcounted singleton consulted by init/finalize; same here.

Thread levels (``MPI_THREAD_*``): the engine itself is thread-safe
(every shared structure is lock-guarded and the GIL serialises the rest),
so ``provided`` is always THREAD_MULTIPLE regardless of the requested
level — which is therefore not stored (MPI-3 §12.4.3's query answers
with the provided level, not the requested one).
"""
from __future__ import annotations

import threading

THREAD_SINGLE = 0
THREAD_FUNNELED = 1
THREAD_SERIALIZED = 2
THREAD_MULTIPLE = 3

_lock = threading.Lock()
_registrations = 0
_main_thread = None


def note_main_thread(force: bool = False) -> None:
    """Record the thread performing MPI init (``MPI_Is_thread_main``'s
    reference point).  ``force`` is used by init itself: MPI defines the
    main thread as the one that called init, so init's anchor overrides
    any earlier register() from a library worker thread."""
    global _main_thread
    with _lock:
        if force or _main_thread is None:
            _main_thread = threading.current_thread()


def register(thread_level: int = THREAD_SINGLE) -> int:
    """A library announces itself (``ompi_interlib_declare``); returns
    the provided thread level."""
    global _registrations
    with _lock:
        _registrations += 1
    note_main_thread()
    return THREAD_MULTIPLE


def deregister() -> int:
    """Returns the remaining registration count — finalize may only tear
    down the runtime when this hits zero."""
    global _registrations
    with _lock:
        _registrations = max(0, _registrations - 1)
        return _registrations


def registrations() -> int:
    with _lock:
        return _registrations


def query_thread() -> int:
    """``MPI_Query_thread``: the provided level."""
    return THREAD_MULTIPLE


def is_thread_main() -> bool:
    """``MPI_Is_thread_main``."""
    with _lock:
        return _main_thread is None or \
            threading.current_thread() is _main_thread


def reset_for_testing() -> None:
    global _registrations, _main_thread
    with _lock:
        _registrations = 0
        _main_thread = None
