"""Debugger handle introspection — the MPIR / debugger-DLL analog.

Re-design of ``/root/reference/ompi/debuggers/ompi_common_dll.c`` +
``ompi_msgq_dll.c``: parallel debuggers (TotalView, DDT) attach to an
MPI job and walk the library's internal handle tables — the
communicator list, the three per-communicator message queues (posted
receives, unexpected messages, pending sends), and the MPIR proctable —
through a compiled debugger-support DLL that knows the struct layouts.

The tpu-native analog needs no struct-layout DLL: debuggers here attach
with pdb/py-spy or query over the launcher, so the same three views are
exposed as plain data:

- :func:`comm_table` — every live communicator (the handle-table walk).
- :func:`message_queues` — pml/ob1 matching state per (cid, rank):
  posted receives, unexpected frags, out-of-order frags, active
  send/recv requests (the ``mqs_setup_operation_iterator`` views).
- :func:`proc_table` — MPIR_proctable analog (world ranks, node, pid).
- :func:`dump` — everything, as one plain dict (otpu_info --debug-dump).
"""
from __future__ import annotations

import os
from typing import Any, Optional


def comm_table() -> list:
    """One row per live communicator, ``ompi_common_dll``'s
    communicator iteration."""
    from ompi_tpu.api.comm import live_comms

    rows = []
    for c in live_comms():
        if getattr(c, "freed", False):
            continue
        rows.append({
            "cid": c.cid, "epoch": c.epoch, "name": c.name,
            "rank": c.rank, "size": c.size,
            "peers": list(c.group.world_ranks),
            "inter": bool(c.remote_group is not None),
            "topo": type(c.topo).__name__ if c.topo is not None else None,
            "revoked": bool(getattr(c, "revoked", False)),
        })
    return rows


def _frag_row(frag) -> dict:
    data = getattr(frag, "data", None)
    return {"src": frag.src, "tag": frag.tag,
            "seq": getattr(frag, "seq", None),
            "nbytes": 0 if data is None else len(data),
            "kind": getattr(frag, "kind", None)}


def _req_row(req) -> dict:
    return {"peer": getattr(req, "dest", getattr(req, "source", None)),
            "tag": getattr(req, "tag", None),
            "nbytes": getattr(req, "nbytes", None),
            "complete": bool(getattr(req, "complete", False)),
            "type": type(req).__name__}


def _find_ob1(pml):
    """Unwrap interposition layers (monitoring, vprotocol) down to the
    matching engine that owns the queues."""
    seen = set()
    while pml is not None and id(pml) not in seen:
        seen.add(id(pml))
        if hasattr(pml, "_match"):
            return pml
        pml = getattr(pml, "pml", getattr(pml, "_pml", None))
    return None


def message_queues(comm=None) -> list:
    """The three MPIR message queues per (cid, receiver-rank) matching
    state — ``ompi_msgq_dll.c``'s pending-receive / unexpected /
    pending-send iterations."""
    from ompi_tpu.api.comm import live_comms

    comms = [comm] if comm is not None else [
        c for c in live_comms() if not getattr(c, "freed", False)]
    rows = []
    for c in comms:
        ob1 = _find_ob1(getattr(c, "pml", None))
        if ob1 is None:
            continue
        with ob1._lock:
            for (cid, rank), st in ob1._match.items():
                if cid != c.cid:
                    continue
                rows.append({
                    "cid": cid, "rank": rank,
                    "posted_recvs": [_req_row(r) for r in st.posted],
                    "unexpected": [_frag_row(f) for f in st.unexpected],
                    "out_of_order": {
                        src: sorted(frags)
                        for src, frags in ((s, list(d)) for s, d in
                                           st.ooo.items()) if frags},
                })
            pending_sends = [_req_row(r)
                             for r in ob1._send_reqs.values()
                             if getattr(r, "comm", None) is c]
            pending_recvs = [_req_row(r)
                             for r in ob1._recv_reqs.values()
                             if getattr(r, "comm", None) is c]
        if pending_sends or pending_recvs:
            rows.append({"cid": c.cid, "active_send_requests":
                         pending_sends,
                         "active_recv_requests": pending_recvs})
    return rows


def proc_table(rte=None) -> list:
    """MPIR_proctable analog: every world rank the runtime knows, with
    node identity and (where local) the pid."""
    if rte is None:
        from ompi_tpu.runtime import init as rt

        rte = getattr(rt, "_rte", None)
    if rte is None:
        return []
    rows = []
    n = getattr(rte, "nprocs", 1)
    me = getattr(rte, "my_world_rank", 0)
    for rank in range(n):
        rows.append({
            "rank": rank,
            "node": (os.environ.get("OTPU_NODE_ID")
                     if rank == me else None),
            "pid": os.getpid() if rank == me else None,
            "is_me": rank == me,
        })
    return rows


def dump(comm: Optional[Any] = None) -> dict:
    """Everything a debugger wants, as one plain dict."""
    return {"comms": comm_table(),
            "message_queues": message_queues(comm),
            "procs": proc_table()}
