"""runtime/reactor — Python front-end of the native progress reactor.

The tentpole of the host-speed tier: an epoll loop in ``otpu_native``
(see the reactor section of ``native/otpu_native.cc``) owns the btl
fds and runs socket drain, wire framing, split-tail reassembly, and
header-type lane routing on a dedicated OS thread — no GIL anywhere on
the receive hot path.  Python only sees COMPLETED work: one ctypes
call per :func:`drain` empties the lock-free record queue, and each
record dispatches to the handler its fd registered (btl/tcp builds the
Frag from a ready-to-unpack fast header; btl/sm just wakes).

Lane contract (the reason the fallback is bit-identical): the native
side forwards any frame that is not a plain fast header (crc-armed,
quantized, pickle, handshake — anything with extra htype bits) as a
RAW record, and the btl feeds those bytes to the exact same
``_parse_frame`` the pure-Python lane uses.  The reactor never
engages under ``OTPU_SANITIZE`` (the sanitizer's strict pure-Python
checks stay authoritative), and with ``otpu_progress_native=0`` or no
native toolchain nothing here ever runs — the selector loop in
``mca/btl/tcp.py`` carries the job exactly as before.

Registered with the central progress engine two ways: :func:`drain`
is a normal progress callback (so the tick path is unchanged — one
list entry, zero ctypes calls when disengaged), and the reactor's
WAIT fd — a nested epoll fd that goes readable on raw btl-socket
readiness or queued records — is a progress WAITER.  ``idle_wait``
therefore wakes the moment wire bytes arrive, and the next drain's
inline pump parses them on the consumer thread itself; the dedicated
(idle-priority) reactor thread only wins the race when a core is
actually free — the overlap case it exists for.
"""
from __future__ import annotations

import os
import struct
import threading
from typing import Callable

import numpy as np

from ompi_tpu.base.var import VarType, registry
from ompi_tpu.runtime import sanitizer, spc
from ompi_tpu.runtime.hotpath import hot_path

# record stream (mirrors the emit() layout in otpu_native.cc):
#   [u32 payload_len][i32 fd][u8 etype][payload]
_REC = struct.Struct("<IiB")

# record etypes (otpu_native.cc REC_*)
REC_RAW = 0        # whole frame -> the Python slow lane (_parse_frame)
REC_FAST = 1       # frame after the htype byte: !IIIiqBqqq hdr + payload
REC_EOF = 2        # peer closed / hard error
REC_ACCEPT = 3     # notify-mode fd readable (oneshot; rearm after)
REC_WRITABLE = 4   # backpressured fd turned writable
REC_DOORBELL = 5   # drain-mode dgram fd rang (dgrams consumed natively)
REC_OVERSIZE = 6   # u64 frame_len parked in the stream (take_oversize)
REC_DESYNC = 7     # u64 bad frame_len: framing desync, fail loudly

#: fd registration modes (otpu_reactor_add)
MODE_STREAM = 0
MODE_NOTIFY = 1
MODE_DRAIN = 2

_native_var = registry.register(
    "progress", None, "native",
    vtype=VarType.BOOL, default=True,
    help="Run the btl hot loops (socket drain, framing, fast-frame "
         "parse) on the native epoll reactor thread when the compiled "
         "otpu_native library is available.  0 keeps the pure-Python "
         "selector loop — bit-identical behavior, only slower.")

_lock = threading.RLock()
_drain_gate = threading.Lock()   # one drainer at a time (SPSC consumer)
_handle = 0
_pid = 0
_wait_fd = -1
_byfd: dict[int, Callable] = {}
_drainbuf: np.ndarray = None
_drainbuf_ptr = 0                # cached buffer address for the raw call
_drain_fn = None                 # bound ctypes entry point (engage())

#: otpu-lint lock-discipline contract: the handler registry and the
#: reactor lifecycle fields mutate only under the module lock (drain
#: reads _byfd lock-free — a GIL-atomic dict get, same discipline as
#: btl/tcp's _by_rank snapshots)
_GUARDED_BY = {"_byfd": "_lock", "_handle": "_lock", "_pid": "_lock",
               "_wait_fd": "_lock"}


def configured() -> bool:
    """The otpu_progress_native knob (env: OTPU_MCA_progress_native)."""
    return bool(_native_var.value)


def available() -> bool:
    """Toolchain contract: the native library compiled AND exports the
    reactor entry points.  False means every caller stays on its
    pure-Python lane — same meaning as ``native.available()``."""
    from ompi_tpu import native

    return native.reactor_supported()


def active() -> bool:
    return _handle != 0 and _pid == os.getpid()


def engage() -> bool:
    """Start (or confirm) the reactor for this process.  Idempotent;
    returns False when disabled, unsupported, or under the sanitizer
    (whose strict checks stay on the authoritative pure-Python lane).
    """
    global _handle, _pid, _wait_fd
    if not configured() or sanitizer.enabled:
        return False
    with _lock:
        if active():
            return True
        if _handle:
            # forked child inherited a dead handle: forget it (the
            # parent's reactor thread did not survive the fork)
            _forget_locked()
        if not available():
            return False
        from ompi_tpu import native

        h = native.reactor_create()
        if h == 0:
            return False
        _handle = h
        _pid = os.getpid()
        _wait_fd = native.reactor_wait_fd(h)
        global _drain_fn
        _drain_fn = native.reactor_drain_fn()
        _ensure_drainbuf(1 << 20)
        from ompi_tpu.runtime import progress as progress_mod

        progress_mod.register(drain)
        progress_mod.register_waiter(_wait_fd)
        return True


def _forget_locked() -> None:
    """Drop reactor state without touching the native side (fork)."""
    global _handle, _pid, _wait_fd
    _handle = 0
    _pid = 0
    _wait_fd = -1
    _byfd.clear()


def shutdown() -> None:
    """Stop the reactor thread and deregister from the progress engine
    (instance teardown / progress.reset_for_testing)."""
    global _handle
    with _lock:
        if not _handle:
            return
        from ompi_tpu.runtime import progress as progress_mod

        progress_mod.unregister(drain)
        if _wait_fd >= 0:
            progress_mod.unregister_waiter(_wait_fd)
        if _pid == os.getpid():
            from ompi_tpu import native

            native.reactor_destroy(_handle)
        _forget_locked()


def add(fd: int, mode: int, handler: Callable) -> bool:
    """Register ``fd`` with ``handler(etype, payload) -> int`` (events
    progressed).  ``payload`` is a memoryview into the drain buffer,
    valid until the next drain — the btl's borrowed-frag contract."""
    with _lock:
        if not active():
            return False
        from ompi_tpu import native

        if not native.reactor_add(_handle, fd, mode):
            return False
        _byfd[fd] = handler
        return True


def remove(fd: int) -> None:
    with _lock:
        _byfd.pop(fd, None)
        if active():
            from ompi_tpu import native

            native.reactor_del(_handle, fd)


def rearm(fd: int) -> None:
    """Re-arm a MODE_NOTIFY fd after servicing its ACCEPT record."""
    if active():
        from ompi_tpu import native

        native.reactor_rearm(_handle, fd)


def want_write(fd: int, on: bool) -> bool:
    """(De)register writability interest for a backpressured stream."""
    if not active():
        return False
    from ompi_tpu import native

    return native.reactor_want_write(_handle, fd, on)


def take_oversize(fd: int) -> np.ndarray:
    """Fetch a parked oversize frame as an OWNED array (the fetch also
    resumes the parked stream on the reactor thread)."""
    from ompi_tpu import native

    out = np.empty(1 << 16, np.uint8)
    n = native.reactor_take_oversize(_handle, fd, out)
    if n < -1:
        out = np.empty(-n, np.uint8)
        n = native.reactor_take_oversize(_handle, fd, out)
    if n < 0:
        raise sanitizer.SanitizeError(
            "reactor oversize frame vanished for fd %d" % fd)
    return out[:n]


def _ensure_drainbuf(nbytes: int) -> np.ndarray:
    global _drainbuf, _drainbuf_ptr
    buf = _drainbuf
    if buf is None or len(buf) < nbytes:
        buf = _drainbuf = np.empty(int(nbytes), np.uint8)
        _drainbuf_ptr = buf.ctypes.data
    return buf


def _native_drain(fn, h, ptr, cap):
    """The CDLL drain call in its own frame: ctypes releases the GIL
    for the call's duration (socket drain, framing, and the inline
    pump all run GIL-free), and the sampling profiler classifies a
    thread parked here as a GIL-released native site by this frame's
    name (``profile._NATIVE_NAMES``)."""
    return fn(h, ptr, cap)


@hot_path
def drain() -> int:
    """Empty the native record queue — the one ctypes call per
    progress() tick (the cached raw-pointer binding: no module lookup,
    no ndarray argument marshalling) — and dispatch each record to its
    fd's handler.  Registered as a normal progress callback while
    engaged."""
    h = _handle
    fn = _drain_fn
    if not h or fn is None or _pid != os.getpid():
        return 0
    if not _drain_gate.acquire(blocking=False):
        return 0      # another thread is mid-drain (SPSC consumer)
    try:
        buf = _drainbuf
        n = _native_drain(fn, h, _drainbuf_ptr, len(buf))
        if n < 0:
            buf = _ensure_drainbuf(-n)
            n = _native_drain(fn, h, _drainbuf_ptr, len(buf))
        if n <= 0:
            return 0
        spc.record("progress_native_drains")
        view = memoryview(buf)
        byfd = _byfd
        events = 0
        pos = 0
        while pos < n:
            plen, fd, etype = _REC.unpack_from(buf, pos)
            pos += _REC.size
            payload = view[pos:pos + plen]
            pos += plen
            handler = byfd.get(fd)
            if handler is not None:
                events += handler(etype, payload)
        return events
    finally:
        _drain_gate.release()


def stats() -> dict:
    """Reactor state for otpu_info/telemetry (racy native counters)."""
    out = {"configured": configured(), "available": available(),
           "active": active(), "registered_fds": len(_byfd)}
    if active():
        from ompi_tpu import native

        out.update(native.reactor_stats(_handle))
    return out
