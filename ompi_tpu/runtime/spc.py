"""Software performance counters (``ompi/runtime/ompi_spc.c`` — inline
counters bumped in the bindings, exported as MPI_T-style pvars)."""
from __future__ import annotations

from ompi_tpu.base.var import PvarClass, registry

_COUNTERS = (
    "send", "isend", "recv", "irecv", "sendrecv", "probe", "iprobe",
    "bcast", "reduce", "allreduce", "gather", "scatter", "allgather",
    "alltoall", "reduce_scatter", "scan", "exscan", "barrier",
    "ibcast", "iallreduce", "ibarrier",
    "bytes_sent", "bytes_received", "bytes_packed", "bytes_unpacked",
    "unexpected_msgs", "out_of_sequence_msgs", "matched_msgs",
    "rget_msgs", "striped_msgs",
    "part_pready", "part_parrived", "part_msgs", "part_bytes",
    "device_collectives", "device_bytes",
    # fastpath counters: the zero-copy host-datapath contract, pinned by
    # test_perf_guard (payload copies on the contiguous tcp send path
    # must stay 0; the schedule cache must hit on repeated collectives)
    "fastpath_hdr_fast", "fastpath_hdr_pickle", "fastpath_sendmsg",
    "fastpath_payload_copies",
    "fastpath_sched_hits", "fastpath_sched_misses", "fastpath_eager_lane",
    "fastpath_staging_hits", "fastpath_staging_misses",
    # native-reactor progress engine (runtime/reactor): non-empty record
    # drains per tick, fast-lane frags parsed natively, and slow-lane
    # frames forwarded to the Python _parse_frame — the frags/raw split
    # shows how much of the receive path actually ran off-GIL.  All
    # three stay EXACTLY flat with otpu_progress_native=0 (identity pin
    # in test_perf_guard).
    "progress_native_drains", "fastpath_native_frags",
    "fastpath_native_raw",
    # serving counters (ompi_tpu/serving): continuous-batching engine
    # admissions/evictions per tick, decoded token volume, KV-slab
    # streaming epochs, and requests requeued by serve-through-failure
    "serve_requests", "serve_tokens", "serve_ticks", "serve_admitted",
    "serve_evicted", "serve_requeued", "serve_kv_epochs", "serve_scaleups",
    # fleet counters (ompi_tpu/serving/fleet + prefix_cache): full
    # prefill passes actually computed, prefix-cache routing hits
    # (worker-verified, prefill skipped), router-side lookup misses,
    # stale hints (registry said hit, worker store said no — perf miss
    # by design), and telemetry-policy scale-downs/re-enlistments
    "serve_prefills", "serve_prefix_hits", "serve_prefix_misses",
    "serve_prefix_stale", "serve_scaledowns", "serve_enlists",
    # chaos counters (ompi_tpu/ft/chaos): every injected fault is
    # counted, so a chaos run self-documents what it actually injected
    "chaos_drop", "chaos_delay", "chaos_dup", "chaos_corrupt",
    "chaos_reset", "chaos_stall", "chaos_disconnect", "chaos_kill",
    # self-healing coord/wire layer: reconnect-retry activity and
    # detected (checksummed) wire corruption
    "coord_reconnects", "coord_rpc_retries", "wire_cksum_fail",
    # native-reactor framing desync (a zero-length frame on the wire,
    # detected on the epoll thread and failed loudly on dispatch)
    "wire_desync",
    # live-telemetry plane (runtime/telemetry + runtime/flight):
    # samples published into the coord KV, crash dumps written
    "telemetry_samples", "flight_dumps",
    # otpu-prof sampling profiler (runtime/profile): frame-sample ticks
    "profile_samples",
    # otpu-crit causal flow layer (runtime/trace flow_start/flow_finish):
    # emitted message-flow halves — finish/start ratio is the cheap
    # live proxy for the merged-timeline link rate
    "flow_starts", "flow_finishes",
    # coll/quant block-scale codec (mca/coll/quant): encode/decode
    # invocations across all three datapaths (device, wire, KV), the
    # wire stage's measured byte savings (original minus encoded bytes
    # of every quantized tcp frame), and quant frames that failed to
    # decode on receive (its OWN counter — the crc did verify, so
    # folding it into wire_cksum_fail would misattribute the fault)
    "quant_encodes", "quant_decodes", "quant_wire_bytes_saved",
    "quant_wire_decode_fail",
    # otpu-req per-request tracing (runtime/trace requests layer):
    # requests whose causal chain was stamped, and per-request stage
    # spans emitted — both stay EXACTLY flat while otpu_trace_requests
    # is off (the zero-overhead identity pin)
    "req_traced", "req_stages",
    # SLO accounting (runtime/telemetry slo plane): completions beating
    # the otpu_serving_slo_p99_ms target vs breaching it — both inert
    # while no SLO target is set
    "slo_goodput", "slo_breaches",
    # MoE expert parallelism (parallel/moe): tokens entering the ragged
    # dispatch, tokens dropped by the capacity policy, and the
    # high-water per-step load-imbalance factor in milli-units
    # (max-expert-load / mean-load * 1000 — a gauge kept as a
    # monotonic high-water so the counter plane stays append-only)
    "moe_dispatch_tokens", "moe_dropped_tokens", "moe_imbalance_max",
    # serving front door (serving/frontdoor) + speculative decode
    # (serving/worker): requests shed at admission with a retry-after,
    # batch-class decodes preempted back into the queue on an
    # interactive-p99 breach, and draft-model tokens the target model
    # accepted vs rejected in the batched verify step — all EXACTLY
    # flat while the front door / spec_k are off (identity pins in
    # test_perf_guard and test_frontdoor)
    "serve_shed", "serve_preempt", "serve_spec_accepts",
    "serve_spec_rejects",
)

_pvars = {}


def init() -> None:
    for name in _COUNTERS:
        _pvars[name] = registry.register_pvar(
            "runtime", "spc", name, pclass=PvarClass.COUNTER,
            help=f"SPC counter: number/volume of {name}")
    # device counters accumulate in module ints (bump_device) and fold in
    # lazily; the pre-read hook keeps direct pvar readers (otpu_info
    # --pvars via registry.all_pvars) coherent too
    for name in ("device_collectives", "device_bytes"):
        if name in _pvars:
            _pvars[name].on_read = _flush_device


def record(name: str, value: float = 1) -> None:
    pv = _pvars.get(name)
    if pv is not None:
        pv.add(value)


_dev_calls_n = 0
_dev_bytes_n = 0


def bump_device(nbytes: int) -> None:
    """Hot-path SPC bump for device collectives: two plain integer adds
    on module globals (folded into the pvars at read time), mirroring the
    reference's inline non-atomic counter increments (``ompi_spc.c`` —
    SPC counters are not atomic unless multithreaded accuracy is
    requested)."""
    global _dev_calls_n, _dev_bytes_n
    _dev_calls_n += 1
    _dev_bytes_n += nbytes


def _flush_device() -> None:
    """Fold the relaxed device-counter accumulators into their pvars."""
    global _dev_calls_n, _dev_bytes_n
    if _dev_calls_n:
        pv = _pvars.get("device_collectives")
        if pv is not None:
            pv.add(_dev_calls_n)
            _dev_calls_n = 0
        pv = _pvars.get("device_bytes")
        if pv is not None:
            pv.add(_dev_bytes_n)
            _dev_bytes_n = 0


def read(name: str) -> float:
    pv = _pvars.get(name)
    return 0 if pv is None else pv.read()


def counters() -> dict:
    return {k: v.read() for k, v in _pvars.items()}
