"""Software performance counters (``ompi/runtime/ompi_spc.c`` — inline
counters bumped in the bindings, exported as MPI_T-style pvars)."""
from __future__ import annotations

from ompi_tpu.base.var import PvarClass, registry

_COUNTERS = (
    "send", "isend", "recv", "irecv", "sendrecv", "probe", "iprobe",
    "bcast", "reduce", "allreduce", "gather", "scatter", "allgather",
    "alltoall", "reduce_scatter", "scan", "exscan", "barrier",
    "ibcast", "iallreduce", "ibarrier",
    "bytes_sent", "bytes_received", "bytes_packed", "bytes_unpacked",
    "unexpected_msgs", "out_of_sequence_msgs", "matched_msgs",
    "device_collectives", "device_bytes",
)

_pvars = {}


def init() -> None:
    for name in _COUNTERS:
        _pvars[name] = registry.register_pvar(
            "runtime", "spc", name, pclass=PvarClass.COUNTER,
            help=f"SPC counter: number/volume of {name}")


def record(name: str, value: float = 1) -> None:
    pv = _pvars.get(name)
    if pv is not None:
        pv.add(value)


_dev_calls = None
_dev_bytes = None


def bump_device(nbytes: int) -> None:
    """Hot-path SPC bump for device collectives: relaxed (unlocked) adds,
    mirroring the reference's plain inline counter increments
    (``ompi_spc.c`` — SPC counters are not atomic unless multithreaded
    accuracy is requested)."""
    global _dev_calls, _dev_bytes
    if _dev_calls is None:
        _dev_calls = _pvars.get("device_collectives")
        _dev_bytes = _pvars.get("device_bytes")
        if _dev_calls is None:
            return
    _dev_calls.add_relaxed(1)
    _dev_bytes.add_relaxed(nbytes)


def read(name: str) -> float:
    pv = _pvars.get(name)
    return 0 if pv is None else pv.read()


def counters() -> dict:
    return {k: v.read() for k, v in _pvars.items()}
