"""monitoring — per-peer traffic matrices (pml/coll/osc interposition).

Re-design of ``/root/reference/ompi/mca/common/monitoring/
common_monitoring.h:48-91`` and the pml/coll/osc ``monitoring``
interposition components: when enabled (``otpu_monitoring_enable``), every
point-to-point send is recorded into a per-(src, dst) byte/message matrix,
and every collective invocation into per-collective counters — the data the
reference exports through MPI_T pvars and dumps at finalize.

The interposition points are the pml module (wrapped at selection time,
the ``pml/monitoring`` slot) and the per-comm c_coll table (wrapped after
``comm_select``, the ``coll/monitoring`` slot).
"""
from __future__ import annotations

import atexit
import threading
from typing import Optional

import numpy as np

from ompi_tpu.base.var import VarType, registry

_enable_var = registry.register(
    "monitoring", None, "enable", vtype=VarType.BOOL, default=False,
    help="Record per-peer p2p byte/message matrices and per-collective "
         "counters (pml/coll monitoring interposition)")
_dump_var = registry.register(
    "monitoring", None, "dump_at_exit", vtype=VarType.BOOL, default=False,
    help="Print the monitoring matrices at finalize (stderr)")

_lock = threading.Lock()
# (src_world, dst_world) -> [messages, bytes]
_p2p: dict[tuple[int, int], list] = {}
# (coll_name) -> [calls, bytes]
_coll: dict[str, list] = {}
_osc: dict[str, list] = {}


def enabled() -> bool:
    return bool(_enable_var.value)


def record_p2p(src: int, dst: int, nbytes: int) -> None:
    with _lock:
        cell = _p2p.setdefault((src, dst), [0, 0])
        cell[0] += 1
        cell[1] += nbytes


def record_coll(name: str, nbytes: int) -> None:
    with _lock:
        cell = _coll.setdefault(name, [0, 0])
        cell[0] += 1
        cell[1] += nbytes


def record_osc(op: str, nbytes: int) -> None:
    with _lock:
        cell = _osc.setdefault(op, [0, 0])
        cell[0] += 1
        cell[1] += nbytes


def p2p_matrix(n: Optional[int] = None):
    """(msgs, bytes) matrices as dense numpy arrays over world ranks."""
    with _lock:
        if not _p2p and not n:
            return np.zeros((0, 0), np.int64), np.zeros((0, 0), np.int64)
        size = n or (max(max(s, d) for s, d in _p2p) + 1)
        msgs = np.zeros((size, size), np.int64)
        byts = np.zeros((size, size), np.int64)
        for (s, d), (m, b) in _p2p.items():
            if s < size and d < size:
                msgs[s, d] = m
                byts[s, d] = b
        return msgs, byts


def coll_counters() -> dict:
    with _lock:
        return {k: tuple(v) for k, v in _coll.items()}


def osc_counters() -> dict:
    with _lock:
        return {k: tuple(v) for k, v in _osc.items()}


def reset() -> None:
    with _lock:
        _p2p.clear()
        _coll.clear()
        _osc.clear()


def summary() -> str:
    lines = ["monitoring: per-peer p2p matrix (src -> dst: msgs/bytes)"]
    with _lock:
        for (s, d) in sorted(_p2p):
            m, b = _p2p[(s, d)]
            lines.append(f"  {s} -> {d}: {m} msgs, {b} bytes")
        for name in sorted(_coll):
            c, b = _coll[name]
            lines.append(f"  coll {name}: {c} calls, {b} bytes")
        for name in sorted(_osc):
            c, b = _osc[name]
            lines.append(f"  osc {name}: {c} calls, {b} bytes")
    return "\n".join(lines)


class MonitoringPml:
    """pml/monitoring: records, then forwards to the real pml module."""

    def __init__(self, inner) -> None:
        self._inner = inner

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def _record(self, comm, buf, dest) -> None:
        grp = comm.remote_group if comm.is_inter else comm.group
        try:
            dst_world = grp.world_rank(dest)
        except Exception:
            return
        record_p2p(comm.world_rank(comm.rank), dst_world,
                   int(np.asarray(buf).nbytes))

    def send(self, comm, buf, dest, tag, **kw):
        self._record(comm, buf, dest)
        return self._inner.send(comm, buf, dest, tag, **kw)

    def isend(self, comm, buf, dest, tag, **kw):
        self._record(comm, buf, dest)
        return self._inner.isend(comm, buf, dest, tag, **kw)


_COLL_BYTES_ARG = {"bcast", "allreduce", "reduce", "allgather", "alltoall",
                   "reduce_scatter", "gather", "scatter", "scan", "exscan",
                   "allreduce_array", "bcast_array", "allgather_array",
                   "reduce_scatter_array", "alltoall_array"}


def wrap_coll_table(comm) -> None:
    """coll/monitoring: wrap every selected c_coll slot with a recorder."""
    if not enabled():
        return

    def make(name, fn):
        def wrapped(comm_arg, *args, **kw):
            nbytes = 0
            if name in _COLL_BYTES_ARG and args:
                try:
                    nbytes = int(np.asarray(args[0]).nbytes)
                except Exception:
                    nbytes = 0
            record_coll(name, nbytes)
            return fn(comm_arg, *args, **kw)

        wrapped.__monitored__ = True
        wrapped.__self__ = getattr(fn, "__self__", None)
        return wrapped

    for name, fn in list(comm.c_coll.items()):
        if not getattr(fn, "__monitored__", False):
            comm.c_coll[name] = make(name, fn)


def maybe_wrap_pml(pml_module):
    """Interpose the pml when monitoring is on (pml/monitoring slot)."""
    if enabled():
        return MonitoringPml(pml_module)
    return pml_module


_KV_KEY = "otpu_monitoring"


def finalize_publish(rte) -> None:
    """Publish this rank's monitoring matrices into the coord KV at
    finalize (instance teardown, while the client is still alive) so
    the launcher can print ONE job-wide communication matrix instead of
    requiring N interleaved per-rank atexit dumps.  The explicit
    ``monitoring_dump_at_exit`` dump is NOT suppressed by the publish:
    only a launcher that actually gathers the KV prints the merged
    view, and a non-tpurun embedding must not lose its matrices."""
    if not enabled():
        return
    client = getattr(rte, "client", None)
    if client is None:
        return
    import json

    rank = int(getattr(rte, "my_world_rank", 0) or 0)
    with _lock:
        payload = {
            "rank": rank,
            "p2p": [[s, d, m, b] for (s, d), (m, b) in
                    sorted(_p2p.items())],
            "coll": {k: list(v) for k, v in _coll.items()},
            "osc": {k: list(v) for k, v in _osc.items()},
        }
    client.put(rank, _KV_KEY, json.dumps(payload))


def merged_summary(payloads: list, nprocs: int) -> str:
    """Launcher-side job-wide view: sum every rank's published p2p
    matrix into one ``src -> dst`` table plus per-collective totals
    (``tpurun`` prints this at job end when monitoring ran)."""
    p2p: dict = {}
    coll: dict = {}
    for p in payloads:
        for s, d, m, b in p.get("p2p", []):
            cell = p2p.setdefault((int(s), int(d)), [0, 0])
            cell[0] += int(m)
            cell[1] += int(b)
        for name, (c, b) in p.get("coll", {}).items():
            cell = coll.setdefault(name, [0, 0])
            cell[0] += int(c)
            cell[1] += int(b)
    lines = [f"monitoring: job-wide p2p matrix ({nprocs} ranks, "
             f"{len(payloads)} reporting; src -> dst: msgs/bytes)"]
    for (s, d) in sorted(p2p):
        m, b = p2p[(s, d)]
        lines.append(f"  {s} -> {d}: {m} msgs, {b} bytes")
    for name in sorted(coll):
        c, b = coll[name]
        lines.append(f"  coll {name}: {c} calls, {b} bytes")
    return "\n".join(lines)


def _atexit_dump() -> None:
    if enabled() and bool(_dump_var.value):
        import sys

        print(summary(), file=sys.stderr, flush=True)


atexit.register(_atexit_dump)
