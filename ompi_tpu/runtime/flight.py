"""Crash-time flight recorder — "why did rank 3 die" without rerunning.

Every rank keeps cheap rolling state anyway: the otpu-trace ring, the
coord client's recent-RPC ring, the chaos event log, the SPC counters.
This module turns that state into a post-mortem artifact at the moment
something goes wrong: on MPI_Abort, on an observed peer failure (the
survivor side, dumped at teardown so the recovery spans are in the
ring), on a :class:`~ompi_tpu.runtime.sanitizer.SanitizeError`, on an
uncaught top-level exception, and on a chaos-scheduled kill (which
exits via ``os._exit`` — no atexit would ever run), each rank writes

    <otpu_flight_dir>/rank<r>.json

containing its trace-ring tail, last-N coordination RPCs, chaos event
log, SPC snapshot, known-failed ranks, and a freshly measured clock
offset to the coord server — and best-effort publishes the same payload
into the coord KV (key ``otpu_flight``) over a throwaway short-timeout
client, so the launcher can gather the victim's view even though the
victim's filesystem may be remote.  ``tpurun`` merges every gathered
dump plus the coord service's own event view into one clock-aligned
bundle (``<dir>/bundle.json``).

Dump *reasons* are a closed, ``show_help``-registered vocabulary
(``help-flight:<reason>`` — the dump announcement IS the registered
diagnostic); the otpu-lint observability pass statically rejects a
dump site whose literal reason has no registered template.

Each process dumps at most once per *death*: the triggers overlap, so
the first reason wins — with one exception.  A ``sanitize`` dump can be
a recoverable event (``SanitizeError`` subclasses ``AssertionError``
and tolerant handlers may swallow it), so a later FATAL trigger
(abort / chaos kill / uncaught exception / the survivor post-mortem)
is allowed to supersede it: the process's actual last state must not
be lost to an earlier handled trip.
"""
from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time
from typing import Optional

from ompi_tpu.base.var import VarType, registry

_KV_KEY = "otpu_flight"

_enable_var = registry.register(
    "flight", None, "enable", vtype=VarType.BOOL, default=True,
    help="Arm the crash-time flight recorder (per-rank post-mortem "
         "dump on abort / peer failure / sanitizer error / uncaught "
         "exception / chaos kill); costs nothing until a dump fires")
_dir_var = registry.register(
    "flight", None, "dir", vtype=VarType.STRING, default="otpu-crash",
    help="Directory the per-rank flight-recorder dumps (rank<r>.json) "
         "and the tpurun-gathered bundle.json are written into")
_events_var = registry.register(
    "flight", None, "events", vtype=VarType.INT, default=256,
    help="Trace-ring tail length carried in a flight-recorder dump "
         "(the newest N events of the otpu-trace ring)")

_lock = threading.Lock()
_armed_rte = None
_dumped: Optional[str] = None     # first dump's reason (once per process)
_dump_gen = 0                     # bumped per claimed dump (see dump())
_orig_excepthook = None

#: otpu-lint lock-discipline contract: the once-guard and armed-RTE
#: slot are touched from app threads, the excepthook, and chaos timers
_GUARDED_BY = {"_dumped": "_lock", "_armed_rte": "_lock"}


def flight_dir() -> str:
    return str(_dir_var.value or "otpu-crash")


def _estimate_offset_us(client) -> float:
    """This rank's wall clock minus the coord server's, in us —
    measured NOW, so the dump aligns even if the rank never reached
    finalize.  Delegates to the tracer's estimator: the sign-sensitive
    ``merge_timelines`` convention must live in exactly one place."""
    from ompi_tpu.runtime.trace import _estimate_coord_offset

    return _estimate_coord_offset(client)


def _payload(rank: int, reason: str, detail: str,
             offset_us: float) -> dict:
    from ompi_tpu.ft import chaos, state as ft_state
    from ompi_tpu.runtime import profile, spc, trace

    tail = int(_events_var.value or 256)
    events = trace.chrome_events()[-tail:]
    for ev in events:
        ev["pid"] = rank
    return {
        "rank": rank,
        "reason": reason,
        "detail": detail,
        "t_wall": time.time(),
        "host": socket.gethostname(),
        "pid_os": os.getpid(),
        "clock_offset_us": offset_us,
        "flight_dir": flight_dir(),
        "trace_tail": events,
        "coord_rpcs": _recent_rpcs(),
        "chaos_events": chaos.event_log(),
        "spc": {k: v for k, v in spc.counters().items() if v},
        "failed_ranks": sorted(ft_state.failed_ranks()),
        # otpu-prof's last stage-histogram snapshot + phase-sample
        # counts: the post-crash bundle shows where host time was going
        # (None when neither profile half was armed)
        "profile": profile.export_payload(),
        # otpu-req SLO state: a crashed fleet leaves its rolling-window
        # goodput/breach/burn accounting behind (None off the router
        # rank or while no SLO target was ever set)
        "slo": _slo_state(),
    }


def _slo_state() -> Optional[dict]:
    try:
        from ompi_tpu.runtime import telemetry

        return telemetry.slo_snapshot()
    except Exception:
        return None


def _recent_rpcs() -> list:
    with _lock:
        rte = _armed_rte
    client = getattr(rte, "client", None)
    if client is None:
        return []
    try:
        return client.recent_rpcs()
    except Exception:
        return []


#: reasons that mean the process (or a peer) actually died — these may
#: supersede an earlier RECOVERABLE dump (see module docstring)
_FATAL = ("abort", "chaos-kill", "uncaught", "proc-failed")


def dump(reason: str, detail: str = "") -> Optional[str]:
    """Write (and best-effort publish) this rank's post-mortem dump.

    Returns the dump path, or None when the recorder is disarmed /
    disabled / already fired (a fatal reason may supersede an earlier
    ``sanitize`` dump — a handled sanitizer trip must not leave the
    real crash later undumped).  Never raises — a recorder must not
    turn one failure into two."""
    global _dumped, _dump_gen
    with _lock:
        rte = _armed_rte
        allowed = (_dumped is None
                   or (_dumped == "sanitize" and reason in _FATAL))
        if rte is None or not bool(_enable_var.value) or not allowed:
            return None
        _dumped = reason
        _dump_gen += 1
        gen = _dump_gen
    try:
        return _dump_armed(rte, reason, detail, gen)
    except Exception:
        return None


def _superseded(gen: int) -> bool:
    """True when a newer dump claimed the slot while this one was still
    gathering: the async sanitize thread spends seconds measuring a
    clock offset, and a fatal dump completing in that window must not
    be overwritten by the stale one's file/KV writes."""
    with _lock:
        return _dump_gen != gen


def _dump_armed(rte, reason: str, detail: str, gen: int) -> Optional[str]:
    from ompi_tpu.base.output import show_help
    from ompi_tpu.runtime import spc

    rank = int(getattr(rte, "my_world_rank", 0) or 0)
    # throwaway short-timeout client: the shared client's lock may be
    # held by the very operation that is crashing, and a kill path must
    # not hang behind it (or behind a dead coord's full RPC timeout)
    client = None
    offset_us = 0.0
    try:
        from ompi_tpu.rte.coord import CoordClient

        client = CoordClient(timeout=2.0, retries=0)
        offset_us = _estimate_offset_us(client)
    except Exception:
        client = None
    payload = _payload(rank, reason, detail, offset_us)
    encoded = json.dumps(payload)
    path = None
    if _superseded(gen):          # re-check after the slow gather
        if client is not None:
            try:
                client.close()
            except Exception:
                pass
        return None
    try:
        os.makedirs(flight_dir(), exist_ok=True)
        path = os.path.join(flight_dir(), f"rank{rank}.json")
        with open(path, "w") as f:
            f.write(encoded)
    except OSError:
        path = None               # unwritable dir: the KV leg may still land
    if client is not None:
        try:
            if not _superseded(gen):
                client.put(rank, _KV_KEY, encoded)
        except Exception:
            pass
        try:
            client.close()
        except Exception:
            pass
    spc.record("flight_dumps")
    show_help("help-flight", reason, rank=rank,
              path=path or "<unwritable>", detail=detail or "-")
    return path


def maybe_dump_postmortem(rte) -> Optional[str]:
    """Survivor-side trigger, called at instance teardown: when this
    process observed peer failures during the job, its ring now holds
    the whole recovery (revoke/shrink/respawn spans) — dump it."""
    from ompi_tpu.ft import state as ft_state

    failed = sorted(ft_state.failed_ranks())
    if not failed:
        return None
    return dump("proc-failed", detail=",".join(str(r) for r in failed))


def _excepthook(tp, val, tb):
    try:
        # classify by the failure already observed: when this process
        # saw peers die, the exception unwinding it now is almost
        # always secondary fallout of that death (the documented
        # fleet-soak flake: a survivor's recovery-path coord RPC times
        # out and the dump said 'uncaught' instead of 'proc-failed').
        # The failed-set wins; the exception rides along as detail.
        failed = []
        try:
            from ompi_tpu.ft import state as ft_state

            failed = sorted(ft_state.failed_ranks())
        except Exception:
            pass
        if failed:
            dump("proc-failed",
                 detail=",".join(str(r) for r in failed)
                 + f" (then {val!r})")
        else:
            dump("uncaught", detail=repr(val))
    except Exception:
        pass
    hook = _orig_excepthook or sys.__excepthook__
    hook(tp, val, tb)


def arm(rte) -> None:
    """Arm the recorder for this process (instance boot): remember the
    RTE and chain the uncaught-exception hook.  Idempotent."""
    global _armed_rte, _orig_excepthook
    with _lock:
        if _armed_rte is not None:
            _armed_rte = rte      # re-boot: track the live RTE
            return
        _armed_rte = rte
    _orig_excepthook = sys.excepthook
    sys.excepthook = _excepthook


def disarm() -> None:
    """Disarm and restore the exception hook (teardown / tests).  The
    once-guard survives disarm within a process run; tests reset it via
    :func:`reset_for_testing`."""
    global _armed_rte, _orig_excepthook
    with _lock:
        _armed_rte = None
    if _orig_excepthook is not None:
        sys.excepthook = _orig_excepthook
        _orig_excepthook = None


def reset_for_testing() -> None:
    global _dumped
    disarm()
    with _lock:
        _dumped = None


from ompi_tpu.base.output import register_help as _rh

_rh("help-flight", "abort",
    "Rank {rank} called MPI_Abort ({detail}); flight-recorder dump "
    "written to {path} (trace tail, recent coord RPCs, chaos log, SPC "
    "snapshot).")
_rh("help-flight", "proc-failed",
    "Rank {rank} observed peer failure(s) [{detail}] during this job; "
    "survivor flight-recorder dump written to {path} — it carries the "
    "detection and recovery timeline.")
_rh("help-flight", "sanitize",
    "Rank {rank} tripped a sanitizer invariant ({detail}); "
    "flight-recorder dump written to {path}.")
_rh("help-flight", "uncaught",
    "Rank {rank} is dying on an uncaught exception ({detail}); "
    "flight-recorder dump written to {path}.")
_rh("help-flight", "chaos-kill",
    "Rank {rank} is being killed by its chaos schedule ({detail}); "
    "flight-recorder dump written to {path} before os._exit.")
