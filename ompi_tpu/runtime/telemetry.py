"""otpu-top telemetry plane — the per-rank live sampler.

Every observability surface before this one is post-mortem (otpu-trace
exports at finalize, monitoring dumps at exit) or in-process (SPC/pvar
reads need code running inside the rank).  This module closes the gap:
a flag-guarded sampler thread wakes every ``otpu_telemetry_interval_ms``
(deterministically jittered per rank so N ranks don't stampede the
coord service in phase), snapshots

- the SPC counters (cumulative nonzero values + per-interval deltas),
- the otpu-trace latency histograms through the snapshot/delta API
  (``trace.hist_snapshot`` — the live populations are never reset, so
  percentile pvars and the finalize export keep their full-run view),
- every registered component source (tcp out-queue depth, staging-pool
  occupancy, serving scheduler queue, progress callback count),

and publishes one compact JSON sample per rank into the CoordServer KV
space (key ``otpu_telemetry``) over a dedicated idempotent-retry
``CoordClient`` — the PR 9 self-healing RPC layer, on its own
connection so a sampler publish can never queue behind (or stall) the
application's shared client.  ``tools/otpu_top.py`` attaches to the
coord service from outside the job and renders the samples live.

**Schema discipline**: every top-level key a sample may carry is
declared in :data:`SCHEMA`; component sources register under one of
those names through :func:`register_source` and the otpu-lint
observability pass statically rejects a literal source name outside the
schema (the SPC ``_COUNTERS`` convention, applied to telemetry keys).

**Cost contract**: ``enabled`` is a module bool, False unless
:func:`start` found a positive interval — with the sampler off no
thread exists, no snapshot is ever taken, and ``register_source`` is
one dict insert at component init (pinned by
``test_perf_guard.test_telemetry_disabled_zero_overhead``).  Enabled,
the whole cost is one snapshot + one KV put per interval; the sampled
hot paths are never touched (pinned sub-interval overhead on the 4KB
allreduce loop by ``test_telemetry_enabled_overhead_bounded``).
"""
from __future__ import annotations

import json
import random
import threading
import time
import weakref
from typing import Any, Callable, Optional

from ompi_tpu.base.var import VarType, registry
from ompi_tpu.runtime.hotpath import hot_path

#: Declared sample schema: every top-level key a published telemetry
#: sample may carry, with its meaning (``otpu_info --telemetry``
#: enumerates this table; the otpu-lint observability pass enforces
#: that ``register_source`` names come from it).
SCHEMA = {
    "seq": "monotonic per-rank sample number (stale-rank detection)",
    "t": "rank wall-clock at sample time (seconds since epoch)",
    "rank": "world rank that published the sample",
    "interval_ms": "configured sampling interval of this rank",
    "spc": "cumulative nonzero SPC counters (runtime/spc.py)",
    "spc_delta": "SPC counter deltas since the previous sample",
    "hist": "per-collective interval n/sum_us/p50_us/p99_us from the "
            "otpu-trace latency-histogram deltas",
    "progress": "progress-engine registered callback count",
    "tcp": "tcp btl out-queue depth/bytes and live connection count",
    "staging": "staging-pool occupancy: pooled bytes, checkouts, "
               "hits/misses",
    "serving": "continuous-batching scheduler queue/running/done depth",
    "chaos": "injected-fault totals of an armed chaos engine",
    "profile": "otpu-prof host-overhead estimates: interval stage-clock "
               "deltas plus sampling-profiler phase/GIL fractions "
               "(runtime/profile.py)",
    "fleet": "serving-fleet control plane: per-pool worker/queue "
             "tables, prefix-cache hit/miss, reserve size, and recent "
             "autoscale decisions (serving/fleet.py)",
    "slo": "per-pool/per-tenant rolling-window SLO accounting against "
           "otpu_serving_slo_p99_ms: goodput (within-SLO completions "
           "per second), breach counts, and error-budget burn rate "
           "(this module's SloAccountant; otpu-req)",
    "moe": "MoE expert-parallel layer: per-step dispatch/dropped token "
           "totals, expert count and capacity, and the latest per-step "
           "load-imbalance factor (parallel/moe.py)",
    "frontdoor": "serving admission plane: per-class queue depths and "
                 "caps, per-tenant token-bucket levels, shed/preempt "
                 "totals with the last retry-after hint, and the "
                 "interactive-p99 ladder state (serving/frontdoor.py)",
}

#: keys the sampler itself produces; component sources may only claim
#: the remaining schema names
_BUILTIN = ("seq", "t", "rank", "interval_ms", "spc", "spc_delta",
            "hist")

_KV_KEY = "otpu_telemetry"

_interval_var = registry.register(
    "telemetry", None, "interval_ms", vtype=VarType.INT, default=0,
    help="Live-telemetry sampling interval in milliseconds; 0 (the "
         "default) disables the sampler entirely — no thread is "
         "started and the hot paths are never touched.  250 is a "
         "reasonable operational cadence for otpu_top")
_jitter_var = registry.register(
    "telemetry", None, "jitter", vtype=VarType.FLOAT, default=0.2,
    help="Per-rank deterministic jitter fraction applied to each "
         "sampling sleep (rank-seeded, so N ranks spread their coord "
         "KV publishes instead of stampeding in phase)")

#: THE guard: False means no sampler thread exists and nothing below
#: ever runs (the trace/chaos module-bool discipline)
enabled = False
_sampler: Optional["Sampler"] = None

_lock = threading.Lock()
#: name -> provider: a plain callable, or a WeakMethod for bound
#: methods (see register_source)
_sources: dict[str, Any] = {}

#: otpu-lint lock-discipline contract: the source registry is mutated
#: from component init threads and snapshotted by the sampler thread
_GUARDED_BY = {"_sources": "_lock"}


def register_source(name: str, fn: Callable[[], Optional[dict]]) -> None:
    """Register a component stat provider under a :data:`SCHEMA` key.

    ``fn`` is called ONLY by the sampler thread, once per interval; it
    must return a small JSON-serializable dict (or None to skip this
    sample).  Registration is one dict insert — components register
    unconditionally at init and pay nothing while the sampler is off.
    A name outside the declared schema is a loud error (the otpu-lint
    observability pass also rejects it statically).

    Bound methods are held through ``weakref.WeakMethod``: the registry
    must neither keep a torn-down component alive nor publish a dead
    object's frozen stats as live data — when the owner is collected
    the source silently drops out.  (Long-lived components with an
    explicit teardown — the tcp btl, chaos — also
    :func:`unregister_source` there.)"""
    if name not in SCHEMA or name in _BUILTIN:
        from ompi_tpu.base.output import show_help

        show_help("help-telemetry", "bad-source", name=name,
                  allowed=sorted(set(SCHEMA) - set(_BUILTIN)))
        raise ValueError(f"telemetry source {name!r} is not a declared "
                         "SCHEMA key")
    entry: Any = fn
    if hasattr(fn, "__self__"):
        entry = weakref.WeakMethod(fn)
    with _lock:
        _sources[name] = entry


def unregister_source(name: str) -> None:
    with _lock:
        _sources.pop(name, None)


class Sampler:
    """The per-rank sampler thread (see module docstring).

    State written by the sampling loop is thread-confined; ``_stop``
    is the only cross-thread signal."""

    def __init__(self, rank: int, interval_ms: int) -> None:
        self.rank = int(rank)
        self.interval_ms = max(1, int(interval_ms))
        self._seq = 0
        self._last_spc: dict = {}
        self._last_hist: dict = {}
        self._stop = threading.Event()
        self._jitter = random.Random(f"telemetry:{self.rank}")
        self._thread = threading.Thread(
            target=self._run, name="otpu-telemetry", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        self._thread.join(timeout)

    @hot_path
    def _sample_once(self) -> dict:
        """Build one schema'd sample dict (no publish, no blocking —
        the allocation-budgeted half the perf pins cover)."""
        from ompi_tpu.runtime import spc, trace

        self._seq += 1
        spc_now = spc.counters()
        spc_delta = {}
        for k, v in spc_now.items():
            d = v - self._last_spc.get(k, 0)
            if d:
                spc_delta[k] = d
        self._last_spc = spc_now
        hist_now = trace.hist_snapshot()
        hist = trace.hist_delta_stats(self._last_hist, hist_now)
        self._last_hist = hist_now
        sample = {
            "seq": self._seq,
            "t": time.time(),
            "rank": self.rank,
            "interval_ms": self.interval_ms,
            "spc": {k: v for k, v in spc_now.items() if v},
            "spc_delta": spc_delta,
            "hist": hist,
        }
        with _lock:
            sources = dict(_sources)
        for name, entry in sources.items():
            fn = entry() if isinstance(entry, weakref.WeakMethod) \
                else entry
            if fn is None:
                # owner collected: drop THIS entry only — a fresh
                # registration under the same name since the snapshot
                # (re-shard built a new scheduler) must survive
                with _lock:
                    if _sources.get(name) is entry:
                        del _sources[name]
                continue
            try:
                val = fn()
            except Exception:
                continue          # a broken source must not kill sampling
            if val is not None:
                sample[name] = val
        return sample

    def _run(self) -> None:
        from ompi_tpu.base.output import show_help
        from ompi_tpu.rte.coord import CoordClient
        from ompi_tpu.runtime import spc

        try:
            client = CoordClient()
        except Exception:
            return                # no coord service: nothing to publish to
        jit = float(_jitter_var.value or 0.0)
        try:
            while not self._stop.is_set():
                sleep_s = (self.interval_ms / 1e3) * (
                    1.0 + jit * (2.0 * self._jitter.random() - 1.0))
                if self._stop.wait(sleep_s):
                    break
                sample = self._sample_once()
                try:
                    client.put(self.rank, _KV_KEY, json.dumps(sample))
                    spc.record("telemetry_samples")
                except Exception:
                    # coord gone mid-job (it already exhausted the
                    # idempotent-retry ladder): stop sampling loudly
                    # once instead of spinning on a dead service
                    show_help("help-telemetry", "publish-failed",
                              rank=self.rank)
                    return
        finally:
            try:
                client.close()
            except Exception:
                pass


# -- SLO accounting (otpu-req) -------------------------------------------

#: error budget of a p99 SLO: 1% of requests may breach the latency
#: target.  Burn rate is the observed breach fraction divided by this
#: allowance — 1.0 means the window consumed its budget exactly, above
#: it the budget is burning down (the SRE burn-rate convention).
SLO_BUDGET = 0.01

_slo_window_var = registry.register(
    "serving", None, "slo_window_s", vtype=VarType.FLOAT, default=60.0,
    help="Rolling window in seconds of the SLO accountant: goodput, "
         "breach counts and error-budget burn rate are computed over "
         "completions no older than this (full-run totals are kept "
         "alongside).  The accountant itself is inert until "
         "otpu_serving_slo_p99_ms sets a latency target")


class SloAccountant:
    """Per-(pool, tenant) rolling-window SLO accounting.

    Fed one ``observe`` per completed serving request by the router's
    finish path; publishes through the ``slo`` SCHEMA key, renders as
    the otpu_top burn column, and rides flight-recorder dumps so a
    crashed fleet leaves its SLO state behind.  Inert — no state, no
    SPC traffic — while ``otpu_serving_slo_p99_ms`` is unset/0: the
    target var is registered by ``serving/fleet.py``, looked up lazily
    so this runtime module never imports the serving package.

    ``observe`` runs on the router's engine-tick thread and
    ``snapshot`` on the sampler thread: both take the accountant's own
    lock for O(window) at worst (amortized O(1): each completion is
    appended once and pruned once)."""

    _GUARDED_BY = {"_win": "_lock", "_totals": "_lock"}

    def __init__(self) -> None:
        import collections

        self._lock = threading.Lock()
        #: (pool, tenant) -> deque[(monotonic_s, ok_bool)]
        self._win: dict = collections.defaultdict(
            lambda: collections.deque(maxlen=65536))
        #: (pool, tenant) -> [total, breaches]  (full-run)
        self._totals: dict = {}
        self._target_var = None

    def target_ms(self) -> float:
        """The live SLO target (0 disables accounting).  The var
        belongs to the serving group (``serving/fleet.py``) — lazy
        registry lookup, cached once found."""
        if self._target_var is None:
            self._target_var = registry.lookup("otpu_serving_slo_p99_ms")
            if self._target_var is None:
                return 0.0
        return float(self._target_var.value or 0.0)

    def observe(self, pool: str, tenant: str, dur_ms: float) -> bool:
        """Account one completed request; returns True when it beat
        the SLO target (always True — and a no-op — with no target)."""
        from ompi_tpu.runtime import spc

        target = self.target_ms()
        if target <= 0:
            return True
        ok = float(dur_ms) <= target
        key = (str(pool), str(tenant or "-"))
        t = time.monotonic()
        with self._lock:
            self._win[key].append((t, ok))
            tot = self._totals.get(key)
            if tot is None:
                tot = self._totals[key] = [0, 0]
            tot[0] += 1
            if not ok:
                tot[1] += 1
        if ok:
            spc.record("slo_goodput")
        else:
            spc.record("slo_breaches")
        return ok

    def snapshot(self) -> Optional[dict]:
        """The ``slo`` sample value: {target_ms, window_s, budget,
        pools: {pool: {tenant: {total, breaches, goodput_rps, burn}}}}
        over the rolling window, with full-run totals alongside.  None
        while nothing was ever accounted (keeps samples compact)."""
        target = self.target_ms()
        window = max(1e-3, float(_slo_window_var.value or 60.0))
        horizon = time.monotonic() - window
        with self._lock:
            if not self._totals:
                return None
            pools: dict = {}
            for (pool, tenant), dq in self._win.items():
                while dq and dq[0][0] < horizon:
                    dq.popleft()
                n = len(dq)
                breaches = sum(1 for _, ok in dq if not ok)
                run_tot, run_breach = self._totals[(pool, tenant)]
                # elapsed covered by the window: bounded by the window
                # itself, but a younger window (the run just started)
                # uses its real span so goodput is not diluted
                span = window
                if dq:
                    span = min(window,
                               max(1e-3, time.monotonic() - dq[0][0]))
                frac = (breaches / n) if n else 0.0
                pools.setdefault(pool, {})[tenant] = {
                    "total": n,
                    "breaches": breaches,
                    "goodput_rps": round((n - breaches) / span, 3),
                    "burn": round(frac / SLO_BUDGET, 3),
                    "run_total": run_tot,
                    "run_breaches": run_breach,
                }
        return {"target_ms": target, "window_s": window,
                "budget": SLO_BUDGET, "pools": pools}

    def reset(self) -> None:
        with self._lock:
            self._win.clear()
            self._totals.clear()
        self._target_var = None


#: the process-wide accountant (router finish path feeds it; the
#: sampler, otpu_top, and the flight recorder read it)
slo = SloAccountant()


def slo_observe(pool: str, tenant: str, dur_ms: float) -> bool:
    """Module-level convenience used by ``serving/router.py``."""
    return slo.observe(pool, tenant, dur_ms)


def slo_snapshot() -> Optional[dict]:
    return slo.snapshot()


def start(rte) -> bool:
    """Arm the sampler for this rank (called from the instance boot).

    No-op — and zero-cost from then on — unless
    ``otpu_telemetry_interval_ms`` is positive and the RTE has a coord
    client to publish through.  Idempotent."""
    global enabled, _sampler
    if _sampler is not None:
        return True
    interval = int(_interval_var.value or 0)
    if interval <= 0 or getattr(rte, "client", None) is None:
        return False
    _sampler = Sampler(int(getattr(rte, "my_world_rank", 0) or 0),
                       interval)
    enabled = True
    _sampler.start()
    return True


def stop() -> None:
    """Disarm (instance teardown / tests); restores the zero-cost
    identity."""
    global enabled, _sampler
    s, _sampler = _sampler, None
    enabled = False
    if s is not None:
        s.stop()


# the accountant is module-owned (never collected), registered like
# any component source: one dict insert, sampled only while the
# sampler runs, skipped (None) until something was accounted
register_source("slo", slo.snapshot)

from ompi_tpu.base.output import register_help as _rh

_rh("help-telemetry", "bad-source",
    "Telemetry source {name!r} is not declared in "
    "runtime/telemetry.py SCHEMA (allowed component keys: {allowed}). "
    "Published sample keys must come from the declared schema so "
    "otpu_top and the analyzer can rely on their meaning.")
_rh("help-telemetry", "publish-failed",
    "Rank {rank}'s telemetry sampler lost the coordination service and "
    "could not re-establish it; live telemetry from this rank stops "
    "here (the job itself is unaffected).")
