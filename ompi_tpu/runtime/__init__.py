"""Runtime: init/finalize state machine, progress engine, RTE adapters, SPC.

Equivalent of ``/root/reference/ompi/runtime/`` + ``opal/runtime/``.
"""
