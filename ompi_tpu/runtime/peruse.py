"""PERUSE — introspection callbacks on the matching engine's internals.

Re-design of ``/root/reference/ompi/peruse/peruse.h`` (+ the hook sites in
``pml_ob1_recvfrag.c``): tools subscribe per-communicator callbacks on
named internal events of the point-to-point engine — request activation,
posted-queue insertion, unexpected-queue insertion, matching in both
directions, transfer completion.  This is the layer BELOW the PMPI
profiling shift: it sees queue behaviour (unexpected-message growth,
match latency) that no wrapper around MPI_Recv can observe.

The hot path stays cheap: every hook site is guarded by a module flag
that is only true while at least one subscription is active, so the
disabled cost is one attribute load + branch.
"""
from __future__ import annotations

import itertools
import threading
from typing import Callable, Optional

# event names (peruse.h PERUSE_COMM_* event set)
REQ_ACTIVATE = "REQ_ACTIVATE"
REQ_INSERT_IN_POSTED_Q = "REQ_INSERT_IN_POSTED_Q"
REQ_MATCH_UNEX = "REQ_MATCH_UNEX"
REQ_XFER_END = "REQ_XFER_END"
REQ_COMPLETE = "REQ_COMPLETE"
MSG_ARRIVED = "MSG_ARRIVED"
MSG_INSERT_IN_UNEX_Q = "MSG_INSERT_IN_UNEX_Q"
MSG_MATCH_POSTED_REQ = "MSG_MATCH_POSTED_REQ"

EVENTS = (REQ_ACTIVATE, REQ_INSERT_IN_POSTED_Q, REQ_MATCH_UNEX,
          REQ_XFER_END, REQ_COMPLETE, MSG_ARRIVED, MSG_INSERT_IN_UNEX_Q,
          MSG_MATCH_POSTED_REQ)

ANY_COMM = -1          # subscribe across all communicators

_active = False        # fast-path guard, mirrored by ob1 hook sites
_lock = threading.Lock()
_subs: dict = {}       # (event, cid) -> {handle: cb}
_ids = itertools.count(1)


class Handle:
    """An activated event subscription (``peruse_event_h`` analog)."""

    def __init__(self, event: str, cid: int, hid: int) -> None:
        self.event = event
        self.cid = cid
        self._hid = hid

    def release(self) -> None:
        unsubscribe(self)


def subscribe(event: str, cb: Callable, comm=None) -> Handle:
    """Register ``cb(event, cid, **info)`` for an event, optionally
    scoped to one communicator (``PERUSE_Event_comm_register`` +
    activate collapsed — the reference's two-step is about object
    lifetime C can't infer)."""
    global _active
    if event not in EVENTS:
        raise ValueError(f"unknown PERUSE event {event!r}")
    cid = ANY_COMM if comm is None else comm.cid
    h = Handle(event, cid, next(_ids))
    with _lock:
        _subs.setdefault((event, cid), {})[h._hid] = cb
        _active = True
    return h


def unsubscribe(handle: Handle) -> None:
    global _active
    with _lock:
        d = _subs.get((handle.event, handle.cid))
        if d:
            d.pop(handle._hid, None)
            if not d:
                _subs.pop((handle.event, handle.cid), None)
        _active = any(_subs.values())


def active() -> bool:
    return _active


def fire(event: str, cid: int, **info) -> None:
    """Deliver an event to matching subscriptions (exact cid + ANY)."""
    if not _active:
        return
    with _lock:
        cbs = list(_subs.get((event, cid), {}).values()) \
            + list(_subs.get((event, ANY_COMM), {}).values())
    for cb in cbs:
        try:
            cb(event, cid, **info)
        except Exception:
            pass  # an introspection callback must never break the engine


def reset() -> None:
    global _active
    with _lock:
        _subs.clear()
        _active = False
