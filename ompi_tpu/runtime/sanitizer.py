"""OTPU_SANITIZE=1 — the runtime half of the otpu-lint invariants.

Static passes prove what is provable from source; this mode turns the
*dynamic* ownership invariants into hard assertions for the fuzz
workers:

- the staging pool's ownership tags: a double release (the PR 4 aliasing
  family) or a non-contiguous release raises :class:`SanitizeError`
  instead of being silently tolerated,
- the tcp wire's borrowed contract: after a borrowed send returns, no
  out-queue entry may still alias the caller's buffer; inbound framing
  asserts frame sanity before parse (a desynced stream fails at the
  first bad length, not three messages later),
- ``runtime/memchecker.py`` is force-enabled, so writing into a buffer
  MPI still owns fails at the racy write.

Cost contract: ``enabled`` is a module bool read once at import from the
environment; every check site is on a cold/error path or behind an
``if sanitizer.enabled`` branch the default-off mode never enters.  The
decorator/hook structure compiles out to no-ops when off — pinned by
``test_perf_guard.test_sanitizer_off_zero_overhead``.  Tests may flip
``sanitizer.enabled`` directly (consumers read it at use time).

The weave interleaving explorer (``analysis/weave.py``) arms this flag
for the duration of every scheduled run, so these assertions double as
the failure oracles of the schedule search: a race that slips past a
guard (the reverted-fix scenarios) fails the run's invariant check,
while a schedule where the guard correctly catches a deliberate
mis-use raises ``SanitizeError`` the scenario swallows.  Outside a run
weave touches nothing here — same identity-off contract, pinned by
``test_perf_guard.test_weave_off_zero_overhead``.
"""
from __future__ import annotations

import os

#: read once at import; tpurun-spawned ranks inherit the launcher's env
enabled = os.environ.get("OTPU_SANITIZE", "").strip() not in ("", "0")


class SanitizeError(AssertionError):
    """An ownership/framing invariant the sanitizer enforces was broken."""


def fail(msg: str) -> None:
    try:
        # crash-time post-mortem: a sanitizer trip is exactly the
        # moment the trace tail / SPC snapshot explain the broken
        # invariant (no-op unless the flight recorder is armed).  On
        # its OWN short-lived thread (the propagator.wire_suspicion
        # pattern): fail() fires inside hot paths holding declared
        # locks (tcp send_lock), and the dump dials the coord service
        # for a clock offset — seconds of blocking I/O that must not
        # stall the connection, and must not run under the lock.
        import threading

        from ompi_tpu.runtime import flight

        threading.Thread(target=flight.dump, args=("sanitize",),
                         kwargs={"detail": msg},
                         name="otpu-flight-sanitize",
                         daemon=True).start()
    except Exception:
        pass
    raise SanitizeError(msg)
