"""otpu-trace — always-on span tracing with per-rank ring buffers.

The missing *timeline* layer of the observability stack: SPC counts
(`runtime/spc.py`), monitoring sums per peer (`runtime/monitoring.py`),
PERUSE sees queue internals (`runtime/peruse.py`) — none of them record
WHEN a collective started and ended on each rank, so collective skew,
straggler ranks, and FT detection latency were invisible.  This module
records spans (name, category, t_start/t_end ns, args) and instant
events into a fixed-size per-rank ring buffer, plus log2-size-binned
latency histograms per collective exported as MPI_T pvars.

Hot-path discipline is peruse.py's: every instrumentation site is
guarded by the single module flag ``enabled`` — the disabled cost is one
attribute load + branch.  The enabled record path is lock-light: slot
allocation is one ``itertools.count`` bump (atomic in CPython), the ring
overwrites oldest entries, and only the histogram update takes a lock
(it is exact, the way SPC's relaxed counters are not).

At finalize each rank exports a Chrome trace-event JSON file
(``otpu_trace_dir`` cvar) and publishes the payload into the
CoordServer KV space so the launcher (``tools/tpurun.py``) can gather
every rank's timeline, align clocks with the mpisync offset estimator,
and emit one merged timeline plus a skew report.

**Causal flow keys (otpu-crit).**  Per-rank spans say what each rank
did; they cannot say which rank's message a recv waited on.  The flow
layer stamps every pml message span with a compact key —
``cid.src.dst.seq``, the (comm, sender, receiver, per-peer sequence)
tuple that ALREADY rides every btl match header — and every traced
collective span with ``(cid, cseq)``, a per-communicator collective
sequence every member rank counts identically (MPI requires identical
collective order per comm, so rank A's Nth collective on a cid IS rank
B's Nth).  Send completion and recv delivery additionally emit Chrome
flow events (``ph:"s"``/``"f"`` sharing an ``id``), so a merged
timeline renders real cross-rank message arrows and
``tools/otpu_analyze.py`` can assemble the cross-rank activity graph
(program-order edges, message edges, collective barrier edges) behind
``--critical-path``.  Guarded by its own module bool ``flow_enabled``
(`otpu_trace_flow`): flow-off runs pay nothing beyond the existing
``enabled`` checks.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, Optional

from ompi_tpu.base.var import PvarClass, VarType, registry

#: THE fast-path guard (peruse._active discipline): instrumentation
#: sites read this module attribute and branch — nothing else happens
#: while tracing is disabled.
enabled = False

#: the flow layer's own guard: true only while ``enabled`` AND the
#: ``otpu_trace_flow`` cvar is set.  Flow stamping sites (pml span
#: keys, flow_start/flow_finish, the coll-wrapper cseq) read this and
#: branch — a flow-disabled run records exactly what it did before
#: otpu-crit existed.
flow_enabled = False

#: the request layer's guard (otpu-req): true only while ``enabled``
#: AND the ``otpu_trace_requests`` cvar is set.  Serving call sites
#: (router stage stamps, worker prefill/kv/decode spans, the kv-slab
#: per-sequence flow hops) read this and branch — a requests-disabled
#: run records exactly what it did before otpu-req existed.
requests_enabled = False

#: Declared span categories (the registry ``otpu_info --trace``
#: enumerates; every ``trace.span``/``instant`` call site uses one).
CATEGORIES = {
    "boot": "instance boot path (coord connect, modex fence)",
    "btl": "transport-layer wire operations (sendmsg, ring push)",
    "chaos": "injected-fault instants (ft/chaos)",
    "coll": "collective invocations (c_coll interposition)",
    "device": "device-world dispatch (coll/xla)",
    "ft": "failure detection/propagation/agreement + elastic recovery",
    "io": "MPI-IO (ompio) operations",
    "osc": "one-sided epochs (fence/lock/PSCW/flush)",
    "part": "partitioned communication (Pready/Parrived)",
    "pml": "point-to-point send/recv completion spans",
    "serving": "continuous-batching serving ticks",
    "serve_req": "per-request serving stage spans (otpu-req: queue/"
                 "dispatch/prefill/kv/decode/stream, args carry the "
                 "rid — otpu_analyze --requests consumes them)",
    "staging": "accelerator staging-pool checkouts",
    "step": "application/training step windows (critical-path unit)",
    "flow": "Chrome flow events binding send completion to recv "
            "delivery (ph s/f; otpu-crit message arrows)",
}

#: Declared flow-key categories: the closed vocabulary ``flow_start``/
#: ``flow_finish`` accept (otpu-lint's observability pass checks
#: literal call sites against this table, the STAGES discipline).  The
#: key format is part of the contract — otpu_analyze parses it.
FLOW_CATEGORIES = {
    "pml_msg": "one point-to-point message: send completion -> recv "
               "delivery, id 'cid.src.dst.seq' (world ranks; the "
               "per-(cid,src,dst) pml sequence that rides every btl "
               "match header)",
    "coll_round": "one collective round: every member rank's span "
                  "carries the same (cid, cseq) key in its args; the "
                  "analyzer builds last-arrival->all-release barrier "
                  "edges from it",
    "serve_req": "one serving-request hop: id 'rid.hop' where hop "
                 "numbers the causal chain router dispatch (0) -> "
                 "prefill shard -> KV slab Pready/Parrived (1) -> "
                 "decode/token stream (2) -> router completion; a "
                 "merged timeline renders one arrow chain per request "
                 "across router and worker ranks",
}

_ring: Optional[list] = None
_ring_n = 0
_slot = itertools.count()

#: per-communicator collective sequence counters (cid -> count); every
#: rank assigns cseq at record time in program order, so the counters
#: agree across ranks without any wire traffic
_coll_seq: dict = {}

#: wall/monotonic anchor pair: spans carry perf_counter_ns timestamps
#: (monotonic, ns resolution); export maps them onto the wall clock via
#: this pair so cross-rank merge has a common (pre-offset) timebase.
_anchor_wall_ns = time.time_ns()
_anchor_mono_ns = time.perf_counter_ns()

# histogram state: (coll, log2 size bin) -> [count, sum_ns, min_ns,
# max_ns, count_pvar, sum_pvar, {log2 dur bin: count}]; exact under
# _hist_lock (enabled path only).  The trailing dict is the log2
# LATENCY sub-histogram percentile estimation interpolates over.
_hist: dict = {}
_hist_lock = threading.Lock()

_events_pvar = None
_KV_KEY = "otpu_trace"
_DEFAULT_DIR = "otpu-trace"


def _sync_flow() -> None:
    # defensive lookup: the flow var's own registration may fire this
    # hook (env/file value applied) before the module global binds
    global flow_enabled
    var = globals().get("_flow_var")
    flow_enabled = enabled and (var is None or bool(var.value))


def _sync_requests() -> None:
    # same defensive lookup as _sync_flow, same reason — but note the
    # inverted default: flow rides enabled tracing unless opted OUT,
    # the request layer stays off unless opted IN
    global requests_enabled
    var = globals().get("_requests_var")
    requests_enabled = enabled and var is not None and bool(var.value)


def _set_enabled(value: bool) -> None:
    global enabled, _ring, _ring_n
    if value:
        want = max(1024, int(_buf_var.value or 65536))
        if _ring is None or want != _ring_n:
            # honor a buffer_events change across a disable/re-enable
            # cycle; the resize starts a fresh (empty) ring
            _ring_n = want
            _ring = [None] * want
    enabled = bool(value)
    _sync_flow()
    _sync_requests()


# buffer/dir/flow register first: registering the enable var applies
# its env/file value immediately, and the on_set hook sizes the ring
_dir_var = registry.register(
    "trace", None, "dir", vtype=VarType.STRING, default="",
    help="Directory for per-rank Chrome trace JSON written at finalize "
         f"(empty: '{_DEFAULT_DIR}' when tracing is enabled)")
_buf_var = registry.register(
    "trace", None, "buffer_events", vtype=VarType.INT, default=65536,
    help="Ring buffer capacity in events; the ring overwrites oldest "
         "entries, so a trace always holds the run's tail — the "
         "overwritten count is surfaced in the export metadata and the "
         "otpu_analyze report header")
_flow_var = registry.register(
    "trace", None, "flow", vtype=VarType.BOOL, default=True,
    help="Stamp pml message spans with their cid.src.dst.seq flow key "
         "(emitted as Chrome flow-event arrows) and collective spans "
         "with a per-comm (cid, cseq) round key — the causal edges "
         "otpu_analyze --critical-path consumes.  Only meaningful "
         "while tracing is enabled; off pins the pre-otpu-crit "
         "record path",
    on_set=lambda _v: _sync_flow())
_requests_var = registry.register(
    "trace", None, "requests", vtype=VarType.BOOL, default=False,
    help="Thread every serving request through the trace as a "
         "request-scoped span/flow layer: per-stage 'serve_req' spans "
         "(queue/dispatch/prefill/kv/decode/stream, keyed by rid) and "
         "a 'rid.hop' flow-arrow chain router -> prefill -> decode -> "
         "router riding the KV slab's per-sequence Pready keys — what "
         "otpu_analyze --requests decomposes.  Default off: the "
         "serving hot path pays nothing until a request-granular "
         "question is asked",
    on_set=lambda _v: _sync_requests())
_enable_var = registry.register(
    "trace", None, "enable", vtype=VarType.BOOL, default=False,
    help="Record span/instant events (pml, coll host+device, osc epochs, "
         "MPI-IO, FT) into the per-rank trace ring buffer and export "
         "Chrome trace JSON at finalize; disabled cost is one flag check",
    on_set=_set_enabled)


def init() -> None:
    """Register the tracer's own pvars (called from runtime init; safe
    to call repeatedly)."""
    global _events_pvar
    _events_pvar = registry.register_pvar(
        "trace", None, "events_recorded", pclass=PvarClass.COUNTER,
        help="Total trace events recorded (ring may have overwritten "
             "the oldest: capacity is otpu_trace_buffer_events)")
    _events_pvar.on_read = \
        lambda: _events_pvar.set(float(recorded_count()))


def recorded_count() -> int:
    """Total events ever recorded: the highest slot index still in the
    ring, +1.  Slot allocation is the one atomic counter (itertools
    .count), so this needs no second — racy — accumulator; overwritten
    events can only have LOWER indices than the survivors."""
    if _ring is None:
        return 0
    return max((e[-1] for e in _ring if e is not None), default=-1) + 1


def now() -> int:
    """Span start timestamp (perf_counter_ns)."""
    return time.perf_counter_ns()


def span(name: str, cat: str, t_start: int, t_end: Optional[int] = None,
         args: Optional[dict] = None) -> None:
    """Record one complete span.  Callers capture ``t_start = trace.now()``
    inside their own ``if trace.enabled`` guard."""
    if not enabled:
        return
    if t_end is None:
        t_end = time.perf_counter_ns()
    i = next(_slot)
    _ring[i % _ring_n] = ("X", name, cat, t_start, t_end - t_start,
                          threading.get_ident(), args, i)


def instant(name: str, cat: str, args: Optional[dict] = None) -> None:
    """Record one instant event (FT detection, propagation, delivery)."""
    if not enabled:
        return
    i = next(_slot)
    _ring[i % _ring_n] = ("i", name, cat, time.perf_counter_ns(), 0,
                          threading.get_ident(), args, i)


# -- causal flow events (otpu-crit) --------------------------------------

def _flow_id(fid) -> str:
    """Normalize a flow key to the Chrome id string: tuple keys (what
    @hot_path call sites pass — string building is banned there) render
    dot-joined, matching the documented ``cid.src.dst.seq`` format."""
    return fid if isinstance(fid, str) else ".".join(map(str, fid))


def flow_start(fcat: str, fid, t_ns: Optional[int] = None) -> None:
    """Record the producing half of one flow edge (Chrome ``ph:"s"``).

    ``fcat`` must be a :data:`FLOW_CATEGORIES` key (otpu-lint-enforced
    at literal call sites); ``fid`` is the category's documented key —
    a string or a tuple rendered dot-joined.  ``t_ns`` anchors the
    arrow inside the emitting span — callers pass the span's own end
    timestamp so viewers bind the flow to that slice."""
    if not flow_enabled:
        return
    from ompi_tpu.runtime import spc

    spc.record("flow_starts")
    i = next(_slot)
    _ring[i % _ring_n] = ("s", fcat, "flow",
                         t_ns if t_ns is not None
                         else time.perf_counter_ns(), 0,
                         threading.get_ident(), {"id": _flow_id(fid)}, i)


def flow_finish(fcat: str, fid, t_ns: Optional[int] = None) -> None:
    """Record the consuming half of one flow edge (Chrome ``ph:"f"``,
    bound to the enclosing slice via ``bp:"e"``)."""
    if not flow_enabled:
        return
    from ompi_tpu.runtime import spc

    spc.record("flow_finishes")
    i = next(_slot)
    _ring[i % _ring_n] = ("f", fcat, "flow",
                         t_ns if t_ns is not None
                         else time.perf_counter_ns(), 0,
                         threading.get_ident(), {"id": _flow_id(fid)}, i)


def next_coll_seq(cid: int) -> int:
    """Allocate this rank's next collective sequence number on ``cid``
    (the coll_round flow key's second half).  Program order per comm is
    identical on every member rank by MPI semantics, so the counters
    agree with zero wire traffic; assignment happens at record time, so
    ring overwrite can never desynchronise surviving spans."""
    c = _coll_seq.get(cid)
    if c is None:
        c = _coll_seq.setdefault(cid, itertools.count())
    return next(c)


# -- log2-size-binned latency histograms --------------------------------

def _bin_label(b: int) -> str:
    """Human label of log2 bin ``b`` (its lower bound): 0, 1b..512b,
    1k..512k, 1m.."""
    if b == 0:
        return "0"
    lo = 1 << (b - 1)
    if lo < (1 << 10):
        return f"{lo}b"
    if lo < (1 << 20):
        return f"{lo >> 10}k"
    if lo < (1 << 30):
        return f"{lo >> 20}m"
    return f"{lo >> 30}g"


def hist_record(coll: str, nbytes: int, dur_ns: int) -> None:
    """Fold one collective invocation into its (coll, log2 size) bin and
    the bin's MPI_T pvars (lazily registered on first hit so the pvar
    namespace only carries bins the run actually touched)."""
    b = int(nbytes).bit_length()
    key = (coll, b)
    with _hist_lock:
        cell = _hist.get(key)
        if cell is None:
            label = _bin_label(b)
            cnt = registry.register_pvar(
                "trace", "hist", f"{coll}_{label}_count",
                pclass=PvarClass.COUNTER,
                help=f"{coll} invocations in the [{label}, next-bin) "
                     "payload size bin")
            tot = registry.register_pvar(
                "trace", "hist", f"{coll}_{label}_sum_us",
                pclass=PvarClass.AGGREGATE,
                help=f"Summed {coll} latency (us) in the [{label}, "
                     "next-bin) payload size bin")
            cell = _hist[key] = [0, 0, dur_ns, dur_ns, cnt, tot, {}]
            for q, qname in ((0.5, "p50"), (0.99, "p99")):
                pv = registry.register_pvar(
                    "trace", "hist", f"{coll}_{label}_{qname}_us",
                    pclass=PvarClass.LEVEL,
                    help=f"{qname} {coll} latency (us, interpolated from "
                         f"the log2 latency bins) in the [{label}, "
                         "next-bin) payload size bin")
                # pre-read hook: percentiles are derived, not accumulated
                pv.on_read = (lambda pv=pv, key=key, q=q:
                              pv.set(_key_percentile_us(key, q)))
        cell[0] += 1
        cell[1] += dur_ns
        cell[2] = min(cell[2], dur_ns)
        cell[3] = max(cell[3], dur_ns)
        cell[4].add_relaxed(1)
        cell[5].add_relaxed(dur_ns / 1000.0)
        db = int(dur_ns).bit_length()
        cell[6][db] = cell[6].get(db, 0) + 1


def histograms() -> dict:
    """{(coll, bin_label): (count, sum_us, min_us, max_us)} snapshot."""
    with _hist_lock:
        return {
            (coll, _bin_label(b)): (c[0], c[1] / 1000.0, c[2] / 1000.0,
                                    c[3] / 1000.0)
            for (coll, b), c in _hist.items()
        }


def _interp_percentile_ns(dur_bins: dict, q: float, lo_clamp: int,
                          hi_clamp: int) -> float:
    """Estimate the q-quantile (ns) from a {log2 bin: count} latency
    histogram: find the bin holding the q*N-th sample and interpolate
    linearly inside it (bin b covers [2^(b-1), 2^b)), clamped to the
    exact observed [min, max] so single-bin cells don't over-report."""
    total = sum(dur_bins.values())
    if total == 0:
        return 0.0
    target = q * total
    cum = 0.0
    est = float(hi_clamp)
    for b in sorted(dur_bins):
        cnt = dur_bins[b]
        if cum + cnt >= target:
            lo = 0 if b == 0 else (1 << (b - 1))
            hi = 1 if b == 0 else (1 << b)
            frac = (target - cum) / cnt
            est = lo + frac * (hi - lo)
            break
        cum += cnt
    return float(max(lo_clamp, min(hi_clamp, est)))


def _key_percentile_us(key, q: float) -> float:
    """q-quantile (us) of ONE (coll, size-bin) cell (pvar read hook)."""
    with _hist_lock:
        cell = _hist.get(key)
        if cell is None:
            return 0.0
        return _interp_percentile_ns(cell[6], q, cell[2], cell[3]) / 1000.0


def hist_snapshot() -> dict:
    """Deep-copied histogram state for delta consumers (the telemetry
    sampler): ``{(coll, size_bin): (count, sum_ns, min_ns, max_ns,
    {log2 dur bin: count})}``.  Pure read — the live populations are
    NEVER reset or otherwise disturbed, so a sampler can snapshot at
    its own cadence while percentile pvars, ``hist_percentile`` and the
    finalize export keep seeing the full-run populations."""
    with _hist_lock:
        return {k: (c[0], c[1], c[2], c[3], dict(c[6]))
                for k, c in _hist.items()}


def hist_delta_stats(prev: dict, cur: dict) -> dict:
    """Per-collective interval statistics between two
    :func:`hist_snapshot` results: ``{coll: {"n": invocations,
    "sum_us": total latency, "p50_us": ..., "p99_us": ...}}`` computed
    from the BIN-COUNT DELTAS (size bins merged per collective), so the
    percentiles describe only the interval's population.  Collectives
    with no new invocations are omitted — the samples stay compact.
    ``bytes`` is a payload-volume estimate (count x size-bin lower
    bound, exact to within one log2 bin) — the live-rate signal for
    traffic that never touches the pml SPC counters (sm collectives)."""
    merged: dict = {}   # coll -> [dn, dsum_ns, {dur bin: dcount}, bytes]
    clamps: dict = {}        # coll -> [lo_ns, hi_ns] (from cur cells)
    for key, cell in cur.items():
        coll = key[0]
        old = prev.get(key)
        dn = cell[0] - (old[0] if old else 0)
        if dn <= 0:
            continue
        dsum = cell[1] - (old[1] if old else 0)
        acc = merged.setdefault(coll, [0, 0, {}, 0])
        acc[0] += dn
        acc[1] += dsum
        b = key[1]
        acc[3] += dn * (0 if b == 0 else (1 << (b - 1)))
        old_bins = old[4] if old else {}
        for db, cnt in cell[4].items():
            d = cnt - old_bins.get(db, 0)
            if d > 0:
                acc[2][db] = acc[2].get(db, 0) + d
        cl = clamps.setdefault(coll, [cell[2], cell[3]])
        cl[0] = min(cl[0], cell[2])
        cl[1] = max(cl[1], cell[3])
    out = {}
    for coll, (dn, dsum, dbins, dbytes) in merged.items():
        lo, hi = clamps[coll]
        out[coll] = {
            "n": dn,
            "bytes": dbytes,
            "sum_us": round(dsum / 1000.0, 1),
            "p50_us": round(
                _interp_percentile_ns(dbins, 0.5, lo, hi) / 1000.0, 1),
            "p99_us": round(
                _interp_percentile_ns(dbins, 0.99, lo, hi) / 1000.0, 1),
        }
    return out


def hist_reset(coll: str) -> None:
    """Drop every histogram cell of ``coll`` so the next records start
    a fresh population — measurement harnesses (the serving driver) use
    this to keep per-run percentiles from merging with an earlier run's
    samples in the same process.  The cells' pvars stay registered
    (counters remain cumulative, like every SPC pvar); the percentile
    pvars re-bind to the new cells on the next record."""
    with _hist_lock:
        for key in [k for k in _hist if k[0] == coll]:
            del _hist[key]


def hist_percentile(coll: str, q: float,
                    nbytes: Optional[int] = None) -> float:
    """Estimated q-quantile latency in MICROSECONDS of ``coll``'s
    recorded invocations — interpolated from the log2-duration bins the
    histogram keeps per cell (exact to within one log2 bin; the serving
    driver's p50/p99 report and ``otpu_info --pvars`` read this).

    ``nbytes`` restricts the estimate to that payload's size bin;
    without it the duration bins of every size bin are merged."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    if nbytes is not None:
        return _key_percentile_us((coll, int(nbytes).bit_length()), q)
    with _hist_lock:
        merged: dict = {}
        lo_clamp, hi_clamp, any_cell = None, 0, False
        for (c, _b), cell in _hist.items():
            if c != coll:
                continue
            any_cell = True
            lo_clamp = cell[2] if lo_clamp is None else min(lo_clamp,
                                                            cell[2])
            hi_clamp = max(hi_clamp, cell[3])
            for db, cnt in cell[6].items():
                merged[db] = merged.get(db, 0) + cnt
        if not any_cell:
            return 0.0
        return _interp_percentile_ns(merged, q, lo_clamp, hi_clamp) / 1000.0


# -- per-comm coll table interposition ----------------------------------

#: collectives whose first argument carries the payload (superset of
#: monitoring's set: the device *_array entry points are sized too)
_SIZED_COLLS = {
    "bcast", "allreduce", "reduce", "allgather", "allgatherv", "alltoall",
    "reduce_scatter", "reduce_scatter_block", "gather", "gatherv",
    "scatter", "scan", "exscan",
    "ibcast", "iallreduce", "ireduce", "iallgather", "ialltoall",
    "igather", "iscatter", "ireduce_scatter", "iscan", "iexscan",
    "allreduce_array", "bcast_array", "allgather_array",
    "allgatherv_array", "reduce_scatter_array", "alltoall_array",
    "alltoallv_array", "ppermute_array", "psum_scatter_array",
    "reduce_array", "gather_array", "scatter_array", "scan_array",
    "exscan_array",
}


def wrap_coll_table(comm) -> None:
    """coll/trace interposition: wrap every selected c_coll slot with a
    span + histogram recorder.  Installed unconditionally at comm_select
    (tracing can be switched on mid-run through MPI_T); the wrapper's
    disabled path is one flag check, verified by test_perf_guard."""

    def make(name, fn):
        def traced(comm_arg, *args, **kw):
            if not enabled:
                return fn(comm_arg, *args, **kw)
            # .nbytes is an attribute on both numpy and jax arrays — no
            # np.asarray here, which would pull a device buffer to host
            nbytes = 0
            if name in _SIZED_COLLS and args:
                nbytes = getattr(args[0], "nbytes", 0) or 0
            # coll_round flow key: cseq allocated BEFORE the collective
            # runs, in program order — every member rank's span for this
            # round carries the same (cid, cseq)
            cseq = next_coll_seq(comm_arg.cid) if flow_enabled else None
            t0 = time.perf_counter_ns()
            try:
                return fn(comm_arg, *args, **kw)
            finally:
                t1 = time.perf_counter_ns()
                eargs = {"nbytes": int(nbytes), "cid": comm_arg.cid}
                if cseq is not None:
                    eargs["cseq"] = cseq
                span(name, "coll", t0, t1, args=eargs)
                hist_record(name, int(nbytes), t1 - t0)

        # carry the inner slot's marker attributes (__sync_wrapped__,
        # __monitored__, ...) — interposition layers and tests probe the
        # outermost callable for them
        traced.__dict__.update(getattr(fn, "__dict__", {}))
        traced.__traced__ = True
        traced.__wrapped__ = fn
        traced.__self__ = getattr(fn, "__self__", None)
        return traced

    for name, fn in list(comm.c_coll.items()):
        if not getattr(fn, "__traced__", False):
            comm.c_coll[name] = make(name, fn)


# -- export --------------------------------------------------------------

def _wall_us(t_ns: int) -> float:
    return (_anchor_wall_ns + (t_ns - _anchor_mono_ns)) / 1000.0


def chrome_events() -> list:
    """Ring contents as Chrome trace-event dicts (ts/dur in wall-clock
    microseconds), oldest first."""
    if _ring is None:
        return []
    events = [e for e in _ring if e is not None]
    events.sort(key=lambda e: e[3])
    out = []
    for ph, name, cat, t0, dur, tid, eargs, _slot_i in events:
        ev = {"ph": ph, "name": name, "cat": cat,
              "ts": _wall_us(t0), "tid": tid}
        if ph == "X":
            ev["dur"] = dur / 1000.0
        if ph in ("s", "f"):
            # flow events: the id is a top-level field in the Chrome
            # schema; "f" binds to its enclosing slice (bp:"e") so the
            # arrow lands on the recv span, not the next event
            eargs = dict(eargs or {})
            ev["id"] = eargs.pop("id", "")
            if ph == "f":
                ev["bp"] = "e"
        if eargs:
            ev["args"] = eargs
        out.append(ev)
    return out


def chrome_payload(rank: int, clock_offset_us: float = 0.0,
                   extra_meta: Optional[dict] = None) -> dict:
    """Full per-rank Chrome trace JSON object (events + metadata)."""
    import socket

    recorded = recorded_count()
    events = chrome_events()
    for ev in events:
        ev["pid"] = rank
    meta = {
        "rank": rank,
        "host": socket.gethostname(),
        "pid_os": os.getpid(),
        "clock_offset_us": clock_offset_us,
        "events_recorded": recorded,
        "events_overwritten": max(0, int(recorded) - len(events)),
        "trace_dir": str(_dir_var.value or _DEFAULT_DIR),
    }
    if extra_meta:
        meta.update(extra_meta)
    return {"traceEvents": events, "metadata": meta}


def _estimate_coord_offset(client) -> float:
    """This rank's wall clock MINUS the coord server's clock, in us
    (the sign convention ``merge_timelines``/``skew_report`` consume:
    ``ts - offset`` lands every rank on the coord timebase), via the
    mpisync min-RTT estimator.  ``estimate_offset`` reports the peer's
    clock minus ours, hence the negation."""
    from ompi_tpu.tools.mpisync import estimate_offset

    off_s, _rtt = estimate_offset(client.server_time, iters=5)
    return -off_s * 1e6


def finalize_export(rte) -> None:
    """Called from runtime finalize (while the coord client is still
    alive): write this rank's Chrome trace JSON and publish the payload
    into the CoordServer KV space for the launcher-side merge."""
    if not enabled or _ring is None:
        return
    rank = int(getattr(rte, "my_world_rank", 0) or 0)
    client = getattr(rte, "client", None)
    offset_us = 0.0
    if client is not None:
        try:
            offset_us = _estimate_coord_offset(client)
        except Exception:
            offset_us = 0.0
    # otpu-prof rides in the payload metadata: the per-rank stage
    # breakdown reaches the launcher/analyzer over the same file + KV
    # gather the timeline already takes
    extra_meta = None
    try:
        from ompi_tpu.runtime import profile as _profile

        prof = _profile.export_payload()
        if prof is not None:
            extra_meta = {"profile": prof}
    except Exception:
        extra_meta = None
    payload = chrome_payload(rank, clock_offset_us=offset_us,
                             extra_meta=extra_meta)
    tdir = payload["metadata"]["trace_dir"]
    encoded = json.dumps(payload)   # one encode serves file AND publish
    try:
        os.makedirs(tdir, exist_ok=True)
        with open(os.path.join(tdir, f"trace_rank{rank}.json"), "w") as f:
            f.write(encoded)
    except OSError:
        pass   # unwritable dir must not break finalize
    if client is not None:
        try:
            client.put(rank, _KV_KEY, encoded)
        except Exception:
            pass   # coord gone: the per-rank file still exists


# -- launcher-side merge (used by tools/tpurun.py) -----------------------

def merge_timelines(payloads: list) -> list:
    """Merge per-rank Chrome payloads into one clock-aligned event list:
    each rank's timestamps are shifted by its measured offset to the
    coord clock, pid is the world rank."""
    merged = []
    for p in payloads:
        meta = p.get("metadata", {})
        off_us = float(meta.get("clock_offset_us", 0.0))
        rank = int(meta.get("rank", 0))
        for ev in p.get("traceEvents", []):
            e = dict(ev)
            e["ts"] = float(e["ts"]) - off_us
            e["pid"] = rank
            merged.append(e)
    merged.sort(key=lambda e: e["ts"])
    return merged


def _percentile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


def skew_report(payloads: list) -> str:
    """Cross-rank skew analysis of the collective spans: per
    (collective, communicator) the arrival spread (start-time skew of
    matched rounds), the most-often-slowest rank, and p50/p99 latency
    by log2 size bin.

    Rounds are matched per (name, cid) by occurrence index FROM THE
    TAIL: the ring overwrites oldest events, so when ranks lost unequal
    prefixes only the newest min-count occurrences still line up across
    ranks.  Grouping by cid keeps a sub-communicator's collectives from
    being index-matched against another comm's rounds."""
    per_rank: dict = {}       # rank -> (name, cid) -> [(ts, dur, nbytes)]
    overwritten = 0
    for p in payloads:
        meta = p.get("metadata", {})
        rank = int(meta.get("rank", 0))
        off_us = float(meta.get("clock_offset_us", 0.0))
        overwritten += int(meta.get("events_overwritten", 0) or 0)
        by_key = per_rank.setdefault(rank, {})
        for ev in p.get("traceEvents", []):
            if ev.get("cat") != "coll" or ev.get("ph") != "X":
                continue
            eargs = ev.get("args") or {}
            key = (ev["name"], eargs.get("cid"))
            by_key.setdefault(key, []).append(
                (float(ev["ts"]) - off_us, float(ev.get("dur", 0.0)),
                 int(eargs.get("nbytes", 0))))
    ranks = sorted(per_rank)
    keys = sorted({k for d in per_rank.values() for k in d},
                  key=lambda k: (k[0], str(k[1])))
    lines = [f"otpu-trace skew report — {len(ranks)} ranks "
             f"({', '.join(str(r) for r in ranks)})"]
    if overwritten:
        lines.append(
            f"note: {overwritten} events overwritten across ranks (ring "
            "capacity otpu_trace_buffer_events); rounds are tail-aligned")
    lines += ["",
              "collective          cid  rounds  spread_mean_us  "
              "spread_max_us  slowest_rank"]
    bin_lat: dict = {}           # (name, bin_label) -> [dur...]
    for key in keys:
        name, cid = key
        seqs = {r: per_rank[r].get(key, []) for r in ranks}
        # rounds match across the ranks that HAVE spans for this key: a
        # rank with none (died early, ring-wrapped, or sat out the comm
        # — crash bundles produce all three) must not zero every other
        # rank's rounds and erase the survivors' skew
        members = [r for r in ranks if seqs[r]]
        rounds = min((len(seqs[r]) for r in members), default=0) \
            if len(members) >= 2 else 0
        # tail-align: the ring keeps the newest events on every rank
        tails = {r: seqs[r][len(seqs[r]) - rounds:] for r in members}
        spreads, slow_count = [], {}
        for k in range(rounds):
            starts = {r: tails[r][k][0] for r in members}
            durs = {r: tails[r][k][1] for r in members}
            spreads.append(max(starts.values()) - min(starts.values()))
            slowest = max(durs, key=durs.get)
            slow_count[slowest] = slow_count.get(slowest, 0) + 1
        for r in ranks:
            for _ts, dur, nbytes in tails.get(r, []) if rounds \
                    else seqs[r]:
                label = _bin_label(int(nbytes).bit_length())
                bin_lat.setdefault((name, label), []).append(dur)
        cid_s = "-" if cid is None else str(cid)
        if rounds:
            slowest_rank = max(slow_count, key=slow_count.get)
            absent = len(ranks) - len(members)
            lines.append(
                f"{name:<18}  {cid_s:>3}  {rounds:>6}"
                f"  {sum(spreads)/len(spreads):>14.1f}"
                f"  {max(spreads):>13.1f}  {slowest_rank:>12}"
                f"  ({slow_count[slowest_rank]}/{rounds} rounds"
                + (f"; {absent} rank(s) absent)" if absent else ")"))
        else:
            # unmatched across ranks (some rank never ran it): note only
            total = sum(len(s) for s in seqs.values())
            lines.append(f"{name:<18}  {cid_s:>3}  {0:>6}  "
                         f"{'-':>14}  {'-':>13}  {'-':>12}  "
                         f"({total} unmatched spans)")
    lines += ["", "latency by log2 payload-size bin:",
              "collective          bin      n     p50_us     p99_us"]
    for (name, label), durs in sorted(bin_lat.items()):
        durs.sort()
        lines.append(
            f"{name:<18}  {label:>5}  {len(durs):>5}  "
            f"{_percentile(durs, 0.50):>9.1f}  {_percentile(durs, 0.99):>9.1f}")
    return "\n".join(lines) + "\n"


def reset_for_testing() -> None:
    """Drop all tracer state and re-arm from the cvar (tests only)."""
    global _ring, _ring_n, _slot, enabled, flow_enabled, requests_enabled
    with _hist_lock:
        _hist.clear()
    _ring = None
    _ring_n = 0
    _slot = itertools.count()
    _coll_seq.clear()
    enabled = False
    flow_enabled = False
    requests_enabled = False
    _set_enabled(bool(_enable_var.value))
