"""Init/finalize state machine (``ompi/runtime/ompi_mpi_init.c:391`` flow).

World-model ``MPI_Init`` is now "acquire the default instance": the
RTE boot (base/var init → RTE/PMIx wire-up → pml selection → modex
fence) lives in :mod:`ompi_tpu.instance` and is shared, refcounted, with
MPI-4 Sessions — N open sessions plus world init boot the runtime ONCE,
and the last release finalizes it (``ompi_mpi_instance_init`` /
``_finalize`` in OMPI 5.x).  This module owns what remains world-model
specific: WORLD/SELF construction, per-comm coll selection
(``ompi_mpi_init.c:449-962``), the FT event poller, and the CID space.

Because the instance refcount — not this state machine — now gates the
real teardown, ``MPI_Init`` after ``MPI_Finalize`` is legal (the MPI-4
relaxation): finalize returns the state machine to ground when its
reference is dropped, and the next init boots (or re-joins) the
instance fresh.
"""
from __future__ import annotations

import atexit
import enum
import sys
import threading
from typing import Optional

from ompi_tpu.base.containers import Bitmap
from ompi_tpu.base.var import VarType, registry


class State(enum.IntEnum):
    NOT_INITIALIZED = 0
    INIT_STARTED = 1
    INIT_COMPLETED = 2
    FINALIZE_STARTED = 3
    FINALIZE_COMPLETED = 4


_lock = threading.RLock()
_state = State.NOT_INITIALIZED
_world = None
_self = None
_rte = None
_cid_map = Bitmap(64)
_cid_lock = threading.Lock()
_atexit_armed = False


def initialized() -> bool:
    return _state in (State.INIT_STARTED, State.INIT_COMPLETED)


def finalized() -> bool:
    return _state >= State.FINALIZE_STARTED


def get_rte():
    return _rte


def get_world_if_initialized():
    """COMM_WORLD if init completed, else None (no implicit init) —
    for background services (detector) that must not trigger init."""
    return _world if _state is State.INIT_COMPLETED else None


# -- CID space ----------------------------------------------------------

def next_local_cid() -> int:
    with _cid_lock:
        return _cid_map.find_and_set_first_unset()


def reserve_cid(cid: int) -> None:
    with _cid_lock:
        _cid_map.set(cid)


def candidate_cid(floor: int = 0) -> int:
    """First locally-free CID >= floor, WITHOUT reserving it.

    Proposals are not reserved until the group agreement succeeds, so a
    losing proposal never punches a hole in the bitmap (the hole would
    break the MAX-of-candidates agreement: a candidate chosen from a hole
    can already back a live communicator on another rank).
    """
    with _cid_lock:
        cid = floor
        while _cid_map.is_set(cid):
            cid += 1
        return cid


def is_cid_free(cid: int) -> bool:
    with _cid_lock:
        return not _cid_map.is_set(cid)


def release_cid(cid: int) -> None:
    """Return a NEVER-USED CID to the pool (spawn partial-failure path).

    Only legal for a cid no communicator was ever built on, on any rank:
    dpm's bridge CIDs come from the coordination service's atomic
    counter, so a reservation made before the children joined can be
    dropped on join failure without any reuse hazard — the counter never
    hands the value out again.  Used CIDs must go through
    :func:`retire_cid` instead."""
    with _cid_lock:
        _cid_map.clear(cid)


def retire_cid(cid: int) -> None:
    """Freed CIDs are retired, never returned to the pool: reuse would
    both break the agreement's density assumption and allow a revoked
    (cid, epoch) to be confused with a new incarnation (the reference
    instead re-runs a multi-round agreement until the candidate is
    globally unused — ``comm_cid.c:53-93``; retirement buys the same
    safety from a 64-bit CID space)."""
    # the bit simply stays set; the function records intent at call sites


def clear_cid_space() -> None:
    """Reset the CID bitmap — called by the instance layer at LAST
    release (the CID space is instance-scoped: session-built comms and
    world comms share it, so neither may clear it alone)."""
    with _cid_lock:
        _cid_map.clear_all()


# -- init / finalize ----------------------------------------------------

def init(devices=None, rte=None, argv: Optional[list] = None):
    """Initialize the runtime; idempotent (returns COMM_WORLD)."""
    global _state, _world, _self, _rte, _atexit_armed
    with _lock:
        if _state is State.INIT_COMPLETED:
            return _world
        if _state is State.FINALIZE_STARTED:
            raise RuntimeError("cannot init while finalize is running")
        # FINALIZE_COMPLETED falls through: MPI-4 allows init → finalize
        # → init (the instance layer decides whether a real re-boot is
        # needed or an open session kept the runtime alive)
        _state = State.INIT_STARTED

        from ompi_tpu import instance as inst_mod

        inst = inst_mod.acquire(argv=argv, devices=devices, rte=rte)
        try:
            return _build_world(inst)
        except BaseException:
            # failed world construction must not leak the instance
            # reference (a later retry would double-acquire and the
            # matching finalize could then never reach teardown)
            inst_mod.release()
            _world = _self = _rte = None
            _state = State.NOT_INITIALIZED
            raise


def _build_world(inst):
    """World-model construction on an acquired instance (the body of
    ``init()`` after the boot; caller holds ``_lock``)."""
    global _state, _world, _self, _rte, _atexit_armed
    _rte = inst.rte
    pml_module = inst.pml

    # world/self communicators (ompi_mpi_init.c:779)
    from ompi_tpu.api.comm import Comm
    from ompi_tpu.api.group import Group

    # a dpm-spawned job's COMM_WORLD is its own rank set (global ranks
    # allocated by the coord server), not 0..size-1
    world_group = Group(getattr(_rte, "job_ranks",
                                range(_rte.world_size)))
    _world = Comm(world_group, cid=0, rte=_rte, name="COMM_WORLD")
    reserve_cid(0)
    my = _rte.my_world_rank
    _self = Comm(Group([my]), cid=1, rte=_rte, name="COMM_SELF")
    reserve_cid(1)
    _world.pml = pml_module
    _self.pml = pml_module
    pml_module.add_comm(_world)
    pml_module.add_comm(_self)

    # eager add_procs: build every peer's endpoint list NOW, while the
    # modex is guaranteed reachable (the reference does this at
    # ompi_mpi_init.c:833 — BML endpoint lists are an init product,
    # not a first-send side effect; the FT detector's p2p carrier
    # depends on endpoints surviving a later coord death)
    inner = pml_module
    while inner is not None and not hasattr(inner, "bml"):
        inner = getattr(inner, "_inner", None)
    bml = getattr(inner, "bml", None) if inner is not None else None
    if bml is not None and not _rte.is_device_world:
        for wr in _world.group.world_ranks:
            if wr != _rte.my_world_rank:
                try:
                    bml.add_proc(wr)
                except Exception:
                    pass   # peer reachable lazily or not at all

    # per-comm coll selection (ompi_mpi_init.c:956,962)
    from ompi_tpu.mca.coll.base import comm_select

    comm_select(_world)
    comm_select(_self)

    # ULFM FT runtime: event poller + optional heartbeat ring
    # (PMIX_ERR_PROC_ABORTED handler registration, ompi_mpi_init.c:400-402)
    _ft_enable = registry.register(
        "ft", None, "enable", vtype=VarType.BOOL, default=True,
        help="Start the FT event poller (failure/revocation delivery)")
    _ft_detector = registry.register(
        "ft", None, "detector", vtype=VarType.BOOL, default=False,
        help="Start the heartbeat ring failure detector")
    if not _rte.is_device_world and getattr(_rte, "client", None) is not None:
        if _ft_enable.value:
            from ompi_tpu.ft import propagator

            propagator.start(_rte, with_detector=bool(_ft_detector.value))

    # hook framework: post-init interposition (hook/comm_method dump)
    from ompi_tpu.mca.hook import run_hooks

    run_hooks("init", _world)

    _state = State.INIT_COMPLETED
    if not _atexit_armed:
        _atexit_armed = True
        atexit.register(_atexit_finalize)
    return _world


def comm_world():
    if _world is None:
        init()
    return _world


def comm_self():
    if _self is None:
        init()
    return _self


# Upper-case aliases used by the lazy top-level API
def COMM_WORLD():  # pragma: no cover - thin alias
    return comm_world()


def COMM_SELF():  # pragma: no cover - thin alias
    return comm_self()


def init_thread(required: int = 0, devices=None, rte=None, argv=None):
    """``MPI_Init_thread``: returns (world, provided).

    The engine is thread-safe throughout, so provided is always
    THREAD_MULTIPLE whatever level was required."""
    from ompi_tpu.runtime import interlib

    world = init(devices=devices, rte=rte, argv=argv)
    return world, interlib.query_thread()


def finalize() -> None:
    global _state, _world, _self, _rte
    from ompi_tpu.runtime import interlib

    with _lock:
        if _state is not State.INIT_COMPLETED:
            return
        # interlib guard INSIDE the init lock: a register() racing this
        # finalize either lands before the check (runtime stays up; the
        # last deregister's caller finalizes) or after teardown began —
        # register while concurrently finalizing is the one ordering MPI
        # itself leaves undefined (ompi_mpi_finalize's interlib guard)
        if interlib.registrations() > 0:
            return
        _state = State.FINALIZE_STARTED
        from ompi_tpu import instance as inst_mod

        try:
            # pre-teardown synchronisation (ompi_mpi_finalize's barrier)
            # BEFORE any shared-segment release, but only when dropping
            # our reference will actually tear the runtime down — with a
            # session still open, the process (and its segments) lives on
            # and the real fence runs at the session's last release.
            inst = inst_mod.current()
            if inst is not None and inst_mod.refcount() <= 1:
                inst._fence_final()
            from ompi_tpu.ft import propagator as _ft_prop

            _ft_prop.stop()
            # release per-comm coll resources (shared segments etc.) for
            # the built-in comms the user never frees — the reference
            # destroys WORLD/SELF in ompi_mpi_finalize the same way
            for c in (_world, _self):
                if c is not None and not getattr(c, "freed", False):
                    c.release_coll_modules()
            # drop the world's instance reference; the LAST release runs
            # the real teardown (trace export, pml finalize, rte
            # finalize, thread pools, mca close, CID clear)
            inst_mod.release()
        finally:
            _world = _self = _rte = None
            _state = State.FINALIZE_COMPLETED


def _atexit_finalize() -> None:
    try:
        finalize()
    except Exception:
        pass


def reset_for_testing() -> None:
    """Full teardown allowing re-init (tests only)."""
    global _state
    from ompi_tpu.runtime import interlib

    interlib.reset_for_testing()
    finalize()
    # drain session references a test may have leaked — the instance
    # must not survive into the next test's boot
    from ompi_tpu import instance as inst_mod

    inst_mod.reset_for_testing()
    from ompi_tpu.ft import state as _ft_state

    _ft_state.reset_for_testing()
    with _lock:
        _state = State.NOT_INITIALIZED


def abort(obj, errorcode: int = 1) -> None:
    """``MPI_Abort``: tear down the job."""
    print(f"[ompi_tpu] MPI_Abort on {obj!r} with code {errorcode}",
          file=sys.stderr, flush=True)
    try:
        from ompi_tpu.runtime import flight

        flight.dump("abort", detail=f"code {errorcode} on {obj!r}")
    except Exception:
        pass
    if _rte is not None:
        _rte.event_notify("abort", {"code": errorcode})
    sys.exit(errorcode)
