"""memchecker — buffer-ownership checking (the valgrind-annotation analog).

Re-design of ``/root/reference/opal/mca/memchecker/memchecker.h:25-52``:
the reference marks user buffers "owned by MPI" with valgrind client
requests so a data race with an in-flight nonblocking operation is caught
at the faulty access.  Python's analog is numpy's writeable flag: while a
rendezvous isend is in flight, the user's send buffer is flipped
read-only, so the classic bug — writing into a buffer before the request
completes — raises ``ValueError: assignment destination is read-only`` AT
THE RACY WRITE instead of silently corrupting the message.

Debug aid, off by default (``otpu_memchecker_enable=1``); eager sends
copy at post time and need no guard, exactly as the reference only
annotates buffers MPI still references.
"""
from __future__ import annotations

import numpy as np

from ompi_tpu.base.var import VarType, registry
from ompi_tpu.runtime import sanitizer

_enable_var = registry.register(
    "memchecker", None, "enable", vtype=VarType.BOOL, default=False,
    help="Mark in-flight nonblocking send buffers read-only so user "
         "writes race-fail loudly (valgrind memchecker analog)")


def enabled() -> bool:
    # OTPU_SANITIZE=1 force-enables the guard: the sanitizer mode turns
    # every ownership invariant — this one included — into a hard check
    return bool(_enable_var.value) or sanitizer.enabled


def protect_send(req, buf) -> None:
    """Freeze ``buf`` until ``req`` completes (no-op when disabled or the
    buffer isn't a plain writable ndarray)."""
    if not enabled():
        return
    if not isinstance(buf, np.ndarray) or not buf.flags.writeable:
        return
    try:
        buf.setflags(write=False)
    except ValueError:
        return   # base array not owned: cannot guard this view

    def _release(_req) -> None:
        try:
            buf.setflags(write=True)
        except ValueError:
            pass

    req.on_complete(_release)
