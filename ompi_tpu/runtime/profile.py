"""otpu-prof — per-message stage clocks + the sampling host profiler.

The observability stack can say WHICH rank is slow (otpu-top, the
analyzer's straggler attribution) and WHEN a collective ran (otpu-trace
spans), but not WHERE inside the host datapath a message's latency went:
convertor pack vs staging checkout vs out-queue wait vs the sendmsg
syscall vs receive parse vs delivery.  The native-reactor refactor
(ROADMAP item 2) is accepted against exactly that decomposition — a
per-message host-overhead budget and a GIL-released fraction — so this
module is the measurement substrate it is proven with.

Two halves, both off by default with the trace/telemetry/chaos
module-bool identity discipline:

**Stage clocks** (``otpu_profile_stages``): near-zero-cost monotonic
marks threaded through the host datapath.  Every instrumentation site is
``if profile.enabled:`` guarded; enabled, a site costs one
``perf_counter_ns`` pair plus one locked histogram fold (the
``trace.hist_record`` shape).  Stage names are a CLOSED, declared table
(:data:`STAGES`) — the otpu-lint observability pass statically rejects a
literal stage outside it, and :func:`stage_span` rejects it loudly at
runtime — so ``otpu_analyze`` can decompose any message's latency into
pack/queue/wire/parse/deliver buckets with stable meaning.

**Sampling profiler** (``otpu_profile_interval_ms``): a rank-jittered
thread sampling ``sys._current_frames()``, bucketing each thread's
innermost ``@hot_path``-registered frame into a progress-loop phase
(the ``runtime/hotpath.py`` registry IS the phase table), and estimating

- ``gil_released``: the fraction of thread observations parked at a
  known GIL-dropping wait site (threading/selectors/socket waits, the
  progress engine's ``idle_wait``) — a LOWER bound: a thread caught
  mid-syscall under its own Python frame is not counted;
- ``gil_wait``: the profiler's own scheduling-delay excess (actual vs
  requested sleep) as a fraction of elapsed time — a GIL/scheduler
  contention proxy (the gil_load technique).

Both halves publish through the PR 10 telemetry ``SCHEMA`` (key
``profile``) so otpu_top shows a live host-overhead column, ride in the
flight recorder's crash dumps, and export at finalize inside the trace
payload's metadata (``chrome_payload`` ``extra_meta``) for
``otpu_analyze``'s per-rank exposed-host report.
"""
from __future__ import annotations

import random
import sys
import threading
import time
from typing import Optional

from ompi_tpu.base.var import VarType, registry

#: Declared stage table — the CLOSED vocabulary of datapath stage
#: clocks.  Keys are ``<path>.<stage>``; ``otpu_info --profile``
#: enumerates this table and the otpu-lint observability pass enforces
#: that every literal ``stage_span``/``stage_mark`` name comes from it.
STAGES = {
    "send.pack": "convertor pack/pack_borrow: user buffer -> wire-shaped "
                 "chunk (O(1) slice on the contiguous borrow path)",
    "send.staging": "staging-pool checkout (device-path host bounce "
                    "buffers, mca/accelerator)",
    "send.queue": "btl send(): header build + out-queue enqueue, wire "
                  "syscall excluded",
    "send.wire": "wire handoff: socket sendmsg / sm ring write",
    "recv.parse": "frame parse: header decode + payload slice out of "
                  "the recv scratch / sm ring frame",
    "recv.deliver": "pml frag delivery: match + unpack into the user "
                    "buffer",
    "recv.complete": "ob1 request completion: status fill + completion "
                     "callbacks",
    "coll.decide": "coll/tuned decision: ladder + rule-file lookup",
    "coll.alg": "coll/tuned algorithm body (schedule execution, wire "
                "waits included)",
    "quant.encode": "coll/quant block-scale encode (wire quantize-on-"
                    "pack, host quant collectives, KV slab write)",
    "quant.decode": "coll/quant block-scale decode (receive-parse "
                    "dequant, dequant-accumulate folds, KV slab read)",
}

#: THE fast-path guard (trace/telemetry/chaos discipline): stage-clock
#: sites read this module bool and branch — nothing else happens while
#: profiling is disabled.
enabled = False

_lock = threading.Lock()
#: stage -> [count, sum_ns, min_ns, max_ns, {log2 dur bin: count}];
#: exact under _lock (enabled path only)
_stages: dict = {}

#: otpu-lint lock-discipline contract: the stage table is folded into
#: from every datapath thread and snapshotted by samplers/exports
_GUARDED_BY = {"_stages": "_lock"}

_profiler: Optional["HostProfiler"] = None

#: monotonic ns of the FIRST arming of either half: the stage
#: histograms accumulate from here to export, so this — not the
#: bounded trace ring's surviving-event window — is the honest
#: denominator for the exposed-host fraction on long runs
_armed_mono_ns: Optional[int] = None


def _note_armed() -> None:
    global _armed_mono_ns
    if _armed_mono_ns is None:
        _armed_mono_ns = time.perf_counter_ns()


def _set_enabled(value: bool) -> None:
    global enabled
    enabled = bool(value)
    if enabled:
        _note_armed()


_stages_var = registry.register(
    "profile", None, "stages", vtype=VarType.BOOL, default=False,
    on_set=_set_enabled,
    help="Arm the per-message stage clocks (pack/queue/wire/parse/"
         "deliver latency histograms through the host datapath); "
         "disabled cost is one flag check per site")
_interval_var = registry.register(
    "profile", None, "interval_ms", vtype=VarType.INT, default=0,
    help="Sampling-profiler interval in milliseconds; 0 (the default) "
         "means no profiler thread exists.  10-50 gives useful phase/"
         "GIL estimates at negligible cost")
_jitter_var = registry.register(
    "profile", None, "jitter", vtype=VarType.FLOAT, default=0.2,
    help="Per-rank deterministic jitter fraction on the sampling sleep "
         "(rank-seeded, so N ranks' samples interleave instead of "
         "phase-locking)")


def now() -> int:
    """Stage-clock begin timestamp (perf_counter_ns).  Call only inside
    an ``if profile.enabled:`` guard — the disabled path must not pay
    for the syscall."""
    return time.perf_counter_ns()


def _check_stage(stage: str) -> None:
    from ompi_tpu.base.output import show_help

    show_help("help-profile", "bad-stage", stage=stage,
              known=", ".join(sorted(STAGES)))
    raise ValueError(f"profile stage {stage!r} is not declared in "
                     "runtime/profile.py STAGES")


def stage_span(stage: str, t0: int, t_end: Optional[int] = None) -> None:
    """Fold one stage occurrence of duration ``now - t0`` into the
    stage's log2 latency histogram.  ``t0 <= 0`` is ignored — a site
    whose begin predates a mid-run enable must not record garbage."""
    if not enabled or not t0:
        return
    if t_end is None:
        t_end = time.perf_counter_ns()
    dur = t_end - t0
    with _lock:
        cell = _stages.get(stage)
        if cell is None:
            if stage not in STAGES:
                _check_stage(stage)
            cell = _stages[stage] = [0, 0, dur, dur, {}]
        cell[0] += 1
        cell[1] += dur
        cell[2] = min(cell[2], dur)
        cell[3] = max(cell[3], dur)
        db = int(dur).bit_length() if dur > 0 else 0
        cell[4][db] = cell[4].get(db, 0) + 1


def stage_mark(stage: str) -> None:
    """Count one occurrence of ``stage`` without a duration (discrete
    datapath events a decomposition normalizes by)."""
    if not enabled:
        return
    with _lock:
        cell = _stages.get(stage)
        if cell is None:
            if stage not in STAGES:
                _check_stage(stage)
            cell = _stages[stage] = [0, 0, 0, 0, {}]
        cell[0] += 1


def stage_snapshot() -> dict:
    """Deep-copied stage state for delta consumers (the telemetry
    source): ``{stage: (count, sum_ns, min_ns, max_ns, {bin: count})}``.
    Pure read — populations are never reset."""
    with _lock:
        return {k: (c[0], c[1], c[2], c[3], dict(c[4]))
                for k, c in _stages.items()}


def stage_stats(snap: Optional[dict] = None) -> dict:
    """Human/JSON stage table: ``{stage: {n, sum_us, mean_us, min_us,
    max_us, p50_us, p99_us}}`` (percentiles interpolated from the log2
    duration bins, THE trace estimator)."""
    from ompi_tpu.runtime.trace import _interp_percentile_ns

    if snap is None:
        snap = stage_snapshot()
    out = {}
    for stage, (n, total, lo, hi, bins) in sorted(snap.items()):
        row = {"n": n, "sum_us": round(total / 1000.0, 1),
               "mean_us": round(total / n / 1000.0, 2) if n else 0.0,
               "min_us": round(lo / 1000.0, 2),
               "max_us": round(hi / 1000.0, 2)}
        if bins:
            row["p50_us"] = round(
                _interp_percentile_ns(bins, 0.5, lo, hi) / 1000.0, 2)
            row["p99_us"] = round(
                _interp_percentile_ns(bins, 0.99, lo, hi) / 1000.0, 2)
        out[stage] = row
    return out


def stage_delta_stats(prev: dict, cur: dict) -> dict:
    """Per-stage interval statistics between two :func:`stage_snapshot`
    results: ``{stage: {n, sum_us}}`` from the count/sum deltas; stages
    with no new occurrences are omitted (compact samples)."""
    out = {}
    for stage, cell in cur.items():
        old = prev.get(stage)
        dn = cell[0] - (old[0] if old else 0)
        if dn <= 0:
            continue
        dsum = cell[1] - (old[1] if old else 0)
        out[stage] = {"n": dn, "sum_us": round(dsum / 1000.0, 1)}
    return out


# -- sampling profiler ---------------------------------------------------

#: wait-primitive filename suffixes whose frames mean "parked with the
#: GIL released" (stdlib wait/IO internals); see gil_released caveat in
#: the module docstring
_BLOCKED_FILES = ("threading.py", "selectors.py", "socket.py",
                  "connection.py", "queue.py", "ssl.py")
_BLOCKED_NAMES = ("idle_wait", "select", "poll", "epoll")

#: native entry points that release the GIL while doing real work —
#: ctypes drops the GIL for the call's duration, so a thread sampled
#: here counts toward gil_released but keeps its hot-path phase (the
#: reactor's drain/pump runs socket drain + framing inside this call;
#: classifying it "idle" would hide the work from phase attribution)
_NATIVE_NAMES = ("_native_drain",)


class HostProfiler:
    """The per-rank sampling thread.  Aggregates are WRITTEN by the
    profiler thread and READ by the telemetry sampler and the flight
    recorder's crash path, so every aggregate update folds in under the
    module ``_lock`` (one uncontended acquire per tick) — a reader
    iterating ``phase_counts`` mid-insert would otherwise raise, and on
    the flight path that exception silently costs the whole dump."""

    def __init__(self, rank: int, interval_ms: int) -> None:
        self.rank = int(rank)
        self.interval_ms = max(1, int(interval_ms))
        self._stop = threading.Event()
        self._jitter = random.Random(f"profile:{self.rank}")
        self._hot_index: Optional[dict] = None
        # aggregates (written under the module _lock by the profiler
        # thread, snapshotted under it by profiler_stats)
        self.samples = 0
        self.phase_counts: dict = {}
        self.blocked_obs = 0
        self.total_obs = 0
        self.gil_wait_ns = 0
        self.elapsed_ns = 0
        self._thread = threading.Thread(
            target=self._run, name="otpu-prof", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)

    def _hot(self) -> dict:
        """(module, function-name) -> phase label, from the @hot_path
        registry (built lazily so late-imported components register)."""
        from ompi_tpu.runtime import hotpath

        reg = hotpath.registered()
        if self._hot_index is None or len(self._hot_index) != len(reg):
            idx = {}
            for qual, module in reg.items():
                tail = qual[len(module) + 1:] if qual.startswith(module) \
                    else qual
                idx[(module, tail.rsplit(".", 1)[-1])] = tail
            self._hot_index = idx
        return self._hot_index

    def _classify(self, frame) -> tuple:
        """(phase, blocked) for one thread's stack: innermost @hot_path
        frame names the phase; a top frame inside a stdlib wait
        primitive counts as GIL-released."""
        hot = self._hot()
        top = frame
        fn = top.f_code.co_filename
        if fn.endswith(_BLOCKED_FILES) or \
                top.f_code.co_name in _BLOCKED_NAMES:
            return "idle", True
        released = top.f_code.co_name in _NATIVE_NAMES
        phase = None
        f = frame
        while f is not None:
            key = (f.f_globals.get("__name__", ""), f.f_code.co_name)
            label = hot.get(key)
            if label is not None:
                phase = label
                break
            f = f.f_back
        return phase or ("native" if released else "other"), released

    def _run(self) -> None:
        from ompi_tpu.runtime import spc

        jit = float(_jitter_var.value or 0.0)
        me = self._thread.ident
        t_prev = time.perf_counter_ns()
        while not self._stop.is_set():
            sleep_s = (self.interval_ms / 1e3) * (
                1.0 + jit * (2.0 * self._jitter.random() - 1.0))
            if self._stop.wait(sleep_s):
                break
            t_now = time.perf_counter_ns()
            dt = t_now - t_prev
            t_prev = t_now
            try:
                frames = sys._current_frames()
            except Exception:
                continue
            spc.record("profile_samples")
            # classify into locals first, fold in under the lock (see
            # class docstring)
            phases: dict = {}
            blocked = total = 0
            for tid, frame in frames.items():
                if tid == me:
                    continue
                try:
                    phase, is_blocked = self._classify(frame)
                except Exception:
                    continue   # a torn frame must not kill the profiler
                total += 1
                blocked += int(is_blocked)
                phases[phase] = phases.get(phase, 0) + 1
            with _lock:
                self.samples += 1
                # scheduling-delay excess over the requested sleep =
                # the gil_load-style contention proxy
                self.elapsed_ns += dt
                self.gil_wait_ns += max(0, dt - int(sleep_s * 1e9))
                self.total_obs += total
                self.blocked_obs += blocked
                for phase, n in phases.items():
                    self.phase_counts[phase] = \
                        self.phase_counts.get(phase, 0) + n


def profiler_stats() -> Optional[dict]:
    """Aggregate sampling-profiler estimates, or None when no profiler
    ran: ``{samples, phases, gil_released, gil_wait}``.  Snapshotted
    under the module lock against the profiler thread's folds."""
    with _lock:
        p = _profiler
        if p is None or p.samples == 0:
            return None
        return {
            "samples": p.samples,
            "phases": dict(sorted(p.phase_counts.items(),
                                  key=lambda kv: -kv[1])),
            "gil_released": round(p.blocked_obs / max(1, p.total_obs),
                                  3),
            "gil_wait": round(p.gil_wait_ns / max(1, p.elapsed_ns), 3),
        }


def export_payload() -> Optional[dict]:
    """The per-rank profile artifact (trace-payload metadata, flight
    dumps): stage stats + profiler estimates, or None when neither half
    recorded anything."""
    snap = stage_snapshot()
    prof = profiler_stats()
    if not snap and prof is None and not enabled:
        return None
    out: dict = {"stages": stage_stats(snap)}
    if _armed_mono_ns is not None:
        # the wall covered by the accumulated histograms (arm->export):
        # the analyzer's exposed-host denominator, immune to the trace
        # ring overwriting early events on long runs
        out["elapsed_us"] = round(
            (time.perf_counter_ns() - _armed_mono_ns) / 1000.0, 1)
    if prof is not None:
        out["profiler"] = prof
    return out


def start(rte) -> bool:
    """Arm the sampling profiler for this rank (instance boot).  No-op
    unless ``otpu_profile_interval_ms`` is positive.  The stage clocks
    are var-armed independently and need no thread.  Idempotent."""
    global _profiler
    with _lock:
        if _profiler is not None:
            return True
        interval = int(_interval_var.value or 0)
        if interval <= 0:
            return False
        _profiler = HostProfiler(
            int(getattr(rte, "my_world_rank", 0) or 0), interval)
        p = _profiler
    _note_armed()
    p.start()
    return True


def stop() -> None:
    """Stop the sampling profiler and clear the slot (teardown /
    tests), restoring the no-profiler state — a later re-init's
    :func:`start` must arm a FRESH sampler, not early-return against a
    dead thread whose frozen estimates would read as live (the
    telemetry.stop() discipline).  Runs after the teardown's trace
    export / flight postmortem, which carry the final aggregates."""
    global _profiler
    with _lock:
        p, _profiler = _profiler, None
    if p is not None:
        p.stop()


def reset_for_testing() -> None:
    global _armed_mono_ns, enabled
    stop()
    with _lock:
        _stages.clear()
    _armed_mono_ns = None
    enabled = False
    _set_enabled(bool(_stages_var.value))


# -- telemetry source ----------------------------------------------------

_last_tele_snap: dict = {}

#: message-path HOST stages: what otpu_top's host% column sums.  The
#: wire handoff and the coll.* phases are excluded — coll.alg contains
#: the algorithm's wire WAITS by design, so summing it would report
#: >100% of the interval as "host overhead".
_HOST_STAGES = ("send.pack", "send.staging", "send.queue",
                "recv.parse", "recv.deliver", "recv.complete")


def _telemetry_stats() -> Optional[dict]:
    """otpu_top's live host-overhead column (sampler-thread-only
    provider, so the delta state needs no lock of its own): interval
    stage deltas + the profiler's cumulative estimates."""
    global _last_tele_snap
    prof = profiler_stats()
    if not enabled and prof is None:
        return None
    cur = stage_snapshot()
    deltas = stage_delta_stats(_last_tele_snap, cur)
    _last_tele_snap = cur
    out: dict = {
        "host_us": round(sum(d["sum_us"] for s, d in deltas.items()
                             if s in _HOST_STAGES), 1),
        "stages": deltas,
    }
    if prof is not None:
        out["gil_released"] = prof["gil_released"]
        out["gil_wait"] = prof["gil_wait"]
        out["samples"] = prof["samples"]
    return out


from ompi_tpu.runtime import telemetry as _telemetry

_telemetry.register_source("profile", _telemetry_stats)

from ompi_tpu.base.output import register_help as _rh

_rh("help-profile", "bad-stage",
    "Profile stage {stage!r} is not declared in runtime/profile.py "
    "STAGES (known: {known}).  Stage clocks aggregate into a closed, "
    "declared table so otpu_analyze's latency decomposition keeps a "
    "stable meaning — declare the stage there (and in the docs table) "
    "before marking it.")
