"""Central progress engine — THE hot loop of the host-side runtime.

Re-design of ``/root/reference/opal/runtime/opal_progress.c``: registered
callbacks are polled by :func:`progress` (``opal_progress.c:216,224``);
low-priority callbacks run every 8th call (``:227``); components register via
:func:`register` / :func:`unregister` (``:414``).  On the ICI path XLA
schedules collectives itself and needs no progress engine — this loop serves
the host-side stack: BTL polling (tcp/sm), rendezvous pipelines, nonblocking
collective schedules (libnbc equivalent), FT heartbeats, RMA passive targets.
"""
from __future__ import annotations

import os
import selectors
import threading
import time
from typing import Callable

from ompi_tpu.base.var import VarType, registry
from ompi_tpu.runtime import sanitizer
from ompi_tpu.runtime.hotpath import hot_path

_LOW_PRIORITY_CADENCE = 8  # opal_progress.c:227


def _set_lp_cadence(v) -> None:
    global _LOW_PRIORITY_CADENCE
    _LOW_PRIORITY_CADENCE = max(1, int(v))


registry.register(
    "progress", None, "lp_cadence",
    vtype=VarType.INT, default=_LOW_PRIORITY_CADENCE,
    help="Run low-priority progress callbacks every Nth tick "
         "(opal_progress's event-loop tick ratio)",
    on_set=_set_lp_cadence)

_lock = threading.RLock()
_callbacks: list[Callable[[], int]] = []
_lp_callbacks: list[Callable[[], int]] = []
_counter = 0
_in_progress = threading.local()

#: otpu-lint lock-discipline contract: callback lists, the cadence
#: counter, and the waiter registry mutate only under the module lock
_GUARDED_BY = {"_callbacks": "_lock", "_lp_callbacks": "_lock",
               "_counter": "_lock", "_waiter_count": "_lock"}

# -- event-based idle wait (the libevent role in opal_progress) ----------
#
# Transports register a readable fd that goes hot when work arrives (the
# btl/sm doorbell socket, tcp data sockets).  An idle waiter blocks in
# select() on these instead of sleeping blind: message arrival wakes it
# in ~10µs instead of a scheduler-quantum-sized nap — the difference
# between µs and ms per rendezvous round-trip on an oversubscribed host.
_waiter_sel = selectors.DefaultSelector()
_waiter_count = 0


def register_waiter(fileobj) -> None:
    global _waiter_count
    with _lock:
        _waiter_sel.register(fileobj, selectors.EVENT_READ)
        _waiter_count += 1


def unregister_waiter(fileobj) -> None:
    global _waiter_count
    with _lock:
        try:
            _waiter_sel.unregister(fileobj)
            _waiter_count -= 1
        except KeyError:
            pass


def _prune_dead_waiters() -> None:
    """Drop registrations whose fd has been closed out from under the
    selector (a conn torn down concurrently by ``_drop_conn``): probe
    each registered fd and unregister the dead ones so the surviving
    registrations keep working."""
    global _waiter_count
    with _lock:
        for key in list(_waiter_sel.get_map().values()):
            try:
                os.fstat(key.fd)
            except OSError:
                try:
                    _waiter_sel.unregister(key.fileobj)
                    _waiter_count -= 1
                except KeyError:
                    pass


def idle_wait(timeout: float) -> bool:
    """Block until a transport fd is readable or ``timeout`` elapses.
    Returns True when woken by an fd (caller should poll progress)."""
    if _waiter_count == 0:
        time.sleep(timeout)
        return False
    deadline = time.monotonic() + timeout
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return False
        try:
            return bool(_waiter_sel.select(remaining))
        except OSError:
            # an fd closed concurrently with the select (a conn dropped
            # by another thread): prune the dead registrations and
            # RETRY on the survivors for the remaining budget — the old
            # blind time.sleep(timeout) here burned the full timeout
            # and turned every teardown race into a latency cliff
            _prune_dead_waiters()
            if _waiter_count == 0:
                remaining = deadline - time.monotonic()
                if remaining > 0:
                    time.sleep(remaining)
                return False


def register(cb: Callable[[], int], low_priority: bool = False) -> None:
    """Register a callback returning the number of events it progressed."""
    with _lock:
        target = _lp_callbacks if low_priority else _callbacks
        if cb not in target:
            target.append(cb)


def unregister(cb: Callable[[], int]) -> None:
    with _lock:
        for target in (_callbacks, _lp_callbacks):
            if cb in target:
                target.remove(cb)


@hot_path
def progress() -> int:
    """Poll all registered callbacks once; returns events progressed."""
    global _counter
    if getattr(_in_progress, "active", False):
        return 0  # no recursive progress (callbacks may wait internally)
    _in_progress.active = True
    try:
        with _lock:
            cbs = list(_callbacks)
            _counter += 1
            if _counter % _LOW_PRIORITY_CADENCE == 0:
                cbs += _lp_callbacks
        events = 0
        for cb in cbs:
            try:
                events += cb()
            except sanitizer.SanitizeError:
                # a sanitizer trip (wire corruption, quant frame that
                # does not decode, aliasing assert) is a DELIBERATE
                # fatal integrity stop, not a broken callback:
                # quarantining it here swallowed the error and turned
                # detected corruption into a silent hang — propagate,
                # so the waiting caller dies loudly and the launcher
                # tears the job down
                raise
            except Exception:
                # a broken progress callback must not kill the loop; it is
                # removed and reported once
                unregister(cb)
                from ompi_tpu.base.output import show_help

                import traceback

                show_help("help-progress", "callback-failed",
                          detail=traceback.format_exc(limit=3))
        return events
    finally:
        _in_progress.active = False


def callback_count() -> int:
    with _lock:
        return len(_callbacks) + len(_lp_callbacks)


def reset_for_testing() -> None:
    global _counter
    # the native reactor registers a callback + waiter here: tear its
    # thread down BEFORE clearing the lists so a late record dispatch
    # cannot fire into a half-reset engine (instance teardown routes
    # through this too)
    from ompi_tpu.runtime import reactor as _reactor

    _reactor.shutdown()
    with _lock:
        _callbacks.clear()
        _lp_callbacks.clear()
        _counter = 0


from ompi_tpu.base.output import register_help as _rh

_rh("help-progress", "callback-failed",
    "A progress callback raised and was unregistered:\n{detail}")

# progress-engine depth for otpu_top (sampler-thread-only provider)
from ompi_tpu.runtime import telemetry as _telemetry


def _telemetry_stats() -> dict:
    from ompi_tpu.runtime import reactor as _reactor

    with _lock:
        out = {"callbacks": len(_callbacks) + len(_lp_callbacks),
               "low_priority": len(_lp_callbacks),
               "waiters": _waiter_count}
    out["reactor_active"] = _reactor.active()
    return out


_telemetry.register_source("progress", _telemetry_stats)
